"""Production inference serving: continuous batching over the paged KV pool.

Role parity: the reference ships fused inference kernels and an
``InferenceEngine`` but no request scheduler — serving is delegated to
MII/externals.  This module is that missing layer, built TPU-first:

- **continuous (in-flight) batching** — a FIFO request queue feeds a
  fixed-width decode batch (``batch_slots``); sequences JOIN a free slot
  the step after their prefill and EVICT the step they finish, so the
  decode executable never re-specializes while traffic churns (one
  compiled step per serving configuration, AOT-warm-started from the
  persistent compile cache across restarts);
- **paged KV cache** — slots hold per-sequence block lists into one
  shared pool (``paged_kv.py``), with slot/block reuse on completion and
  an optional int8 pool (block-quantized via the ZeRO++ quantizer,
  ``runtime/comm/quantized.py``) halving the KV byte term;
- **fused decode** — the token step is the models' stacked-scan paged
  decode (``GPT2.decode_step_paged``): ONE executable per step for all
  slots, not 4·L separately scheduled small matmuls (the measured b=8
  scheduling-gap term, DECODE_PROFILE.json);
- **admission control** — capacity math (blocks needed vs free) gates
  the queue, and the decode executable's ``memory_analysis()`` is
  preflighted against the HBM budget BEFORE any step executes (the same
  protocol as ``DeepSpeedEngine.preflight_memory`` / the bench ladder),
  so a mis-sized pool refuses to start instead of dying
  RESOURCE_EXHAUSTED mid-traffic;
- **latency accounting** — per-request submit→first-token and
  submit→done stamps; p50/p99/p999 from mergeable log-bucketed
  histograms over EVERY completion (``stats()``; exact counts, ≤1%
  value error, bounded memory — ``monitor/histogram.py``); long-running
  servers drain finished records with ``pop_result(uid)`` so
  ``results`` never grows unbounded.

Resilience (docs/serving.md#resilience — the serving twin of the
training fault ladder, PR 1/3/7 composed):

- **deadlines + overload policy** — per-request ``deadline_ms``
  enforced at admit (predictively, against the measured decode-step
  EMA) and per decode step; queue admission follows
  ``ServingConfig.overload`` (``reject`` | ``shed_oldest`` | ``block``)
  with hysteresis watermarks, so sustained overload degrades to
  bounded-latency shedding instead of unbounded queueing;
- **poisoned-request quarantine** — an in-graph per-slot non-finite
  sentinel on the decode logits (``runtime/health.rows_nonfinite``; no
  host callbacks, sampling branchlessly forced to a sentinel token)
  with host-side eviction, block scrubbing + return, and a circuit
  breaker that trips to reject-all with a forensic ring dump when the
  poison rate exceeds ``poison_budget``;
- **crash-recoverable in-flight state** — a rank-0 append-only request
  journal (``inference/journal.py``); a restarted engine re-queues lost
  in-flight requests and regenerates token-identical answers;
- **graceful drain** — ``drain(timeout_s)`` stops admission, finishes
  the active slots and journals a clean shutdown; ``close()`` drains.

Every terminal outcome is typed (``OK``/``SHED``/``DEADLINE``/
``POISONED`` in the result record's ``outcome``; ``QueueFullError``/
``ServingStalledError``/``CircuitOpenError`` raised), and the
shed/deadline/poisoned/requeued totals ride the monitor bus as counters
(rendered by ``ds_top``).

Determinism: each request's sampling stream is
``fold_in(PRNGKey(request.seed), token_index)`` — a function of the
request alone, never of batch composition — and slots compute
independently (row-independent matmuls, per-slot attention masks), so
the same requests produce the same tokens REGARDLESS of arrival order,
slot assignment, or what else shares the batch (tested:
``tests/test_serving.py::test_arrival_order_determinism``).
"""

import dataclasses
import os
import shutil
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from . import paged_kv as pk
from .. import fault
from ..monitor.histogram import LogHistogram
from ..monitor.ring import RingBuffer
from ..runtime.health import rows_nonfinite, write_forensics
from ..utils.logging import logger, log_dist


# ------------------------------------------------------------ typed results
# terminal outcomes, carried in every result record's "outcome" field
OK = "ok"                 # completed normally (length or eos)
SHED = "shed"             # dropped by the overload policy before serving
DEADLINE = "deadline"     # could not finish by its deadline (at admit or
#                           mid-decode; mid-decode keeps the partial tokens)
POISONED = "poisoned"     # quarantined: drove the decode logits non-finite
TRANSFERRED = "transferred"   # prefill role: handed off to the transfer
#                               queue — the DECODE worker owns the stream
#                               now (docs/serving.md#disaggregation)

OUTCOMES = (OK, SHED, DEADLINE, POISONED, TRANSFERRED)

# token the in-graph sentinel forces into a poisoned slot's sample (the
# value is irrelevant — the scheduler evicts the slot the same step and
# never appends it — it only has to be a valid vocab id)
POISON_SENTINEL_TOKEN = 0


class ServingError(RuntimeError):
    """Base of the serving layer's typed errors."""


class QueueFullError(ServingError):
    """``submit()`` refused: the queue is at its high watermark under
    ``overload: reject`` (callers can distinguish load shedding from a
    malformed request, which raises ``ValueError``)."""


class KVRestoreError(ServingError):
    """A KV snapshot could not be restored into this engine (torn or
    corrupt image, mismatched geometry, no capacity).  Always caught by
    :meth:`ServingEngine.submit_restored`, which degrades the stream to
    the plain recompute queue with a typed ``migration_fallback``
    monitor event — the error type exists so that fallback is a
    decision, never an accident."""


class ServingStalledError(ServingError):
    """The scheduler cannot make progress: requests are queued, zero
    slots are active, and admission seated nothing — or ``run()``
    overran its step bound.  The message carries the blocking request's
    block math."""


class CircuitOpenError(ServingError):
    """The poison circuit breaker tripped: new submissions are rejected
    until the operator investigates (the forensic dump path is in the
    message and on the monitor bus)."""


@dataclasses.dataclass
class SpeculativeConfig:
    """The ``serving.speculative`` block (docs/serving.md#speculative-
    decoding): self-drafting n-gram speculation over the paged decode.

    Per scheduler step the drafter proposes ``k`` tokens per live slot
    (``draft: "ngram"`` — the most recent previous occurrence of the
    slot's tail ``ngram``-gram, falling back to shorter grams then to
    last-token repeat), the fused scan scores current + k drafts in ONE
    decode dispatch, and the per-slot accept length is computed
    in-graph.  Accept/reject is a pure function of the request
    (seed + committed tokens), so outputs are TOKEN-IDENTICAL to plain
    autoregressive decode under any arrival order/co-batching — a
    drafted token is accepted iff it equals the token the model would
    have sampled anyway."""
    k: int = 4                      # drafted tokens per slot per step
    draft: str = "ngram"            # the only drafter (self-drafting)
    ngram: int = 3                  # longest tail gram the drafter matches

    def __post_init__(self):
        assert self.k >= 1, f"speculative.k must be >= 1, got {self.k}"
        assert self.draft == "ngram", \
            f"speculative.draft must be 'ngram', got {self.draft!r}"
        assert self.ngram >= 1, \
            f"speculative.ngram must be >= 1, got {self.ngram}"

    @classmethod
    def from_value(cls, v):
        """None/False → off; True → defaults; dict → the JSON block."""
        if not v:
            return None
        if v is True:
            return cls()
        if isinstance(v, cls):
            return v
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(v) - known
        if unknown:
            raise ValueError(
                f"unknown serving.speculative keys: {sorted(unknown)} "
                f"(known: {sorted(known)})")
        return cls(**v)


# the drafter's search window over each slot's committed history: a
# fixed rule (the LAST `DRAFT_WINDOW` tokens), so drafting stays a pure
# function of the history (replay/replica-deterministic) while the
# per-step host cost stays O(window), not O(generated-so-far)
DRAFT_WINDOW = 1024


def ngram_draft(history, k: int, ngram: int):
    """Self-drafting proposal: the ``k`` tokens that followed the most
    recent PREVIOUS occurrence of the history's tail n-gram (longest
    gram first, shorter grams as fallback; last-token repeat when
    nothing matches).  A pure function of the slot's committed token
    history — the determinism contract's drafter half: replicas,
    journal replays and permuted arrivals all draft identically.

    Greedy decode of a fixed model frequently falls into repeating
    loops, which is exactly this drafter's best case (the classic
    prompt-lookup/self-speculation observation)."""
    h = np.asarray(history, np.int64)
    L = h.size
    out = np.full((k,), int(h[-1]) if L else 0, np.int32)
    if L < 2:
        return out
    for order in range(min(ngram, L - 1), 0, -1):
        tail = h[L - order:]
        # all previous windows of length `order` (the last one, ending
        # at L, IS the tail — excluded)
        n_win = L - order
        win = np.lib.stride_tricks.sliding_window_view(h, order)[:n_win]
        hits = np.nonzero((win == tail).all(axis=1))[0]
        if hits.size == 0:
            continue
        start = int(hits[-1]) + order       # continuation of the match
        cont = h[start:start + k]
        if cont.size == 0:
            continue
        out[:cont.size] = cont
        out[cont.size:] = int(cont[-1])
        return out
    return out


# ------------------------------------------- KV snapshot/migration config
KV_SNAPSHOT_DIR = "kv_snapshots"


def stream_snapshot_dir(journal_dir: str, uid: int) -> str:
    """On-disk home of one stream's committed KV snapshot images —
    beside the request journal, one atomic-checkpoint ``save_dir`` per
    uid (tags inside, newest = deepest decode position), so a router
    reaches a dead replica's snapshots exactly the way it already
    reaches its journal."""
    return os.path.join(journal_dir, KV_SNAPSHOT_DIR, f"uid-{int(uid):08d}")


@dataclasses.dataclass
class KVSnapshotConfig:
    """The ``serving.kv_snapshot`` block (docs/serving.md#kv-migration).

    Off by default.  Arming needs ``journal_dir``: snapshots only make
    sense where a journal already makes the uid durable, and they live
    beside it.  Everything here is host-side — the compiled decode step
    is byte-identical armed vs off (PR-9 discipline, asserted by the
    tier-1 jaxpr-equality test)."""
    every_tokens: int = 32    # per-stream cadence, in emitted tokens
    keep_n: int = 2           # retained images per stream (the
    #                           checkpoint.keep_n mirror; retention's
    #                           terminal half is deletion at finish/close)
    export_on_evict: bool = True  # final image at a DEADLINE eviction —
    #                               the partial work stays restorable
    verify: str = "full"      # manifest level a restore demands:
    #                           full | size | off (per-block digests
    #                           are always checked)

    def __post_init__(self):
        assert self.every_tokens >= 1, \
            f"kv_snapshot.every_tokens must be >= 1, got {self.every_tokens}"
        assert self.keep_n >= 1, \
            f"kv_snapshot.keep_n must be >= 1, got {self.keep_n}"
        assert self.verify in ("full", "size", "off"), \
            f"kv_snapshot.verify must be full|size|off, got {self.verify!r}"

    @classmethod
    def from_value(cls, v):
        """None/False → off; True → defaults; dict → the JSON block."""
        if not v:
            return None
        if v is True:
            return cls()
        if isinstance(v, cls):
            return v
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(v) - known
        if unknown:
            raise ValueError(
                f"unknown serving.kv_snapshot keys: {sorted(unknown)} "
                f"(known: {sorted(known)})")
        return cls(**v)

    def describe(self) -> dict:
        return {"enabled": True, "every_tokens": self.every_tokens,
                "keep_n": self.keep_n,
                "export_on_evict": self.export_on_evict,
                "verify": self.verify,
                "handoff": "restore-first, recompute-fallback",
                "wire_format": "int8+scales block image, per-block sha256"}


def describe_kv_snapshot(value=None) -> dict:
    """Resolved snapshot/migration policy for ``bin/ds_report``."""
    kvs = KVSnapshotConfig.from_value(value)
    if kvs is None:
        return {"enabled": False,
                "defaults_when_armed": KVSnapshotConfig().describe()}
    return kvs.describe()


# ------------------------------------------- prefix sharing config (PR 19)
@dataclasses.dataclass
class PrefixCacheConfig:
    """The ``serving.prefix_cache`` block (docs/serving.md#prefix-
    sharing): block-granular copy-on-write radix cache over the paged
    pool.  Off by default.  Entirely host-side bookkeeping — block
    tables are runtime operands of the compiled decode step, so the
    decode jaxpr is byte-identical armed vs off, and outputs are
    token-identical to the unshared path (the suffix-only prefill
    replays the prompt through the SAME decode executable and samples
    the first token at the same ``fold_in(seed, 0)`` index)."""
    max_blocks: int = 0        # cached-block cap; 0 = evict only under
    #                            pool pressure (admission's retry path)
    min_prefix_blocks: int = 1  # smallest full-block match worth sharing

    def __post_init__(self):
        assert self.max_blocks >= 0, \
            f"prefix_cache.max_blocks must be >= 0, got {self.max_blocks}"
        assert self.min_prefix_blocks >= 1, \
            f"prefix_cache.min_prefix_blocks must be >= 1, " \
            f"got {self.min_prefix_blocks}"

    @classmethod
    def from_value(cls, v):
        """None/False → off; True → defaults; dict → the JSON block."""
        if not v:
            return None
        if v is True:
            return cls()
        if isinstance(v, cls):
            return v
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(v) - known
        if unknown:
            raise ValueError(
                f"unknown serving.prefix_cache keys: {sorted(unknown)} "
                f"(known: {sorted(known)})")
        return cls(**v)

    def describe(self) -> dict:
        return {"enabled": True, "max_blocks": self.max_blocks,
                "min_prefix_blocks": self.min_prefix_blocks,
                "hash": "chained sha256 over int32 token blocks, "
                        "full-content verified (collision -> miss)",
                "cow": "first divergent token (private block clone)",
                "eviction": "LRU over unreferenced leaf entries only",
                "capacity": "admission charges unique blocks "
                            "(analysis/capacity.request_unique_blocks)"}


def describe_prefix_cache(value=None) -> dict:
    """Resolved prefix-sharing policy for ``bin/ds_report``."""
    pc = PrefixCacheConfig.from_value(value)
    if pc is None:
        return {"enabled": False,
                "defaults_when_armed": PrefixCacheConfig().describe()}
    return pc.describe()


@dataclasses.dataclass
class ServingConfig:
    """Knobs for one serving deployment (docs/serving.md has the
    capacity math; JSON surface: the ``serving`` block in
    docs/config-json.md)."""
    batch_slots: int = 8            # fixed decode batch width
    block_size: int = 16            # tokens per KV block
    # pool blocks INCLUDING the reserved scratch block 0; 0 → auto:
    # every slot can hold max_seq tokens (the no-eviction-safe maximum)
    num_blocks: int = 0
    kv_bits: int = 16               # 16 | 8 (int8 payloads + block scales)
    kv_quant_block: int = 64        # quantizer block over the head dim
    max_new_tokens: int = 64        # per-request default
    top_k: Optional[int] = None     # static: part of the compiled step
    eos_token_id: Optional[int] = None
    preflight: bool = True          # memory-gate startup (see preflight())
    hbm_budget_bytes: Optional[int] = None   # None → backend memory_stats
    preflight_safety: float = 0.92  # allocator headroom (bench.py's margin)
    max_queue: int = 4096
    # ---- resilience block (docs/serving.md#resilience) ----
    deadline_ms: Optional[float] = None   # per-request default; None = none
    overload: str = "reject"        # reject | shed_oldest | block
    queue_high_watermark: int = 0   # 0 → max_queue
    queue_low_watermark: int = 0    # 0 → 3/4 of the high watermark
    poison_budget: int = 4          # breaker trips when poisoned count in
    poison_window: int = 64         # the last `poison_window` outcomes
    #                                 EXCEEDS the budget
    journal_dir: Optional[str] = None     # None = journaling off
    forensic_dir: Optional[str] = None    # None → journal_dir or cwd
    drain_timeout_s: float = 60.0   # close()'s drain bound
    # ---- request tracing (docs/monitoring.md#request-tracing) ----
    # fraction of requests that carry a host-side trace (submit →
    # queue-wait → prefill → per-decode-step → finish, emitted as a
    # schema-v2 `trace` event; exportable as Chrome trace-event JSON).
    # Sampling is a pure function of the uid, so replicas/restarts
    # sample the same requests.  0.0 = off; needs an armed monitor.
    trace_sample_rate: float = 0.0
    # ---- speculative decoding (docs/serving.md#speculative-decoding) ----
    # None/false = off; true = defaults; or the JSON block
    # {"k": 4, "draft": "ngram", "ngram": 3}.  Token-identical to plain
    # autoregressive decode (acceptance == "the model would have
    # sampled this token anyway"); per-request acceptance stats ride
    # the monitor bus.
    speculative: Any = None
    # ---- shadow sanitizer (docs/static-analysis.md#sanitizer) ----
    # None → resolve from env DSTPU_SANITIZE / `deepspeed --sanitize`
    # (OFF by default); True/False pin it.  Pure host-side shadow
    # bookkeeping — the compiled decode step is byte-identical armed
    # vs off (--audit-step serving-lifecycle proves it).
    sanitize: Optional[bool] = None
    sanitize_halt: bool = True      # raise at the first finding
    # ---- KV snapshot/migration (docs/serving.md#kv-migration) ----
    # None/false = off; true = defaults; or the JSON block
    # {"every_tokens": 32, "keep_n": 2, "export_on_evict": true,
    # "verify": "full"}.  Needs journal_dir (images live beside the
    # journal); restore-first crash handoff reads them via the router.
    kv_snapshot: Any = None
    # ---- prefix sharing (docs/serving.md#prefix-sharing) ----
    # None/false = off; true = defaults; or the JSON block
    # {"max_blocks": 0, "min_prefix_blocks": 1}.  Copy-on-write radix
    # cache over the paged pool: co-batched and successive requests
    # share the KV blocks of a common prompt prefix, prefill skips
    # every shared block, and admission charges UNIQUE blocks.  Outputs
    # stay token-identical to the unshared path and the compiled decode
    # step is byte-identical on/off.
    prefix_cache: Any = None
    # ---- prefill/decode disaggregation (docs/serving.md#disaggregation) ----
    # "mixed" (default) = the classic engine, byte-identical to a build
    # without roles.  "prefill" runs bucketed prefill only and publishes
    # each stream's paged-KV blocks + seat record on the transfer queue;
    # "decode" admits from the queue via the KVRestoreError-guarded
    # restore path and runs pure fused-scan decode at steady cadence.
    # Either role degrades to mixed per-stream when the queue misbehaves
    # (backpressure, torn image) — never blocks, never drops.
    role: str = "mixed"
    # None/false = off; true = defaults; or the JSON block
    # {"dir": ..., "max_pending": 64, "keep_n": 128, "verify": "full"}.
    # The queue dir defaults to <journal_dir>/kv_transfer.  Armed
    # implicitly by role != "mixed".
    transfer: Any = None

    @classmethod
    def from_dict(cls, d: dict) -> "ServingConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown serving config keys: {sorted(unknown)}"
                             f" (known: {sorted(known)})")
        return cls(**d)


@dataclasses.dataclass
class Request:
    """One generation request.  ``seed`` alone determines the sampling
    stream (see module docstring); ``uid`` is assigned by ``submit``
    when absent."""
    tokens: Any                     # 1-D int32 prompt
    max_new_tokens: Optional[int] = None
    temperature: float = 1.0
    do_sample: bool = False
    seed: int = 0
    uid: Optional[int] = None
    # latency budget from submit time (None → serving.deadline_ms;
    # float("inf") opts OUT of a config default).  A relative budget,
    # not a wall-clock instant: a recovered engine re-arms it at requeue
    # time (monotonic clocks don't survive a restart, and a re-run
    # request deserves a fresh budget).
    deadline_ms: Optional[float] = None


def _mem_analysis(exe) -> Optional[dict]:
    """Shared executable-memory reading (``runtime/compile_cache.py``)
    — one implementation for every preflight gate."""
    from ..runtime.compile_cache import executable_memory_analysis
    return executable_memory_analysis(exe)


class _Slot:
    """Host-side state of one active decode-batch slot."""

    def __init__(self, req: Request, blocks: List[int], prompt_len: int,
                 max_new: int):
        self.req = req
        self.blocks = blocks
        self.prompt_len = prompt_len
        self.max_new = max_new
        self.out_tokens: List[int] = []
        # committed token history (prompt + emitted), maintained
        # incrementally for the speculative drafter — rebuilding
        # prompt+outputs with np.concatenate every scheduler step is
        # O(history) host work per live slot in the hot loop
        self.hist: List[int] = [int(t) for t in np.asarray(req.tokens)]
        # speculative-decode acceptance accounting (per request)
        self.spec_proposed = 0
        self.spec_accepted = 0
        # ---- prefix sharing (docs/serving.md#prefix-sharing) ----
        # pending is None on the plain path; a prefix-hit slot seats
        # with the not-yet-ingested prompt tail here and replays it
        # through the decode step (teacher-forced), so TTFT collapses
        # to the new-suffix cost without a second prefill executable
        self.pending: Optional[List[int]] = None
        self.shared_blocks = 0          # leading blocks borrowed read-only
        self.shared_keys: List[str] = []  # their radix chain (insert parents)
        # restored-from-image KV is wire-precision, not prefill output:
        # never publish it into the prefix cache
        self.wire_kv = False
        # disaggregation: a prefill-role stream the transfer queue
        # refused (backpressure / publish failure) decodes LOCALLY —
        # the per-stream degrade-to-mixed latch
        self.no_transfer = False


class ServingEngine:
    """Continuous-batching scheduler over an :class:`InferenceEngine`.

    Build from a model (``ServingEngine(model=..., params=...)``) or an
    existing engine (``ServingEngine(engine=...)`` — int8 weights, TP
    mesh and the persistent compile cache carry over).  ``config`` is a
    :class:`ServingConfig`, a plain dict (the JSON ``serving`` block),
    or None for defaults.
    """

    def __init__(self, model=None, params=None, engine=None, config=None,
                 mesh=None, compile_cache=None, monitor=None,
                 **engine_kwargs):
        from .engine import InferenceEngine
        self._owns_engine = engine is None
        if engine is None:
            engine = InferenceEngine(model=model, params=params, mesh=mesh,
                                     compile_cache=compile_cache,
                                     **engine_kwargs)
        self.engine = engine
        # unified telemetry (docs/monitoring.md): pass a Monitor, True
        # (env-default run dir), or None -> env DSTPU_MONITOR decides.
        # The serving stats export rides the same bus/schema as training.
        from ..monitor import core as moncore
        if monitor is None:
            monitor = bool(moncore.env_enabled(False))
        self._owns_monitor = not hasattr(monitor, "armed")
        if monitor is True:
            monitor = moncore.Monitor(run_dir=moncore.resolve_run_dir(),
                                      role="serving")
        self.monitor = monitor if monitor else moncore.NullMonitor()
        if config is None:
            config = ServingConfig()
        elif isinstance(config, dict):
            config = ServingConfig.from_dict(config)
        self.config = config
        assert config.kv_bits in (8, 16)
        assert config.batch_slots >= 1 and config.block_size >= 1
        assert config.overload in ("reject", "shed_oldest", "block"), \
            f"serving.overload must be reject|shed_oldest|block, " \
            f"got {config.overload!r}"
        assert 0.0 <= config.trace_sample_rate <= 1.0, \
            f"serving.trace_sample_rate must be in [0, 1], " \
            f"got {config.trace_sample_rate!r}"
        # speculative decoding (docs/serving.md#speculative-decoding):
        # None = plain one-token autoregressive decode
        self.spec = SpeculativeConfig.from_value(config.speculative)

        # quantized-weight routing: the SAME helper InferenceEngine
        # .generate uses (models whose decode consumes int8 leaves
        # directly get raw params; otherwise dequantize once per jitted
        # call) — one implementation, no drift between the paths
        from ..module_inject.module_quantize import resolve_decode_params
        inner, self._deq = resolve_decode_params(engine.module)
        assert getattr(inner, "supports_paged_decode", False), \
            f"{type(inner).__name__} has no paged decode path"
        self.model = inner
        mc = inner.config
        self.max_seq = mc.max_seq
        self.nb_max = pk.blocks_needed(mc.max_seq, config.block_size)
        self.num_blocks = config.num_blocks or (
            1 + config.batch_slots * self.nb_max)
        assert self.num_blocks >= 2, "num_blocks must be >= 2"

        cache_dtype = getattr(inner, "dtype", jnp.bfloat16)
        with jax.set_mesh(engine.mesh):
            self.pool = pk.init_pool(
                mc.n_layer, self.num_blocks, config.block_size, mc.n_head,
                mc.head_dim, cache_dtype, kv_bits=config.kv_bits,
                quant_block=config.kv_quant_block)
        self.allocator = pk.BlockAllocator(self.num_blocks)
        # shadow lifecycle sanitizer (docs/static-analysis.md#sanitizer):
        # OFF by default; config pin wins, else env DSTPU_SANITIZE /
        # `deepspeed --sanitize`.  Pure host-side shadow bookkeeping —
        # one `is not None` test per hook when disarmed, and the
        # compiled decode step is byte-identical armed vs off
        # (--audit-step serving-lifecycle).
        from ..analysis import sanitize as _sanitize
        self._sanitizer = None
        armed = (_sanitize.resolve_enabled(False)
                 if config.sanitize is None else bool(config.sanitize))
        if armed:
            self._sanitizer = _sanitize.ShadowSanitizer(
                self.num_blocks, scratch_block=pk.SCRATCH_BLOCK,
                halt=config.sanitize_halt)
            logger.warning("serving: shadow sanitizer ARMED "
                           "(DSTPU31x lifecycle checks, halt="
                           f"{config.sanitize_halt})")

        # KV snapshot/migration (docs/serving.md#kv-migration): periodic
        # per-stream block images beside the journal, restore-first crash
        # handoff.  Off by default; host-side only.
        self.kvs = KVSnapshotConfig.from_value(config.kv_snapshot)
        if self.kvs is not None and not config.journal_dir:
            raise ValueError(
                "serving.kv_snapshot needs journal_dir: snapshot images "
                "live beside the request journal, and a snapshot without "
                "a durable uid is unrestorable (docs/serving.md#kv-"
                "migration)")
        # restore-path compile warmup fires after the FIRST decode step
        # (see _warm_restore_path for why it cannot run here)
        self._kv_warm_pending = self.kvs is not None

        # prefix sharing (docs/serving.md#prefix-sharing): block-granular
        # COW radix cache over the paged pool.  Host-side bookkeeping
        # only — the decode jaxpr is byte-identical armed vs off
        # (--audit-step decode with the cache armed proves it).
        self.prefix = PrefixCacheConfig.from_value(config.prefix_cache)
        self._prefix_index = None
        if self.prefix is not None:
            self._prefix_index = pk.PrefixIndex(
                self.allocator, max_blocks=self.prefix.max_blocks)
            logger.info("serving: prefix cache ARMED "
                        f"({self.prefix.describe()})")

        # prefill/decode disaggregation (docs/serving.md#disaggregation):
        # role "mixed" is the classic engine — no queue, no publish, the
        # compiled decode step byte-identical to a roleless build.  A
        # role worker needs a queue directory (serving.transfer.dir or
        # <journal_dir>/kv_transfer).  Everything transfer-shaped is
        # host-side file I/O: the step jaxpr never changes.
        from . import transfer as xfer
        self.role = config.role or "mixed"
        if self.role not in xfer.ROLES:
            raise ValueError(
                f"serving.role must be one of {xfer.ROLES}, "
                f"got {config.role!r} (docs/serving.md#disaggregation)")
        self.transfer = xfer.TransferConfig.from_value(config.transfer)
        if self.role != "mixed" and self.transfer is None:
            self.transfer = xfer.TransferConfig()
        self._txq = None
        if self.transfer is not None:
            qdir = self.transfer.dir or (
                xfer.transfer_dir(config.journal_dir)
                if config.journal_dir else None)
            if qdir is None:
                raise ValueError(
                    "serving.role/transfer needs a queue directory: set "
                    "serving.transfer.dir or serving.journal_dir (the "
                    "queue defaults to <journal_dir>/kv_transfer — "
                    "docs/serving.md#disaggregation)")
            self._txq = xfer.TransferQueue(qdir, self.transfer)
            log_dist(
                f"serving: role={self.role} transfer queue at {qdir} "
                f"(max_pending={self.transfer.max_pending} "
                f"keep_n={self.transfer.keep_n})", ranks=[0])
        # transfer accounting (this engine's own publishes/claims; the
        # queue object carries the directory-level totals)
        self._transfers_total = 0
        self._transfer_bytes_total = 0
        self._transfer_backpressure_total = 0
        self._transfer_pub_ms: List[float] = []
        self._transfer_outbox: Dict[int, dict] = {}

        S = config.batch_slots
        self._slots: List[Optional[_Slot]] = [None] * S
        self._snap_last = np.zeros((S,), np.int32)  # ngen at last snapshot
        self._tables = np.zeros((S, self.nb_max), np.int32)
        self._lengths = np.zeros((S,), np.int32)
        self._toks = np.zeros((S,), np.int32)
        self._seeds = np.zeros((S,), np.int32)
        self._ngen = np.zeros((S,), np.int32)
        self._temps = np.ones((S,), np.float32)
        self._flags = np.zeros((S,), bool)

        self.queue: deque = deque()
        # uid → record; completed records stay until the caller
        # pop_result()s them.  The latency aggregates are mergeable
        # log-bucketed histograms (monitor/histogram.py): bounded
        # memory, EXACT counts over the whole run — the bounded deques
        # they replace silently dropped history under sustained traffic,
        # so "p99" was really "p99 of the last 4096 completions"
        # (regression-tested in test_serving.py)
        self.results: Dict[int, dict] = {}
        self._lat_hist = LogHistogram()
        self._ttft_hist = LogHistogram()
        self._step_wall_hist = LogHistogram()   # decode-step wall, ms
        self._completed_total = 0
        self._generated_total = 0
        self._next_uid = 0
        self._steps = 0
        self._decode = None
        self._prefills = {}       # bucket length → CachedStep
        self._blockset = None     # jitted poison/scrub scatter (lazy)
        self._blockcopy = None    # jitted COW block clone (lazy)
        self._preflight_done = False

        # ---- resilience state (docs/serving.md#resilience) ----
        self._outcomes = {k: 0 for k in OUTCOMES}
        self._requeued_total = 0
        # KV migration accounting (docs/serving.md#kv-migration)
        self._kv_snapshots_total = 0
        self._kv_migrated_total = 0
        self._kv_fallback_total = 0
        self._kv_tokens_saved_total = 0
        self._kv_restore_ms: List[float] = []
        # (terminal, bad) totals at the last error_rate emission — the
        # SLO engine's windowed error-rate series (monitor/slo.py)
        self._err_window_last = (0, 0)
        # speculative-decode acceptance accounting (drafted vs accepted
        # draft tokens; the bonus token after a fully-accepted window is
        # free and not counted on either side)
        self._spec_proposed_total = 0
        self._spec_accepted_total = 0
        # prefix-sharing accounting (counted once per SEATED request)
        self._prefix_requests_total = 0
        self._prefix_hits_total = 0
        self._prefix_shared_blocks_total = 0
        self._prefix_cow_total = 0
        self._prefix_evicted_total = 0
        self._breaker_open = False
        self._forensic_path = None
        self._draining = False
        self._closed = False
        self._step_ema_s = None   # measured decode-step wall EMA (the
        self._step_last_s = None  # predictive-deadline denominator; see
        #                           _step_estimate_s for the fast-bias)
        self._spec_rate_ema = None  # emitted tokens/slot/step EMA (spec)
        # bounded ring of recent terminal outcomes: the poison-rate
        # window AND the breaker's forensic payload (PR-9 RingBuffer)
        self._recent = RingBuffer(max(1, int(config.poison_window)))
        # ---- request tracing (docs/monitoring.md#request-tracing) ----
        # host-side only: uid -> open trace record; nothing here touches
        # the compiled step (--audit-step tracing proves jaxpr equality
        # armed vs disarmed).  Disarmed = one boolean check per call.
        self._traces: Dict[int, dict] = {}
        self._traces_emitted = 0
        self._exe_cost_emitted = False
        self.journal = None
        if config.journal_dir:
            from . import journal as jr
            recovered = jr.replay(config.journal_dir)
            self.journal = jr.RequestJournal(config.journal_dir)
            self._recover(recovered)
        log_dist(
            f"ServingEngine ready: slots={S} block_size={config.block_size} "
            f"blocks={self.num_blocks} (nb_max={self.nb_max}) "
            f"kv_bits={config.kv_bits} "
            f"pool={pk.pool_bytes(self.pool) / 1e6:.1f} MB", ranks=[0])

    # ------------------------------------------------------------- recovery
    def _recover(self, state):
        """Fold a replayed journal into this engine: finished records are
        restored into ``results`` (tokens + outcome — a caller polling a
        pre-crash uid still gets its answer), pending requests are
        RE-QUEUED in journal order, and ``_next_uid`` resumes past every
        journaled uid so fresh traffic cannot collide."""
        if state["max_uid"] >= 0:
            self._next_uid = state["max_uid"] + 1
        if state["clean_shutdown"] and not state["pending"]:
            # the previous generation drained clean with nothing left:
            # every journaled uid was answered and handed over, so the
            # history is dead weight — rotate instead of re-materializing
            # every request ever served into results on each restart
            self.journal.rotate()
            log_dist(
                f"serving journal: clean shutdown with nothing pending — "
                f"rotated {self.config.journal_dir}", ranks=[0])
            return
        for uid, rec in state["finished"].items():
            self.results[uid] = {
                "tokens": rec.get("tokens"), "outcome": rec.get("outcome"),
                "t_submit": None, "t_first": None,
                "t_done": rec.get("t", 0.0), "prompt_len": None,
                "deadline": None, "recovered": True}
        for spec in state["pending"]:
            dl_ms = spec.get("deadline_ms")
            if dl_ms == "inf":     # journal spelling of float("inf")
                dl_ms = float("inf")
            req = Request(tokens=np.asarray(spec["tokens"], np.int32),
                          max_new_tokens=spec["max_new_tokens"],
                          temperature=spec.get("temperature", 1.0),
                          do_sample=spec.get("do_sample", False),
                          seed=spec.get("seed", 0), uid=spec["uid"],
                          deadline_ms=dl_ms)
            try:
                self.submit(req, _requeue=True)
            except ValueError as e:
                # the restart may run a SMALLER serving configuration
                # (fewer blocks, shorter max_seq — the elastic-resize
                # workflows): a pending request that no longer fits gets
                # a typed terminal outcome and a journal finish record
                # instead of wedging every restart in __init__ (degrade,
                # never die — recovery must recover the rest)
                logger.warning(
                    f"journal recovery: pending request {req.uid} no "
                    f"longer fits this serving configuration ({e}); "
                    f"finalized as '{SHED}'")
                self.results[req.uid] = {
                    "tokens": None, "outcome": None, "t_submit": None,
                    "t_first": None, "t_done": None,
                    "prompt_len": None, "deadline": None,
                    "recovered": True}
                self._finalize_unseated(
                    req, SHED, "recovery: no longer fits this "
                    "configuration")
                continue
            self.journal.requeue(req.uid)
            self._requeued_total += 1
        if state["pending"]:
            self.journal.flush()
            torn = state.get("torn_lines", 0)
            foreign = state.get("foreign_lines", 0)
            log_dist(
                f"serving journal recovery: re-queued "
                f"{len(state['pending'])} in-flight request(s), restored "
                f"{len(state['finished'])} finished record(s) "
                f"(clean_shutdown={state['clean_shutdown']}"
                + (f", torn_lines={torn}" if torn else "")
                + (f", foreign_lines={foreign}" if foreign else "")
                + f") from {self.config.journal_dir}", ranks=[0])

    # ------------------------------------------------------------- capacity
    def capacity(self) -> dict:
        """The admission math (docs/serving.md): pool size, per-request
        block cost at the default generation length, concurrent-request
        bound."""
        c = self.config
        # the ONE function every capacity owner shares (admission here,
        # ds_mem serving_plan/max_streams, the ledger split) — PR 19
        from ..analysis.capacity import request_unique_blocks
        ub = request_unique_blocks(
            prompt_tokens=c.block_size, max_new_tokens=c.max_new_tokens,
            block_size=c.block_size, max_seq=self.max_seq)
        out = {
            "batch_slots": c.batch_slots,
            "block_size": c.block_size,
            "num_blocks": self.num_blocks,
            "allocatable_blocks": self.num_blocks - 1,
            "capacity_tokens": pk.capacity_tokens(self.pool),
            "pool_bytes": pk.pool_bytes(self.pool),
            "kv_bits": c.kv_bits,
            "blocks_per_request_at_defaults": ub["total_blocks"],
            "free_blocks": self.allocator.free_blocks,
        }
        if self._prefix_index is not None:
            # admission counts UNIQUE blocks when the cache is armed —
            # surface the sharing split next to the classic math
            out["unique_blocks_in_use"] = self.allocator.used_blocks
            out["shared_blocks"] = self.allocator.shared_blocks
            out["logical_blocks"] = self.allocator.logical_blocks
            out["prefix_cached_blocks"] = self._prefix_index.cached_blocks
        return out

    # ------------------------------------------------------------ preflight
    def preflight_memory(self) -> Optional[dict]:
        """Peak-HBM estimate of the serving executables via
        ``memory_analysis()``, BEFORE anything executes — same protocol
        as ``DeepSpeedEngine.preflight_memory``.  Covers the decode step
        (the hot loop; its detail is the flat keys) AND the largest
        prefill bucket — a near-max_seq prompt arriving mid-traffic must
        not be the first time that executable's peak is discovered.
        ``peak_bytes`` is the max of the two.  None when the backend
        exposes no analysis."""
        self._build_decode()
        c = self.config
        bucket = self.nb_max * c.block_size
        pf = self._prefill_fn(bucket)
        toks = jnp.zeros((1, min(bucket, self.max_seq)), jnp.int32)
        blocks = jnp.zeros((bucket // c.block_size,), jnp.int32)
        with jax.set_mesh(self.engine.mesh):
            dec_exe = self._decode.executable(*self._decode_args())
            pre_exe = pf.executable(
                self.engine.params, toks, self.pool, blocks, jnp.int32(1),
                jnp.int32(0), jnp.float32(1.0), jnp.asarray(False))
        dec = _mem_analysis(dec_exe)
        if dec is None:
            return None
        out = dict(dec)
        pre = _mem_analysis(pre_exe)
        if pre is not None:
            out["prefill_max_bucket_peak_bytes"] = pre["peak_bytes"]
            out["peak_bytes"] = max(dec["peak_bytes"], pre["peak_bytes"])
        return out

    def _budget_bytes(self) -> Optional[int]:
        if self.config.hbm_budget_bytes is not None:
            return int(self.config.hbm_budget_bytes)
        from ..monitor.gauges import hbm_limit_bytes
        return hbm_limit_bytes()

    def _preflight_gate(self):
        """Refuse to serve a configuration whose decode step cannot fit
        the chip (admission control's outer gate; the inner gate is the
        per-request block math).  ``_preflight_done`` is only set on a
        PASS — a caller catching the MemoryError and calling ``step()``
        again re-runs the gate (and re-raises) instead of serving the
        configuration the preflight just rejected."""
        if not self.config.preflight:
            self._preflight_done = True
            return
        budget = self._budget_bytes()
        if budget is None:       # no budget, nothing to gate on — and no
            self._preflight_done = True       # point compiling the max-
            return                            # bucket prefill eagerly
        pre = self.preflight_memory()
        if pre is None:
            self._preflight_done = True
            return
        if pre["peak_bytes"] > budget * self.config.preflight_safety:
            # pre-written post-mortem: the ledger + capacity verdict name
            # which subsystem blew the budget and which knob buys
            # headroom (docs/monitoring.md#memory-explainability)
            path = self._memory_forensics(
                f"serving preflight: peak {pre['peak_bytes']} B over "
                f"budget {budget} B", budget_bytes=budget,
                extra={"preflight": pre})
            raise MemoryError(
                f"serving preflight: decode step peak "
                f"{pre['peak_bytes'] / 1e9:.2f} GB exceeds "
                f"{self.config.preflight_safety:.0%} of the "
                f"{budget / 1e9:.2f} GB budget — shrink num_blocks/"
                "batch_slots, use kv_bits=8, or quantize the weights "
                "(docs/serving.md capacity math)"
                + (f"; memory forensics: {path}" if path else ""))
        self._preflight_done = True

    # ------------------------------------------------------------ submission
    def _breaker_gate(self):
        if self._breaker_open:
            raise CircuitOpenError(
                "serving circuit breaker is OPEN (poison rate exceeded "
                f"budget {self.config.poison_budget}); forensics: "
                f"{self._forensic_path}")

    def _watermarks(self):
        # clamped to max_queue: a high watermark beyond it must not
        # silently disable the queue's absolute bound
        high = min(self.config.queue_high_watermark
                   or self.config.max_queue, self.config.max_queue)
        low = self.config.queue_low_watermark or max(1, (high * 3) // 4)
        return high, min(low, high)

    def _apply_overload_policy(self):
        """The queue-admission gate at the high watermark: ``reject``
        raises typed, ``shed_oldest`` sheds queue-head requests down past
        the LOW watermark (hysteresis: one burst of shedding absorbs a
        sustained overload wave instead of per-submit churn), ``block``
        drives the scheduler until the queue drains below the mark."""
        high, low = self._watermarks()
        if len(self.queue) < high:
            return
        pol = self.config.overload
        if pol == "reject":
            raise QueueFullError(
                f"queue full ({len(self.queue)} >= high watermark {high}; "
                "overload=reject) — retry later, raise the watermark, or "
                "use overload=shed_oldest/block (docs/serving.md)")
        if pol == "shed_oldest":
            shed = 0
            while self.queue and len(self.queue) >= low:
                self._finalize_unseated(self.queue.popleft(), SHED,
                                      "overload: shed_oldest watermark")
                shed += 1
            logger.warning(
                f"serving overload: shed {shed} oldest queued request(s) "
                f"(queue hit {high}, drained below {low})")
            return
        # pol == "block": serve until the backlog clears the mark — the
        # scheduler makes progress or raises ServingStalledError itself
        while len(self.queue) >= high:
            self.step()

    def submit(self, req: Request, _requeue: bool = False) -> int:
        """Queue a request; returns its uid.  Rejects prompts whose
        worst-case length cannot fit ``max_seq`` or the pool (ValueError),
        refuses new work while the poison breaker is open
        (:class:`CircuitOpenError`) or a drain is in progress, and applies
        the configured overload policy at the queue's high watermark."""
        self._breaker_gate()
        if self._draining:
            raise ServingError("serving engine is draining: admission "
                               "is stopped")
        toks = np.asarray(req.tokens, np.int32).reshape(-1)
        if toks.size == 0:
            raise ValueError("empty prompt")
        new = (self.config.max_new_tokens if req.max_new_tokens is None
               else int(req.max_new_tokens))
        if new < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {new}")
        total = toks.size + new
        if total > self.max_seq:
            raise ValueError(
                f"prompt {toks.size} + max_new_tokens {new} = {total} "
                f"exceeds max_seq {self.max_seq}")
        nb = pk.blocks_needed(total, self.config.block_size)
        if nb > self.num_blocks - 1:
            raise ValueError(
                f"request needs {nb} blocks; the pool only has "
                f"{self.num_blocks - 1} allocatable")
        if req.uid is not None and req.uid in self.results:
            # validated BEFORE the overload gate: an inadmissible
            # (duplicate-uid) submission must not shed legitimate queued
            # work on its way to a ValueError
            raise ValueError(
                f"uid {req.uid} already submitted — a duplicate would "
                "corrupt that request's result record")
        if not _requeue:
            # recovered requests were admitted once already; only fresh
            # traffic passes the overload gate
            self._apply_overload_policy()
            # overload='block' drove the scheduler, which may have
            # quarantined poison and TRIPPED the breaker mid-call —
            # reject-all must hold for this submission too
            self._breaker_gate()
        # mutate in place: the caller's handle keeps the uid submit
        # assigns and the resolved generation length
        req.tokens = toks
        req.max_new_tokens = new
        if req.uid is None:
            req.uid = self._next_uid
        self._next_uid = max(self._next_uid, req.uid) + 1
        dl_ms = (req.deadline_ms if req.deadline_ms is not None
                 else self.config.deadline_ms)
        if self.journal is not None and not _requeue:
            # durability contract: an ACCEPTED request survives a crash —
            # the submit record (plus any buffered shed finishes from the
            # overload gate above) flushes now, not at the next step, and
            # BEFORE the request enters the queue: if the flush fails
            # (retry exhausted), submit raises with nothing enqueued,
            # so the caller's view ("acceptance failed") stays true
            self.journal.submit(req, deadline_ms=dl_ms)
        now = time.monotonic()
        self.results[req.uid] = {"tokens": None, "outcome": None,
                                 "t_submit": now,
                                 "t_first": None, "t_done": None,
                                 "prompt_len": int(toks.size),
                                 "deadline": (now + dl_ms / 1e3
                                              if dl_ms is not None else None)}
        if self._tracing and self._trace_sampled(req.uid):
            self._trace_open(req.uid, int(toks.size), now)
        self.queue.append(req)
        return req.uid

    def _finalize_unseated(self, req: Request, outcome: str, why: str):
        """Terminal result for a request that never held a slot (overload
        shed / deadline-at-admit / prefill quarantine): typed outcome, no
        tokens."""
        rec = self.results[req.uid]
        rec["tokens"] = None
        rec["outcome"] = outcome
        rec["t_done"] = time.monotonic()
        self._outcomes[outcome] += 1
        self._recent.append({"uid": req.uid, "outcome": outcome,
                             "why": why, "t": time.time()})
        self._trace_finish(req.uid, outcome)
        if self.journal is not None:
            self.journal.finish(req.uid, outcome, None)

    # ------------------------------------------------------- request tracing
    # Host-side only (docs/monitoring.md#request-tracing): every sampled
    # request accumulates spans relative to its submit instant — queue
    # wait, prefill, one span per decode step — and emits ONE schema-v2
    # `trace` event at its terminal outcome.  Nothing here is visible to
    # jit: the compiled decode step is byte-identical armed vs disarmed
    # (--audit-step tracing), and a disarmed engine pays one boolean
    # check per call site.

    @property
    def _tracing(self) -> bool:
        return self.config.trace_sample_rate > 0.0 and self.monitor.armed

    def _trace_sampled(self, uid: int) -> bool:
        """Deterministic sampling: a Knuth multiplicative hash of the
        uid against the rate — a pure function of the request, so a
        journal replay (and every replica of an item-3 router) samples
        the SAME requests, keeping merged trace sets coherent."""
        if self.config.trace_sample_rate >= 1.0:
            return True
        return ((uid * 2654435761) & 0xFFFFFFFF) < (
            self.config.trace_sample_rate * 4294967296.0)

    def _trace_open(self, uid: int, prompt_len: int, m_now: float):
        self._traces[uid] = {"uid": uid, "t0_unix": time.time(),
                             "m0": m_now, "prompt_len": prompt_len,
                             "spans": []}

    def _trace_span(self, uid: int, name: str, start_m: float,
                    dur_s: float, step: Optional[int] = None):
        tr = self._traces.get(uid)
        if tr is None:
            return
        span = {"name": name, "start_ms": (start_m - tr["m0"]) * 1e3,
                "dur_ms": dur_s * 1e3}
        if step is not None:
            span["step"] = step
        tr["spans"].append(span)

    def _trace_finish(self, uid: int, outcome: str, generated: int = 0):
        tr = self._traces.pop(uid, None)
        if tr is None:
            return
        m_now = time.monotonic()
        if not tr["spans"]:
            # never seated (shed / deadline at admit): its whole life
            # was queue wait
            tr["spans"].append({"name": "queue_wait", "start_ms": 0.0,
                                "dur_ms": (m_now - tr["m0"]) * 1e3})
        qw = next((s for s in tr["spans"] if s["name"] == "queue_wait"),
                  None)
        rec = self.results.get(uid) or {}
        ttft = None
        if rec.get("t_first") is not None and rec.get("t_submit") is not None:
            ttft = (rec["t_first"] - rec["t_submit"]) * 1e3
        self.monitor.trace(
            "request", step=self._steps, uid=uid, outcome=outcome,
            t0_unix=tr["t0_unix"], prompt_len=tr["prompt_len"],
            generated=generated,
            queue_wait_ms=(qw["dur_ms"] if qw is not None else None),
            ttft_ms=ttft, total_ms=(m_now - tr["m0"]) * 1e3,
            spans=tr["spans"])
        self._traces_emitted += 1

    # ---------------------------------------------------------- jitted steps
    def _decode_args(self, toks=None):
        """Operands of the armed decode step.  With speculation armed the
        token operand is the (B, k+1) window [current, draft_1..draft_k];
        ``toks=None`` (preflight/audit/pricing callers) sends a window
        whose draft columns repeat the current token — same shapes, same
        program."""
        if toks is None:
            toks = self._toks
            if self.spec is not None:
                toks = np.repeat(self._toks[:, None], self.spec.k + 1,
                                 axis=1)
        return (self.engine.params, self.pool, jnp.asarray(self._tables),
                jnp.asarray(self._lengths), jnp.asarray(toks),
                jnp.asarray(self._seeds), jnp.asarray(self._ngen),
                jnp.asarray(self._temps), jnp.asarray(self._flags))

    def _sample_tokens(self, logits, seeds, ngen, temps, flags):
        """(B, V) fp32 → (B,) int32: per-slot greedy/sampled select with
        the request-deterministic key stream (module docstring)."""
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        lg = logits / jnp.maximum(temps, 1e-6)[:, None]
        if self.config.top_k is not None:
            kth = jax.lax.top_k(lg, self.config.top_k)[0][:, -1:]
            lg = jnp.where(lg < kth, -jnp.inf, lg)
        keys = jax.vmap(lambda s, n: jax.random.fold_in(
            jax.random.PRNGKey(s), n))(seeds, ngen)
        sampled = jax.vmap(
            lambda k, row: jax.random.categorical(k, row))(keys, lg)
        return jnp.where(flags, sampled.astype(jnp.int32), greedy)

    def _build_decode(self):
        if self._decode is not None:
            return
        deq = self._deq

        def step(params, pool, tables, lengths, toks, seeds, ngen, temps,
                 flags):
            logits, pool = self.model.decode_step_paged(
                deq(params), toks, pool, tables, lengths)
            # quarantine sentinel (docs/serving.md#resilience): per-slot
            # non-finite flag computed IN-GRAPH (no host callback — the
            # PR-3 discipline, audited by --audit-step serving-resilience)
            # and the poisoned slot's sample branchlessly forced to a
            # sentinel.  Slots are row-independent, so neighbors' tokens
            # are bit-identical to a run without the poisoned request.
            poisoned = rows_nonfinite(logits)
            nxt = self._sample_tokens(logits, seeds, ngen, temps, flags)
            nxt = jnp.where(poisoned, jnp.int32(POISON_SENTINEL_TOKEN), nxt)
            return nxt, poisoned, pool

        def spec_step(params, pool, tables, lengths, toks_win, seeds, ngen,
                      temps, flags):
            """Speculative scoring step: ONE fused dispatch scores the
            (B, k+1) window [current, drafts...] — window position i's
            logits are what plain decode would see at generation index
            ``ngen + i``, so sampling each position with its own
            ``fold_in(seed, ngen + i)`` key reproduces the plain
            stream EXACTLY.  A draft is accepted iff it equals the
            token position i-1 sampled anyway; the per-slot accept
            length (1 committed token + accepted-draft run + the free
            bonus token) is computed in-graph.  Rejected tails never
            advance ``lengths`` — that host-side non-advance IS the
            rollback (stale K/V above the committed length is masked
            and overwritten when decode reaches those positions)."""
            logits, pool = self.model.decode_step_paged(
                deq(params), toks_win, pool, tables, lengths)  # (B, W, V)
            nonfin = rows_nonfinite(logits)                    # (B, W)
            outs = []
            for i in range(toks_win.shape[1]):
                nxt = self._sample_tokens(logits[:, i], seeds, ngen + i,
                                          temps, flags)
                outs.append(jnp.where(nonfin[:, i],
                                      jnp.int32(POISON_SENTINEL_TOKEN),
                                      nxt))
            out = jnp.stack(outs, axis=1)                      # (B, W)
            match = (toks_win[:, 1:] == out[:, :-1]).astype(jnp.int32)
            accept_len = 1 + jnp.sum(jnp.cumprod(match, axis=1), axis=1)
            return out, accept_len, nonfin, pool

        c = self.config
        spec_tag = f",spec{self.spec.k}" if self.spec is not None else ""
        self._decode = self.engine._wrap_step(
            f"serving.decode[{c.batch_slots}x{self.nb_max}"
            f"x{c.block_size},kv{c.kv_bits},{c.top_k}{spec_tag}]",
            spec_step if self.spec is not None else step,
            donate_argnums=(1,))

    def _prefill_fn(self, bucket: int):
        """Jitted prefill for prompts padded to ``bucket`` tokens: runs
        the model's contiguous cached forward on ONE sequence, scatters
        its K/V into the slot's first blocks, and returns the real last
        token's logits.  One executable per bucket (buckets are
        block-size multiples, so their count is bounded by nb_max).

        The FORWARD runs at ``min(bucket, max_seq)`` tokens — a bucket
        rounded past ``max_seq`` (max_seq not a block multiple) would
        trip ``init_cache``'s position-table guard — and the extracted
        K/V rows zero-pad up to the bucket for the block scatter (pad
        rows sit beyond the slot's length: masked, then overwritten by
        decode writes).  The FIRST generated token samples inside this
        executable (same ``_sample_tokens`` stream as the decode step)
        — an eager per-request sampling tail would sit directly on the
        time-to-first-token metric."""
        fn = self._prefills.get(bucket)
        if fn is not None:
            return fn
        deq = self._deq
        model = self.model
        fwd_len = min(bucket, self.max_seq)

        def prefill(params, toks, pool, blocks, t_real, seed, temp, flag):
            cache = model.init_cache(1, fwd_len)
            logits, cache = model.apply_with_cache(deq(params), toks, cache)
            # both cache layouts expose (L, T, H, hd) at B=1
            if cache["k"].shape[1] == 1:          # legacy (L, B, S, H, hd)
                k, v = cache["k"][:, 0], cache["v"][:, 0]
            else:                                  # seq-major (L, S, B, ...)
                k, v = cache["k"][:, :, 0], cache["v"][:, :, 0]
            if fwd_len < bucket:
                pad = ((0, 0), (0, bucket - fwd_len), (0, 0), (0, 0))
                k, v = jnp.pad(k, pad), jnp.pad(v, pad)
            pool = pk.write_prefill(pool, blocks, k, v)
            row = logits[0, t_real - 1][None]
            # prefill half of the quarantine sentinel: without it, a
            # request whose PREFILL logits are already non-finite would
            # sample a garbage first token — and at max_new_tokens == 1
            # complete typed OK, invisibly to the circuit breaker
            bad = rows_nonfinite(row)[0]
            first = self._sample_tokens(
                row, seed[None],
                jnp.zeros((1,), jnp.int32), temp[None], flag[None])
            first = jnp.where(bad, jnp.int32(POISON_SENTINEL_TOKEN),
                              first[0])
            return first, bad, pool

        fn = self.engine._wrap_step(
            f"serving.prefill[{bucket},kv{self.config.kv_bits}]", prefill,
            donate_argnums=(2,))
        self._prefills[bucket] = fn
        return fn

    # ------------------------------------------------------------- scheduler
    def _admit(self):
        """Move queue-head requests into free slots while capacity lasts
        (strict FIFO: a blocked head waits for blocks rather than being
        overtaken — no starvation).  Deadline enforcement's admit half
        lives here: a head whose deadline already passed, or provably
        cannot be met (remaining budget < max_new · measured step EMA),
        is shed with a typed ``DEADLINE`` result instead of occupying a
        slot it cannot use."""
        if self._draining:
            return
        fault.site("serving.admit")
        c = self.config
        while self.queue:
            req: Request = self.queue[0]
            dl = self.results[req.uid]["deadline"]
            if dl is not None:
                now = time.monotonic()
                est = self._step_estimate_s()
                eta = now + (req.max_new_tokens * est if est else 0.0)
                if now >= dl or eta > dl:
                    self.queue.popleft()
                    self._finalize_unseated(req, DEADLINE,
                                          "deadline unmeetable at admit")
                    continue
            free = [i for i, s in enumerate(self._slots) if s is None]
            if not free:
                return
            new = req.max_new_tokens       # resolved >= 1 by submit()
            share = self._prefix_match(req)
            ns = share["ns"] if share is not None else 0
            # the unified capacity math (analysis/capacity.py): the SAME
            # function ds_mem's serving_plan/--max-streams and the
            # memory ledger use — admission charges UNIQUE blocks only
            from ..analysis.capacity import request_unique_blocks
            ub = request_unique_blocks(
                prompt_tokens=len(req.tokens), max_new_tokens=new,
                block_size=c.block_size,
                shared_prefix_tokens=ns * c.block_size)
            assert ub["shared_blocks"] == ns   # same clamp by construction
            fresh = self._alloc_blocks(ub["unique_blocks"], uid=req.uid)
            if fresh is None:
                return
            if ns:
                # borrow the cached prefix read-only: one refcount per
                # co-tenant on top of the cache's own reference
                self.allocator.incref(share["blocks"])
                blocks = list(share["blocks"]) + fresh
            else:
                blocks = fresh
            self.queue.popleft()
            if self.journal is not None:
                self.journal.admit(req.uid)
            slot = free[0]
            self._prefix_requests_total += (
                1 if self._prefix_index is not None else 0)
            try:
                self._start(slot, req, blocks, new, share=share)
            except Exception:
                # a prefill that dies mid-dispatch (device OOM, a
                # poisoned executable) must not leak the blocks: free
                # them unless _start already seated the slot (the slot
                # owns them then) or already returned them itself (the
                # quarantine-at-prefill path).  The guard is keyed on
                # the FRESH blocks — shared ones stay allocated under
                # the cache's reference either way; free() decrefs our
                # borrow exactly once and reports only truly-released
                # ids to the sanitizer.  InjectedCrash is a
                # BaseException on purpose — a simulated kill skips
                # this cleanup exactly like a real one would.
                s = self._slots[slot]
                if ((s is None or s.blocks is not blocks)
                        and all(self.allocator.is_allocated(b)
                                for b in fresh)):
                    released = self.allocator.free(blocks)
                    if self._sanitizer is not None:
                        self._sanitizer.on_free(released, uid=req.uid)
                raise

    def _prefix_match(self, req: Request) -> Optional[dict]:
        """Clamped radix lookup for one admission.  ``ns`` is capped at
        ``(T-1)//block_size``: the final prompt token (and everything the
        decode step will ever WRITE) must land in a PRIVATE block —
        writing a shared block would corrupt every co-tenant.  Returns
        None on a miss (or when the hit is below ``min_prefix_blocks``
        and there is no same-parent COW donor)."""
        if self._prefix_index is None:
            return None
        if len(self._prefix_index) == 0:
            return None
        c = self.config
        T = int(len(req.tokens))
        limit = (T - 1) // c.block_size
        m = self._prefix_index.match(req.tokens, c.block_size,
                                     limit_blocks=limit)
        ns = len(m["blocks"])
        donor = m["donor"]
        if ns >= self.prefix.min_prefix_blocks:
            return {"ns": ns, "blocks": m["blocks"], "keys": m["keys"],
                    "donor": donor}
        if ns == 0 and donor is not None:
            # root-level COW: no full block matched, but a cached first
            # block shares a leading run of tokens
            return {"ns": 0, "blocks": [], "keys": [], "donor": donor}
        # a sub-threshold chain cannot keep its donor (the donor's copy
        # is only correct ON TOP of the matched chain) — full miss
        return None

    def _alloc_blocks(self, n: int, uid=None) -> Optional[List[int]]:
        """Allocator front-end for admission/restore: on exhaustion,
        evict unreferenced prefix-cache entries (LRU, leaf-first) and
        retry once.  Eviction can never reclaim a block a live stream
        still references — the cache only releases refcount-1 entries."""
        blocks = self.allocator.alloc(n)
        if blocks is None and self._prefix_index is not None:
            shortfall = n - self.allocator.free_blocks
            evicted = self._prefix_index.evict(max(1, shortfall))
            if evicted:
                self._prefix_evicted_total += len(evicted)
                if self._sanitizer is not None:
                    self._sanitizer.on_unshare(evicted)
                    self._sanitizer.on_free(evicted)
                blocks = self.allocator.alloc(n)
        if blocks is not None and self._sanitizer is not None:
            self._sanitizer.on_alloc(blocks, uid=uid)
        return blocks

    def _step_estimate_s(self) -> Optional[float]:
        """PER-TOKEN wall estimate for predictive deadline shedding:
        the step EMA, clamped to the LAST measured step when that was
        faster, divided by the measured tokens-per-step rate when
        speculation is armed (a spec step emits up to k+1 tokens — the
        per-step wall alone would over-shed).  Fast-biased on purpose —
        a compile/deserialize-laden first step must not convince the
        gate that every deadline is hopeless; an underestimate only
        admits a request the per-step deadline check will still evict
        on time, while an overestimate sheds work the server could have
        finished."""
        if self._step_ema_s is None:
            return None
        est = self._step_ema_s
        if self._step_last_s is not None:
            est = min(est, self._step_last_s)
        if self._spec_rate_ema is not None:
            est = est / max(1.0, self._spec_rate_ema)
        return est

    def _start(self, slot: int, req: Request, blocks: List[int], new: int,
               share: Optional[dict] = None):
        fault.site("serving.prefill")
        tr = self._traces.get(req.uid)
        m_admit = time.monotonic() if tr is not None else 0.0
        if tr is not None:
            # queue wait ends the instant this request is seated
            self._trace_span(req.uid, "queue_wait", tr["m0"],
                             m_admit - tr["m0"])
        if share is not None:
            self._start_shared(slot, req, blocks, new, share)
            return
        c = self.config
        T = int(len(req.tokens))
        bucket = pk.blocks_needed(T, c.block_size) * c.block_size
        toks = np.zeros((1, min(bucket, self.max_seq)), np.int32)
        toks[0, :T] = req.tokens
        nb_pre = bucket // c.block_size
        blk = jnp.asarray(np.asarray(blocks[:nb_pre], np.int32))
        fn = self._prefill_fn(bucket)
        with jax.set_mesh(self.engine.mesh):
            with self.monitor.span("prefill"):
                first, bad, self.pool = fn(
                    self.engine.params, jnp.asarray(toks), self.pool, blk,
                    jnp.int32(T), jnp.int32(req.seed),
                    jnp.float32(req.temperature), jnp.asarray(req.do_sample))
        first = int(np.asarray(first))
        if tr is not None:
            # the int() above synced the prefill dispatch: this bracket
            # is a true prefill cost, starting where queue_wait ended
            self._trace_span(req.uid, "prefill", m_admit,
                             time.monotonic() - m_admit)
        if bool(np.asarray(bad)):
            # quarantined AT prefill: the slot is never seated, the
            # sentinel token is never surfaced, and the blocks go back
            # scrubbed (prompt K/V of a poisoned forward may be
            # non-finite too)
            if self._sanitizer is not None:
                self._sanitizer.on_scrub(blocks, uid=req.uid)
            self._set_blocks(blocks, poison=False)
            released = self.allocator.free(blocks)
            if self._sanitizer is not None:
                self._sanitizer.on_free(released, uid=req.uid)
            logger.warning(
                f"serving: request {req.uid} QUARANTINED at prefill — "
                f"non-finite logits; typed '{POISONED}' result "
                f"(docs/serving.md#resilience)")
            self._finalize_unseated(req, POISONED,
                                  "non-finite prefill logits")
            self._check_breaker()
            return

        s = _Slot(req, blocks, T, new)
        s.out_tokens.append(first)
        s.hist.append(first)
        self._slots[slot] = s
        self._tables[slot] = 0
        self._tables[slot, :len(blocks)] = blocks
        if self._sanitizer is not None:
            self._sanitizer.on_attach(req.uid, blocks)
        self._lengths[slot] = T
        self._toks[slot] = first
        self._seeds[slot] = req.seed
        self._ngen[slot] = 1
        self._temps[slot] = req.temperature
        self._flags[slot] = req.do_sample
        rec = self.results[req.uid]
        rec["t_first"] = time.monotonic()
        if new == 1 or first == c.eos_token_id:
            self._finish(slot)
        elif fault.poison_uid(req.uid):
            # logit_nan chaos fault: NaN this request's OWN blocks (an
            # eager host-side pool edit — the compiled step is untouched;
            # the poison rides the data, exactly like real KV corruption).
            # Only a slot that will actually decode is poisoned: a
            # request finishing at prefill frees its blocks above, and
            # they must go back clean.  A chaos-poisoned slot is NOT
            # published below — its NaN'd prompt blocks must never be
            # served to another tenant.
            if self._sanitizer is not None:
                self._sanitizer.on_quarantine(blocks, uid=req.uid)
            self._set_blocks(blocks, poison=True)
        elif self._prefix_index is not None:
            # publish the full PROMPT blocks immediately: decode writes
            # land strictly above the prompt, so these blocks are final
            # — and requests admitted in this SAME wave (co-batched)
            # can already share them, not just successive traffic
            self._prefix_insert(s)

    def _start_shared(self, slot: int, req: Request, blocks: List[int],
                      new: int, share: dict):
        """Seat a prefix-HIT request without running prefill.  The
        shared leading blocks already hold the prompt's K/V; the
        remaining prompt tail is INGESTED through the compiled decode
        step (teacher-forced: each step writes one prompt position's
        K/V and its sample is discarded) until the final prompt token,
        whose sample — at the same ``fold_in(seed, 0)`` index the
        prefill would have used — IS the first generated token.  TTFT
        therefore collapses to the new-suffix cost, and the output
        stream is token-identical to the unshared path."""
        c = self.config
        bs = c.block_size
        T = int(len(req.tokens))
        ns = share["ns"]
        prompt = [int(t) for t in np.asarray(req.tokens)]
        pos0 = ns * bs                  # first position without K/V yet
        donor = share["donor"]
        if donor is not None:
            # copy-on-write: a cached sibling block shares the leading
            # j tokens of our first DIVERGENT block — clone it into our
            # first private block and skip ingesting the copied run.
            # j is clamped so position T-1 is always re-ingested (its
            # decode step produces the first token's logits).
            db, j = donor
            j = min(int(j), T - 1 - pos0)
            if j > 0:
                self._copy_block(db, blocks[ns])
                self._prefix_cow_total += 1
                if self._sanitizer is not None:
                    self._sanitizer.on_cow(db, blocks[ns], uid=req.uid)
                pos0 += j
        s = _Slot(req, blocks, T, new)
        s.pending = prompt[pos0 + 1:]
        s.shared_blocks = ns
        s.shared_keys = list(share["keys"])
        self._slots[slot] = s
        self._tables[slot] = 0
        self._tables[slot, :len(blocks)] = blocks
        if self._sanitizer is not None:
            self._sanitizer.on_attach(req.uid, blocks)
        self._lengths[slot] = pos0
        self._toks[slot] = prompt[pos0]
        self._seeds[slot] = req.seed
        self._ngen[slot] = 0            # no token emitted yet
        self._temps[slot] = req.temperature
        self._flags[slot] = req.do_sample
        self._prefix_hits_total += 1
        self._prefix_shared_blocks_total += ns
        if fault.poison_uid(req.uid):
            # logit_nan chaos: poison only the PRIVATE blocks — the
            # shared prefix has co-tenants (and the cache) reading it
            priv = blocks[ns:]
            if self._sanitizer is not None:
                self._sanitizer.on_quarantine(priv, uid=req.uid)
            self._set_blocks(priv, poison=True)

    def _copy_block(self, src: int, dst: int):
        """Jitted whole-block clone for COW (every layer, K and V and
        the int8 scales).  A separate tiny executable — the decode step
        itself is untouched, so its jaxpr stays byte-identical with the
        cache armed."""
        if self._blockcopy is None:
            def copier(pool, s, d):
                return {k: v.at[:, d].set(v[:, s]) for k, v in pool.items()}
            donate = (0,) if jax.default_backend() != "cpu" else ()
            self._blockcopy = jax.jit(copier, donate_argnums=donate)
        with jax.set_mesh(self.engine.mesh):
            self.pool = self._blockcopy(self.pool, jnp.int32(src),
                                        jnp.int32(dst))

    # ---------------------- KV snapshot/restore (docs/serving.md#kv-migration)
    def _snapshot_slot(self, slot: int) -> str:
        """Export one live slot's KV blocks + stream state as a committed
        snapshot image under ``stream_snapshot_dir(journal_dir, uid)``:
        stage ``image.npz``/``image.json``, manifest, publish rename
        (``checkpoint/atomic.py`` — a torn write is detectable, never
        restorable), then apply ``keep_n`` retention.  Entirely
        host-side: the compiled decode step never sees any of it."""
        from ..checkpoint import atomic
        s = self._slots[slot]
        uid = s.req.uid
        ngen = int(self._ngen[slot])
        sdir = stream_snapshot_dir(self.config.journal_dir, uid)
        with jax.set_mesh(self.engine.mesh):
            image = pk.export_block_image(
                self.pool, s.blocks, quant_block=self.config.kv_quant_block)
        meta = {
            # atomic.py's newest-first ordering key: the decode position
            "global_steps": ngen,
            "stream": {
                "uid": int(uid),
                "prompt": [int(t) for t in np.asarray(s.req.tokens)],
                "out_tokens": [int(t) for t in s.out_tokens],
                "max_new_tokens": int(s.max_new),
                "seed": int(s.req.seed),
                "temperature": float(s.req.temperature),
                "do_sample": bool(s.req.do_sample),
                "num_blocks": len(s.blocks),
                "block_size": int(self.config.block_size),
                "kv_bits": int(self.config.kv_bits),
                # prefix sharing: the image is SELF-CONTAINED (every
                # block exported once, shared or not) — the count is
                # observability, not a restore dependency; the restorer
                # re-establishes sharing against its own LOCAL index
                "shared_blocks": int(s.shared_blocks)}}
        final = pk.save_block_image(sdir, f"snap-{ngen:06d}", image, meta)
        keep = self.kvs.keep_n if self.kvs is not None else 1
        atomic.rotate_checkpoints(sdir, keep, level="size")
        self._snap_last[slot] = ngen
        self._kv_snapshots_total += 1
        return final

    def _snapshot_slot_safe(self, slot: int):
        """Cadence wrapper: a failed snapshot must not take serving down
        — the stream simply stays recompute-only at migration time.  An
        :class:`fault.InjectedCrash` (a simulated kill, e.g. the
        ``kv_snapshot_torn`` site) propagates like the real thing."""
        try:
            self._snapshot_slot(slot)
        except Exception as e:
            logger.warning(
                f"serving: kv snapshot of uid {self._slots[slot].req.uid} "
                f"failed ({e}); stream stays recompute-only")

    def _delete_stream_snapshots(self, uid: int):
        """Retention's terminal half: a finished uid's images are dead
        weight — nothing ever restores a completed stream."""
        if not self.config.journal_dir:
            return
        sdir = stream_snapshot_dir(self.config.journal_dir, uid)
        if os.path.isdir(sdir):
            shutil.rmtree(sdir, ignore_errors=True)

    def _cleanup_snapshot_dirs(self):
        """``close()``'s retention half: drop every stream's images
        except those of still-pending uids (a drain timeout leaves their
        requests journaled in-flight, and a restart or a router handoff
        may still restore them).  Without this, nothing owns snapshot
        retention once the engine is gone."""
        if not self.config.journal_dir:
            return
        root = os.path.join(self.config.journal_dir, KV_SNAPSHOT_DIR)
        if not os.path.isdir(root):
            return
        keep = {int(u) for u, r in self.results.items()
                if r["outcome"] is None}
        for name in os.listdir(root):
            try:
                uid = int(name.split("-", 1)[1])
            except (IndexError, ValueError):
                continue         # not ours; never delete what we don't own
            if uid not in keep:
                shutil.rmtree(os.path.join(root, name), ignore_errors=True)
        try:
            os.rmdir(root)       # only when empty
        except OSError:  # dstpu: disable=DSTPU002 (non-empty root is the signal)
            pass

    def submit_restored(self, req: Request, snapshot_dir: str,
                        seat: Optional[dict] = None) -> dict:
        """Restore-first admission for a migrated stream: journal the
        request durably on THIS engine (its submit record lives on the
        dead replica's journal, not here), then try to seat it directly
        from ``snapshot_dir`` — a committed image of the dead replica's
        KV — so only the post-snapshot suffix re-decodes
        (token-identical: sampling is a pure function of
        ``(seed, token_index)``).  ANY restore defect — torn or corrupt
        image, wrong geometry, no free slot or blocks — degrades loudly
        to the plain recompute queue with a typed ``migration_fallback``
        monitor event.  The uid is never lost (journaled before the
        attempt) and never duplicated (either seated OR queued, never
        both).

        ``seat`` (disaggregation): the transfer queue's seat record —
        carries the prefill worker's claimed generation (the stale-
        handoff guard), first token, and prefix-cache block hashes the
        restore verifies before re-sharing.

        Returns ``{"uid", "restored", "restore_ms", "tokens_saved",
        "reason"}`` (``reason`` set on fallback)."""
        uid = self.submit(req, _requeue=True)
        if self.journal is not None:
            dl = (req.deadline_ms if req.deadline_ms is not None
                  else self.config.deadline_ms)
            self.journal.submit(req, deadline_ms=dl)
        t0 = time.perf_counter()
        reason, saved = None, 0
        try:
            saved = self._restore_stream(req, snapshot_dir, seat=seat)
            restored = True
        except (pk.BlockImageError, KVRestoreError) as e:
            restored, reason = False, str(e)
        ms = (time.perf_counter() - t0) * 1e3
        if restored:
            # submit() queued the request; the restore seated it
            # directly, so unqueue it — seated OR queued, never both
            assert self.queue and self.queue[-1] is req
            self.queue.pop()
            self._kv_migrated_total += 1
            self._kv_tokens_saved_total += saved
            self._kv_restore_ms.append(ms)
        else:
            self._kv_fallback_total += 1
            logger.warning(
                f"serving: KV restore of uid {uid} fell back to recompute "
                f"({reason}) — typed migration_fallback "
                "(docs/serving.md#kv-migration)")
            if self.monitor.armed:
                self.monitor.trace("migration_fallback", step=self._steps,
                                   uid=int(uid), reason=str(reason)[:200])
        if self.journal is not None:
            # informational for replay; the router's poll channel for
            # subprocess replicas (ProcessReplica tails it)
            self.journal.record("restore", uid=int(uid), restored=restored,
                                restore_ms=round(ms, 3), tokens_saved=saved)
            self.journal.flush()
        return {"uid": uid, "restored": restored,
                "restore_ms": round(ms, 3), "tokens_saved": saved,
                "reason": reason}

    def _warm_restore_path(self):
        """Compile-warm the block-image round-trip against the LIVE
        pool, once, right after the first decode step.  The import
        scatter's trace cache keys on the pool's sharding, and the
        first decode step replaces the init-time placement with the
        decode jit's output sharding — an init-time warm is invalidated
        by the very first step.  pad_to pins the scatter to one
        nb_max-wide shape, so this single round-trip covers every
        future restore regardless of stream depth (measured ~130-650 ms
        cold vs ~5 ms warm — latency that otherwise lands inside a
        crash handoff's restore window).  Block 0 is the scratch block,
        garbage by design, so rewriting it with its own (de)quantized
        image is inert."""
        with jax.set_mesh(self.engine.mesh):
            warm = pk.export_block_image(
                self.pool, [pk.SCRATCH_BLOCK],
                quant_block=self.config.kv_quant_block)
            self.pool = pk.import_block_image(
                self.pool, [pk.SCRATCH_BLOCK], warm, pad_to=self.nb_max)

    def _restore_stream(self, req: Request, snapshot_dir: str,
                        seat: Optional[dict] = None) -> int:
        """Seat ``req`` directly from a committed image: verify manifest
        + per-block digests, allocate fresh blocks, scatter the image
        into the pool, and resume decode at the snapshot's exact
        position.  Returns the recompute tokens saved (prompt prefill +
        already-emitted decode steps).  Raises
        :class:`KVRestoreError`/:class:`pk.BlockImageError` on any
        defect — :meth:`submit_restored` owns the fallback.  With a
        transfer ``seat`` the image must be at least as deep as the
        seat's claimed generation and agree on the first sampled token
        (the stale-handoff guard, satellite fix)."""
        # a survivor restores even when it doesn't snapshot itself
        kvs = self.kvs or KVSnapshotConfig()
        image, meta = pk.load_block_image(snapshot_dir, verify=kvs.verify)
        stream = (meta or {}).get("stream")
        if not stream:
            raise KVRestoreError(
                f"snapshot {snapshot_dir} carries no stream metadata")
        if int(stream["uid"]) != int(req.uid):
            raise KVRestoreError(
                f"snapshot is of uid {stream['uid']}, not {req.uid}")
        prompt = np.asarray(stream["prompt"], np.int32)
        if not np.array_equal(prompt, np.asarray(req.tokens, np.int32)):
            raise KVRestoreError(
                "snapshot prompt differs from the request being restored")
        out_tokens = [int(t) for t in stream["out_tokens"]]
        if not out_tokens:
            raise KVRestoreError("snapshot holds no emitted tokens")
        if seat:
            # stale-handoff guard (satellite fix): a transfer image whose
            # generation predates the seat record is an OLDER publish of
            # the same uid (a re-published entry superseded it) — seating
            # it would silently rewind the stream.  Fall back to
            # recompute (typed migration_fallback) instead.
            seat_gen = int(seat.get("gen", 0) or 0)
            if len(out_tokens) < seat_gen:
                raise KVRestoreError(
                    f"stale transfer image: image generation "
                    f"{len(out_tokens)} predates the seat record's "
                    f"gen {seat_gen} (stale-handoff guard)")
            first = seat.get("first_token")
            if first is not None and int(first) != out_tokens[0]:
                raise KVRestoreError(
                    f"transfer image's first token {out_tokens[0]} "
                    f"differs from the seat record's {int(first)} — "
                    f"image and seat are not the same publish")
        if int(stream["block_size"]) != self.config.block_size:
            raise KVRestoreError(
                f"snapshot block_size {stream['block_size']} != pool "
                f"{self.config.block_size}")
        new = int(req.max_new_tokens)
        nb = pk.blocks_needed(prompt.size + new, self.config.block_size)
        if int(stream["num_blocks"]) != nb:
            raise KVRestoreError(
                f"snapshot covers {stream['num_blocks']} block(s); this "
                f"request needs {nb}")
        free = [i for i, sl in enumerate(self._slots) if sl is None]
        if not free:
            raise KVRestoreError("no free slot for restore")
        # prefix sharing across migration: the image is self-contained,
        # but when the SURVIVOR's own radix index already holds the
        # prompt's leading blocks, re-establish sharing instead of
        # importing duplicate copies.  Restore may share every full
        # PROMPT block (decode resumes at >= prompt_len, so its writes
        # can never land in a shared block).  No local match degrades
        # LOUDLY to a full private import — never a torn refcount.
        ns = 0
        shared: List[int] = []
        resident: List[int] = []    # cache-resident prompt blocks the
        #                             import is about to DUPLICATE —
        #                             DSTPU317 evidence; empty on the
        #                             correct incref-and-share path
        if self._prefix_index is not None and len(self._prefix_index):
            m = self._prefix_index.match(prompt, self.config.block_size,
                                         limit_blocks=prompt.size
                                         // self.config.block_size)
            shared, ns = m["blocks"], len(m["blocks"])
            if ns and seat and seat.get("prefix_keys") is not None:
                # the seat's chained block hashes are a pure function of
                # the prompt tokens — the local radix chain MUST agree.
                # A disagreement means the seat (or the index) is
                # corrupt: refuse the share, import privately, and let
                # the sanitizer call the duplication out (DSTPU317).
                want = list(seat["prefix_keys"])[:ns]
                if list(m["keys"]) != want:
                    logger.warning(
                        f"serving: restore of uid {req.uid}: seat "
                        f"record's prefix keys disagree with the local "
                        f"radix chain over {ns} block(s) — refusing the "
                        f"share, importing privately")
                    resident, shared, ns = list(shared), [], 0
            if ns:
                logger.info(
                    f"serving: restore of uid {req.uid} re-established "
                    f"prefix sharing over {ns}/{nb} block(s)")
            else:
                logger.warning(
                    f"serving: restore of uid {req.uid} found no local "
                    f"prefix match — degrading to a full private import "
                    f"({nb} block(s) duplicated)")
        fresh = self._alloc_blocks(nb - ns, uid=req.uid)
        if fresh is None:
            raise KVRestoreError(
                f"allocator cannot serve {nb - ns} block(s) "
                f"({self.allocator.free_blocks} free)")
        if ns:
            self.allocator.incref(shared)
            self._prefix_shared_blocks_total += ns
        blocks = list(shared) + fresh
        slot = free[0]
        try:
            fault.site("serving.crash_during_restore")
            with jax.set_mesh(self.engine.mesh):
                if ns:
                    # import only the private tail of the image; the
                    # shared head's K/V is already resident (per-block
                    # digests still verify — they are per-block)
                    sub = dict(image,
                               k=image["k"][:, ns:], v=image["v"][:, ns:],
                               k_scale=image["k_scale"][:, ns:],
                               v_scale=image["v_scale"][:, ns:],
                               block_sha256=list(image["block_sha256"])[ns:])
                    self.pool = pk.import_block_image(
                        self.pool, fresh, sub, pad_to=self.nb_max)
                else:
                    self.pool = pk.import_block_image(
                        self.pool, blocks, image, pad_to=self.nb_max)
            s = _Slot(req, blocks, int(prompt.size), new)
            s.out_tokens = list(out_tokens)
            s.hist.extend(out_tokens)
            s.shared_blocks = ns
            # wire-precision KV (and a partially image-sourced stream)
            # never publishes into the prefix cache at finish
            s.wire_kv = True
            self._slots[slot] = s
            self._tables[slot] = 0
            self._tables[slot, :len(blocks)] = blocks
            if self._sanitizer is not None:
                self._sanitizer.on_attach(req.uid, blocks)
                # DSTPU317 (satellite fix): a restore that imports a
                # private copy of a prompt block the PrefixIndex already
                # holds is silent pool waste — the shadow sanitizer
                # makes it a lint failure
                self._sanitizer.on_import(fresh, uid=req.uid,
                                          resident=resident)
        except BaseException:
            # UNLIKE _admit's prefill edge, cleanup runs for
            # BaseException here too: a failed restore leaves the
            # SURVIVOR alive — it is the migration that died, not this
            # process — so the blocks must go home or this engine leaks
            # them for its whole remaining life (DSTPU312 at close).  A
            # real kill doesn't care either way: the allocator dies with
            # the process.  free() decrefs the shared borrow and
            # releases the fresh blocks EXACTLY once (guarded on the
            # fresh ids — the cache's own reference keeps shared blocks
            # allocated), so a mid-restore crash can never tear a
            # refcount.
            sl = self._slots[slot]
            if ((sl is None or sl.blocks is not blocks)
                    and all(self.allocator.is_allocated(b)
                            for b in fresh)):
                released = self.allocator.free(blocks)
                if self._sanitizer is not None:
                    self._sanitizer.on_free(released, uid=req.uid)
            raise
        # decode resumes where the snapshot stopped: lengths trails
        # out_tokens by the one token whose KV the NEXT step writes
        # (_start's invariant), and sampling continues at
        # fold_in(seed, ngen) — token-identical to the dead replica's
        # stream by the determinism contract
        self._lengths[slot] = int(prompt.size) + len(out_tokens) - 1
        self._toks[slot] = out_tokens[-1]
        self._seeds[slot] = req.seed
        self._ngen[slot] = len(out_tokens)
        self._temps[slot] = req.temperature
        self._flags[slot] = req.do_sample
        self._snap_last[slot] = len(out_tokens)
        rec = self.results[req.uid]
        rec["t_first"] = time.monotonic()
        if (len(out_tokens) >= new
                or out_tokens[-1] == self.config.eos_token_id):
            # a snapshot taken exactly at the stream's end (an
            # export_on_evict image can be): finish immediately instead
            # of decoding past the budget
            self._finish(slot)
        return int(prompt.size) + len(out_tokens)

    # ---------------------------------------------- prefill/decode handoff
    # (docs/serving.md#disaggregation) — everything below is host-side
    # file I/O over the TransferQueue; the compiled decode step never
    # sees any of it (--audit-step disagg proves jaxpr equality).

    def _seat_record(self, slot: int) -> dict:
        """The handoff's control-plane half: everything the decode
        worker needs to SEAT the stream without recomputing — the
        sampled first token, lengths, the RNG fold position (``gen``:
        sampling resumes at ``fold_in(seed, gen)``), and the prompt's
        chained prefix-block hashes so the decode side re-SHARES
        resident prefixes instead of re-importing them.  ``stream`` is
        the same block the restore path reads from any snapshot — a
        transfer entry IS a restorable image."""
        s = self._slots[slot]
        c = self.config
        dl = s.req.deadline_ms
        if dl is not None and dl == float("inf"):
            dl = "inf"          # the journal's JSON spelling
        return {
            "uid": int(s.req.uid),
            "gen": len(s.out_tokens),
            "first_token": int(s.out_tokens[0]),
            "prompt_len": int(s.prompt_len),
            "max_new_tokens": int(s.max_new),
            "seed": int(s.req.seed),
            "temperature": float(s.req.temperature),
            "do_sample": bool(s.req.do_sample),
            "deadline_ms": dl,
            "block_size": int(c.block_size),
            "kv_bits": int(c.kv_bits),
            "prefix_keys": pk.prefix_block_keys(s.req.tokens,
                                                c.block_size),
            "stream": {
                "uid": int(s.req.uid),
                "prompt": [int(t) for t in np.asarray(s.req.tokens)],
                "out_tokens": [int(t) for t in s.out_tokens],
                "max_new_tokens": int(s.max_new),
                "seed": int(s.req.seed),
                "temperature": float(s.req.temperature),
                "do_sample": bool(s.req.do_sample),
                "num_blocks": len(s.blocks),
                "block_size": int(c.block_size),
                "kv_bits": int(c.kv_bits),
                "shared_blocks": int(s.shared_blocks)}}

    def _publish_slot(self, slot: int) -> dict:
        """Export one prefill-finished slot's KV blocks as a block image,
        commit image + seat record on the transfer queue (one atomic
        publish), journal the handoff, and retire the slot with the
        typed ``TRANSFERRED`` outcome — the decode worker owns the
        stream now.  Raises to :meth:`_publish_transfers` on any
        refusal; the caller degrades the slot to local decode."""
        s = self._slots[slot]
        uid = int(s.req.uid)
        gen = len(s.out_tokens)
        with jax.set_mesh(self.engine.mesh):
            image = pk.export_block_image(
                self.pool, s.blocks, quant_block=self.config.kv_quant_block)
        seat = self._seat_record(slot)
        pub = self._txq.publish(uid, gen, image, seat)
        self._transfers_total += 1
        self._transfer_bytes_total += int(pub["bytes"])
        self._transfer_pub_ms.append(float(pub["publish_ms"]))
        out = {"kind": "transfer", "uid": uid, "entry": pub["entry"],
               "gen": gen, "bytes": int(pub["bytes"]),
               "publish_ms": float(pub["publish_ms"]),
               "seat": {k: v for k, v in seat.items() if k != "stream"}}
        self._transfer_outbox[uid] = out
        if self.monitor.armed:
            # the handoff trace span: per-transfer bytes + publish
            # latency on the bus (docs/monitoring.md)
            self.monitor.trace("kv_transfer", step=self._steps, uid=uid,
                               gen=gen, bytes=out["bytes"],
                               publish_ms=out["publish_ms"],
                               entry=os.path.basename(pub["entry"]))
        if self.journal is not None:
            # the router's poll channel for subprocess replicas
            # (ProcessReplica tails it); flushes eagerly — the seat must
            # be durable before the TRANSFERRED finish retires the uid
            self.journal.transfer(uid, pub["entry"], gen, out["bytes"],
                                  out["publish_ms"], seat=out["seat"])
        self._finish(slot, outcome=TRANSFERRED)
        return out

    def _publish_transfers(self):
        """Prefill role: hand every prefill-finished slot off through the
        transfer queue.  A slot qualifies once its first token is
        sampled (``ngen >= 1``; a prefix-hit slot still ingesting has
        ``ngen == 0`` and publishes a later step) unless it is restored
        wire-KV (a stream seated HERE decodes here) or degrade-latched.
        Any refusal — backpressure, a publish defect, chaos poison —
        degrades that ONE stream to local mixed decode: the prefill
        worker never blocks and never drops.  Returns the number of
        streams handed off (the scheduler's progress evidence)."""
        from . import transfer as xfer
        published = 0
        for i, s in enumerate(self._slots):
            if (s is None or s.wire_kv or s.no_transfer
                    or int(self._ngen[i]) < 1):
                continue
            if fault.poison_uid(s.req.uid):
                # chaos-poisoned prefill output stays LOCAL: the next
                # decode step quarantines it here (typed POISONED) —
                # publishing known-poison would just move the quarantine
                # across the wire
                s.no_transfer = True
                continue
            try:
                self._publish_slot(i)
                published += 1
            except xfer.TransferBackpressureError as e:
                s.no_transfer = True
                self._transfer_backpressure_total += 1
                logger.warning(
                    f"serving: transfer of uid {s.req.uid} hit queue "
                    f"backpressure ({e}); degrading to local decode")
            except Exception as e:
                s.no_transfer = True
                logger.warning(
                    f"serving: transfer publish of uid {s.req.uid} "
                    f"failed ({e}); degrading to local decode")
        return published

    def pop_transfer(self, uid: int) -> Optional[dict]:
        """Take ownership of one published handoff record (``{"entry",
        "seat", "bytes", ...}``) — the router's poll channel for
        in-process replicas."""
        return self._transfer_outbox.pop(int(uid), None)

    def admit_next_transfer(self) -> Optional[dict]:
        """Decode role: exclusively claim the oldest committed queue
        entry and seat it through :meth:`submit_restored` (restore-
        first; ANY defect — torn image, stale seat, no capacity —
        degrades to the plain recompute queue with a typed
        ``migration_fallback``).  Returns ``submit_restored``'s dict
        (or a fallback-shaped one), None when nothing is pending."""
        if self._txq is None:
            return None
        claim = self._txq.claim()
        if claim is None:
            return None
        seat = claim.get("seat") or {}
        stream = seat.get("stream") or {}
        if not stream:
            # unreadable manifest: nothing to rebuild a Request from.
            # Drop the entry — the PRODUCER's journal still holds the
            # uid; zero-loss across the edge is the router's guarantee.
            logger.warning(
                f"serving: claimed transfer entry {claim['tag']} carries "
                f"no stream metadata; dropping it")
            self._txq.done(claim["entry"])
            return {"uid": seat.get("uid"), "restored": False,
                    "restore_ms": 0.0, "tokens_saved": 0,
                    "reason": "claimed entry carries no stream metadata"}
        dl = seat.get("deadline_ms")
        if dl == "inf":
            dl = float("inf")
        req = Request(tokens=np.asarray(stream["prompt"], np.int32),
                      max_new_tokens=int(stream["max_new_tokens"]),
                      temperature=float(stream.get("temperature", 1.0)),
                      do_sample=bool(stream.get("do_sample", False)),
                      seed=int(stream.get("seed", 0)),
                      uid=int(stream["uid"]), deadline_ms=dl)
        try:
            out = self.submit_restored(req, claim["entry"], seat=seat)
        except ValueError as e:
            # duplicate uid (a superseded re-publish of a stream this
            # engine already owns) or a request that no longer fits:
            # the entry is dead weight either way
            logger.warning(
                f"serving: claimed transfer entry {claim['tag']} "
                f"rejected ({e}); dropping it")
            self._txq.done(claim["entry"])
            return {"uid": req.uid, "restored": False, "restore_ms": 0.0,
                    "tokens_saved": 0, "reason": str(e)}
        self._txq.done(claim["entry"])
        return out

    def _admit_transfers(self):
        """Decode role: seat queued handoffs into free slots, one claim
        per free slot per step (admission-bounded, like ``_admit``).  A
        restore fallback lands its request on the recompute queue, which
        this same step's ``_admit`` picks up — degrade-to-mixed."""
        while any(sl is None for sl in self._slots):
            if self.admit_next_transfer() is None:
                return

    def _set_blocks(self, blocks: List[int], poison: bool):
        """Pool edit over a block list, outside the decode step:
        ``poison=True`` NaN-fills the payload (int8 pools NaN the fp32
        scales — the int8 lanes cannot hold a NaN), ``poison=False``
        scrubs back to zeros/unit scales.  Scrubbing matters on eviction:
        a stale non-finite row would leak NaN into the block's NEXT
        tenant through the masked attention tail (0 · NaN = NaN), where
        stale *finite* garbage is harmless.

        Runs as ONE small jitted scatter with the pool donated (the
        decode step's in-place discipline — an eager ``.at[].set`` would
        materialize a full pool copy per quarantine event, transiently
        doubling a production pool's bytes).  The block list pads to
        ``nb_max`` by repeating its first id (duplicate scatter indices
        write the same value), so every request shape shares one
        executable."""
        if self._blockset is None:
            quant = pk.is_quantized_pool(self.pool)

            def setter(pool, blk, val):
                if quant:
                    return dict(pool,
                                k_scale=pool["k_scale"].at[:, blk].set(val),
                                v_scale=pool["v_scale"].at[:, blk].set(val))
                v = val.astype(pool["k"].dtype)
                return dict(pool, k=pool["k"].at[:, blk].set(v),
                            v=pool["v"].at[:, blk].set(v))

            # cpu backend: donation would only warn (PR-4's copy-on-
            # donate note); device backends get the in-place update
            donate = (0,) if jax.default_backend() != "cpu" else ()
            self._blockset = jax.jit(setter, donate_argnums=donate)
        padded = np.full((self.nb_max,), blocks[0], np.int32)
        padded[:len(blocks)] = blocks
        val = jnp.float32(jnp.nan if poison else (1.0 if
                          pk.is_quantized_pool(self.pool) else 0.0))
        with jax.set_mesh(self.engine.mesh):
            self.pool = self._blockset(self.pool, jnp.asarray(padded), val)

    def _finish(self, slot: int, outcome: str = OK):
        s = self._slots[slot]
        if (self.kvs is not None and self.kvs.export_on_evict
                and outcome == DEADLINE and s.out_tokens):
            # on-evict export: a deadline eviction keeps its partial
            # tokens — one final image (while the blocks are still ours)
            # keeps the partial KV restorable too.  Every OTHER terminal
            # outcome deletes the stream's images below: nothing ever
            # restores a completed uid.
            self._snapshot_slot_safe(slot)
        if outcome == POISONED:
            # quarantine eviction: scrub the non-finite rows out of the
            # blocks BEFORE they return to the free list.  Only SOLE-
            # OWNER blocks are scrubbed — a shared prefix block has
            # live co-tenants (or the cache) reading it, and poison can
            # only ever land in private blocks (the decode step writes
            # nothing below the private boundary; attempting the shared
            # scrub anyway is exactly what DSTPU316 catches)
            scrub = [b for b in s.blocks
                     if self.allocator.refcount(b) == 1]
            if self._sanitizer is not None:
                self._sanitizer.on_scrub(scrub, uid=s.req.uid)
            if scrub:
                self._set_blocks(scrub, poison=False)
        elif not s.wire_kv and self._prefix_index is not None:
            # publish this request's fully-WRITTEN prompt+output blocks
            # into the radix cache (the cache takes its own refcount)
            # BEFORE our release below — restored-from-image slots never
            # publish (their KV is wire-precision, not prefill output)
            self._prefix_insert(s)
        if self._sanitizer is not None:
            self._sanitizer.on_detach(s.req.uid)
        # free() decrefs; only ids that actually dropped to zero are
        # RELEASED (cache/co-tenant-held blocks stay live) — the shadow
        # sanitizer must see exactly the released set
        released = self.allocator.free(s.blocks)
        if self._sanitizer is not None:
            self._sanitizer.on_free(released, uid=s.req.uid)
        rec = self.results[s.req.uid]
        rec["tokens"] = list(s.out_tokens)
        rec["outcome"] = outcome
        rec["t_done"] = time.monotonic()
        if self.spec is not None:
            # per-request acceptance stats (docs/serving.md#speculative-
            # decoding); the run totals ride the monitor bus as counters
            rec["spec"] = {"proposed": s.spec_proposed,
                           "accepted": s.spec_accepted}
        self._outcomes[outcome] += 1
        self._recent.append({"uid": s.req.uid, "outcome": outcome,
                             "generated": len(s.out_tokens),
                             "t": time.time()})
        if outcome == OK:
            self._completed_total += 1
        self._generated_total += len(s.out_tokens)
        if outcome in (OK, DEADLINE):
            # admitted-request latency accounting: completions AND
            # deadline evictions (their latency ≈ the deadline — the
            # bound the overload tests assert); queue sheds never ran
            self._lat_hist.add((rec["t_done"] - rec["t_submit"]) * 1e3)
            if rec["t_first"] is not None:
                self._ttft_hist.add(
                    (rec["t_first"] - rec["t_submit"]) * 1e3)
        self._trace_finish(s.req.uid, outcome,
                           generated=len(s.out_tokens))
        if self.journal is not None:
            self.journal.finish(s.req.uid, outcome, rec["tokens"])
        if not (self.kvs is not None and self.kvs.export_on_evict
                and outcome == DEADLINE):
            # eos-evict (and every non-resumable outcome) owns deleting
            # the stream's on-disk images — the retention fix: before
            # this, nothing did
            self._delete_stream_snapshots(s.req.uid)
        self._slots[slot] = None
        self._snap_last[slot] = 0
        self._tables[slot] = 0
        self._lengths[slot] = 0
        self._toks[slot] = 0
        self._seeds[slot] = 0
        self._ngen[slot] = 0
        self._temps[slot] = 1.0
        self._flags[slot] = False

    def _prefix_insert(self, s: _Slot):
        """Publish one finishing request's fully-written KV blocks into
        the radix cache.  Block ``i`` is insertable iff every one of its
        positions has real K/V: the last emitted token's KV is never
        written (the step that would write it never ran), so the
        writable frontier is ``prompt_len + len(out) - 1``.  Leading
        shared blocks dedupe onto their existing entries; a same-content
        race with another tenant's freshly-published block dedupes too
        (our copy simply stays private and is released below)."""
        bs = self.config.block_size
        written = s.prompt_len + len(s.out_tokens) - 1
        toks = s.hist                    # prompt + emitted tokens
        parent = None
        newly: List[int] = []
        for i in range(written // bs):
            b = s.blocks[i]
            held_before = self._prefix_index.holds(b)
            key = self._prefix_index.insert(parent, toks[i * bs:(i + 1) * bs],
                                            b)
            if key is None:              # collision or capped — stop chain
                break
            if not held_before and self._prefix_index.holds(b):
                newly.append(b)
            parent = key
        if newly and self._sanitizer is not None:
            self._sanitizer.on_share(newly, uid=s.req.uid)

    def _evict_poisoned(self, slot: int):
        s = self._slots[slot]
        logger.warning(
            f"serving: request {s.req.uid} QUARANTINED — its decode "
            f"logits went non-finite; evicted with a typed '{POISONED}' "
            f"result, blocks scrubbed and returned "
            f"(docs/serving.md#resilience)")
        self._finish(slot, outcome=POISONED)
        self._check_breaker()

    def _check_breaker(self):
        """Trip to reject-all when the poison count in the recent-outcome
        window EXCEEDS ``poison_budget`` — one bad input is an eviction,
        a stream of them is an attack or a broken model, and the server
        must say so loudly instead of grinding through it."""
        if self._breaker_open:
            return
        recent = list(self._recent)
        poisoned = sum(1 for r in recent if r["outcome"] == POISONED)
        if poisoned <= self.config.poison_budget:
            return
        self._breaker_open = True
        dirpath = (self.config.forensic_dir or self.config.journal_dir
                   or os.getcwd())
        payload = {
            "event": "serving_forensics",
            "reason": f"poison rate: {poisoned} poisoned of "
                      f"{len(recent)} recent outcomes exceeds budget "
                      f"{self.config.poison_budget}",
            "time_unix": time.time(),
            "decode_steps": self._steps,
            "counters": dict(self._outcomes,
                             requeued=self._requeued_total),
            "policy": {"poison_budget": self.config.poison_budget,
                       "poison_window": self.config.poison_window,
                       "overload": self.config.overload,
                       "deadline_ms": self.config.deadline_ms},
            "recent": recent,
        }
        self._forensic_path = write_forensics(
            dirpath, f"serving_forensics_step{self._steps}.json", payload)
        logger.error(
            "serving circuit breaker TRIPPED: rejecting all new "
            f"submissions ({payload['reason']}); forensics: "
            f"{self._forensic_path}")
        mon = self.monitor
        if mon.armed:
            mon.counter("breaker_open", 1, step=self._steps)
            if self._forensic_path is not None:
                mon.artifact("serving_forensics", self._forensic_path,
                             step=self._steps,
                             reason=payload["reason"])
            mon.flush()

    def step(self) -> bool:
        """One scheduler iteration: admit from the queue, ONE fused
        decode dispatch for the whole batch, sample, join/evict (with
        quarantine + deadline enforcement), flush the journal.
        Returns False when there is nothing left to do."""
        if not self._preflight_done:
            self._preflight_gate()
        fault.site("serving.step")
        mon = self.monitor
        mon.begin_step()
        if self._txq is not None and self.role == "decode":
            # BEFORE _admit: a restore fallback re-queues its request,
            # and this same step's admission must pick it up (otherwise
            # the livelock guard below would see a queued request no
            # admission pass ever looked at)
            with mon.span("kv_transfer"):
                self._admit_transfers()
        with mon.span("admit"):
            self._admit()
        published = 0
        if self._txq is not None and self.role == "prefill":
            # AFTER _admit: slots seated by this step's prefill publish
            # immediately — the handoff adds zero decode-step latency
            with mon.span("kv_transfer"):
                published = self._publish_transfers()
        active = [i for i, s in enumerate(self._slots) if s is not None]
        if not active:
            if self.queue and not self._draining and not published:
                # a prefill worker that just PUBLISHED its whole batch
                # made progress — empty slots + a queued backlog is its
                # steady state, not a livelock
                # livelock guard: requests are waiting, EVERY slot is
                # free, and admission still seated nothing — spinning a
                # hot no-op step() forever would hide the bug; raise
                # with the head's block math instead
                self._raise_stalled()
            # idle poll: nothing decoded — discard the bracket instead of
            # emitting spans under a reused step number
            mon.abort_step()
            if self.journal is not None:
                self.journal.flush()
            return bool(self.queue)
        self._build_decode()
        spec = self.spec
        toks_win = None
        if spec is not None:
            # draft k tokens per live slot from its committed history —
            # a pure host-side function of the request (module
            # docstring: determinism survives), proposed as runtime
            # operands so the compiled step never re-specializes
            with mon.span("draft"):
                toks_win = np.repeat(self._toks[:, None], spec.k + 1,
                                     axis=1)
                for i in active:
                    s = self._slots[i]
                    if s.pending:
                        # prompt ingestion (prefix sharing): draft
                        # columns carry the next prompt tokens, teacher-
                        # forced, so one window step writes up to k+1
                        # prompt positions' K/V.  Any remaining columns
                        # keep the repeated current token — they write
                        # junk past the prompt, masked and rewritten
                        # when decode reaches those positions.
                        fill = s.pending[:spec.k]
                        toks_win[i, 1:1 + len(fill)] = fill
                    else:
                        toks_win[i, 1:] = ngram_draft(
                            s.hist[-DRAFT_WINDOW:], spec.k, spec.ngram)
        t0 = time.perf_counter()
        m_step = time.monotonic()      # decode-step span base (tracing)
        with jax.set_mesh(self.engine.mesh):
            with mon.span("dispatch"):
                if spec is not None:
                    out, accept_len, nonfin, self.pool = self._decode(
                        *self._decode_args(toks=toks_win))
                else:
                    nxt, poisoned, self.pool = \
                        self._decode(*self._decode_args())
        if self._kv_warm_pending:
            self._kv_warm_pending = False
            self._warm_restore_path()
        with mon.span("sample_join"):
            if spec is not None:
                out = np.asarray(out)                   # (B, k+1)
                accept_len = np.asarray(accept_len)     # (B,)
                nonfin = np.asarray(nonfin)             # (B, k+1)
            else:
                # plain decode is the W=1 window: one token, always
                # "accepted"
                out = np.asarray(nxt)[:, None]
                nonfin = np.asarray(poisoned)[:, None]
                accept_len = np.ones((out.shape[0],), np.int64)
            # the value read above synced the dispatch: this wall time is
            # a true decode-step cost, the predictive-deadline EMA's input
            dt = time.perf_counter() - t0
            self._step_wall_hist.add(dt * 1e3)
            self._step_last_s = dt
            if self._step_ema_s is None:
                self._step_ema_s = dt
            elif dt < self._step_ema_s:
                # adapt DOWN fast: one compile-heavy outlier step decays
                # in a few iterations instead of poisoning the
                # predictive-deadline gate for a long tail
                self._step_ema_s = 0.5 * self._step_ema_s + 0.5 * dt
            else:
                self._step_ema_s = 0.7 * self._step_ema_s + 0.3 * dt
            self._steps += 1
            c = self.config
            now = time.monotonic()
            emitted_step = 0
            for i in active:
                s = self._slots[i]
                if self._traces:
                    # one span per decode step this request was live in
                    self._trace_span(s.req.uid, "decode", m_step, dt,
                                     step=self._steps)
                if s.pending:
                    # prompt ingestion (prefix sharing): the committed
                    # columns wrote prompt K/V — their samples are
                    # DISCARDED.  Advance stops one token short of the
                    # prompt end: the step where pending is empty has
                    # the final prompt token as its operand, and its
                    # column-0 sample (key fold_in(seed, 0), ngen still
                    # 0) IS the first generated token — the same index
                    # the prefill path samples, so outputs stay token-
                    # identical to the unshared path.
                    W = out.shape[1]
                    rem = len(s.pending)
                    adv = W if rem >= W else rem
                    if nonfin[i, :adv].any():
                        self._evict_poisoned(i)
                        continue
                    self._lengths[i] += adv
                    self._toks[i] = s.pending[adv - 1]
                    del s.pending[:adv]
                    dl = self.results[s.req.uid]["deadline"]
                    if dl is not None and now >= dl:
                        self._finish(i, outcome=DEADLINE)
                    continue
                was_ingest = s.pending is not None   # [] = final step
                if was_ingest:
                    s.pending = None
                a = int(accept_len[i])
                # emission plan: walk the accepted window until poison /
                # eos / max_new truncates it (side-effect-free, so the
                # acceptance booking below lands BEFORE _finish writes
                # the terminal record)
                plan = []
                poisoned_here = False
                finished_here = False
                for j in range(a):
                    if nonfin[i, j]:
                        # poison at this position: the sentinel token is
                        # NOT appended — the record keeps only its
                        # pre-poison tokens, exactly as plain decode
                        # would have at this generation index
                        poisoned_here = True
                        break
                    tok = int(out[i, j])
                    plan.append(tok)
                    if len(s.out_tokens) + len(plan) >= s.max_new \
                            or tok == c.eos_token_id:
                        # finish mid-window: accepted tokens past this
                        # one are discarded (plain decode would have
                        # stopped here; the slot frees either way)
                        finished_here = True
                        break
                emitted = len(plan)
                emitted_step += emitted
                if spec is not None:
                    # acceptance books only drafts that CONTRIBUTED an
                    # emitted token (emitted = 1 committed + used
                    # drafts): a draft the model agreed with but whose
                    # token was truncated at eos/max_new/poison must not
                    # inflate the accept rate the bus/alerting reads
                    used = max(0, emitted - 1)
                    s.spec_proposed += spec.k
                    s.spec_accepted += used
                    self._spec_proposed_total += spec.k
                    self._spec_accepted_total += used
                s.out_tokens.extend(plan)
                s.hist.extend(plan)
                if was_ingest and plan:
                    # first token of a prefix-HIT request: TTFT stamps
                    # here (the plain path stamps it at prefill) — by
                    # construction one decode step after the suffix
                    # finished ingesting, i.e. the new-suffix cost
                    rec = self.results[s.req.uid]
                    if rec["t_first"] is None:
                        rec["t_first"] = now
                if poisoned_here:
                    self._evict_poisoned(i)
                    continue
                if finished_here:
                    self._finish(i)
                    continue
                self._lengths[i] += emitted
                self._ngen[i] += emitted
                self._toks[i] = s.out_tokens[-1]
                dl = self.results[s.req.uid]["deadline"]
                if dl is not None and now >= dl:
                    # mid-decode deadline: evict with the partial tokens
                    # — the slot goes back to work that can still meet
                    # its budget
                    self._finish(i, outcome=DEADLINE)
                    continue
                if (self.kvs is not None
                        and int(self._ngen[i]) - int(self._snap_last[i])
                        >= self.kvs.every_tokens):
                    # periodic per-stream image at the configured token
                    # cadence (docs/serving.md#kv-migration) — host-side
                    # export + atomic commit; the compiled step above
                    # never changes
                    with mon.span("kv_snapshot"):
                        self._snapshot_slot_safe(i)
            if spec is not None and active:
                # tokens-per-step EMA: the predictive deadline gate's
                # per-token denominator under speculation
                rate = max(1.0, emitted_step / len(active))
                self._spec_rate_ema = (
                    rate if self._spec_rate_ema is None
                    else 0.7 * self._spec_rate_ema + 0.3 * rate)
        if self.journal is not None:
            with mon.span("journal"):
                # ONE buffered append per scheduler step (admits +
                # finishes); submits flushed eagerly at submit()
                self.journal.flush()
        self._monitor_finish(len(active), tokens=emitted_step)
        return True

    def _raise_stalled(self):
        c = self.config
        req: Request = self.queue[0]
        nb = pk.blocks_needed(len(req.tokens) + req.max_new_tokens,
                              c.block_size)
        # admission failure: the ledger dump makes the block math a
        # forensic artifact, not just an exception message
        path = self._memory_forensics(
            f"serving admission stalled: head uid {req.uid} needs {nb} "
            f"block(s), allocator has {self.allocator.free_blocks} free")
        raise ServingStalledError(
            f"serving stalled: {len(self.queue)} request(s) queued, zero "
            f"slots active, and admission made no progress — head uid "
            f"{req.uid} needs {nb} block(s) "
            f"(= ceil(({len(req.tokens)} prompt + {req.max_new_tokens} "
            f"new) / block_size {c.block_size})) but the allocator has "
            f"{self.allocator.free_blocks} free of "
            f"{self.num_blocks - 1} allocatable "
            f"({self.allocator.used_blocks} leaked or still held)"
            + (f"; memory forensics: {path}" if path else ""))

    # decode steps between latency-percentile/hist emissions: quantile
    # walks are cheap (O(buckets)) but need not run per generated token
    _PERCENTILES_EVERY = 16

    def _monitor_finish(self, active_slots, tokens=None):
        """Per-decode-step telemetry: the serving stats (previously an
        export-only dict) re-routed through the bus in the one schema.
        Cheap counters ride every emitted step; the percentile gauges
        (a sort over the completion windows) ride a coarser cadence.
        ``tokens``: tokens emitted this step (== active_slots for plain
        decode; up to (k+1)·active under speculation)."""
        mon = self.monitor
        # memory-ledger cadence: the monitor's `memory_interval` when it
        # carries one (config-built monitors; 0 = the documented off
        # switch), else the serving role default.  Independent of
        # monitor.interval thinning: the cadence is the documented one,
        # not the lcm.  Static terms latched — memory_ledger._static_terms.
        mem_every = mon.memory_interval
        if mem_every is None:
            mem_every = self._PERCENTILES_EVERY
        if (mon.armed and mon.bus is not None and mon.bus.sinks
                and mem_every and self._steps % mem_every == 0):
            from ..monitor import memory_ledger as mled
            mled.attribute_serving(self).emit(mon, step=self._steps)
        if not mon.armed:
            mon.end_step(self._steps, name="serving_step")
            return
        # scalars/counters are cheap host reads: pass them even on
        # thinned steps so the monitor's terminal flush (drain/close)
        # lands the run's FINAL state in the stream — `monitor.interval`
        # must not truncate what ds_fleet merges see
        scalars = {"active_slots": active_slots,
                   "queued": len(self.queue),
                   "completed_total": self._completed_total,
                   "generated_total": self._generated_total,
                   "free_blocks": self.allocator.free_blocks}
        # resilience outcomes as counters: the ds_top serving line and
        # any alerting pipeline read shed/deadline/poison pressure from
        # the one event stream (docs/monitoring.md).  The cumulative
        # completion/token totals ride as counters too — counters are
        # what ds_fleet SUMS across replicas (fleet.py), and the fleet's
        # completed count must equal the sum of the replicas' exactly
        counters = {"shed_total": self._outcomes[SHED],
                    "deadline_total": self._outcomes[DEADLINE],
                    "poisoned_total": self._outcomes[POISONED],
                    "requeued_total": self._requeued_total,
                    "breaker_open": int(self._breaker_open),
                    "completed_total": self._completed_total,
                    "generated_total": self._generated_total}
        if (self.kvs is not None or self._kv_migrated_total
                or self._kv_fallback_total):
            # KV migration counters (docs/serving.md#kv-migration):
            # summed fleet-wide by ds_fleet like every other counter
            counters["kv_snapshots_total"] = self._kv_snapshots_total
            counters["migrated_streams_total"] = self._kv_migrated_total
            counters["migration_fallbacks_total"] = self._kv_fallback_total
        gauges = {}
        if self._txq is not None:
            # disaggregation handoff telemetry (docs/serving.md
            # #disaggregation): per-edge bytes/latency plus the queue
            # depth the router's placement reads
            counters["kv_transfers_total"] = self._transfers_total
            counters["transfer_bytes_total"] = self._transfer_bytes_total
            counters["transfer_backpressure_total"] = \
                self._transfer_backpressure_total
            counters["transfer_claimed_total"] = self._txq.claimed_total
            scalars["transfer_queue_depth"] = self._txq.depth()
            if self._transfer_pub_ms:
                gauges["handoff_ms"] = round(
                    sum(self._transfer_pub_ms)
                    / len(self._transfer_pub_ms), 3)
        if self._prefix_index is not None:
            # prefix-sharing pressure (docs/serving.md#prefix-sharing):
            # hit rate of admissions against the radix cache, and the
            # fraction of logical blocks that are physically unique —
            # ds_bench_diff classifies prefix_hit_rate higher-better and
            # unique_block_frac lower-better
            counters["prefix_hits_total"] = self._prefix_hits_total
            counters["prefix_cow_total"] = self._prefix_cow_total
            counters["prefix_evicted_total"] = self._prefix_evicted_total
            gauges["prefix_hit_rate"] = round(
                self._prefix_hits_total
                / max(1, self._prefix_requests_total), 4)
            logical = self.allocator.logical_blocks
            gauges["unique_block_frac"] = round(
                self.allocator.used_blocks / max(1, logical), 4)
            scalars["shared_blocks"] = self.allocator.shared_blocks
            scalars["prefix_cached_blocks"] = \
                self._prefix_index.cached_blocks
        # windowed error rate from the outcome counters (the SLO
        # engine's error-budget series, docs/monitoring.md#slo-tracking):
        # bad/total over the terminal outcomes since the last EMISSION —
        # a cumulative ratio would dilute a fresh burn under a long
        # healthy history.  The baseline advances only on emitted steps:
        # a thinned step's gauge lands at most once (the terminal-flush
        # tail), so advancing the baseline there would silently drop its
        # outcomes from the error budget forever.
        term = sum(self._outcomes.values())
        bad = term - self._outcomes[OK]
        d_term = term - self._err_window_last[0]
        if d_term > 0:
            gauges["error_rate"] = round(
                (bad - self._err_window_last[1]) / d_term, 4)
        if not mon.should_emit(self._steps):
            mon.end_step(self._steps, scalars=scalars, gauges=gauges,
                         counters=counters, name="serving_step")
            return
        if d_term > 0:
            self._err_window_last = (term, bad)
        if self.spec is not None:
            # speculative acceptance on the bus: drafted vs accepted
            # draft tokens (counters merge across replicas/restarts),
            # plus the run accept-rate as a gauge for ds_top/alerting
            counters["spec_proposed_total"] = self._spec_proposed_total
            counters["spec_accepted_total"] = self._spec_accepted_total
            if self._spec_proposed_total:
                gauges["spec_accept_rate"] = round(
                    self._spec_accepted_total / self._spec_proposed_total,
                    4)
        if self._steps % self._PERCENTILES_EVERY == 0:
            st = self.stats()
            if "latency_ms" in st:
                gauges["latency_p50_ms"] = st["latency_ms"]["p50"]
                gauges["latency_p99_ms"] = st["latency_ms"]["p99"]
                gauges["latency_p999_ms"] = st["latency_ms"]["p999"]
            if "ttft_ms" in st:
                gauges["ttft_p50_ms"] = st["ttft_ms"]["p50"]
            # the distributions themselves ride the bus as mergeable
            # schema-v2 hist events: replicas/restarts (and the item-3
            # router) aggregate them exactly (docs/monitoring.md)
            for hname, h in (("latency_ms", self._lat_hist),
                             ("ttft_ms", self._ttft_hist),
                             ("step_wall_ms", self._step_wall_hist)):
                if h:
                    mon.hist(hname, h, step=self._steps, unit="ms")
        self._emit_exe_cost(mon)
        mon.set_rates(tokens_per_step=(
            active_slots if tokens is None else tokens))
        mon.end_step(self._steps, scalars=scalars, gauges=gauges,
                     counters=counters, name="serving_step")

    # --------------------------------------------------- roofline attribution
    def _exe_cost_fields(self) -> Optional[dict]:
        """Price the LIVE decode executable for roofline attribution
        (analysis/roofline.py): XLA cost-analysis FLOPs + bytes
        accessed, the HLO wire census, the chip identity, and the paged
        path's gather-materialization bytes (modeled from the serving
        configuration — the exact traffic the ROADMAP-1 in-place kernel
        deletes).  None until a decode executable is live."""
        import jax as _jax
        from ..analysis.roofline import gather_materialization_bytes
        from ..monitor import gauges as mg
        if self._decode is None:
            return None
        if not getattr(self._decode, "_exes", None):
            # no live executable recorded (compile cache off -> CachedStep
            # passthrough): acquire one, once, exactly like the training
            # engine's pricing path (runtime/engine._monitor_step_stats)
            try:
                with jax.set_mesh(self.engine.mesh):
                    self._decode.executable(*self._decode_args())
            except Exception as e:
                logger.warning(f"serving: could not price the decode step "
                               f"({e}); roofline attribution unavailable")
                return None
        flops = mg.executable_flops(self._decode)
        hbm = mg.executable_bytes_accessed(self._decode)
        wire = mg.executable_wire_report(self._decode)
        mc = self.model.config
        c = self.config
        # impl-aware gather pricing: the kernel path reports 0 (the
        # bytes are GONE, not modeled-and-ignored); only the gather
        # fallback keeps the modeled term.  ds_explain names the impl.
        impl = self.model.paged_attention_impl()
        gather = gather_materialization_bytes(
            n_layer=mc.n_layer, batch_slots=c.batch_slots,
            nb_max=self.nb_max, block_size=c.block_size,
            n_head=mc.n_head, head_dim=mc.head_dim,
            itemsize=(1 if c.kv_bits == 8 else jnp.dtype(
                getattr(self.model, "dtype", jnp.bfloat16)).itemsize),
            paged_impl=impl)
        if not (flops or hbm):
            return None
        # with speculation armed a step emits up to (k+1)·batch_slots
        # tokens: report the MEASURED rate (the ds_explain verdict's
        # per-token view must not understate spec throughput by k+1x)
        tokens_per_step = c.batch_slots
        if self.spec is not None and self._spec_rate_ema is not None:
            tokens_per_step = round(c.batch_slots * self._spec_rate_ema, 1)
        out = {"exe": "serving_step", "flops": flops, "hbm_bytes": hbm,
               "wire_bytes": wire.get("wire_bytes_per_step", 0),
               "gather_bytes": gather, "paged_impl": impl,
               "tokens_per_step": tokens_per_step,
               "device_kind": _jax.devices()[0].device_kind,
               "n_chips": len(_jax.devices())}
        if self.spec is not None:
            out["speculative_k"] = self.spec.k
        return out

    def _emit_exe_cost(self, mon):
        """One `exe_cost` gauge per serving configuration — the
        ds_explain feed; priced once, constant per executable.  The
        attempt latches once a decode executable exists EVEN on a
        pricing failure (same executable → same outcome): a backend
        exposing no cost analysis must not re-run the HLO census — or
        re-try a failing AOT compile — on every monitored step."""
        if self._exe_cost_emitted or self._decode is None:
            return
        self._exe_cost_emitted = True
        fields = self._exe_cost_fields()
        if fields is None:
            return
        mon.gauge("exe_cost", float(fields["flops"]), step=self._steps,
                  **fields)

    def roofline_report(self) -> Optional[dict]:
        """The live engine's own roofline verdict (`ds_explain` without
        the stream round-trip — bench rungs embed this as
        ``extra.roofline``): the decode executable's priced costs
        against the chip table, with the measured step-wall histogram's
        p50 as the wall term.  None before any measured decode step."""
        from ..analysis.roofline import attribute
        fields = self._exe_cost_fields()
        if fields is None or not self._step_wall_hist:
            return None
        return attribute(
            wall_s=self._step_wall_hist.quantile(0.5) / 1e3,
            flops=fields["flops"], hbm_bytes=fields["hbm_bytes"],
            wire_bytes=fields["wire_bytes"],
            gather_bytes=fields["gather_bytes"],
            paged_impl=fields.get("paged_impl"),
            n_chips=fields["n_chips"])

    # ----------------------------------------------------------------- slo
    def slo_report(self) -> Optional[dict]:
        """The live SLO engine's roll-up verdict (``monitor/slo.py``;
        docs/monitoring.md#slo-tracking): per-objective error budgets +
        burn rates over the serving series this engine emits
        (``latency_p99_ms``/``ttft_p50_ms``/``error_rate``/
        ``tokens_per_sec``), plus the regression sentinel's trip count.
        What a bench rung embeds as ``extra.slo`` and the SLO-driven
        autotuner (ROADMAP #5) scores candidates by.  None unless the
        attached monitor carries a ``monitor.slo`` config."""
        return self.monitor.slo_verdict()

    # ------------------------------------------------------------ memory ledger
    def memory_ledger(self) -> dict:
        """One memory-ledger snapshot (``monitor/memory_ledger.py``):
        weights, the paged-KV pool with its in-use block split, decode +
        per-bucket prefill executables, compile-cache disk, measured
        gauges, and the explicit residual.  Host-side reads only — the
        compiled decode step is byte-identical ledger-on vs off
        (``--audit-step mem``)."""
        from ..monitor import memory_ledger as mled
        return mled.attribute_serving(self).snapshot()

    def _memory_forensics(self, reason, budget_bytes=None, extra=None):
        """Ledger + capacity-verdict dump for a memory-shaped failure
        (preflight over budget, admission stall).  Best-effort; returns
        the path or None and never masks the raise it accompanies.
        Needs an explicitly configured ``forensic_dir``/``journal_dir``
        — unlike the breaker (whose dump IS the event record), a
        memory dump must not litter the launch cwd of every
        mis-submitted request."""
        from ..monitor import memory_ledger as mled
        dirpath = self.config.forensic_dir or self.config.journal_dir
        if not dirpath:
            return None
        try:
            path = mled.oom_forensics(
                dirpath, self.memory_ledger(), reason=reason,
                budget_bytes=budget_bytes,
                filename=f"serving_memory_forensics_step"
                         f"{self._steps}.json", extra=extra)
        except Exception as e:
            logger.warning(f"serving memory forensics unavailable ({e})")
            return None
        mon = self.monitor
        if path and mon.armed:
            mon.artifact("memory_forensics", path, step=self._steps,
                         reason=str(reason)[:200])
            mon.flush()
        return path

    def run(self, requests=None, max_steps: int = 10 ** 6) -> Dict[int, dict]:
        """Submit ``requests`` (if given) and drive :meth:`step` until
        the queue drains and every slot completes.  Returns
        ``self.results`` (uid → tokens + stamps + outcome)."""
        for r in requests or ():
            self.submit(r)
        steps = 0
        while self.step():
            steps += 1
            if steps > max_steps:
                raise ServingStalledError(
                    f"serving run exceeded {max_steps} steps with work "
                    f"still pending ({len(self.queue)} queued, "
                    f"{sum(s is not None for s in self._slots)} active)")
        return self.results

    # ----------------------------------------------------------------- drain
    def drain(self, timeout_s: Optional[float] = None) -> dict:
        """Graceful shutdown: stop admission, let the ACTIVE slots finish
        (bounded by ``timeout_s``, default ``serving.drain_timeout_s``),
        and journal a clean-shutdown marker.  Queued-but-unseated
        requests are left journaled as pending — a restarted engine
        re-queues and serves them (:meth:`_recover`); WITHOUT a journal
        no restart will ever serve them, so they finalize as typed
        ``SHED`` results instead of staying in-flight forever.
        Idempotent; :meth:`close` drains first.  Returns a summary
        dict."""
        if timeout_s is None:
            timeout_s = self.config.drain_timeout_s
        self._draining = True
        deadline = time.monotonic() + max(0.0, float(timeout_s))
        timed_out = False
        while any(s is not None for s in self._slots):
            if time.monotonic() >= deadline:
                timed_out = True
                break
            self.step()
        active = sum(s is not None for s in self._slots)
        summary = {"clean": not timed_out, "active": active,
                   "queued": len(self.queue)}
        if timed_out:
            logger.warning(
                f"serving drain timed out after {timeout_s}s with "
                f"{active} slot(s) still active — "
                + ("their requests stay journaled as in-flight (a "
                   "restart re-queues them)" if self.journal is not None
                   else "their requests finalize as typed 'shed' "
                        "results (no journal, no restart)"))
        mon = self.monitor
        if mon.armed:
            # final whole-run distributions: a run shorter than the
            # periodic cadence still leaves mergeable hist events in its
            # stream (what ds_explain / a restart merge reads)
            for hname, h in (("latency_ms", self._lat_hist),
                             ("ttft_ms", self._ttft_hist),
                             ("step_wall_ms", self._step_wall_hist)):
                if h:
                    mon.hist(hname, h, step=self._steps, unit="ms")
            self._emit_exe_cost(mon)
            mon.flush()
        if self.journal is not None:
            self.journal.shutdown(clean=not timed_out,
                                  pending=active + len(self.queue))
        else:
            # no journal = no restart will ever serve the leftovers:
            # give each — queued AND timed-out active — a typed terminal
            # outcome instead of an eternally in-flight record ("every
            # terminal outcome is typed" must hold on the default
            # configuration too; close() frees the pool right after)
            while self.queue:
                self._finalize_unseated(self.queue.popleft(), SHED,
                                        "drain without a journal")
            for i, s in enumerate(self._slots):
                if s is not None:
                    self._finish(i, outcome=SHED)
        log_dist(f"serving drained: {summary}", ranks=[0])
        return summary

    # ------------------------------------------------------------- reporting
    def pop_result(self, uid: int) -> dict:
        """Take ownership of a completed request's record (tokens +
        stamps) and drop it from ``results`` — the drain API a
        long-running server uses so records don't accumulate.  The
        latency aggregates behind :meth:`stats` are kept separately and
        survive the pop.  Raises KeyError for an unknown uid,
        RuntimeError for one still in flight."""
        rec = self.results[uid]
        if rec["t_done"] is None:
            raise RuntimeError(f"request {uid} is still in flight")
        if self._sanitizer is not None:
            self._sanitizer.on_serve(uid)
        return self.results.pop(uid)

    def reset_stats(self):
        """Zero the latency/throughput aggregates, the outcome counters
        and the recent-outcome ring, and drop completed records;
        in-flight requests and the breaker state are untouched (bench
        warmup hygiene — an OPEN breaker must survive a stats reset)."""
        for uid in [u for u, r in self.results.items()
                    if r["t_done"] is not None]:
            del self.results[uid]
        self._lat_hist = LogHistogram()
        self._ttft_hist = LogHistogram()
        self._step_wall_hist = LogHistogram()
        self._completed_total = 0
        self._generated_total = 0
        self._steps = 0
        self._outcomes = {k: 0 for k in OUTCOMES}
        self._requeued_total = 0
        self._err_window_last = (0, 0)
        self._spec_proposed_total = 0
        self._spec_accepted_total = 0
        self._kv_snapshots_total = 0
        self._kv_migrated_total = 0
        self._kv_fallback_total = 0
        self._kv_tokens_saved_total = 0
        self._kv_restore_ms = []
        self._transfers_total = 0
        self._transfer_bytes_total = 0
        self._transfer_backpressure_total = 0
        self._transfer_pub_ms = []
        self._traces_emitted = 0
        # prefix-sharing counters reset; the CACHE itself is kept (warm
        # prefixes are the bench's measured state, not its warmup noise)
        self._prefix_requests_total = 0
        self._prefix_hits_total = 0
        self._prefix_shared_blocks_total = 0
        self._prefix_cow_total = 0
        self._prefix_evicted_total = 0
        self._recent = RingBuffer(max(1, int(self.config.poison_window)))

    def stats(self) -> dict:
        """Latency/throughput summary over completed requests: p50/p99/
        p999 submit→done and submit→first-token (ms), generated tokens.
        Percentiles come from the mergeable log-bucketed histograms
        (monitor/histogram.py) and cover EVERY completion since the last
        :meth:`reset_stats` — exact counts, ≤1% relative value error —
        not a truncated deque window."""
        out = {"completed": self._completed_total,
               "pending": len(self.queue) + sum(
                   s is not None for s in self._slots),
               "decode_steps": self._steps,
               "generated_tokens": self._generated_total,
               "outcomes": dict(self._outcomes),
               "requeued": self._requeued_total,
               "breaker_open": self._breaker_open,
               "traces_emitted": self._traces_emitted}
        if self.spec is not None:
            out["speculative"] = {
                "k": self.spec.k,
                "proposed": self._spec_proposed_total,
                "accepted": self._spec_accepted_total,
                "accept_rate": round(
                    self._spec_accepted_total
                    / max(1, self._spec_proposed_total), 4),
                "tokens_per_step": round(
                    self._generated_total / max(1, self._steps), 2)}
        if self._lat_hist:
            p = self._lat_hist.percentiles()
            out["latency_ms"] = {
                "p50": round(p["p50"], 2), "p99": round(p["p99"], 2),
                "p999": round(p["p999"], 2), "max": round(p["max"], 2)}
        if self._ttft_hist:
            p = self._ttft_hist.percentiles()
            out["ttft_ms"] = {
                "p50": round(p["p50"], 2), "p99": round(p["p99"], 2),
                "p999": round(p["p999"], 2)}
        if self._sanitizer is not None:
            out["sanitizer"] = self._sanitizer.stats()
        if (self.kvs is not None or self._kv_migrated_total
                or self._kv_fallback_total):
            kv = {"snapshots": self._kv_snapshots_total,
                  "migrated_streams": self._kv_migrated_total,
                  "migration_fallbacks": self._kv_fallback_total,
                  "recompute_tokens_saved": self._kv_tokens_saved_total}
            if self._kv_restore_ms:
                kv["restore_ms"] = {
                    "mean": round(sum(self._kv_restore_ms)
                                  / len(self._kv_restore_ms), 3),
                    "max": round(max(self._kv_restore_ms), 3)}
            if self.kvs is not None:
                kv["policy"] = self.kvs.describe()
            out["kv_snapshot"] = kv
        if self._txq is not None:
            tr = dict(self._txq.stats())
            tr["role"] = self.role
            tr["published_by_this_engine"] = self._transfers_total
            tr["published_bytes_by_this_engine"] = \
                self._transfer_bytes_total
            tr["backpressure_degraded"] = \
                self._transfer_backpressure_total
            if self._transfer_pub_ms:
                tr["handoff_ms"] = {
                    "mean": round(sum(self._transfer_pub_ms)
                                  / len(self._transfer_pub_ms), 3),
                    "max": round(max(self._transfer_pub_ms), 3)}
            out["transfer"] = tr
        if self._prefix_index is not None:
            out["prefix_cache"] = {
                "requests": self._prefix_requests_total,
                "requests_hit": self._prefix_hits_total,
                "hit_rate": round(
                    self._prefix_hits_total
                    / max(1, self._prefix_requests_total), 4),
                "shared_blocks_attached": self._prefix_shared_blocks_total,
                "cow_copies": self._prefix_cow_total,
                "evicted_blocks": self._prefix_evicted_total,
                "unique_blocks_in_use": self.allocator.used_blocks,
                "logical_blocks": self.allocator.logical_blocks,
                "index": self._prefix_index.stats(),
                "policy": self.prefix.describe()}
        return out

    def compile_report(self):
        return self.engine.compile_report()

    def close(self):
        """Graceful shutdown: :meth:`drain` (finish active slots, journal
        a clean shutdown), then drop live executables and the pool (bench
        hygiene — the same contract as ``DeepSpeedEngine.close``).  An
        engine the CALLER passed in (``engine=``) stays usable — only an
        internally built one is torn down.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        try:
            # a drain failure (wedged backend, armed crash site) must not
            # leak the pool/executables/journal fd: teardown runs anyway
            self.drain()
            if self._prefix_index is not None:
                # the cache's references are deliberate, not leaks:
                # release them BEFORE the shadow leak check below
                dropped, released = self._prefix_index.clear()
                if self._sanitizer is not None:
                    self._sanitizer.on_unshare(dropped)
                    self._sanitizer.on_free(released)
            if self._sanitizer is not None:
                # after a clean drain every block must be home —
                # anything still allocated is a leak (DSTPU312)
                self._sanitizer.on_close()
            # snapshot retention at teardown: finished uids' images go;
            # journaled still-pending uids keep theirs (a restart or a
            # router handoff may restore them)
            self._cleanup_snapshot_dirs()
        finally:
            try:
                if self.journal is not None:
                    self.journal.close()
            except OSError as e:
                logger.warning(f"serving: journal close failed ({e}); "
                               "continuing teardown")
            for fn in [self._decode] + list(self._prefills.values()):
                if fn is not None and hasattr(fn, "clear"):
                    fn.clear()
            self._decode = None
            self._prefills.clear()
            self._blockset = None
            self._blockcopy = None
            self.pool = None
            if self._owns_monitor:
                self.monitor.close()
            if self._owns_engine:
                self.engine.close()
