"""Prefill/decode transfer queue (docs/serving.md#disaggregation).

The disaggregated serving plane (ROADMAP #2(b)) splits one mixed
``ServingEngine`` into role workers: a PREFILL worker runs bucketed
prefill only and publishes each finished stream's paged-KV blocks as a
PR-18 block image plus a **seat record** (sampled first token, lengths,
RNG fold position, prefix-cache block hashes); a DECODE worker admits
those images through the ``KVRestoreError``-guarded restore path and
runs pure fused-scan decode at steady cadence.  This module is the
**data plane** between them: a directory-based queue of committed block
images with the crash-consistency discipline of ``checkpoint/atomic.py``
— stage, manifest, publish rename — so a torn publish is *detectable,
never claimable*, exactly like a torn checkpoint.

Layout (satellite fix: transfer images get their OWN namespace — the
per-uid ``kv_snapshots/`` tree is cadence-snapshot retention, this is a
queue)::

    <dir>/kv_transfer/
        xfer-<uid:08d>-<gen:06d>/        committed entry (image + manifest)
        xfer-<uid:08d>-<gen:06d>.tmp/    torn publish (never listed)
        claimed/<tag>/                   claimed by a decode worker

Semantics:

- **atomic commit** — ``publish`` stages ``image.npz``/``image.json``
  and commits via manifest + rename (``paged_kv.save_block_image``); a
  reader only ever sees fully-committed entries (``find_valid_tags``).
- **torn-image rejection** — a staged-but-uncommitted entry is
  invisible to ``pending``/``claim``; a committed-but-corrupt one fails
  its per-block sha256 at ``load_block_image`` and the decode side
  degrades to recompute with a typed ``migration_fallback``.
- **LRU bound + backpressure** — at ``max_pending`` committed entries,
  ``publish`` raises :class:`TransferBackpressureError` (the decode
  side lags; the prefill worker degrades that stream to local mixed
  decode — never blocks, never drops).
- **keep_n GC** — ``gc()`` (run on every publish) rotates the oldest
  committed entries beyond ``keep_n`` out (``rotate_checkpoints``), so
  a busy prefill worker whose consumer died cannot grow the directory
  unbounded.  A GC'd entry is NOT a lost request: the uid still lives
  in the router's result table and re-decodes from scratch
  (``migration_fallback``) when its image is gone.
- **exclusive claim** — ``claim`` moves an entry into ``claimed/`` with
  one atomic rename, so two decode workers polling the same queue can
  never double-admit an image.

Everything here is host-side file I/O: the compiled decode step never
sees any of it (the PR-9 contract — jaxpr byte-identical with roles
armed).
"""

import os
import shutil
import time
from dataclasses import dataclass
from typing import Any, List, Optional

from ..checkpoint import atomic
from ..utils.logging import logger
from . import paged_kv as pk

# the transfer namespace under a journal/run dir — a sibling of
# KV_SNAPSHOT_DIR, never mixed with per-uid cadence snapshots
KV_TRANSFER_DIR = "kv_transfer"
CLAIMED_DIR = "claimed"

ROLES = ("mixed", "prefill", "decode")
TRANSFERRED = "transferred"     # terminal outcome on the PREFILL worker


class TransferError(Exception):
    """A transfer-queue defect (bad entry, bad config)."""


class TransferBackpressureError(TransferError):
    """``publish`` refused: the queue is at ``max_pending`` committed
    entries — the decode side lags and the prefill worker must degrade
    (local decode), not block and not drop."""


@dataclass
class TransferConfig:
    """``serving.transfer`` (docs/config-json.md): the transfer-queue
    policy a role-split engine resolves.  ``dir`` defaults to
    ``<journal_dir>/kv_transfer`` when unset."""
    dir: Optional[str] = None     # queue root (overrides journal_dir)
    max_pending: int = 64         # backpressure bound (committed entries)
    keep_n: int = 128             # GC bound (oldest entries rotate out)
    verify: str = "full"          # restore verification: full | manifest

    @classmethod
    def from_value(cls, v: Any) -> Optional["TransferConfig"]:
        if not v:
            return None
        if v is True:
            return cls()
        if isinstance(v, cls):
            return v
        if isinstance(v, dict):
            unknown = set(v) - {f for f in cls.__dataclass_fields__}
            if unknown:
                raise ValueError(
                    f"serving.transfer: unknown key(s) {sorted(unknown)} "
                    f"(docs/config-json.md)")
            return cls(**v)
        raise ValueError(
            f"serving.transfer wants a bool/dict/TransferConfig, "
            f"got {type(v).__name__}")

    def describe(self) -> dict:
        return {"enabled": True, "dir": self.dir,
                "max_pending": int(self.max_pending),
                "keep_n": int(self.keep_n), "verify": self.verify,
                "wire_format": "paged-KV block image "
                               "(int8 + per-block scales, sha256)"}


def describe_transfer(value: Any = None) -> dict:
    """Resolved ``serving.transfer`` policy for ``ds_report`` — off by
    default, with the defaults an armed config would get."""
    cfg = TransferConfig.from_value(value)
    if cfg is None:
        return {"enabled": False,
                "defaults_when_armed": TransferConfig().describe()}
    return cfg.describe()


def transfer_dir(root: str) -> str:
    """The queue namespace under a journal/run dir."""
    return os.path.join(root, KV_TRANSFER_DIR)


def _tag(uid: int, gen: int) -> str:
    return f"xfer-{int(uid):08d}-{int(gen):06d}"


def _tag_uid(tag: str) -> Optional[int]:
    try:
        return int(tag.split("-")[1])
    except (IndexError, ValueError):
        return None


def find_transfer_entry(journal_root: str, uid: int) -> Optional[str]:
    """Newest committed transfer entry for ``uid`` under a replica's
    journal dir, or None — the router's restore-first handoff uses this
    when a prefill worker dies mid-transfer (the committed image
    survives the process; ``find_valid_tags`` skips torn ones)."""
    qdir = transfer_dir(journal_root)
    if not os.path.isdir(qdir):
        return None
    tags = [t for t in atomic.find_valid_tags(qdir) if _tag_uid(t) == int(uid)]
    if not tags:
        return None
    return os.path.join(qdir, sorted(tags)[-1])


class TransferQueue:
    """Directory-based prefill→decode handoff queue (module docstring).

    One instance per role worker, all pointed at the same directory:
    the prefill side ``publish``es, the decode side ``claim``s/``done``s.
    Multi-process safe by construction — commit is a manifest + rename,
    claim is a rename, GC never touches the newest valid entry."""

    def __init__(self, dirpath: str, config: Optional[TransferConfig] = None):
        self.cfg = config or TransferConfig()
        self.dir = dirpath
        os.makedirs(self.dir, exist_ok=True)
        self.published_total = 0
        self.published_bytes_total = 0
        self.backpressure_total = 0
        self.gc_dropped_total = 0
        self.claimed_total = 0

    # ------------------------------------------------------------ producer
    def publish(self, uid: int, gen: int, image: dict, seat: dict) -> dict:
        """Commit one stream's block image + seat record as a queue
        entry.  Raises :class:`TransferBackpressureError` at the
        ``max_pending`` bound BEFORE writing anything.  Returns
        ``{"entry", "tag", "bytes", "publish_ms"}``."""
        depth = len(self.pending())
        if depth >= self.cfg.max_pending:
            self.backpressure_total += 1
            raise TransferBackpressureError(
                f"transfer queue at max_pending={self.cfg.max_pending} "
                f"({depth} committed entr(ies) unclaimed) — decode side "
                f"lags; degrade to local decode")
        t0 = time.perf_counter()
        tag = _tag(uid, gen)
        meta = {
            # atomic.py's newest-first ordering key: publish time in ms,
            # NOT the decode position — entries of different uids must
            # rotate oldest-published-first under keep_n GC (gen values
            # of unrelated streams are not comparable)
            "global_steps": int(time.time() * 1e3),
            "kind": "kv_transfer",
            "seat": dict(seat),
            # the restore path reads the stream block verbatim — a
            # transfer entry IS a restorable snapshot, same wire format
            "stream": dict(seat.get("stream") or {}),
        }
        final = pk.save_block_image(self.dir, tag, image, meta)
        nbytes = _entry_bytes(final)
        self.published_total += 1
        self.published_bytes_total += nbytes
        self.gc()
        return {"entry": final, "tag": tag, "bytes": nbytes,
                "publish_ms": round((time.perf_counter() - t0) * 1e3, 3)}

    def gc(self) -> int:
        """keep_n retention over committed entries (oldest first, the
        newest valid entry always survives — ``rotate_checkpoints``'s
        own guarantee) plus orphaned staging dirs.  Returns the number
        of entries dropped."""
        before = set(atomic.find_valid_tags(self.dir))
        if len(before) > self.cfg.keep_n:
            atomic.rotate_checkpoints(self.dir, self.cfg.keep_n,
                                      level="size")
            after = set(atomic.find_valid_tags(self.dir))
            dropped = len(before) - len(after)
            if dropped > 0:
                self.gc_dropped_total += dropped
                logger.warning(
                    f"transfer queue: GC dropped {dropped} unclaimed "
                    f"entr(ies) beyond keep_n={self.cfg.keep_n} — their "
                    f"streams re-decode from scratch if still wanted "
                    f"(typed migration_fallback)")
            return max(0, dropped)
        return 0

    # ------------------------------------------------------------ consumer
    def pending(self) -> List[str]:
        """Committed, unclaimed entry tags in FIFO (publish) order —
        torn publishes are invisible by construction."""
        tags = atomic.find_valid_tags(self.dir)

        def order(tag):
            try:
                return (os.path.getmtime(
                    os.path.join(self.dir, tag, atomic.MANIFEST_FILE)), tag)
            except OSError:
                return (float("inf"), tag)
        return sorted(tags, key=order)

    def depth(self) -> int:
        return len(self.pending())

    def claim(self, uid: Optional[int] = None) -> Optional[dict]:
        """Exclusively claim the oldest committed entry (or the oldest
        for ``uid``): one atomic rename into ``claimed/`` — two decode
        workers on the same directory can never double-admit.  Returns
        ``{"entry", "tag", "seat"}`` or None when nothing is pending."""
        for tag in self.pending():
            if uid is not None and _tag_uid(tag) != int(uid):
                continue
            src = os.path.join(self.dir, tag)
            dst_root = os.path.join(self.dir, CLAIMED_DIR)
            os.makedirs(dst_root, exist_ok=True)
            dst = os.path.join(dst_root, tag)
            try:
                os.rename(src, dst)
            except OSError:
                continue            # a sibling won the race — next entry
            seat = {}
            try:
                man = atomic.read_manifest(dst)
                seat = dict((man.get("meta") or {}).get("seat") or {})
            except Exception as e:
                logger.warning(
                    f"transfer queue: claimed entry {tag} has an "
                    f"unreadable manifest ({e}); restore will reject it")
            self.claimed_total += 1
            return {"entry": dst, "tag": tag, "seat": seat}
        return None

    def done(self, entry: str):
        """Drop a claimed (or still-queued) entry after its restore
        resolved — restored or fallen back, the image is dead weight."""
        drop_entry(entry)

    # --------------------------------------------------------- observability
    def residency(self) -> dict:
        """Bytes + entry count resident in the queue directory (pending
        AND claimed-but-unresolved) — the ds_mem ledger's queue line.
        Bounded by keep_n, so the walk stays cheap on the hot loop."""
        entries, nbytes = 0, 0
        for root in (self.dir, os.path.join(self.dir, CLAIMED_DIR)):
            if not os.path.isdir(root):
                continue
            for name in os.listdir(root):
                p = os.path.join(root, name)
                if name == CLAIMED_DIR or not os.path.isdir(p):
                    continue
                entries += 1
                nbytes += _entry_bytes(p)
        return {"entries": entries, "bytes": nbytes}

    def stats(self) -> dict:
        return {"published": self.published_total,
                "published_bytes": self.published_bytes_total,
                "backpressure": self.backpressure_total,
                "gc_dropped": self.gc_dropped_total,
                "claimed": self.claimed_total,
                "queue_depth": self.depth(),
                "policy": self.cfg.describe()}


def drop_entry(entry: Optional[str]):
    """Remove one consumed entry directory (restored, fallen back, or
    abandoned) — the router's seating path uses this without holding a
    :class:`TransferQueue` on the publisher's directory."""
    if entry and os.path.isdir(entry):
        shutil.rmtree(entry, ignore_errors=True)


def _entry_bytes(path: str) -> int:
    try:
        names = os.listdir(path)
    except OSError:
        return 0                # entry dropped under us (racing done/GC)
    total = 0
    for name in names:
        try:
            total += os.path.getsize(os.path.join(path, name))
        except OSError:
            continue            # file consumed mid-walk — skip, not fatal
    return total
