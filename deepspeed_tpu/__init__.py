"""deepspeed_tpu — TPU-native training framework with DeepSpeed's capabilities.

API facade parity: reference ``deepspeed/__init__.py`` —
``initialize`` (:51), ``init_inference`` (:221), ``add_config_arguments``
(:205), ``init_distributed``.  Built from scratch on JAX/XLA/Pallas; the
compute path is jitted SPMD over a named device mesh, not a port of the
reference's torch/CUDA machinery.
"""

from .utils import jax_compat as _jax_compat  # must precede runtime imports
from .version import __version__
from .runtime.activation_checkpointing import checkpointing
from .runtime.engine import DeepSpeedEngine
from .runtime.config import DeepSpeedConfig
from .runtime.health import HealthMonitor, TrainingHealthError
from .runtime.lr_schedules import get_lr_scheduler
from .runtime import zero
from .utils.logging import logger, log_dist


def initialize(args=None, model=None, optimizer=None, model_parameters=None,
               training_data=None, lr_scheduler=None, mpu=None,
               dist_init_required=None, collate_fn=None, config=None,
               config_params=None, mesh=None, loss_fn=None, params=None,
               apply_fn=None, rng_seed=0, auto_resume=None, elastic=None,
               monitor=None):
    """Initialize the engine. Returns ``(engine, optimizer, dataloader, lr_scheduler)``.

    Parity: reference ``deepspeed/__init__.py:51-151``.  ``args.deepspeed_config``
    is honored when ``config`` is not given.  If the model is a
    ``PipelineModule``, a ``PipelineEngine`` is built instead
    (reference ``__init__.py:119-143``).

    ``auto_resume=True`` (or config ``checkpoint.auto_resume``, or env
    ``DSTPU_AUTO_RESUME=1`` as set by ``deepspeed --auto-resume``) restarts
    the job from the newest *valid* checkpoint under ``checkpoint.dir`` when
    one exists — the restart path of a preempted TPU job
    (docs/fault-tolerance.md).  A missing or empty checkpoint dir is a
    normal cold start, not an error.

    ``elastic=True`` (or env ``DSTPU_ELASTIC=1`` as set by ``deepspeed
    --elastic``) turns the config's ``elasticity`` block on without editing
    the JSON: the (micro_batch, gas) pair is recomputed from the elastic
    schedule at THIS world size, so a preempted job relaunched on a
    different chip count keeps its global batch and ``auto_resume`` can
    re-partition the checkpoint onto the new mesh (docs/elasticity.md).
    Combined, ``--elastic --auto-resume`` is the full
    preemption-survival path.

    ``monitor=True`` (or env ``DSTPU_MONITOR=1`` as set by ``deepspeed
    --monitor``, or config ``monitor.enabled``) arms the unified runtime
    telemetry bus (``deepspeed_tpu/monitor``; docs/monitoring.md):
    per-step spans, MFU/memory gauges, wire-byte counters and trace
    capture streamed as JSONL for ``python -m deepspeed_tpu.monitor``
    (``ds_top``) to tail.  ``monitor=False`` forces it off against both.
    """
    if config is None and config_params is not None:
        config = config_params
    if config is None and args is not None and \
            getattr(args, "deepspeed_config", None) is not None:
        config = args.deepspeed_config
    assert config is not None, \
        "DeepSpeed requires --deepspeed_config to specify configuration file"

    try:
        from .runtime.pipe.module import PipelineModule
        is_pipe = isinstance(model, PipelineModule)
    except ImportError:
        is_pipe = False
    if is_pipe:
        from .runtime.pipe.engine import PipelineEngine
        engine = PipelineEngine(model=model, optimizer=optimizer, config=config,
                                training_data=training_data,
                                lr_scheduler=lr_scheduler, mesh=mesh,
                                collate_fn=collate_fn, rng_seed=rng_seed,
                                elastic=elastic, monitor=monitor)
    else:
        engine = DeepSpeedEngine(model=model, optimizer=optimizer, config=config,
                                 training_data=training_data,
                                 lr_scheduler=lr_scheduler, mesh=mesh,
                                 collate_fn=collate_fn, loss_fn=loss_fn,
                                 params=params, apply_fn=apply_fn,
                                 rng_seed=rng_seed, mpu=mpu,
                                 dist_init_required=dist_init_required,
                                 elastic=elastic, monitor=monitor)
    _maybe_auto_resume(engine, auto_resume)
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def _maybe_auto_resume(engine, auto_resume):
    """Resolve the auto-resume request (kwarg > env > config) and restart
    from the newest valid checkpoint in ``checkpoint.dir`` if any."""
    import os
    ckpt_cfg = engine.config.checkpoint_config
    if auto_resume is None:
        # precedence: kwarg > env (when set, can also DISABLE) > config
        env = os.environ.get("DSTPU_AUTO_RESUME")
        if env:
            auto_resume = env.lower() in ("1", "true", "yes")
        else:
            auto_resume = ckpt_cfg.auto_resume
    if not auto_resume:
        return
    load_dir = ckpt_cfg.dir
    if not load_dir:
        from .runtime.config import DeepSpeedConfigError
        raise DeepSpeedConfigError(
            "auto_resume needs checkpoint.dir in the config (where to look)")
    from .checkpoint import atomic
    atomic.clean_stale_staging(load_dir,
                               min_age_s=atomic.LOAD_STAGING_MIN_AGE_S)
    # cheap cold-start detection only; tag resolution + manifest
    # verification (and torn-tag fallback) happen inside load_checkpoint
    if not atomic.has_checkpoint(load_dir):
        log_dist(f"auto_resume: no checkpoint in {load_dir}; cold start",
                 ranks=[0])
        return
    path, _ = engine.load_checkpoint(load_dir)
    log_dist(f"auto_resume: restarted from {path}", ranks=[0])


def init_distributed(dist_backend=None, auto_mpi_discovery=True,
                     distributed_port=29500, verbose=True, timeout=None,
                     init_method=None):
    """Multi-host runtime init.

    Parity: reference ``deepspeed/utils/distributed.py:12``.  On TPU pods this
    is ``jax.distributed.initialize()`` (one process per host); single-host it
    is a no-op.  NCCL/MPI rendezvous is replaced by the TPU runtime's own
    coordination service.
    """
    import os
    import jax
    # JAX auto-discovers the coordinator on TPU pods (metadata service), SLURM,
    # and Open MPI; call initialize() whenever any multi-host signal is present.
    multi_host_signals = ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
                          "MEGASCALE_COORDINATOR_ADDRESS", "TPU_WORKER_HOSTNAMES",
                          "TPU_WORKER_ID", "SLURM_JOB_ID", "OMPI_COMM_WORLD_SIZE")
    if any(os.environ.get(k) for k in multi_host_signals):
        try:
            jax.distributed.initialize()
            log_dist(f"jax.distributed initialized: process "
                     f"{jax.process_index()}/{jax.process_count()}", ranks=[0])
        except Exception as e:  # already initialized or effectively single-host
            logger.debug(f"jax.distributed.initialize skipped: {e}")
    return None


def add_config_arguments(parser):
    """Add ``--deepspeed``/``--deepspeed_config`` args.

    Parity: reference ``deepspeed/__init__.py:205``.
    """
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag to indicate use)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to the DeepSpeed JSON config file")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help="Deprecated alias of --deepspeed")
    group.add_argument("--deepscale_config", default=None, type=str,
                       help="Deprecated alias of --deepspeed_config")
    group.add_argument("--local_rank", type=int, default=-1,
                       help="Accepted for launcher compatibility; unused on TPU "
                            "(one process drives all local chips)")
    return parser


def init_inference(model=None, **kwargs):
    """Build an InferenceEngine. Parity: reference ``deepspeed/__init__.py:221``."""
    from .inference.engine import InferenceEngine
    return InferenceEngine(model, **kwargs)
