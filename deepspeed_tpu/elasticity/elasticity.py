"""Batch-size elasticity (v0.1 algorithm).

Parity: reference ``deepspeed/elasticity/elasticity.py:128 _get_compatible_gpus_v01``
and ``:226 compute_elastic_config``.  Pure arithmetic, no accelerator involvement:
choose a global ``train_batch_size`` that remains valid (divisible into
micro_batch × gas × world_size) across many possible world sizes, so a job
restarted with a different chip count keeps the same global batch.

The candidate batch sizes are micro_batch × highly-composite multipliers; among
candidates within ``max_acceptable_batch_size`` we pick the one valid for the
greatest number of world sizes (tie-broken by ``prefer_larger_batch``).
"""

import os
import json

from .config import (ElasticityConfig, ElasticityError, ElasticityConfigError,
                     ElasticityIncompatibleWorldSize)
from . import constants as EC
from ..utils.logging import logger

# Highly composite numbers — many divisors per magnitude, so batch sizes built
# from them divide evenly across many world sizes.
HCN_LIST = [1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840,
            1260, 1680, 2520, 5040, 7560, 10080]


def _get_candidate_batch_sizes(micro_batches, max_acceptable_batch_size):
    """All micro_batch × HCN products within the cap, deduped + sorted."""
    candidates = set()
    for micro in micro_batches:
        for hcn in HCN_LIST:
            if micro * hcn <= max_acceptable_batch_size:
                candidates.add(micro * hcn)
    return sorted(candidates)


def _get_valid_gpus(batch_size, micro_batches, min_valid_gpus, max_valid_gpus):
    """World sizes w for which batch_size == micro * gas * w has an integer solution."""
    valid_gpus = set()
    for micro in micro_batches:
        if batch_size % micro != 0:
            continue
        total_steps = batch_size // micro  # gas * world_size
        for w in range(1, total_steps + 1):
            if total_steps % w == 0 and min_valid_gpus <= w <= max_valid_gpus:
                valid_gpus.add(w)
    return sorted(valid_gpus)


def _get_compatible_gpus_v01(micro_batches, max_acceptable_batch_size,
                             min_gpus=None, max_gpus=None, prefer_larger=True):
    """Pick (final_batch_size, valid_gpus) maximizing the number of valid world sizes.

    Parity: reference ``elasticity/elasticity.py:128``.
    """
    min_gpus = min_gpus or 1
    max_gpus = max_gpus or max_acceptable_batch_size // min(micro_batches)

    if not all(mb <= max_acceptable_batch_size for mb in micro_batches):
        raise ValueError(f"All micro batches must be less than or equal to "
                         f"max_acceptable_batch_size: {max_acceptable_batch_size}")

    final_batch_size = int(min(micro_batches))
    valid_gpus = _get_valid_gpus(final_batch_size, micro_batches, min_gpus, max_gpus)

    for candidate in _get_candidate_batch_sizes(micro_batches, max_acceptable_batch_size):
        candidate_valid = _get_valid_gpus(candidate, micro_batches, min_gpus, max_gpus)
        better = len(candidate_valid) > len(valid_gpus)
        tie = len(candidate_valid) == len(valid_gpus) and len(valid_gpus) > 0
        if better or (tie and ((prefer_larger and candidate > final_batch_size) or
                               (not prefer_larger and candidate < final_batch_size))):
            final_batch_size = candidate
            valid_gpus = candidate_valid

    return final_batch_size, valid_gpus


def _compatible_ds_version_check(target_deepspeed_version):
    # All versions of this framework support elasticity v0.1.
    return True


def elasticity_enabled(ds_config):
    if EC.ELASTICITY not in ds_config:
        return False
    return ds_config[EC.ELASTICITY].get(EC.ENABLED, EC.ENABLED_DEFAULT)


def ensure_immutable_elastic_config(runtime_elastic_config_dict):
    """Assert the elastic config hasn't changed across restarts.

    Parity: reference ``elasticity.py:193``.  The scheduler records the config
    in the DEEPSPEED_ELASTICITY_CONFIG env var; later runs must match it.
    """
    if EC.DEEPSPEED_ELASTICITY_CONFIG in os.environ:
        scheduler_elastic_config_dict = json.loads(os.environ[EC.DEEPSPEED_ELASTICITY_CONFIG])
        scheduler_elastic_config = ElasticityConfig(scheduler_elastic_config_dict)
        runtime_elastic_config = ElasticityConfig(runtime_elastic_config_dict)
        err_str = ("Elastic config '{}={}' seen by scheduler does not match config "
                   "passed in at runtime '{}={}'")
        if runtime_elastic_config.max_acceptable_batch_size != \
                scheduler_elastic_config.max_acceptable_batch_size:
            raise ElasticityConfigError(
                err_str.format("max_acceptable_batch_size",
                               scheduler_elastic_config.max_acceptable_batch_size,
                               "max_acceptable_batch_size",
                               runtime_elastic_config.max_acceptable_batch_size))
        if runtime_elastic_config.micro_batches != scheduler_elastic_config.micro_batches:
            raise ElasticityConfigError(
                err_str.format("micro_batches", scheduler_elastic_config.micro_batches,
                               "micro_batches", runtime_elastic_config.micro_batches))
        if runtime_elastic_config.version != scheduler_elastic_config.version:
            raise ElasticityConfigError(
                err_str.format("version", scheduler_elastic_config.version,
                               "version", runtime_elastic_config.version))
    else:
        os.environ[EC.DEEPSPEED_ELASTICITY_CONFIG] = json.dumps(runtime_elastic_config_dict)


def compute_elastic_config(ds_config, target_deepspeed_version, world_size=0,
                           return_microbatch=False):
    """Core entry: (final_batch_size, valid_gpus[, micro_batch]).

    Parity: reference ``elasticity/elasticity.py:226``.  With ``world_size > 0``
    also picks the micro-batch (largest feasible if ``prefer_larger_batch``).
    """
    if isinstance(ds_config, str):
        ds_config = json.loads(ds_config)
    if not isinstance(ds_config, dict):
        raise ValueError("Expected ds_config to be a dict or json string")

    if EC.ELASTICITY not in ds_config:
        raise ElasticityError(f"'{EC.ELASTICITY}' is missing from config json, "
                              f"please add it if running an elastic training job.")
    elastic_config = ElasticityConfig(ds_config[EC.ELASTICITY])

    if float(elastic_config.version) > EC.LATEST_ELASTICITY_VERSION:
        raise ElasticityConfigError(
            f"Unsupported elasticity version {elastic_config.version}, "
            f"latest is {EC.LATEST_ELASTICITY_VERSION}")

    if float(elastic_config.version) == 0.1:
        final_batch_size, valid_gpus = _get_compatible_gpus_v01(
            micro_batches=elastic_config.micro_batches,
            max_acceptable_batch_size=elastic_config.max_acceptable_batch_size,
            min_gpus=elastic_config.min_gpus,
            max_gpus=elastic_config.max_gpus,
            prefer_larger=elastic_config.prefer_larger_batch_size)
        final_batch_size = int(final_batch_size)
    else:
        raise NotImplementedError(
            f"Unable to find elastic logic for version: {elastic_config.version}")

    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityIncompatibleWorldSize(
                f"World size ({world_size}) is not valid with the current list of "
                f"valid GPU counts: {valid_gpus}")
        # Pick the micro batch: prefer the largest micro batch that divides evenly.
        candidate_microbatch = None
        for micro in sorted(elastic_config.micro_batches, reverse=True):
            if final_batch_size // world_size % micro == 0:
                candidate_microbatch = micro
                if elastic_config.prefer_larger_batch_size:
                    break
        if candidate_microbatch is None:
            raise ElasticityError(f"Unable to find appropriate micro batch size for "
                                  f"world size {world_size} and batch {final_batch_size}")
        return final_batch_size, valid_gpus, candidate_microbatch

    return final_batch_size, valid_gpus, None
