from .elasticity import (compute_elastic_config, elasticity_enabled,
                         ensure_immutable_elastic_config, _get_compatible_gpus_v01)
from .config import (ElasticityConfig, ElasticityError, ElasticityConfigError,
                     ElasticityIncompatibleWorldSize)
