"""Elasticity config object. Parity: reference ``deepspeed/elasticity/config.py``."""

import json

from . import constants as EC


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


class ElasticityConfig:
    """Parsed ``elasticity`` section with the v0.1 schema."""

    def __init__(self, param_dict):
        self.enabled = param_dict.get(EC.ENABLED, EC.ENABLED_DEFAULT)
        if self.enabled:
            if EC.MAX_ACCEPTABLE_BATCH_SIZE in param_dict:
                self.max_acceptable_batch_size = param_dict[EC.MAX_ACCEPTABLE_BATCH_SIZE]
            else:
                raise ElasticityConfigError(
                    f"Elasticity config missing {EC.MAX_ACCEPTABLE_BATCH_SIZE}")
            if EC.MICRO_BATCHES in param_dict:
                self.micro_batches = param_dict[EC.MICRO_BATCHES]
            else:
                raise ElasticityConfigError(f"Elasticity config missing {EC.MICRO_BATCHES}")
        else:
            self.max_acceptable_batch_size = param_dict.get(
                EC.MAX_ACCEPTABLE_BATCH_SIZE, EC.MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT)
            self.micro_batches = param_dict.get(EC.MICRO_BATCHES, EC.MICRO_BATCHES_DEFAULT)

        if not isinstance(self.micro_batches, list):
            raise ElasticityConfigError(
                f"{EC.MICRO_BATCHES} must be a list of ints, got {self.micro_batches}")
        if not all(map(lambda m: isinstance(m, int), self.micro_batches)):
            raise ElasticityConfigError(
                f"{EC.MICRO_BATCHES} must contain only ints, got {self.micro_batches}")
        if not all(map(lambda m: m > 0, self.micro_batches)):
            raise ElasticityConfigError(
                f"{EC.MICRO_BATCHES} must contain only positive ints, got {self.micro_batches}")
        if self.micro_batches and \
                max(self.micro_batches) > self.max_acceptable_batch_size:
            # caught here so a bad elasticity block fails at config parse
            # (initialize) with a typed error, not as a ValueError deep in
            # the candidate search
            raise ElasticityConfigError(
                f"every micro batch must be <= {EC.MAX_ACCEPTABLE_BATCH_SIZE} "
                f"({self.max_acceptable_batch_size}); got {self.micro_batches}")

        self.min_gpus = param_dict.get(EC.MIN_GPUS, EC.MIN_GPUS_DEFAULT)
        self.max_gpus = param_dict.get(EC.MAX_GPUS, EC.MAX_GPUS_DEFAULT)
        if self.min_gpus < 1 or self.max_gpus < 1:
            raise ElasticityConfigError("Elasticity min/max gpus must be > 0")
        if self.max_gpus < self.min_gpus:
            raise ElasticityConfigError("Elasticity min_gpus cannot be greater than max_gpus")

        self.min_time = param_dict.get(EC.MIN_TIME, EC.MIN_TIME_DEFAULT)
        if self.min_time < 0:
            raise ElasticityConfigError(f"Elasticity min time needs to be >= 0")

        self.version = param_dict.get(EC.VERSION, EC.VERSION_DEFAULT)
        self.prefer_larger_batch_size = param_dict.get(EC.PREFER_LARGER_BATCH,
                                                       EC.PREFER_LARGER_BATCH_DEFAULT)
        self.ignore_non_elastic_batch_info = param_dict.get(
            EC.IGNORE_NON_ELASTIC_BATCH_INFO, EC.IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)

    def repr_dict(self):
        return self.__dict__

    def __repr__(self):
        return json.dumps(self.__dict__, sort_keys=True, indent=4)
