"""Checkpoint-time weight quantization for inference serving.

Parity: reference ``runtime/weight_quantizer.py`` (``WeightQuantization``:
quantizes selected checkpoint weights to int8 while computing per-group
scales, used by ``init_inference`` when serving quantized models).  Backed
by the same groupwise symmetric math as the MoQ quantizer
(``ops/quantizer``); the int8 payloads flow through
``module_inject/module_quantize.dequantize_tree`` at inference time.
"""

import jax.numpy as jnp

from ..module_inject.module_quantize import (quantize_param_tree,
                                             dequantize_tree,
                                             default_predicate)


class WeightQuantization:
    def __init__(self, mlp_extra_grouping=True, mp_size=1):
        self.mlp_extra_grouping = mlp_extra_grouping
        self.mp_size = mp_size

    def model_quantize(self, params, quantize_policy=None, quantize_bits=8,
                       groups=1):
        """Quantize a parameter pytree; returns (qparams, scales_stats).

        ``quantize_policy``: optional ``(path, leaf) -> bool`` predicate
        (reference: per-architecture policy dict selecting which weights to
        quantize)."""
        pred = quantize_policy or default_predicate
        if self.mlp_extra_grouping:
            # reference doubles the group count for MLP weights to preserve
            # accuracy; here simply doubling the global group count for
            # large 2-D weights achieves the same granularity
            groups = max(1, groups) * 2
        return quantize_param_tree(params, bits=quantize_bits,
                                   groups=groups, predicate=pred)

    @staticmethod
    def dequantize(qparams, dtype=jnp.bfloat16):
        return dequantize_tree(qparams, dtype)
