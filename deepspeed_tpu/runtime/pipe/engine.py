"""PipelineEngine: pipelined training as ONE jitted SPMD program.

Parity: reference ``deepspeed/runtime/pipe/engine.py`` — ``PipelineEngine``
(:46), ``train_batch`` (:302), ``_exec_schedule`` (:1368) dispatching
``_INSTRUCTION_MAP`` (:1355) over p2p send/recv (``pipe/p2p.py``).

TPU-native redesign: the reference interprets the instruction IR, issuing one
NCCL p2p per edge and one autograd call per micro-batch.  Here the ENTIRE
schedule — every tick of every stage — is a single ``lax.scan`` inside a
``shard_map`` over the ``pipe`` mesh axis:

- tick t, stage s computes micro-batch ``t - s`` (the IR's semantics,
  ``schedule.py``); total ticks = M + S - 1;
- stage-to-stage transfer = ``ppermute`` ring rotation (the p2p of
  ``pipe/p2p.py:48,69``), which XLA overlaps with compute over ICI;
- the backward pipeline is NOT hand-written: ``jax.grad`` through the scan +
  ppermute yields exactly the reverse schedule, with grad transfers as the
  transposed ppermutes (reference ``_exec_send_grads``/``_exec_recv_grads``);
- tied-weight gradient reduction (reference ``_exec_reduce_tied_grads`` :240)
  falls out of autodiff: prologue/epilogue params enter the shard_map
  replicated over 'pipe', so their cotangents are psum'd automatically;
- the first-iteration tensor-shape handshake (``:836 _send_tensor_meta``)
  disappears — shapes are static under jit;
- loss aggregation from the last stage (``:552 _aggregate_total_loss``) is a
  masked psum.

Memory: activations live at stage boundaries for all M in-flight
micro-batches (GPipe profile).  ``activation_checkpoint_interval != 0`` remats
the stage body so only the boundary activations persist — the same highwater
the reference's 1F1B + activation checkpointing achieves, without interleaved
manual backward.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..engine import DeepSpeedEngine
from ..utils import tree_cast
from ..zero import partition as zpart
from .module import PipelineModule
from .schedule import TrainSchedule, InferenceSchedule


def _split_labels(batch):
    """(inputs, labels) from a stacked micro-batch pytree.

    Accepted shapes: ``(inputs, labels)`` tuples (reference pipeline data
    contract, ``pipe/engine.py:795 _exec_load_micro_batch``) or dicts with a
    ``'labels'`` key.  Anything else is rejected rather than silently trained
    with ``labels == inputs``.
    """
    if isinstance(batch, (tuple, list)) and len(batch) >= 2:
        return batch[0], batch[1]
    if isinstance(batch, dict) and "labels" in batch:
        inputs = {k: v for k, v in batch.items() if k != "labels"}
        if len(inputs) == 1:
            inputs = next(iter(inputs.values()))
        return inputs, batch["labels"]
    raise ValueError(
        "PipelineEngine batches must be (inputs, labels) tuples or dicts "
        f"with a 'labels' key; got {type(batch).__name__}")


class PipelineEngine(DeepSpeedEngine):
    """Config/mesh-driven pipeline-parallel engine.

    ``gradient_accumulation_steps`` doubles as the micro-batch count M
    (reference: ``train_batch() = micro_batches`` micro-steps,
    ``pipe/engine.py:302``).
    """

    def __init__(self, model=None, **kwargs):
        assert isinstance(model, PipelineModule), \
            "PipelineEngine requires a PipelineModule"
        super().__init__(model=model, loss_fn=self._no_flat_loss, **kwargs)
        S = self.mesh_ctx.pipe_size
        assert S == model.num_stages, \
            (f"mesh pipe axis ({S}) != PipelineModule.num_stages "
             f"({model.num_stages}); set config mesh.axes.pipe")
        self.num_stages = model.num_stages
        self.micro_batches = self.gradient_accumulation_steps()

    @staticmethod
    def _no_flat_loss(params, batch, rng):
        raise RuntimeError("PipelineEngine computes loss via the pipelined "
                           "schedule; flat loss_fn is unused")

    # ------------------------------------------------------------ schedules
    def train_schedule(self, stage_id=0):
        """The instruction-IR view of what the fused program executes."""
        return TrainSchedule(micro_batches=self.micro_batches,
                             stages=self.num_stages, stage_id=stage_id)

    def inference_schedule(self, stage_id=0):
        return InferenceSchedule(micro_batches=self.micro_batches,
                                 stages=self.num_stages, stage_id=stage_id)

    # ------------------------------------------------------------- gradients
    def _grad_fn(self, base, batch, rng, cur_scale):
        """Pipelined forward + autodiff backward (replaces the gas scan)."""
        dtype = self.compute_dtype
        needs_master = dtype != jnp.float32

        def total_loss(base_params):
            p = tree_cast(base_params, dtype) if needs_master else base_params
            p = zpart.constrain(p, self._param_specs, self.mesh)
            return self._pipeline_loss(p, batch, rng) * cur_scale

        scaled_loss, grads = jax.value_and_grad(total_loss)(base)
        grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)
        return grads, scaled_loss

    # ------------------------------------------------------- fused pipeline
    def _pipeline_loss(self, params, batch, rng, train=True):
        """Mean loss over M micro-batches, computed by the collective
        pipeline.  ``batch`` leaves are (M, micro_batch, ...).

        ``train=False`` passes ``rng=None`` to every layer — the layer
        protocol's "deterministic" signal — so eval never runs dropout
        (reference ``eval_batch`` puts the module in eval mode,
        ``pipe/engine.py:382``)."""
        module = self.module
        S = self.num_stages
        inputs, labels = _split_labels(batch)
        M = jax.tree_util.tree_leaves(inputs)[0].shape[0]

        stages = params["stages"]
        other = {k: v for k, v in params.items() if k != "stages"}
        # remat every `interval` layers within the stage body (reference
        # ``pipeline.activation_checkpoint_interval``; 0 disables)
        interval = int(module.activation_checkpoint_interval)

        def per_stage(stages_local, other_p, inp, lab, key):
            s = lax.axis_index("pipe")
            local = jax.tree_util.tree_map(lambda a: a[0], stages_local)

            L = module.layers_per_stage
            def chunk_body(lo, hi):
                def run(h, t):
                    for j in range(lo, hi):
                        r = (jax.random.fold_in(key, (t * S + s) * 131 + j)
                             if train else None)
                        h = module.slot_apply(j, local[j], h, r)
                    return h
                return run

            chunks = []
            step_sz = interval if interval > 0 else L
            for lo in range(0, L, step_sz):
                c = chunk_body(lo, min(lo + step_sz, L))
                if interval > 0:
                    c = jax.checkpoint(c)
                chunks.append(c)

            def stage_body(x, t):
                for c in chunks:
                    x = c(x, t)
                return x

            def load_mb(t):
                return jax.tree_util.tree_map(lambda a: a[t], inp)

            x0_probe = module.prologue_apply(
                other_p, load_mb(0),
                rng=jax.random.fold_in(key, 7) if train else None)
            zero_h = jnp.zeros_like(x0_probe)

            def tick(carry, t):
                y_prev = carry
                # receive previous tick's output from stage s-1 (p2p recv)
                perm = [(i, (i + 1) % S) for i in range(S)]
                x_recv = lax.ppermute(y_prev, "pipe", perm)
                # first stage loads micro-batch t instead
                x0 = module.prologue_apply(
                    other_p, load_mb(jnp.clip(t, 0, M - 1)),
                    rng=jax.random.fold_in(key, t * 7 + 1) if train else None)
                x_in = jnp.where(s == 0, x0, x_recv)
                y = stage_body(x_in, t)
                return y, y

            # carry values become pipe-varying after the first ppermute;
            # mark the initial carry accordingly (shard_map vma typing)
            carry0 = lax.pcast(zero_h, ("pipe",), to="varying")
            _, ys = lax.scan(tick, carry0, jnp.arange(M + S - 1))

            # Epilogue + loss ONCE over the M completed micro-batches
            # (ticks S-1 … M+S-2 on the last stage), batched into a single
            # vmapped application instead of per-tick masked compute.
            ys_valid = ys[S - 1:]                       # (M, mb, ...)
            def one_loss(i, y):
                out = module.epilogue_apply(
                    other_p, y,
                    rng=jax.random.fold_in(key, i * 7 + 3) if train else None)
                lb = jax.tree_util.tree_map(lambda a: a[i], lab)
                return module.compute_loss(out, lb).astype(jnp.float32)
            losses = jax.vmap(one_loss)(jnp.arange(M), ys_valid)
            mean_loss = jnp.mean(losses)
            # aggregate from the last stage (reference _aggregate_total_loss)
            return lax.psum(jnp.where(s == S - 1, mean_loss, 0.0), "pipe")

        fn = jax.shard_map(per_stage, mesh=self.mesh,
                           in_specs=(P("pipe"), P(), P(), P(), P()),
                           out_specs=P(), axis_names={"pipe"})
        return fn(stages, other, inputs, labels, rng)

    # ------------------------------------------------------------------ eval
    def eval_batch(self, batch, rng=None):
        """Pipelined forward-only loss on ONE micro-batch ``(inputs, labels)``
        (promoted internally to a stack of one; pass pre-stacked batches
        through ``_pipeline_loss`` directly if needed)."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        if self._jit_eval is None:
            def eval_fn(params, b, r):
                return self._pipeline_loss(params, b, r, train=False)
            self._jit_eval = jax.jit(eval_fn)
        # promote a single micro-batch to a stack of one
        batch = jax.tree_util.tree_map(lambda a: jnp.asarray(a)[None], batch)
        return self._jit_eval(self.state.params, batch, rng)

    # forward/backward shim is meaningless under a fused pipeline schedule
    def forward(self, *a, **k):
        raise NotImplementedError("PipelineEngine: use train_batch()/eval_batch() "
                                  "(reference PipelineEngine also forbids "
                                  "forward/backward, pipe/engine.py:46)")

    backward = forward
    step = forward
