"""PipelineEngine: pipelined training as ONE jitted SPMD program.

Parity: reference ``deepspeed/runtime/pipe/engine.py`` — ``PipelineEngine``
(:46), ``train_batch`` (:302), ``_exec_schedule`` (:1368) dispatching
``_INSTRUCTION_MAP`` (:1355) over p2p send/recv (``pipe/p2p.py``).

TPU-native redesign: the reference interprets the instruction IR, issuing one
NCCL p2p per edge and one autograd call per micro-batch.  Here the ENTIRE
schedule — every tick of every stage — is a single ``lax.scan`` inside a
``shard_map`` over the ``pipe`` mesh axis:

- tick t, stage s computes micro-batch ``t - s`` (the IR's semantics,
  ``schedule.py``); total ticks = M + S - 1;
- stage-to-stage transfer = ``ppermute`` ring rotation (the p2p of
  ``pipe/p2p.py:48,69``), which XLA overlaps with compute over ICI;
- TRAINING runs the 1F1B timetable with a HAND-WRITTEN backward: each scan
  tick performs (at most) one forward micro-batch AND one backward
  micro-batch per stage.  Stage ``s`` forwards micro-batch ``f`` at tick
  ``f + s`` and backwards micro-batch ``b`` at tick ``b + 2S - 1 - s`` —
  the cotangent produced by stage ``s+1`` at tick ``t`` arrives at stage
  ``s`` exactly at tick ``t + 1``.  Saved state is a circular buffer of
  ``num_pipe_buffers = 2S`` boundary activations per stage (the reference's
  ``schedule.py:243 num_pipe_buffers`` bound), so live memory is **O(S),
  independent of M** — the 1F1B property the reference's ``TrainSchedule``
  (``schedule.py:182``) exists to provide.  Backward recomputes the stage
  body from the saved boundary input (1F1B + activation checkpointing);
- forward sends are ``ppermute`` ring rotations (the p2p of
  ``pipe/p2p.py:48,69``); backward cotangent sends are the reverse rotation
  (reference ``_exec_send_grads``/``_exec_recv_grads``); XLA overlaps both
  with compute over ICI;
- tied-weight gradient reduction (reference ``_exec_reduce_tied_grads`` :240)
  is a psum over 'pipe' of the prologue/epilogue cotangents (stage 0
  contributes the embedding-use grads, stage S-1 the head-use grads);
- the first-iteration tensor-shape handshake (``:836 _send_tensor_meta``)
  disappears — shapes are static under jit;
- loss aggregation from the last stage (``:552 _aggregate_total_loss``) is a
  masked psum.

EVALUATION (forward only) keeps the simpler all-forward scan
(``_pipeline_loss``), which needs no saved activations at all.
"""
# dstpu: disable-file=DSTPU102 (reviewed: the pipeline schedule IS the
# collective choreography -- ppermute ring order is the 1F1B timetable)

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ...utils.logging import log_dist
from ..engine import DeepSpeedEngine
from ..utils import tree_cast
from ..zero import partition as zpart
from .module import PipelineModule
from .schedule import TrainSchedule, InferenceSchedule


def _split_labels(batch):
    """(inputs, labels) from a stacked micro-batch pytree.

    Accepted shapes: ``(inputs, labels)`` tuples (reference pipeline data
    contract, ``pipe/engine.py:795 _exec_load_micro_batch``) or dicts with a
    ``'labels'`` key.  Anything else is rejected rather than silently trained
    with ``labels == inputs``.
    """
    if isinstance(batch, (tuple, list)) and len(batch) >= 2:
        return batch[0], batch[1]
    if isinstance(batch, dict) and "labels" in batch:
        inputs = {k: v for k, v in batch.items() if k != "labels"}
        if len(inputs) == 1:
            inputs = next(iter(inputs.values()))
        return inputs, batch["labels"]
    raise ValueError(
        "PipelineEngine batches must be (inputs, labels) tuples or dicts "
        f"with a 'labels' key; got {type(batch).__name__}")


class PipelineEngine(DeepSpeedEngine):
    """Config/mesh-driven pipeline-parallel engine.

    ``gradient_accumulation_steps`` doubles as the micro-batch count M
    (reference: ``train_batch() = micro_batches`` micro-steps,
    ``pipe/engine.py:302``).
    """

    # 1F1B schedules its own collectives (ppermute activations, per-tick
    # grad accumulation); the qwZ/qgZ wire rewrite does not apply — the
    # `pipe` comms_compression route is accepted-but-full-width
    # (docs/comms-compression.md)
    _supports_comms_compression = False

    def __init__(self, model=None, **kwargs):
        assert isinstance(model, PipelineModule), \
            "PipelineEngine requires a PipelineModule"
        super().__init__(model=model, loss_fn=self._no_flat_loss, **kwargs)
        S = self.mesh_ctx.pipe_size
        assert S == model.num_stages, \
            (f"mesh pipe axis ({S}) != PipelineModule.num_stages "
             f"({model.num_stages}); set config mesh.axes.pipe")
        self.num_stages = model.num_stages
        self.micro_batches = self.gradient_accumulation_steps()
        if self.config.grad_accum_dtype != "fp32":
            from ...utils.logging import logger
            logger.warning(
                "data_types.grad_accum_dtype is ignored by the pipeline "
                "engine: 1F1B accumulates per-tick gradients in fp32 (the "
                "bf16 option applies to the gas scan of the non-pipeline "
                "engine)")

    @staticmethod
    def _no_flat_loss(params, batch, rng):
        raise RuntimeError("PipelineEngine computes loss via the pipelined "
                           "schedule; flat loss_fn is unused")

    # ------------------------------------------------------------ schedules
    def train_schedule(self, stage_id=0):
        """The instruction-IR view of what the fused program executes."""
        return TrainSchedule(micro_batches=self.micro_batches,
                             stages=self.num_stages, stage_id=stage_id)

    def inference_schedule(self, stage_id=0):
        return InferenceSchedule(micro_batches=self.micro_batches,
                                 stages=self.num_stages, stage_id=stage_id)

    # ------------------------------------------------------------- gradients
    @property
    def num_pipe_buffers(self):
        """1F1B live-activation bound per stage (reference
        ``schedule.py:243``): independent of micro-batch count M."""
        return 2 * self.num_stages

    def _grad_fn(self, base, batch, rng, cur_scale):
        """Pipelined 1F1B forward/backward (replaces the gas scan).

        The cast (master→compute) and the sharding constraint are linear /
        identity maps, so gradients w.r.t. ``base`` equal the hand-computed
        gradients w.r.t. the casted params, cast back to fp32.

        Returns the base engine's (grads, scaled_loss, aux) contract, so
        the shared ``_train_step`` — including the health guardian's
        on-device sentinels and branchless skip-step — applies unchanged to
        the pipelined program: a NaN riding the ppermute ring propagates
        into the psum'd loss/grads, trips the non-finite sentinels, and the
        step is ``where``-selected to a no-op on every stage's params.
        """
        dtype = self.compute_dtype
        needs_master = dtype != jnp.float32
        p = tree_cast(base, dtype) if needs_master else base
        p = zpart.constrain(p, self._param_specs, self.mesh)
        scaled_loss, grads = self._pipeline_grads(p, batch, rng, cur_scale)
        return grads, scaled_loss, {}

    def _pipeline_grads(self, params, batch, rng, cur_scale):
        """Hand-scheduled 1F1B: returns ``(mean_loss * cur_scale, grads)``
        with fp32 grads structured like ``params``.

        Timetable (stage ``s`` of ``S``, micro-batch index in ``[0, M)``,
        ticks ``t in [0, M + 2S - 1)``):

        - forward of micro-batch ``f`` runs at tick ``t = f + s``;
        - backward of micro-batch ``b`` runs at tick ``t = b + 2S - 1 - s``;
        - both transfers are one-tick ppermutes, so activations/cotangents
          arrive exactly when consumed.

        A micro-batch's boundary input is held for ``2(S - s) - 1`` ticks in a
        ``2S``-slot circular buffer.  With
        ``activation_checkpoint_interval >= 1`` the stage body is recomputed
        from it in backward (activation checkpointing) — live activation
        memory is O(S·micro) where the reference's GPipe profile is
        O(M·micro).  With ``interval == 0`` (reference semantics: no
        checkpointing, ``runtime/pipe/engine.py:719`` runs backward on stored
        activations) the forward tick runs under ``jax.vjp`` and the
        *residuals* ride the same circular buffer — ``jax.vjp``'s pullback is
        a pytree, so its leaves scan-carry like any activation — trading
        O(S·micro·L) residual memory for a backward with no re-forward.
        """
        module = self.module
        S = self.num_stages
        B = self.num_pipe_buffers
        inputs, labels = _split_labels(batch)
        M = jax.tree_util.tree_leaves(inputs)[0].shape[0]
        T = M + 2 * S - 1
        interval = int(module.activation_checkpoint_interval)
        L = module.layers_per_stage

        def per_stage(stages_local, other_p, inp, lab, key):
            s = lax.axis_index("pipe")
            local = jax.tree_util.tree_map(lambda a: a[0], stages_local)
            is_last = s == S - 1

            def _vary_one(a):
                if "pipe" in getattr(jax.typeof(a), "vma", frozenset()):
                    return a        # pcast rejects varying→varying
                return lax.pcast(a, ("pipe",), to="varying")
            varying = lambda v: jax.tree_util.tree_map(_vary_one, v)
            # CRITICAL: differentiate w.r.t. a pipe-VARYING view of the
            # replicated prologue/epilogue params.  vjp w.r.t. an invariant
            # input inserts an implicit psum over 'pipe' at the use site —
            # inside the per-stage conds below that psum would be executed by
            # only some stages (deadlock).  With a varying view the cotangent
            # stays local; the single explicit psum happens after the scan.
            other_v = varying(other_p)

            def load_mb(tree, f):
                return jax.tree_util.tree_map(lambda a: a[f], tree)

            # rngs depend only on (micro-batch, stage, layer-slot) — NEVER the
            # tick — so backward recompute sees identical dropout masks.
            def r_for(f, slot):
                return jax.random.fold_in(key, (f * S + s) * (L + 2) + slot)

            def stage_fwd(local_p, other_p2, x_recv, f):
                """Stage forward incl. prologue/input-select; differentiable
                w.r.t. (local_p, other_p2, x_recv).  The ``where`` masks the
                prologue's cotangent to stage 0 automatically."""
                x0 = module.prologue_apply(other_p2, load_mb(inp, f),
                                           rng=r_for(f, L))
                h = jnp.where(s == 0, x0, x_recv)

                def chunk(lo, hi):
                    def run(h2, f2):
                        for j in range(lo, hi):
                            h2 = module.slot_apply(j, local_p[j], h2,
                                                   r_for(f2, j))
                        return h2
                    return run

                step_sz = interval if interval > 0 else L
                for lo in range(0, L, step_sz):
                    c = chunk(lo, min(lo + step_sz, L))
                    if interval > 0:
                        c = jax.checkpoint(c)
                    h = c(h, f)
                return h

            def head_loss(other_p2, y, b):
                """Epilogue + loss on the last stage; scaled seed for the
                mean over M micro-batches."""
                out = module.epilogue_apply(other_p2, y, rng=r_for(b, L + 1))
                lb = load_mb(lab, b)
                loss = module.compute_loss(out, lb).astype(jnp.float32)
                return loss * (cur_scale / M)

            # shape/dtype protos (never executed on real data), typed as
            # pipe-varying so cond branches / scan carries agree (shard_map
            # vma typing)
            x_proto = jax.eval_shape(
                lambda op: module.prologue_apply(op, load_mb(inp, 0),
                                                 rng=r_for(0, L)), other_p)
            zero_x = varying(jnp.zeros(x_proto.shape, x_proto.dtype))
            zeros_local = varying(jax.tree_util.tree_map(
                lambda a: jnp.zeros(a.shape, jnp.float32), local))
            zeros_other = varying(jax.tree_util.tree_map(
                lambda a: jnp.zeros(a.shape, jnp.float32), other_p))
            zero_f32 = varying(jnp.float32(0.0))

            store_resid = interval == 0
            if store_resid:
                # One traced vjp OUTSIDE the scan gives the residual-leaf
                # protos AND — by tracer identity — which leaves are just the
                # tick-invariant parameters forwarded through (matmul saves W
                # itself): those must NOT be buffered per slot, or every
                # stage's weights would be materialized 2S times.  Only
                # genuinely per-micro-batch residuals (activations, gathered
                # inputs, rng-derived masks) ride the circular buffer; the
                # unmatched-is-buffered default keeps unknown leaves correct.
                _, _vf0 = jax.vjp(
                    lambda lp, op, xr: stage_fwd(lp, op, xr, jnp.int32(0)),
                    local, other_v, zero_x)
                _leaves0 = jax.tree_util.tree_leaves(_vf0)
                _inv_ids = {id(l) for l in
                            jax.tree_util.tree_leaves((local, other_v))}
                buffered_idx = tuple(i for i, l in enumerate(_leaves0)
                                     if id(l) not in _inv_ids)
                zero_res = tuple(
                    varying(jnp.zeros((B,) + jnp.shape(_leaves0[i]),
                                      jnp.result_type(_leaves0[i])))
                    for i in buffered_idx)
                # visibility: a residual computed FROM params (e.g. a dtype
                # cast) fails the tracer-identity match and silently rides
                # all 2S slots, multiplying stage-weight memory — log the
                # total buffered bytes so that shows up as a number, not a
                # mystery OOM
                _buf_bytes = sum(
                    B * int(np.prod(jnp.shape(_leaves0[i]) or (1,)))
                    * jnp.result_type(_leaves0[i]).itemsize  # noqa: E131
                    for i in buffered_idx)
                log_dist(
                    f"pipeline residual store: {len(buffered_idx)} leaves "
                    f"x {B} slots = {_buf_bytes / 1e6:.1f} MB per stage "
                    f"({len(_leaves0) - len(buffered_idx)} tick-invariant "
                    "leaves excluded)", ranks=[0])

            def tick(carry, t):
                # UNIFORM execution: every device runs the identical op
                # sequence every tick, with inactive work masked by `where`.
                # No `lax.cond` on stage-dependent predicates: the auto-axis
                # (data/tensor) collectives XLA inserts inside a branch would
                # then be executed by only some pipe stages — deadlock.
                if store_resid:
                    res_bufs, y_buf, y_send, g_send, gl, go, lacc = carry
                else:
                    buf, y_send, g_send, gl, go, lacc = carry
                # receives: activation from s-1 (down ring), cotangent from
                # s+1 (up ring) — both from the PREVIOUS tick's sends.
                down = [(i, (i + 1) % S) for i in range(S)]
                up = [((i + 1) % S, i) for i in range(S)]
                x_recv = lax.ppermute(y_send, "pipe", down)
                g_recv = lax.ppermute(g_send, "pipe", up)

                # ---------------- forward: micro-batch f = t - s ------------
                f = t - s
                f_act = (f >= 0) & (f < M)
                fc = jnp.clip(f, 0, M - 1)
                # OOB index B drops buffer writes on inactive ticks (no
                # full-buffer select)
                slot = jnp.where(f_act, fc % B, B)
                if store_resid:
                    # no-recompute mode: forward runs under vjp NOW and the
                    # pullback's per-micro-batch residual leaves ride the
                    # circular buffer to this micro-batch's backward tick
                    # (tick-invariant leaves — the weights — are reused from
                    # this tick's own vjp at backward, see buffered_idx)
                    y, vjp_f = jax.vjp(
                        lambda lp, op, xr: stage_fwd(lp, op, xr, fc),
                        local, other_v, x_recv)
                    leaves_f, res_def = jax.tree_util.tree_flatten(vjp_f)
                    res_bufs = tuple(
                        rb.at[slot].set(_vary_one(leaves_f[i]), mode="drop")
                        for rb, i in zip(res_bufs, buffered_idx))
                    y_buf = y_buf.at[slot].set(y, mode="drop")
                else:
                    y = stage_fwd(local, other_v, x_recv, fc)
                    # save the boundary input for the backward recompute
                    buf = buf.at[slot].set(x_recv, mode="drop")

                # ---------------- backward: micro-batch b = t-(2S-1)+s ------
                b = t - (2 * S - 1) + s
                b_act = (b >= 0) & (b < M)
                bc = jnp.clip(b, 0, M - 1)

                if store_resid:
                    leaves_b = list(leaves_f)   # invariant leaves: this tick's
                    for rb, i in zip(res_bufs, buffered_idx):
                        leaves_b[i] = rb[bc % B]
                    vjp_fn = jax.tree_util.tree_unflatten(res_def, leaves_b)
                    y_r = y_buf[bc % B]
                else:
                    x_saved = buf[bc % B]
                    y_r, vjp_fn = jax.vjp(
                        lambda lp, op, xr: stage_fwd(lp, op, xr, bc),
                        local, other_v, x_saved)
                # seed: last stage differentiates epilogue+loss; other stages
                # use the received cotangent.  The head runs on every stage
                # (masked) to keep the op sequence uniform.
                sl, (g_oe, g_y_last) = jax.value_and_grad(
                    head_loss, argnums=(0, 1))(other_v, y_r, bc)
                g_y = jnp.where(is_last, g_y_last.astype(y_r.dtype), g_recv)
                d_local, d_other, d_x = vjp_fn(g_y)

                mask = lambda z: jax.tree_util.tree_map(
                    lambda a: jnp.where(b_act, a.astype(jnp.float32), 0.0), z)
                gl = jax.tree_util.tree_map(jnp.add, gl, mask(d_local))
                go = jax.tree_util.tree_map(jnp.add, go, mask(d_other))
                go = jax.tree_util.tree_map(
                    lambda a, e: a + jnp.where(b_act & is_last,
                                               e.astype(jnp.float32), 0.0),
                    go, g_oe)
                lacc = lacc + jnp.where(b_act & is_last, sl, 0.0)
                # mask sends so bubble-tick garbage never reaches active ticks
                y_send_n = jnp.where(f_act, y, 0.0).astype(y.dtype)
                g_send_n = jnp.where(b_act, d_x, 0.0).astype(d_x.dtype)
                if store_resid:
                    return (res_bufs, y_buf, y_send_n, g_send_n,
                            gl, go, lacc), None
                return (buf, y_send_n, g_send_n, gl, go, lacc), None

            if store_resid:
                carry0 = (
                    zero_res,
                    varying(jnp.zeros((B,) + x_proto.shape, x_proto.dtype)),
                    zero_x,                          # y_send
                    zero_x,                          # g_send
                    zeros_local, zeros_other, zero_f32)
                (_, _, _, _, gl, go, lacc), _ = lax.scan(
                    tick, carry0, jnp.arange(T))
            else:
                carry0 = (
                    varying(jnp.zeros((B,) + x_proto.shape, x_proto.dtype)),
                    zero_x,                          # y_send
                    zero_x,                          # g_send
                    zeros_local, zeros_other, zero_f32)
                (_, _, _, gl, go, lacc), _ = lax.scan(
                    tick, carry0, jnp.arange(T))

            # stage grads: re-add the stage axis; shard_map concatenates over
            # 'pipe'.  Prologue/epilogue grads: psum reduces the per-stage
            # contributions (stage 0 / stage S-1; zeros elsewhere) — the
            # reference's tied-grad allreduce (pipe/module.py:419).
            gl = jax.tree_util.tree_map(lambda a: a[None], gl)
            go = lax.psum(go, "pipe")
            scaled_loss = lax.psum(jnp.where(is_last, lacc, 0.0), "pipe")
            return scaled_loss, gl, go

        fn = jax.shard_map(per_stage, mesh=self.mesh,
                           in_specs=(P("pipe"), P(), P(), P(), P()),
                           out_specs=(P(), P("pipe"), P()),
                           axis_names={"pipe"})
        stages = params["stages"]
        other = {k: v for k, v in params.items() if k != "stages"}
        scaled_loss, g_stages, g_other = fn(stages, other, inputs, labels, rng)
        grads = dict(g_other)
        grads["stages"] = g_stages
        return scaled_loss, grads

    # ------------------------------------------------------- fused pipeline
    def _pipeline_loss(self, params, batch, rng, train=True):
        """Mean loss over M micro-batches, computed by the collective
        pipeline.  ``batch`` leaves are (M, micro_batch, ...).

        ``train=False`` passes ``rng=None`` to every layer — the layer
        protocol's "deterministic" signal — so eval never runs dropout
        (reference ``eval_batch`` puts the module in eval mode,
        ``pipe/engine.py:382``)."""
        module = self.module
        S = self.num_stages
        inputs, labels = _split_labels(batch)
        M = jax.tree_util.tree_leaves(inputs)[0].shape[0]

        stages = params["stages"]
        other = {k: v for k, v in params.items() if k != "stages"}
        # remat every `interval` layers within the stage body (reference
        # ``pipeline.activation_checkpoint_interval``; 0 disables)
        interval = int(module.activation_checkpoint_interval)

        def per_stage(stages_local, other_p, inp, lab, key):
            s = lax.axis_index("pipe")
            local = jax.tree_util.tree_map(lambda a: a[0], stages_local)

            L = module.layers_per_stage
            def chunk_body(lo, hi):
                def run(h, t):
                    for j in range(lo, hi):
                        r = (jax.random.fold_in(key, (t * S + s) * 131 + j)
                             if train else None)
                        h = module.slot_apply(j, local[j], h, r)
                    return h
                return run

            chunks = []
            step_sz = interval if interval > 0 else L
            for lo in range(0, L, step_sz):
                c = chunk_body(lo, min(lo + step_sz, L))
                if interval > 0:
                    c = jax.checkpoint(c)
                chunks.append(c)

            def stage_body(x, t):
                for c in chunks:
                    x = c(x, t)
                return x

            def load_mb(t):
                return jax.tree_util.tree_map(lambda a: a[t], inp)

            x0_probe = module.prologue_apply(
                other_p, load_mb(0),
                rng=jax.random.fold_in(key, 7) if train else None)
            zero_h = jnp.zeros_like(x0_probe)

            def tick(carry, t):
                y_prev = carry
                # receive previous tick's output from stage s-1 (p2p recv)
                perm = [(i, (i + 1) % S) for i in range(S)]
                x_recv = lax.ppermute(y_prev, "pipe", perm)
                # first stage loads micro-batch t instead
                x0 = module.prologue_apply(
                    other_p, load_mb(jnp.clip(t, 0, M - 1)),
                    rng=jax.random.fold_in(key, t * 7 + 1) if train else None)
                x_in = jnp.where(s == 0, x0, x_recv)
                y = stage_body(x_in, t)
                return y, y

            # carry values become pipe-varying after the first ppermute;
            # mark the initial carry accordingly (shard_map vma typing)
            carry0 = lax.pcast(zero_h, ("pipe",), to="varying")
            _, ys = lax.scan(tick, carry0, jnp.arange(M + S - 1))

            # Epilogue + loss ONCE over the M completed micro-batches
            # (ticks S-1 … M+S-2 on the last stage), batched into a single
            # vmapped application instead of per-tick masked compute.
            ys_valid = ys[S - 1:]                       # (M, mb, ...)
            def one_loss(i, y):
                out = module.epilogue_apply(
                    other_p, y,
                    rng=jax.random.fold_in(key, i * 7 + 3) if train else None)
                lb = jax.tree_util.tree_map(lambda a: a[i], lab)
                return module.compute_loss(out, lb).astype(jnp.float32)
            losses = jax.vmap(one_loss)(jnp.arange(M), ys_valid)
            mean_loss = jnp.mean(losses)
            # aggregate from the last stage (reference _aggregate_total_loss)
            return lax.psum(jnp.where(s == S - 1, mean_loss, 0.0), "pipe")

        fn = jax.shard_map(per_stage, mesh=self.mesh,
                           in_specs=(P("pipe"), P(), P(), P(), P()),
                           out_specs=P(), axis_names={"pipe"})
        return fn(stages, other, inputs, labels, rng)

    # ------------------------------------------------------------------ eval
    def eval_batch(self, batch, rng=None):
        """Pipelined forward-only loss on ONE micro-batch ``(inputs, labels)``
        (promoted internally to a stack of one; pass pre-stacked batches
        through ``_pipeline_loss`` directly if needed)."""
        self._flush_offload()   # a pending DPU update must land first
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        if self._jit_eval is None:
            def eval_fn(params, b, r):
                return self._pipeline_loss(params, b, r, train=False)
            self._jit_eval = self._wrap_step("eval_step", eval_fn)
        # promote a single micro-batch to a stack of one
        batch = jax.tree_util.tree_map(lambda a: jnp.asarray(a)[None], batch)
        return self._jit_eval(self.state.params, batch, rng)

    # forward/backward shim is meaningless under a fused pipeline schedule
    def forward(self, *a, **k):
        raise NotImplementedError("PipelineEngine: use train_batch()/eval_batch() "
                                  "(reference PipelineEngine also forbids "
                                  "forward/backward, pipe/engine.py:46)")

    backward = forward
    step = forward
