"""PipelineModule: partition a layer list across pipeline stages.

Parity: reference ``deepspeed/runtime/pipe/module.py`` — ``LayerSpec`` (:25,
lazy layer construction), ``TiedLayerSpec`` (:73), ``PipelineModule`` (:87)
with ``_partition_layers`` (:363) supporting ``'uniform'``, ``'parameters'``
and ``'type:regex'`` methods.

TPU-native redesign: the reference builds only the LOCAL stage's layers per
process and moves tensors between processes.  Here one process drives the
whole mesh, so the module builds ALL layers and arranges their params for the
SPMD collective pipeline (``pipe/engine.py``):

- stages must be structurally homogeneous (same layer-type sequence, same
  param shapes per slot) so per-slot params can be STACKED along a leading
  stage axis sharded over the ``pipe`` mesh axis — each device then holds
  exactly its stage's weights, like the reference's per-process build;
- heterogeneous head/tail computation (embedding in, loss head out) is
  expressed as ``prologue``/``epilogue`` modules that live OUTSIDE the
  pipelined body, replicated over the ``pipe`` axis; a tied embedding used by
  both IS the reference's tied-layer mechanism — the gradient all-reduce over
  the tie group (reference ``pipe/module.py:419
  allreduce_tied_weight_gradients``) falls out of autodiff-of-shard_map for
  replicated inputs, no explicit collective needed.
"""

import re

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..utils import partition_uniform, partition_balanced
from ...models.layers import Lambda
from ...utils.logging import logger


class LayerSpec:
    """Lazily-constructed layer (parity ``pipe/module.py:25``).

    ``typename`` is a class following the init/apply layer protocol
    (``models/layers.py``); construction is deferred so huge models can
    describe themselves cheaply.
    """

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs
        if not issubclass(typename, object):
            raise RuntimeError("LayerSpec needs a class")

    def build(self, log=False):
        if log:
            logger.info(f"building {repr(self)}")
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        from ..utils import call_to_str
        return call_to_str(self.typename.__name__, *self.module_args,
                           **self.module_kwargs)


class TiedLayerSpec(LayerSpec):
    """Layer whose parameters are shared with every other spec carrying the
    same ``key`` (parity ``pipe/module.py:73``).

    Supported placement: tied specs may appear as the FIRST and/or LAST
    elements of the layer list (the overwhelmingly common case: tied
    embedding/head).  They are lifted out of the pipelined body into the
    prologue/epilogue, sharing one parameter entry.
    """

    def __init__(self, key, typename, *module_args, forward_fn=None,
                 tied_weight_attr="table", **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


def _as_layer(obj):
    """Accept LayerSpec, layer object, or plain callable."""
    if isinstance(obj, LayerSpec):
        return obj.build()
    if hasattr(obj, "init") and hasattr(obj, "apply"):
        return obj
    if callable(obj):
        return Lambda(obj)
    raise TypeError(f"not a layer: {obj!r}")


def _count_params(layer, rng):
    shapes = jax.eval_shape(layer.init, rng)
    return sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(shapes))


class PipelineModule:
    """Partition ``layers`` into ``num_stages`` pipeline stages.

    Exposes the engine model protocol (``init``/``loss``) so
    ``deepspeed.initialize`` can treat it like any model; the pipelined
    execution itself lives in :class:`~..pipe.engine.PipelineEngine`.

    Args (parity with reference ``PipelineModule.__init__``):
        layers: list of LayerSpec / layer objects / callables.
        num_stages: pipeline depth (or derive from ``topology``).
        topology: optional ``ProcessTopology`` with a 'pipe' axis.
        loss_fn: ``loss_fn(outputs, labels) -> scalar``.
        partition_method: 'uniform' | 'parameters' | 'type:regex'.
        activation_checkpoint_interval: >=1 recomputes the stage body in
            backward every `interval` layers (activation checkpointing);
            0 stores the stage residuals at forward and runs backward with
            NO recompute (reference semantics: no checkpointing,
            ``runtime/pipe/engine.py:719``) — ~1/3 less pipeline compute
            for O(S·L) more activation memory.
        prologue/epilogue: optional init/apply modules running outside the
            pipelined body (first / last stage semantics).
    """

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seed_layers=False, base_seed=1234,
                 partition_method="parameters",
                 activation_checkpoint_interval=1,
                 checkpointable_layers=None,
                 prologue=None, epilogue=None):
        if num_stages is None and topology is None:
            raise RuntimeError("must provide num_stages or topology")
        if topology is not None and num_stages is None:
            num_stages = topology.get_dim("pipe")
        self.num_stages = int(num_stages)
        self.topology = topology
        self.loss_fn = loss_fn
        self.base_seed = int(base_seed)
        self.seed_layers = seed_layers
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.checkpointable_layers = checkpointable_layers

        self._layer_specs = list(layers)
        self.prologue, self.epilogue, body = self._lift_tied(
            prologue, epilogue, self._layer_specs)
        self.forward_funcs = [_as_layer(l) for l in body]
        self.parts = self._partition_layers(self.forward_funcs,
                                            partition_method, self.num_stages)
        self._validate_homogeneous()
        self.layers_per_stage = self.parts[1] - self.parts[0]

    # ---------------------------------------------------------------- tying
    def _lift_tied(self, prologue, epilogue, specs):
        """Lift edge TiedLayerSpecs into prologue/epilogue sharing params."""
        body = list(specs)
        tied_first = body and isinstance(body[0], TiedLayerSpec)
        tied_last = len(body) > 1 and isinstance(body[-1], TiedLayerSpec)
        if not (tied_first or tied_last):
            if any(isinstance(s, TiedLayerSpec) for s in body):
                raise NotImplementedError(
                    "TiedLayerSpec inside the pipelined body is unsupported; "
                    "place tied layers first/last (prologue/epilogue)")
            return prologue, epilogue, body
        assert prologue is None and epilogue is None, \
            "cannot mix TiedLayerSpec lifting with explicit prologue/epilogue"
        first = body.pop(0) if tied_first else None
        last = body.pop(-1) if (tied_last and body) else None
        if any(isinstance(s, TiedLayerSpec) for s in body):
            raise NotImplementedError(
                "TiedLayerSpec inside the pipelined body is unsupported")
        pro = None
        if first is not None:
            pro = _TiedEdge(_as_layer(first), first.forward_fn, owner=True)
        epi = None
        if last is not None:
            same = (first is not None and last.key == first.key)
            epi = _TiedEdge(pro.layer if same else _as_layer(last),
                            last.forward_fn, owner=not same,
                            tied_to=pro if same else None)
        return pro, epi, body

    # ----------------------------------------------------------- partitioning
    def _partition_layers(self, layers, method, num_stages):
        """Stage boundary computation (parity ``pipe/module.py:363``)."""
        n = len(layers)
        method = method.lower()
        if method == "uniform":
            parts = partition_uniform(n, num_stages)
        elif method == "parameters":
            rng = jax.random.PRNGKey(0)
            weights = [max(_count_params(l, rng), 1) for l in layers]
            parts = partition_balanced(weights, num_stages)
        elif method.startswith("type:"):
            pattern = method.split(":", 1)[1]
            weights = [1 if re.search(pattern, type(l).__name__, re.IGNORECASE)
                       else 0 for l in layers]
            if sum(weights) == 0:
                raise ValueError(f"no layer matches type:{pattern}")
            parts = partition_balanced(weights, num_stages)
        else:
            raise NotImplementedError(f"partition method {method}")
        return parts

    def _validate_homogeneous(self):
        """The SPMD engine stacks per-slot params over stages: every stage
        needs the same number of layers with matching types.  Fall back to
        uniform when the chosen method yields ragged stages."""
        counts = [self.parts[i + 1] - self.parts[i]
                  for i in range(self.num_stages)]
        if len(set(counts)) != 1:
            if len(self.forward_funcs) % self.num_stages == 0:
                logger.warning(
                    f"partition_method={self.partition_method!r} produced "
                    f"ragged stages {counts}; falling back to uniform for the "
                    f"SPMD collective pipeline")
                self.parts = partition_uniform(len(self.forward_funcs),
                                               self.num_stages)
            else:
                raise ValueError(
                    f"{len(self.forward_funcs)} layers not divisible into "
                    f"{self.num_stages} homogeneous stages (got {counts})")
        L = self.parts[1] - self.parts[0]
        for j in range(L):
            types = {type(self.forward_funcs[self.parts[s] + j])
                     for s in range(self.num_stages)}
            if len(types) != 1:
                raise ValueError(
                    f"slot {j} has mixed layer types across stages: {types}; "
                    "the SPMD pipeline requires structurally homogeneous stages")

    def stage_layers(self, stage_id):
        return self.forward_funcs[self.parts[stage_id]:self.parts[stage_id + 1]]

    # --------------------------------------------------------------- protocol
    def init(self, rng):
        """Params: ``{'stages': [slot_j stacked over stages], 'prologue': …,
        'epilogue': …}``; stacked leaves lead with the stage axis."""
        S, L = self.num_stages, self.layers_per_stage
        n_layers = len(self.forward_funcs)
        keys = jax.random.split(rng, n_layers + 2)
        per_layer = []
        for i, layer in enumerate(self.forward_funcs):
            if self.seed_layers:
                k = jax.random.PRNGKey(self.base_seed + i)
            else:
                k = keys[i]
            per_layer.append(layer.init(k))

        slots = []
        for j in range(L):
            stage_params = [per_layer[self.parts[s] + j] for s in range(S)]
            slots.append(jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *stage_params))
        params = {"stages": slots}
        if self.prologue is not None:
            params["prologue"] = self.prologue.init(keys[n_layers])
        if self.epilogue is not None and self._epilogue_owns_params():
            params["epilogue"] = self.epilogue.init(keys[n_layers + 1])
        return params

    def _epilogue_owns_params(self):
        return not (isinstance(self.epilogue, _TiedEdge)
                    and self.epilogue.tied_to is not None)

    def partition_specs(self, params=None):
        """'pipe' sharding on the leading stage axis of every stacked slot,
        composed with each layer's own tensor-parallel specs when the layer
        declares ``partition_specs()`` (Megatron column/row sharding inside a
        stage → PP×TP); prologue/epilogue use their layer's specs directly
        (replicated over 'pipe')."""
        if params is None:
            params = jax.eval_shape(self.init, jax.random.PRNGKey(0))
        out = {}

        slots = params["stages"]
        stage0 = self.forward_funcs[self.parts[0]:self.parts[1]]
        stage_specs = []
        for j, slot in enumerate(slots):
            layer = stage0[j]
            tp = (layer.partition_specs() if hasattr(layer, "partition_specs")
                  else None)
            def compose(leaf, path_spec):
                ndim = len(np.shape(leaf))
                rest = (tuple(path_spec) + (None,) * (ndim - 1 - len(path_spec))
                        if path_spec is not None else (None,) * (ndim - 1))
                return P("pipe", *rest)
            if tp is None:
                stage_specs.append(jax.tree_util.tree_map(
                    lambda l: compose(l, None), slot))
            else:
                stage_specs.append(jax.tree_util.tree_map(
                    lambda l, sp: compose(l, sp), slot, tp))
        out["stages"] = stage_specs

        for key, edge in (("prologue", self.prologue), ("epilogue", self.epilogue)):
            if key not in params:
                continue
            layer = getattr(edge, "layer", edge)
            tp = (layer.partition_specs() if hasattr(layer, "partition_specs")
                  else None)
            if tp is None:
                out[key] = jax.tree_util.tree_map(lambda l: P(), params[key])
            else:
                out[key] = tp
        return out

    # Applied by PipelineEngine inside its shard_map region:
    def slot_apply(self, j, slot_params, x, rng):
        layer = self.forward_funcs[self.parts[0] + j]  # stage-0 rep of slot j
        return layer.apply(slot_params, x, rng=rng)

    def prologue_apply(self, params, x, rng=None):
        if self.prologue is None:
            return x
        return self.prologue.apply(params.get("prologue", {}), x, rng=rng)

    def epilogue_apply(self, params, x, rng=None):
        if self.epilogue is None:
            return x
        p = params.get("epilogue")
        if p is None:  # tied to prologue
            p = params.get("prologue", {})
        return self.epilogue.apply(p, x, rng=rng)

    def compute_loss(self, outputs, labels):
        assert self.loss_fn is not None, "PipelineModule needs loss_fn for training"
        return self.loss_fn(outputs, labels)

    def num_layers(self):
        return len(self.forward_funcs)


class _TiedEdge:
    """Prologue/epilogue wrapper for a (possibly tied) edge layer."""

    def __init__(self, layer, forward_fn=None, owner=True, tied_to=None):
        self.layer = layer
        self.forward_fn = forward_fn
        self.owner = owner
        self.tied_to = tied_to

    def init(self, rng):
        return self.layer.init(rng)

    def apply(self, params, x, rng=None):
        if self.forward_fn is not None:
            return self.forward_fn(params, x)
        return self.layer.apply(params, x, rng=rng)
