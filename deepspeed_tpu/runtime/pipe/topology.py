"""Process/rank topology math for hybrid parallelism.

Parity: reference ``deepspeed/runtime/pipe/topology.py`` —
``ProcessTopology`` (:12) cartesian rank grid, ``PipeDataParallelTopology``
(:235), ``PipeModelDataParallelTopology`` (:246), ``PipelineParallelGrid``
(:252).

On TPU the device mesh (`jax.sharding.Mesh`) subsumes process groups: there is
no NCCL group construction, and collectives ride named mesh axes.  This module
keeps the *pure math* of the rank grid because it is still needed for:

- checkpoint naming across parallel coordinates (reference ``engine.py:2406``),
- the launcher/CLI mapping hosts→coordinates,
- tests of rank arithmetic (reference ``tests/unit/test_topology.py`` is
  CPU-only math too),
- mapping a mesh axis layout to the reference's ``['pipe','model','data']``
  axis vocabulary.

Ranks are assigned in row-major (C) order over the axes: the FIRST axis varies
slowest (reference semantics).
"""

import itertools
from collections import namedtuple

from ..utils import ensure_divisibility


class ProcessTopology:
    """A cartesian grid of ranks over named axes.

    ``axes`` orders dimensions from outermost (slowest-varying rank) to
    innermost.  Parity: reference ``pipe/topology.py:12``.
    """

    def __init__(self, axes, dims):
        assert len(axes) == len(dims), "axes and dims must align"
        self.axes = list(axes)
        self.dims = list(int(d) for d in dims)
        self.ProcessCoord = namedtuple("ProcessCoord", self.axes)

        self.mapping = {}
        ranges = [range(d) for d in self.dims]
        for global_rank, coord in enumerate(itertools.product(*ranges)):
            key = dict(zip(self.axes, coord))
            self.mapping[self.ProcessCoord(**key)] = global_rank

    def get_rank(self, **coord_kwargs):
        """Rank of the process at the given coordinate (all axes required)."""
        if len(coord_kwargs) != len(self.axes):
            raise ValueError(f"get_rank() needs all axes {self.axes}, "
                             f"got {list(coord_kwargs)}")
        key = self.ProcessCoord(**coord_kwargs)
        assert key in self.mapping, f"coord {key} not in topology"
        return self.mapping[key]

    def get_axis_names(self):
        return self.axes

    def get_rank_repr(self, rank, omit_axes=("data",), inner_sep="_",
                      outer_sep="-"):
        """String like ``pipe_00-model_01`` used in checkpoint names
        (reference ``topology.py:79``; consumed by ``engine.py:2406``)."""
        omit_axes = list(omit_axes)
        axes = [a for a in self.axes if a not in omit_axes]
        names = []
        for ax in axes:
            ax_rank = getattr(self.get_coord(rank), ax)
            names.append(f"{ax}{inner_sep}{ax_rank:02d}")
        return outer_sep.join(names)

    def get_dim(self, axis):
        """Extent of one axis (0 if absent — reference behavior)."""
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_coord(self, rank):
        """Coordinate namedtuple of a rank."""
        for coord, idx in self.mapping.items():
            if idx == rank:
                return coord
        raise ValueError(f"rank {rank} not in topology")

    def get_axis_comm_lists(self, axis):
        """Lists of ranks that would form communicators along ``axis``:
        all ranks that differ only in that axis.  Parity ``topology.py:131``."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        ranges = [range(self.get_dim(a)) for a in other_axes]
        for coord in itertools.product(*ranges):
            other = dict(zip(other_axes, coord))
            ranks = [self.get_rank(**{axis: i}, **other)
                     for i in range(self.get_dim(axis))]
            lists.append(ranks)
        return lists

    def filter_match(self, **filter_kwargs):
        """All ranks whose coordinates match the given axis values."""
        def matches(coord):
            return all(getattr(coord, ax) == val
                       for ax, val in filter_kwargs.items())
        return [rank for coord, rank in self.mapping.items() if matches(coord)]

    def get_axis_list(self, axis, idx):
        """Ranks with ``axis == idx``, sorted."""
        return sorted(self.filter_match(**{axis: idx}))

    def world_size(self):
        import math
        return math.prod(self.dims)

    def __str__(self):
        return str(self.mapping)


class PipeDataParallelTopology(ProcessTopology):
    """axes = ['pipe', 'data'] — hybrid PP×DP (parity ``topology.py:235``)."""

    def __init__(self, num_pp, num_dp):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """axes = ['pipe', 'data', 'model'] — 3D (parity ``topology.py:246``)."""

    def __init__(self, num_pp, num_mp, num_dp):
        super().__init__(axes=["pipe", "data", "model"],
                         dims=[num_pp, num_dp, num_mp])


class PipelineParallelGrid:
    """Axis bookkeeping for one rank in a PP×DP(×MP) grid.

    Parity: reference ``pipe/topology.py:252``, which builds NCCL groups for
    every axis.  Here we only keep the rank arithmetic — the actual
    communication rides the `jax` mesh — but the accessors match so checkpoint
    naming, schedule construction, and tests carry over.
    """

    def __init__(self, topology=None, process_group=None, world_size=None,
                 rank=0):
        if topology is None:
            assert world_size is not None
            ensure_divisibility(world_size, 2, "default grid wants even world")
            topology = PipeDataParallelTopology(2, world_size // 2)
        self._topo = topology
        self.global_rank = rank
        self.world_size = topology.world_size()

        self.data_parallel_size = max(topology.get_dim("data"), 1)
        self.pipe_parallel_size = max(topology.get_dim("pipe"), 1)
        self.model_parallel_size = max(topology.get_dim("model"), 1)
        self.slice_parallel_size = self.model_parallel_size

        coord = topology.get_coord(rank)
        self.stage_id = getattr(coord, "pipe", 0)
        self.data_parallel_id = getattr(coord, "data", 0)
        self.model_parallel_id = getattr(coord, "model", 0) \
            if "model" in topology.get_axis_names() else 0

        # peer lists per axis (the reference's group rank lists)
        self.pp_group = self._axis_peers("pipe")
        self.dp_group = self._axis_peers("data")
        self.mp_group = self._axis_peers("model") \
            if "model" in topology.get_axis_names() else [rank]

        # p2p neighbours on the pipe ring (reference p2p group pairs :373)
        self.p2p_matrix = self._build_p2p()

    def _axis_peers(self, axis):
        if axis not in self._topo.get_axis_names():
            return [self.global_rank]
        for lst in self._topo.get_axis_comm_lists(axis):
            if self.global_rank in lst:
                return lst
        return [self.global_rank]

    def _build_p2p(self):
        """(src → dst) pairs along the pipe axis ring for every pipe group."""
        pairs = []
        for lst in self._topo.get_axis_comm_lists("pipe"):
            n = len(lst)
            for i, src in enumerate(lst):
                pairs.append((src, lst[(i + 1) % n]))
        return pairs

    # ---- accessors used by engines/checkpoint naming (reference API) ------
    def get_stage_id(self):
        return self.stage_id

    def get_data_parallel_id(self):
        return self.data_parallel_id

    def get_pipe_parallel_rank(self):
        return self.stage_id

    def get_data_parallel_rank(self):
        return self.data_parallel_id

    def get_model_parallel_rank(self):
        return self.model_parallel_id

    def get_data_parallel_world_size(self):
        return self.data_parallel_size

    def get_pipe_parallel_world_size(self):
        return self.pipe_parallel_size

    def get_model_parallel_world_size(self):
        return self.model_parallel_size

    def get_global_rank(self):
        return self.global_rank

    def is_first_stage(self):
        return self.stage_id == 0

    def is_last_stage(self):
        return self.stage_id == self.pipe_parallel_size - 1

    def stage_to_global(self, stage_id, **kwargs):
        """Global rank of ``stage_id`` keeping this rank's other coords."""
        coord = self._topo.get_coord(self.global_rank)
        transform = coord._replace(pipe=stage_id, **kwargs)._asdict()
        return self._topo.get_rank(**transform)
