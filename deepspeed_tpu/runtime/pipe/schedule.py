"""Pipeline instruction IR and schedules.

Parity: reference ``deepspeed/runtime/pipe/schedule.py`` — ``PipeInstruction``
(:317) and subclasses (:336-460), ``TrainSchedule`` 1F1B (:182),
``InferenceSchedule`` (:129), ``num_pipe_buffers`` memory bound (:243).

Role on TPU: the SPMD pipeline engine (``pipe/engine.py``) executes the whole
schedule inside ONE jitted program (collective pipeline over the ``pipe`` mesh
axis), so the IR is not dispatched instruction-by-instruction on the hot path.
It is kept because (a) it is the precise, testable specification of what the
fused program computes — tick t at stage s processes micro-batch t-s — and
(b) schedule-dependent quantities (total tick count, buffer counts, memory
bounds) are derived from it by both the engine and the tests.
"""

from abc import ABC, abstractmethod


# --------------------------------------------------------------------------
# Instructions
# --------------------------------------------------------------------------
class PipeInstruction:
    """One step of work for one pipeline stage (parity ``schedule.py:317``)."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for key, val in kwargs.items():
            setattr(self, key, val)

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{self.name}({args})"

    def __eq__(self, other):
        return (self.__class__ is other.__class__
                and self.kwargs == other.kwargs)

    def __hash__(self):
        return hash((self.name, tuple(sorted(self.kwargs.items()))))


class OptimizerStep(PipeInstruction):
    """Apply the optimizer (all stages, end of batch)."""


class ReduceGrads(PipeInstruction):
    """Data-parallel gradient reduction."""


class ReduceTiedGrads(PipeInstruction):
    """All-reduce gradients of tied layers over their tie group."""


class BufferOpInstruction(PipeInstruction):
    """Instruction operating on a pipeline buffer slot."""

    def __init__(self, buffer_id, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    """First/last stage: pull a micro-batch from the data iterator."""


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


# --------------------------------------------------------------------------
# Schedules
# --------------------------------------------------------------------------
class PipeSchedule(ABC):
    """Yields lists of :class:`PipeInstruction` to run per step.

    Parity: reference ``schedule.py:24``.
    """

    def __init__(self, micro_batches, stages, stage_id):
        assert 0 <= stage_id < stages
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    @abstractmethod
    def steps(self):
        """Generator of instruction lists, one per schedule step."""

    def num_pipe_buffers(self):
        """Upper bound of concurrently-live activation buffers this stage
        needs (reference ``schedule.py:243``)."""
        return self.micro_batches

    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def _valid_micro_batch(self, micro_batch_id):
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id):
        return 0 <= stage_id < self.stages

    def __iter__(self):
        return iter(self.steps())


class InferenceSchedule(PipeSchedule):
    """Forward-only pipelined schedule (parity ``schedule.py:129``).

    Tick t: stage s forwards micro-batch ``t - s`` when valid.  Total ticks =
    ``micro_batches + stages - 1``.
    """

    def steps(self):
        total = self.micro_batches + self.stages - 1
        for t in range(total):
            cmds = []
            mb = t - self.stage_id
            if self._valid_micro_batch(mb):
                buf = mb % self.num_pipe_buffers()
                if self.is_first_stage or self.is_last_stage:
                    cmds.append(LoadMicroBatch(buf))
                if not self.is_first_stage:
                    cmds.append(RecvActivation(buf))
                cmds.append(ForwardPass(buf))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buf))
            yield cmds

    def num_pipe_buffers(self):
        """Two buffers suffice: one receiving while one computes."""
        return min(2, self.micro_batches)


class TrainSchedule(PipeSchedule):
    """1F1B (one-forward-one-backward) training schedule.

    Parity: reference ``schedule.py:182``.  Stage s runs
    ``warmup = stages - 1 - stage_id`` forwards, then alternates
    forward/backward in steady state, then drains the remaining backwards.
    Every stage issues exactly ``micro_batches`` forwards and backwards; the
    peak number of in-flight (forwarded, not yet backwarded) micro-batches is
    ``warmup + 1``, which bounds activation memory — this is the property the
    SPMD engine's remat policy reproduces.
    """

    def steps(self):
        warmup = min(self.stages - 1 - self.stage_id, self.micro_batches)
        fwd_id, bwd_id = 0, 0
        # Interleave: emit forwards until warmup satisfied, then strictly
        # alternate 1F1B until forwards exhausted, then drain backwards.
        while bwd_id < self.micro_batches:
            if fwd_id < self.micro_batches and (
                    fwd_id - bwd_id <= warmup or fwd_id == bwd_id):
                # forward step
                buf = fwd_id % self.num_pipe_buffers()
                cmds = []
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buf))
                else:
                    cmds.append(RecvActivation(buf))
                if self.is_last_stage:
                    # last stage also owns the labels for loss
                    cmds.append(LoadMicroBatch(buf))
                cmds.append(ForwardPass(buf))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buf))
                fwd_id += 1
                yield cmds
            else:
                # backward step
                buf = bwd_id % self.num_pipe_buffers()
                cmds = []
                if not self.is_last_stage:
                    cmds.append(RecvGrad(buf))
                cmds.append(BackwardPass(buf))
                if not self.is_first_stage:
                    cmds.append(SendGrad(buf))
                bwd_id += 1
                yield cmds
        # batch boundary: reductions + optimizer step (reference order,
        # ``pipe/engine.py:240-257,1162``)
        yield [ReduceTiedGrads(), ReduceGrads(), OptimizerStep()]

    def num_pipe_buffers(self):
        """Peak in-flight micro-batches (parity ``schedule.py:243``)."""
        buffers = min(self.stages - self.stage_id, self.micro_batches)
        return max(2, buffers)


class DataParallelSchedule(PipeSchedule):
    """Degenerate single-stage schedule: plain grad-accumulated DP
    (parity: reference ``schedule.py`` same-named class)."""

    def steps(self):
        for mb in range(self.micro_batches):
            cmds = [LoadMicroBatch(0), ForwardPass(0), BackwardPass(0)]
            if mb == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds

    def num_pipe_buffers(self):
        return 1
