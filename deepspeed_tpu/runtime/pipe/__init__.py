"""Pipeline-parallel subsystem (parity: reference ``deepspeed/runtime/pipe/``)."""

from .module import PipelineModule, LayerSpec, TiedLayerSpec
from .topology import (ProcessTopology, PipeDataParallelTopology,
                       PipeModelDataParallelTopology, PipelineParallelGrid)
from .schedule import (PipeSchedule, TrainSchedule, InferenceSchedule,
                       DataParallelSchedule, PipeInstruction, OptimizerStep,
                       ReduceGrads, ReduceTiedGrads, LoadMicroBatch,
                       ForwardPass, BackwardPass, SendActivation,
                       RecvActivation, SendGrad, RecvGrad)
