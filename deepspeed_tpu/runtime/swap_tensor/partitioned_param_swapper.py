"""NVMe parameter swapper (ZeRO-Infinity param tier).

Parity: reference ``runtime/swap_tensor/partitioned_param_swapper.py:37``
(``AsyncPartitionedParameterSwapper``): parameter payloads live in per-id
files under ``<nvme_path>/zero_stage_3/<dtype>params/rank<r>/``; a bounded
pool of aligned host buffers services swap-in (async reads ahead of use)
and swap-out (async writes after release).  On TPU the "param" is a host
numpy payload that the engine ``device_put``s when the layer block needs
it (reference: CUDA pinned buffer + H2D copy).
"""

import os

import numpy as np

from .utils import (SwapBufferPool, acquire_swap_buffer, aligned_numel,
                    make_swap_path, swap_in_tensors, swap_out_tensors)
from ...utils.logging import logger
from ...utils.retry import RetryPolicy


class AsyncPartitionedParameterSwapper:
    def __init__(self, ds_config_aio, nvme_path, dtype=np.float32,
                 buffer_count=5, buffer_numel=int(1e8), rank=0, retry=None):
        from .utils import make_aio_handle
        self.aio_read_handle = make_aio_handle(ds_config_aio)
        self.aio_write_handle = make_aio_handle(ds_config_aio)
        self.retry = retry or RetryPolicy()
        self.dtype = np.dtype(dtype)
        self.swap_folder = os.path.join(
            nvme_path, "zero_stage_3", f"{self.dtype.name}params", f"rank{rank}")
        os.makedirs(self.swap_folder, exist_ok=True)
        self.buffer_numel = aligned_numel(buffer_numel, self.dtype.itemsize)
        self._pool = SwapBufferPool(buffer_count, self.buffer_numel, self.dtype)
        self._id_to_numel = {}       # swapped param id -> numel
        self._id_to_buffer = {}      # swapped-in id -> SwapBuffer
        self._inflight_reads = []    # ids with reads in flight
        self._inflight_writes = []   # buffers with writes in flight

    # ------------------------------------------------------------------ paths
    def _path(self, param_id):
        return make_swap_path(self.swap_folder, f"param_{param_id}")

    def swappable(self, numel):
        return numel * self.dtype.itemsize >= 1  # all params swappable here

    # --------------------------------------------------------------- swap out
    def swap_out(self, param_id, array: np.ndarray):
        """Write one param payload to NVMe and release its host buffer."""
        flat = np.ascontiguousarray(array, self.dtype).ravel()
        assert flat.size <= self.buffer_numel, \
            f"param {param_id} ({flat.size}) exceeds buffer_size {self.buffer_numel}"
        self._id_to_numel[param_id] = flat.size
        # all buffers may be in flight: drain pending writes between bounded
        # backoff attempts (utils.acquire_swap_buffer)
        buf = acquire_swap_buffer(self._pool, drain=self.synchronize_writes,
                                  retry=self.retry)
        try:
            np.copyto(buf.view(flat.size), flat)
            swap_out_tensors(self.aio_write_handle, [buf.view(flat.size)],
                             [self._path(param_id)], retry=self.retry)
        except Exception:
            # a submit that exhausted its retries must not leak the buffer:
            # it is not in _inflight_writes yet, so nothing else can free it
            self._pool.release(buf)
            raise
        self._inflight_writes.append(buf)
        # drop any stale swapped-in copy
        old = self._id_to_buffer.pop(param_id, None)
        if old is not None:
            self._pool.release(old)

    def synchronize_writes(self):
        if self._inflight_writes:
            self.aio_write_handle.wait()
            for b in self._inflight_writes:
                self._pool.release(b)
            self._inflight_writes = []

    # ---------------------------------------------------------------- swap in
    def swap_in(self, param_ids, async_op=False):
        """Begin reads for the given ids into pool buffers (prefetch when
        ``async_op``; otherwise blocks until resident)."""
        self.synchronize_writes()
        for pid in param_ids:
            if pid in self._id_to_buffer or pid in self._inflight_reads:
                continue
            numel = self._id_to_numel[pid]
            buf = acquire_swap_buffer(self._pool, retry=self.retry)
            try:
                swap_in_tensors(self.aio_read_handle, [buf.view(numel)],
                                [self._path(pid)], retry=self.retry)
            except Exception:
                self._pool.release(buf)
                raise
            self._id_to_buffer[pid] = buf
            self._inflight_reads.append(pid)
        if not async_op:
            self.synchronize_reads()

    def synchronize_reads(self):
        if self._inflight_reads:
            self.aio_read_handle.wait()
            self._inflight_reads = []

    def get_buffer(self, param_id):
        """Host array for a swapped-in param (must be resident)."""
        assert param_id in self._id_to_buffer, f"param {param_id} not swapped in"
        assert param_id not in self._inflight_reads, \
            f"param {param_id} read not synchronized"
        return self._id_to_buffer[param_id].view(self._id_to_numel[param_id])

    def release(self, param_ids):
        """Release host buffers (payload stays on NVMe)."""
        for pid in param_ids:
            buf = self._id_to_buffer.pop(pid, None)
            if buf is not None:
                self._pool.release(buf)

    def available_swap_in_buffers(self):
        return sum(1 for b in self._pool.buffers if not b.in_use)
