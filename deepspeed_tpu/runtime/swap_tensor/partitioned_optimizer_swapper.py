"""NVMe optimizer-state swapper.

Parity: reference ``runtime/swap_tensor/partitioned_optimizer_swapper.py:27``
(``PartitionedOptimizerSwapper``): the fp32 optimizer state of each ZeRO
sub-group (master slice + Adam moments) lives on NVMe between steps; the
step swaps a sub-group in, updates it, and swaps it back out.  The
pipelined variant overlaps the next sub-group's read with the current
sub-group's compute (``pipelined_optimizer_swapper.py``).
"""

import os

import numpy as np

from .utils import aio_submit_read, aio_submit_write, make_swap_path
from ...utils.logging import logger
from ...utils.retry import RetryPolicy


class OptimizerSwapper:
    """Base: per-(group, tensor-name) files, sync swap in/out.  Submits go
    through the shared bounded-backoff retry helpers (``utils/retry.py``)."""

    def __init__(self, swap_config, aio_config, nvme_path, rank=0, retry=None):
        from .utils import make_aio_handle
        self.aio_handle = make_aio_handle(aio_config)
        self.retry = retry or RetryPolicy()
        self.swap_folder = os.path.join(nvme_path, "zero_stage_optimizer",
                                        f"rank{rank}")
        os.makedirs(self.swap_folder, exist_ok=True)
        self._numel = {}   # (group, name) -> numel

    def _path(self, group, name):
        return make_swap_path(self.swap_folder, f"group{group}_{name}")

    def swap_out_group(self, group, tensors: dict, async_op=False):
        """Write {name: flat fp32 array} for one sub-group."""
        for name, arr in tensors.items():
            flat = np.ascontiguousarray(arr, np.float32).ravel()
            self._numel[(group, name)] = flat.size
            aio_submit_write(self.aio_handle, flat, self._path(group, name),
                             retry=self.retry)
        if not async_op:
            self.aio_handle.wait()

    def swap_in_group(self, group, names, out: dict = None, async_op=False):
        """Read the named tensors of one sub-group into (new or provided)
        host arrays; returns {name: array}."""
        out = out if out is not None else {}
        for name in names:
            numel = self._numel[(group, name)]
            if name not in out or out[name].size != numel:
                out[name] = np.zeros(numel, np.float32)
            aio_submit_read(self.aio_handle, out[name],
                            self._path(group, name), retry=self.retry)
        if not async_op:
            self.aio_handle.wait()
        return out

    def wait(self):
        self.aio_handle.wait()


class PartitionedOptimizerSwapper(OptimizerSwapper):
    """Synchronous per-group swap (reference class of the same name)."""


class PipelinedOptimizerSwapper(OptimizerSwapper):
    """Overlapped variant (reference ``pipelined_optimizer_swapper.py``):
    separate read/write queues so group g+1's read and group g-1's write
    proceed while group g computes."""

    def __init__(self, swap_config, aio_config, nvme_path, rank=0, retry=None):
        super().__init__(swap_config, aio_config, nvme_path, rank, retry=retry)
        from .utils import make_aio_handle
        self.aio_read_handle = make_aio_handle(aio_config)
        self._read_bufs = {}   # group -> {name: array} prefetch in flight
        self._reads_pending = set()

    def prefetch_group(self, group, names):
        if group in self._read_bufs or group in self._reads_pending:
            return
        bufs = {}
        for name in names:
            numel = self._numel[(group, name)]
            bufs[name] = np.zeros(numel, np.float32)
            aio_submit_read(self.aio_read_handle, bufs[name],
                            self._path(group, name), retry=self.retry)
        self._read_bufs[group] = bufs
        self._reads_pending.add(group)

    def get_group(self, group, names):
        """Prefetched tensors if available, else a synchronous read."""
        if group in self._read_bufs:
            if self._reads_pending:
                self.aio_read_handle.wait()
                self._reads_pending.clear()
            return self._read_bufs.pop(group)
        return self.swap_in_group(group, names)

    def swap_out_group(self, group, tensors, async_op=True):
        # keep copies so callers may reuse their arrays immediately
        staged = {n: np.array(a, np.float32).ravel() for n, a in tensors.items()}
        for name, flat in staged.items():
            self._numel[(group, name)] = flat.size
            aio_submit_write(self.aio_handle, flat, self._path(group, name),
                             retry=self.retry)
        if not async_op:
            self.aio_handle.wait()
