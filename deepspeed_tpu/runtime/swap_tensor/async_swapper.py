"""Double-buffered async tensor writer.

Parity: reference ``runtime/swap_tensor/async_swapper.py``
(``AsyncTensorSwapper``, 173 LoC): tensors queued for swap-out are copied
into an aligned buffer and written asynchronously while the caller keeps
computing; ``add_buffers``/``flush`` bracket a swap-out burst.
"""

import numpy as np

from .utils import (SwapBufferPool, acquire_swap_buffer, aligned_numel,
                    swap_out_tensors)
from ...utils.logging import logger


class AsyncTensorSwapper:
    def __init__(self, aio_handle, numel_alignment=None,
                 buffer_count=2, buffer_numel=None, retry=None):
        # (a `timers=` parameter used to be accepted and silently ignored
        # — a dead started-but-never-read path; swap timing now comes
        # from the monitor spans around the offload host half)
        self.aio_handle = aio_handle
        from ...utils.retry import RetryPolicy
        self.retry = retry or RetryPolicy()
        self.buffer_count = max(2, buffer_count)
        self._pool = None
        self._buffer_numel = buffer_numel
        self._pending = []          # buffers with writes in flight
        self.swapped_bytes = 0

    def _ensure_pool(self, numel, dtype):
        need = aligned_numel(numel, np.dtype(dtype).itemsize)
        if self._pool is None or self._buffer_numel is None \
                or need > self._buffer_numel \
                or self._pool.buffers[0].data.dtype != np.dtype(dtype):
            # grow-on-demand double buffer (reference allocates from the
            # engine's pinned aio buffers; host RAM here); re-made on dtype
            # change — np.copyto into a mismatched pool would silently cast
            self._flush_pending()
            self._buffer_numel = need
            self._pool = SwapBufferPool(self.buffer_count, need, dtype)

    def swap_out(self, array: np.ndarray, path: str):
        """Queue one array for async write; returns once the data is staged
        (the write itself completes at flush())."""
        flat = np.ascontiguousarray(array).ravel()
        self._ensure_pool(flat.size, flat.dtype)
        # pool exhaustion drains in-flight writes between bounded backoff
        # attempts (shared idiom: utils.acquire_swap_buffer)
        buf = acquire_swap_buffer(self._pool, drain=self._flush_pending,
                                  retry=self.retry)
        try:
            view = buf.view(flat.size)
            np.copyto(view, flat)
            swap_out_tensors(self.aio_handle, [view], [path],
                             retry=self.retry)
        except Exception:
            self._pool.release(buf)
            raise
        self._pending.append(buf)
        self.swapped_bytes += flat.nbytes

    def add_buffers(self, arrays, paths):
        for a, p in zip(arrays, paths):
            self.swap_out(a, p)

    def _flush_pending(self):
        if self._pending:
            self.aio_handle.wait()
            for b in self._pending:
                self._pool.release(b)
            self._pending = []

    def flush(self):
        """Wait for every queued write to hit storage."""
        self._flush_pending()

    def release_buffers(self):
        self._flush_pending()
        self._pool = None
        self._buffer_numel = None
