"""Swap-buffer plumbing shared by the NVMe swappers.

Parity: reference ``runtime/swap_tensor/utils.py`` (``swap_in_tensors`` /
``swap_out_tensors`` submitting one async op per tensor, ``MIN_AIO_BYTES`` /
``AIO_ALIGNED_BYTES`` sizing rules) and the pinned-buffer pool in
``optimizer_utils.py`` — on the TPU host the buffers are plain aligned numpy
arrays (no CUDA pinned memory; the device transfer is a ``device_put``).
"""

import os

import numpy as np

from ... import fault
from ...utils.retry import RetryPolicy, retry_call

MIN_AIO_BYTES = 1024 ** 2
AIO_ALIGNED_BYTES = 1024


def swappable_numel(numel, itemsize=4):
    """A tensor is worth swapping only above MIN_AIO_BYTES (reference
    ``swap_tensor/utils.py MIN_AIO_BYTES`` gate)."""
    return numel * itemsize >= MIN_AIO_BYTES


def aligned_numel(numel, itemsize=4):
    """Round numel up so the byte count is AIO_ALIGNED_BYTES-aligned."""
    align = AIO_ALIGNED_BYTES // itemsize
    return ((numel + align - 1) // align) * align


def aio_submit_read(aio_handle, buf, path, retry=None):
    """Submit one async read with bounded-backoff retry on transient submit
    failures (queue momentarily full, EAGAIN, injected faults)."""
    def _submit():
        fault.site("aio.submit", path=path)
        return aio_handle.async_pread(buf, path)
    return retry_call(_submit, policy=retry or RetryPolicy(),
                      describe=f"aio read submit {path}")


def aio_submit_write(aio_handle, buf, path, retry=None):
    """Submit one async write with bounded-backoff retry."""
    def _submit():
        fault.site("aio.submit", path=path)
        return aio_handle.async_pwrite(buf, path)
    return retry_call(_submit, policy=retry or RetryPolicy(),
                      describe=f"aio write submit {path}")


def swap_in_tensors(aio_handle, buffers, paths, retry=None):
    """Submit one async read per (buffer, path); caller waits on the handle."""
    for buf, path in zip(buffers, paths):
        aio_submit_read(aio_handle, buf, path, retry=retry)


def swap_out_tensors(aio_handle, buffers, paths, retry=None):
    """Submit one async write per (buffer, path)."""
    for buf, path in zip(buffers, paths):
        aio_submit_write(aio_handle, buf, path, retry=retry)


class SwapBuffer:
    """One reusable aligned host buffer with a free/busy flag."""

    def __init__(self, numel, dtype=np.float32):
        self.data = np.zeros(aligned_numel(numel, np.dtype(dtype).itemsize),
                             dtype)
        self.in_use = False

    def view(self, numel):
        return self.data[:numel]


class SwapBufferPool:
    """Fixed pool of swap buffers (reference ``SwapBufferPool``: pinned
    buffers handed out round-robin to in-flight swaps)."""

    def __init__(self, count, numel, dtype=np.float32):
        self.buffers = [SwapBuffer(numel, dtype) for _ in range(count)]

    def get(self):
        for b in self.buffers:
            if not b.in_use:
                b.in_use = True
                return b
        raise RuntimeError("no free swap buffer (increase buffer_count)")

    def release(self, buf):
        buf.in_use = False

    def release_all(self):
        for b in self.buffers:
            b.in_use = False


def acquire_swap_buffer(pool, drain=None, retry=None):
    """Bounded-backoff acquisition of a free swap buffer.

    Replaces the single drain-and-retry on pool exhaustion: each attempt
    first drains pending async writes (``drain``) so their buffers return to
    the pool, then retries with exponential backoff — an in-flight write
    completing a moment later is a transient condition, not a crash.  Shared
    by the param and optimizer swappers.

    Without a ``drain`` nothing can free a buffer between attempts, so
    exhaustion is a logic error (buffer leak / undersized pool) and fails
    fast instead of sleeping through a hopeless backoff schedule.
    """
    def _get():
        try:
            return pool.get()
        except RuntimeError:
            if drain is None:
                raise
            drain()
            return pool.get()
    if drain is None:
        return _get()
    base = retry or RetryPolicy()
    # the RuntimeError-augmented clone is invariant per policy; cache it so
    # the per-parameter swap hot path doesn't rebuild a policy (and copy
    # RNG state) on every acquisition
    policy = getattr(base, "_buffer_acquire_policy", None)
    if policy is None:
        policy = base.clone(
            retriable_types=(RuntimeError,) + base.retriable_types)
        base._buffer_acquire_policy = policy
    return retry_call(_get, policy=policy, describe="acquire_swap_buffer")


def make_swap_path(folder, name):
    os.makedirs(folder, exist_ok=True)
    return os.path.join(folder, f"{name}.swp")


def make_aio_handle(aio_config):
    """One AsyncIOHandle from the ``aio`` config dict (shared defaults —
    reference ``aio`` config keys, ``runtime/constants.py AIO_DEFAULT_DICT``)."""
    from ...ops.aio import AsyncIOHandle
    aio = dict(aio_config or {})
    return AsyncIOHandle(
        block_size=aio.get("block_size", 1048576),
        queue_depth=aio.get("queue_depth", 8),
        single_submit=aio.get("single_submit", False),
        overlap_events=aio.get("overlap_events", True),
        thread_count=aio.get("thread_count", 1))
