"""Sparse (indices, values) tensor for embedding-style gradients.

Parity: reference ``deepspeed/runtime/sparse_tensor.py`` (``SparseTensor``,
70 LoC) + the engine's ``sparse_allreduce_no_retain`` (``engine.py:2227``):
torch's sparse embedding grads carry (indices, values) and the engine
all-gathers both across DP ranks instead of densifying.

JAX autodiff produces dense gradients, so here the class serves the
framework's sparse-reduction path: densify-free averaging of row-sparse
updates via index/value all_gathers inside ``shard_map``.
"""
# dstpu: disable-file=DSTPU102 (reviewed: the sparse-reduction wire format
# is an explicitly scheduled gather protocol, not ad-hoc comms)

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


class SparseTensor:
    """Row-sparse view of a 2-D tensor: ``values[i]`` is row ``indices[i]``."""

    def __init__(self, indices, values, dense_size):
        self.indices = jnp.asarray(indices, jnp.int32)
        self.values = jnp.asarray(values)
        self.dense_size = tuple(dense_size)

    @classmethod
    def from_dense(cls, dense, max_rows: Optional[int] = None, nz=None):
        """Extract the nonzero rows (static count = ``max_rows``; XLA needs
        static shapes, so the densest possible case bounds the buffer).
        ``nz`` — optional precomputed per-row nonzero mask (saves a second
        full scan when the caller already needed it)."""
        dense = jnp.asarray(dense)
        if nz is None:
            nz = jnp.any(dense != 0, axis=tuple(range(1, dense.ndim)))
        k = max_rows if max_rows is not None else dense.shape[0]
        # Integer keys: every nonzero row outranks every zero row, and
        # earlier rows outrank later ones — exactly (no float-epsilon
        # tie-break, which is unrepresentable near 1.0 in fp32), so top_k
        # returns the FIRST k nonzero row indices deterministically.
        rows = dense.shape[0]
        keys = nz.astype(jnp.int32) * rows + jnp.arange(rows, 0, -1)
        _, idx = lax.top_k(keys, k)
        idx = jnp.sort(idx)
        vals = dense[idx] * nz[idx].astype(dense.dtype)[:, None]
        return cls(idx, vals, dense.shape)

    def to_dense(self):
        out = jnp.zeros(self.dense_size, self.values.dtype)
        return out.at[self.indices].add(self.values)

    def add(self, other: "SparseTensor"):
        assert self.dense_size == other.dense_size
        return SparseTensor(jnp.concatenate([self.indices, other.indices]),
                            jnp.concatenate([self.values, other.values]),
                            self.dense_size)

    def sparse_size(self):
        return int(self.indices.shape[0]) * int(np.prod(self.values.shape[1:]))

    def __str__(self):
        return (f"SparseTensor(indices={self.indices.shape}, "
                f"values={self.values.shape}, dense_size={self.dense_size})")


def sparse_allreduce(st: SparseTensor, axis_name: str) -> SparseTensor:
    """Average a row-sparse gradient across an axis WITHOUT densifying the
    wire format (parity: engine ``sparse_allreduce_no_retain``,
    ``engine.py:2227-2280``: all_gather indices + values, concatenate).
    Call inside ``shard_map``.
    """
    n = lax.axis_size(axis_name)
    idx = lax.all_gather(st.indices, axis_name, axis=0, tiled=True)
    vals = lax.all_gather(st.values, axis_name, axis=0, tiled=True)
    return SparseTensor(idx, vals / n, st.dense_size)
