"""Activation checkpointing — remat policies instead of autograd surgery.

Parity: reference ``runtime/activation_checkpointing/checkpointing.py`` —
``CheckpointFunction`` (:493), ``checkpoint()`` (:743), ``configure()``
(:825), ``CudaRNGStatesTracker`` (:122).  The reference re-implements
torch's checkpoint autograd.Function with four extras: activation
PARTITIONING across TP ranks (:367), CPU checkpointing (:480), contiguous
buffers, and profiling.

TPU re-design (SURVEY.md §7: "memory/recompute switches map to JAX remat
policies rather than kernel variants"):

- ``checkpoint(fn, *args)`` = ``jax.checkpoint`` — XLA rematerializes the
  wrapped region in backward; no saved-tensor bookkeeping.
- ``partition_activations`` → the checkpoint *inputs* (what remat saves) get
  a sharding constraint over the ``tensor`` axis; the SPMD partitioner emits
  the scatter/gather pair the reference codes by hand
  (``partition_activations`` :367 / ``gather_partitioned_activations`` :259).
- ``cpu_checkpointing`` → remat policy offloading saved residuals to
  ``pinned_host`` memory via ``jax.checkpoint_policies
  .save_and_offload_only_these_names`` when named checkpoints are used;
  plain regions fall back to full recompute (which uses no more memory).
- ``contiguous_memory_optimization`` → no-op: XLA's allocator packs live
  buffers already; kept as an accepted flag for config parity.
- The CUDA RNG state tracker becomes an explicit named-PRNGKey tracker:
  JAX rngs are values, so "fork" hands out a fresh fold of the named key.
"""

import contextlib
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ...parallel.mesh import maybe_constrain
from ...utils.logging import logger

# module configuration state (parity: reference module globals :30-56)
_enabled = False
mpu = None
num_layers = None
PARTITION_ACTIVATIONS = False
CPU_CHECKPOINT = False
CONTIGUOUS_CHECKPOINTING = False
SYNCHRONIZE = False
PROFILE_TIME = False


# ------------------------------------------------------------ rng tracker
class RNGStatesTracker:
    """Named PRNGKey tracker (parity: ``CudaRNGStatesTracker``, :122).

    The reference snapshots/restores the CUDA RNG state so dropout draws the
    same mask in recompute; with JAX keys-as-values remat replays the same
    key automatically — the tracker's remaining job is giving model-parallel
    regions a distinct, named stream.
    """

    def __init__(self):
        self.states_ = {}

    def reset(self):
        self.states_ = {}

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name, seed):
        if name in self.states_:
            raise Exception(f"seed {name} already exists")
        for existing in self.states_.values():
            if int(existing[1]) == int(seed):
                raise Exception(f"seed {seed} already exists")
        self.states_[name] = [jax.random.PRNGKey(seed), seed, 0]

    @contextlib.contextmanager
    def fork(self, name="model-parallel-rng"):
        """Yields a fresh key from the named stream (the reference swaps the
        global CUDA rng state; here the caller receives the key value)."""
        if name not in self.states_:
            raise Exception(f"rng state {name} is not added")
        key, seed, count = self.states_[name]
        self.states_[name] = [key, seed, count + 1]
        yield jax.random.fold_in(key, count)


_RNG_TRACKER = RNGStatesTracker()


def get_rng_tracker():
    """Parity: reference ``get_cuda_rng_tracker`` (:193)."""
    return _RNG_TRACKER


# alias keeping the reference's public name importable
get_cuda_rng_tracker = get_rng_tracker


def model_parallel_seed(seed, tensor_axis_index: int = 0):
    """Parity: ``model_parallel_cuda_manual_seed`` (:198) — data-parallel
    stream gets ``seed``, model-parallel stream ``seed + 2718 + tp_rank``."""
    _RNG_TRACKER.reset()
    _RNG_TRACKER.add("data-parallel-rng", seed)
    _RNG_TRACKER.add("model-parallel-rng", seed + 2718 + tensor_axis_index)


model_parallel_cuda_manual_seed = model_parallel_seed


# ------------------------------------------------------------- checkpoint
def _shard_leaf(x):
    """Shard a saved activation's largest even axis over ``tensor``
    (reference ``partition_activations`` :367 splits flat activations across
    the TP group)."""
    if not hasattr(x, "ndim") or x.ndim == 0:
        return x
    from jax.sharding import PartitionSpec as P
    am = jax.sharding.get_abstract_mesh()
    if am.empty or "tensor" not in am.axis_names:
        return x
    tp = dict(zip(am.axis_names, am.axis_sizes)).get("tensor", 1)
    if tp <= 1:
        return x
    for axis in np.argsort([-d for d in x.shape]):
        if x.shape[axis] % tp == 0:
            spec = [None] * x.ndim
            spec[int(axis)] = "tensor"
            return maybe_constrain(x, P(*spec))
    return x


def checkpoint(function, *args):
    """Checkpoint (remat) a model region (parity: reference ``checkpoint``
    :743 → ``CheckpointFunction`` :493)."""
    fn = function
    if PARTITION_ACTIVATIONS:
        inner = fn

        def fn(*a):
            a = jax.tree_util.tree_map(_shard_leaf, a)
            return inner(*a)

    policy = None
    if CPU_CHECKPOINT:
        # offload whatever the model marked with jax.ad_checkpoint.checkpoint_name
        try:
            policy = jax.checkpoint_policies.save_and_offload_only_these_names(
                names_which_can_be_saved=[],
                names_which_can_be_offloaded=["ckpt"],
                offload_src="device", offload_dst="pinned_host")
        except Exception:  # backend without pinned_host support
            policy = None
    ck = jax.checkpoint(fn, policy=policy) if policy is not None else jax.checkpoint(fn)
    return ck(*args)


def checkpoint_wrapper(function):
    """Decorator form used by layer libraries."""
    def wrapped(*args):
        return checkpoint(function, *args)
    return wrapped


# ----------------------------------------------------------- configuration
def partition_activations_in_checkpoint(partition_activation):
    """Parity: reference :755."""
    global PARTITION_ACTIVATIONS
    PARTITION_ACTIVATIONS = partition_activation
    logger.info(f"**************Partition Activations {PARTITION_ACTIVATIONS}************")


def set_num_layers(nlayers):
    global num_layers
    num_layers = nlayers


def reset():
    """Parity: reference :768 (frees contiguous buffers — stateless here)."""


def _configure_defaults():
    global PARTITION_ACTIVATIONS, CONTIGUOUS_CHECKPOINTING, num_layers, \
        CPU_CHECKPOINT, SYNCHRONIZE, PROFILE_TIME, _enabled
    PARTITION_ACTIVATIONS = False
    CONTIGUOUS_CHECKPOINTING = False
    num_layers = None
    CPU_CHECKPOINT = False
    SYNCHRONIZE = False
    PROFILE_TIME = False
    _enabled = True


def _configure_using_config_file(config, mpu=None):
    from ..config import DeepSpeedConfig
    global PARTITION_ACTIVATIONS, CONTIGUOUS_CHECKPOINTING, num_layers, \
        CPU_CHECKPOINT, SYNCHRONIZE, PROFILE_TIME
    c = DeepSpeedConfig(config).activation_checkpointing
    PARTITION_ACTIVATIONS = c.partition_activations
    CONTIGUOUS_CHECKPOINTING = c.contiguous_memory_optimization
    num_layers = c.number_checkpoints
    CPU_CHECKPOINT = c.cpu_checkpointing
    SYNCHRONIZE = c.synchronize_checkpoint_boundary
    PROFILE_TIME = c.profile


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None):
    """Parity: reference ``configure`` (:825) — same argument surface."""
    global mpu, num_layers, PARTITION_ACTIVATIONS, CONTIGUOUS_CHECKPOINTING, \
        CPU_CHECKPOINT, SYNCHRONIZE, PROFILE_TIME
    _configure_defaults()
    if mpu_ is not None:
        mpu = mpu_
    if deepspeed_config is not None:
        _configure_using_config_file(deepspeed_config, mpu=mpu)
    if partition_activations is not None:
        PARTITION_ACTIVATIONS = partition_activations
    if contiguous_checkpointing is not None:
        CONTIGUOUS_CHECKPOINTING = contiguous_checkpointing
    if num_checkpoints is not None:
        num_layers = num_checkpoints
    if checkpoint_in_cpu is not None:
        CPU_CHECKPOINT = checkpoint_in_cpu
    if synchronize is not None:
        SYNCHRONIZE = synchronize
    if profile is not None:
        PROFILE_TIME = profile
    if CONTIGUOUS_CHECKPOINTING:
        assert PARTITION_ACTIVATIONS, \
            "Contiguous Checkpointing is only available with partitioned activations."
        assert num_layers is not None, \
            "Must specify the number of layers with contiguous memory checkpointing"


def is_configured():
    """Parity: reference :907."""
    return _enabled
