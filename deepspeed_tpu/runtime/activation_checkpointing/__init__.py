"""Activation checkpointing (remat). Parity: reference
``deepspeed/runtime/activation_checkpointing/``."""

from . import checkpointing

__all__ = ["checkpointing"]
