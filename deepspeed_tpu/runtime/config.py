"""DeepSpeed-compatible JSON config system.

Parity: reference ``deepspeed/runtime/config.py:791`` (``DeepSpeedConfig``) — same
JSON document schema (SURVEY.md §8.1), same batch-size arithmetic invariant
``train_batch_size == micro_batch * gradient_accumulation_steps * dp_world_size``
(reference ``config.py:980 _batch_assertion``).

TPU-native differences:
- ``world_size`` means the data-parallel extent of the device mesh
  (``data * fsdp`` axes), not an NCCL process count.
- New optional ``mesh`` section declares mesh axis sizes
  ``{"data": -1, "fsdp": 1, "tensor": 1, "expert": 1, "pipe": 1, "seq": 1}``;
  ``-1`` means "absorb remaining devices".
- ``fp16`` on TPU is honored (loss scaling + overflow skip implemented), but the
  recommended precision is ``bf16`` which needs no scaler.
"""

import logging

from . import constants as C
from .config_utils import get_scalar_param, get_dict_param, load_config_dict
from .zero.config import DeepSpeedZeroConfig
from ..utils.logging import logger


class DeepSpeedConfigError(Exception):
    pass


class DeepSpeedConfigWriter:
    """Minimal .load/.data holder used by autotuner experiments."""

    def __init__(self, data=None):
        self.data = {} if data is None else data

    def add_config(self, key, value):
        self.data[key] = value

    def load_config(self, filename):
        self.data = load_config_dict(filename)

    def write_config(self, filename):
        import json
        with open(filename, "w") as f:
            # autotuner experiment CONFIG, not a metric stream
            json.dump(self.data, f, indent=4)  # dstpu: disable=DSTPU104


class DeepSpeedFP16Config:
    def __init__(self, param_dict):
        fp16_dict = get_dict_param(param_dict, C.FP16, {})
        self.enabled = get_scalar_param(fp16_dict, C.FP16_ENABLED, C.FP16_ENABLED_DEFAULT)
        self.loss_scale = get_scalar_param(fp16_dict, C.FP16_LOSS_SCALE,
                                           C.FP16_LOSS_SCALE_DEFAULT)
        self.initial_scale_power = get_scalar_param(fp16_dict, C.FP16_INITIAL_SCALE_POWER,
                                                    C.FP16_INITIAL_SCALE_POWER_DEFAULT)
        self.loss_scale_window = get_scalar_param(fp16_dict, C.FP16_LOSS_SCALE_WINDOW,
                                                  C.FP16_LOSS_SCALE_WINDOW_DEFAULT)
        self.hysteresis = get_scalar_param(fp16_dict, C.FP16_HYSTERESIS,
                                           C.FP16_HYSTERESIS_DEFAULT)
        self.min_loss_scale = get_scalar_param(fp16_dict, C.FP16_MIN_LOSS_SCALE,
                                               C.FP16_MIN_LOSS_SCALE_DEFAULT)
        self.master_weights_and_grads = get_scalar_param(
            fp16_dict, "master_weights_and_grads",
            get_scalar_param(param_dict, C.FP16_MASTER_WEIGHTS_AND_GRADS,
                             C.FP16_MASTER_WEIGHTS_AND_GRADS_DEFAULT))

    @property
    def dynamic_loss_scale(self):
        return self.loss_scale == 0


class DeepSpeedBF16Config:
    def __init__(self, param_dict):
        bf16_dict = get_dict_param(param_dict, C.BFLOAT16,
                                   get_dict_param(param_dict, C.BFLOAT16_OLD, {}))
        self.enabled = get_scalar_param(bf16_dict, C.BFLOAT16_ENABLED,
                                        C.BFLOAT16_ENABLED_DEFAULT)


class DeepSpeedActivationCheckpointingConfig:
    """Parity: reference ``runtime/activation_checkpointing/config.py``.

    TPU mapping: ``partition_activations`` → shard the remat'd residual stream on
    the tensor axis; ``cpu_checkpointing`` → host offload of checkpoints via
    ``jax.device_put`` donation; contiguous-memory keys accepted as no-ops (XLA
    owns layout).
    """

    def __init__(self, param_dict):
        act_dict = get_dict_param(param_dict, C.ACTIVATION_CHECKPOINTING, {})
        self.partition_activations = get_scalar_param(act_dict, "partition_activations", False)
        self.contiguous_memory_optimization = get_scalar_param(
            act_dict, "contiguous_memory_optimization", False)
        self.cpu_checkpointing = get_scalar_param(act_dict, "cpu_checkpointing", False)
        self.number_checkpoints = get_scalar_param(act_dict, "number_checkpoints", None)
        self.synchronize_checkpoint_boundary = get_scalar_param(
            act_dict, "synchronize_checkpoint_boundary", False)
        self.profile = get_scalar_param(act_dict, "profile", False)


class DeepSpeedFlopsProfilerConfig:
    def __init__(self, param_dict):
        prof_dict = get_dict_param(param_dict, C.FLOPS_PROFILER, {})
        self.enabled = get_scalar_param(prof_dict, C.FLOPS_PROFILER_ENABLED,
                                        C.FLOPS_PROFILER_ENABLED_DEFAULT)
        self.profile_step = get_scalar_param(prof_dict, C.FLOPS_PROFILER_PROFILE_STEP,
                                             C.FLOPS_PROFILER_PROFILE_STEP_DEFAULT)
        self.module_depth = get_scalar_param(prof_dict, C.FLOPS_PROFILER_MODULE_DEPTH,
                                             C.FLOPS_PROFILER_MODULE_DEPTH_DEFAULT)
        self.top_modules = get_scalar_param(prof_dict, C.FLOPS_PROFILER_TOP_MODULES,
                                            C.FLOPS_PROFILER_TOP_MODULES_DEFAULT)
        self.detailed = get_scalar_param(prof_dict, C.FLOPS_PROFILER_DETAILED,
                                         C.FLOPS_PROFILER_DETAILED_DEFAULT)
        self.output_file = get_scalar_param(prof_dict, C.FLOPS_PROFILER_OUTPUT_FILE,
                                            C.FLOPS_PROFILER_OUTPUT_FILE_DEFAULT)


class DeepSpeedTensorboardConfig:
    def __init__(self, param_dict):
        tb_dict = get_dict_param(param_dict, C.TENSORBOARD, {})
        self.enabled = get_scalar_param(tb_dict, C.TENSORBOARD_ENABLED,
                                        C.TENSORBOARD_ENABLED_DEFAULT)
        self.output_path = get_scalar_param(tb_dict, C.TENSORBOARD_OUTPUT_PATH,
                                            C.TENSORBOARD_OUTPUT_PATH_DEFAULT)
        self.job_name = get_scalar_param(tb_dict, C.TENSORBOARD_JOB_NAME,
                                         C.TENSORBOARD_JOB_NAME_DEFAULT)


class DeepSpeedMonitorConfig:
    """Unified runtime telemetry knobs (``deepspeed_tpu/monitor``;
    docs/monitoring.md): the event bus with its sinks, the gauge/step
    emission interval, and the profiler trace-capture window.

    Env ``DSTPU_MONITOR`` (set by ``deepspeed --monitor`` /
    ``--no-monitor``) overrides ``enabled`` in either direction, matching
    the health-guardian/comms-compression pattern; the ``monitor=``
    kwarg of ``deepspeed_tpu.initialize`` outranks both.
    """

    def __init__(self, param_dict):
        from ..monitor.core import env_enabled
        m = get_dict_param(param_dict, C.MONITOR, {}) or {}
        self.enabled = bool(env_enabled(
            get_scalar_param(m, C.MONITOR_ENABLED,
                             C.MONITOR_ENABLED_DEFAULT)))
        sinks = get_scalar_param(m, C.MONITOR_SINKS, None)
        self.sinks = tuple(sinks if sinks is not None
                           else C.MONITOR_SINKS_DEFAULT)
        bad = [s for s in self.sinks if s not in C.MONITOR_SINKS_VALID]
        if bad:
            raise DeepSpeedConfigError(
                f"monitor.sinks {bad} unknown; valid: "
                f"{list(C.MONITOR_SINKS_VALID)}")
        self.dir = get_scalar_param(m, C.MONITOR_DIR, C.MONITOR_DIR_DEFAULT)
        self.interval = int(get_scalar_param(m, C.MONITOR_INTERVAL,
                                             C.MONITOR_INTERVAL_DEFAULT))
        if self.interval < 1:
            raise DeepSpeedConfigError("monitor.interval must be >= 1")
        self.ring_size = int(get_scalar_param(m, C.MONITOR_RING_SIZE,
                                              C.MONITOR_RING_SIZE_DEFAULT))
        if self.ring_size < 1:
            raise DeepSpeedConfigError("monitor.ring_size must be >= 1")
        self.memory_interval = int(get_scalar_param(
            m, C.MONITOR_MEMORY_INTERVAL,
            C.MONITOR_MEMORY_INTERVAL_DEFAULT))
        if self.memory_interval < 0:
            raise DeepSpeedConfigError(
                "monitor.memory_interval must be >= 0 (0 disables the "
                "memory ledger)")
        trace = get_scalar_param(m, C.MONITOR_TRACE_STEPS,
                                 C.MONITOR_TRACE_STEPS_DEFAULT)
        if trace is not None:
            if (not isinstance(trace, (list, tuple)) or len(trace) != 2
                    or not all(isinstance(x, int) for x in trace)
                    or not 1 <= trace[0] <= trace[1]):
                raise DeepSpeedConfigError(
                    "monitor.trace_steps must be [start, stop] with "
                    f"1 <= start <= stop (got {trace!r})")
            trace = (int(trace[0]), int(trace[1]))
        self.trace_steps = trace
        self.run_id = get_scalar_param(m, C.MONITOR_RUN_ID,
                                       C.MONITOR_RUN_ID_DEFAULT)
        self.rotate_mb = int(get_scalar_param(m, C.MONITOR_ROTATE_MB,
                                              C.MONITOR_ROTATE_MB_DEFAULT))
        if self.rotate_mb < 0:
            raise DeepSpeedConfigError(
                "monitor.rotate_mb must be >= 0 (0 disables rotation)")
        # monitor.slo: the declarative SLO engine (monitor/slo.py;
        # docs/monitoring.md#slo-tracking) — validated at parse time so
        # a typo'd objective fails the config, not the 400th step
        slo = get_dict_param(m, C.MONITOR_SLO, C.MONITOR_SLO_DEFAULT)
        if slo is not None:
            from ..monitor.slo import SLOConfig
            try:
                SLOConfig.from_value(slo)
            except ValueError as e:
                raise DeepSpeedConfigError(f"monitor.slo: {e}")
        self.slo = slo

    def describe(self) -> dict:
        return {"enabled": self.enabled, "sinks": list(self.sinks),
                "dir": self.dir, "interval": self.interval,
                "ring_size": self.ring_size,
                "memory_interval": self.memory_interval,
                "run_id": self.run_id, "rotate_mb": self.rotate_mb,
                "slo": self.slo,
                "trace_steps": (list(self.trace_steps)
                                if self.trace_steps else None)}


class DeepSpeedAnalysisConfig:
    """Lifecycle shadow-sanitizer policy (``analysis/sanitize.py``;
    docs/static-analysis.md#sanitizer): the ``analysis.sanitize`` block
    arms ASan-style DSTPU31x lifecycle checking on serving engines
    built from this config.  Env ``DSTPU_SANITIZE`` (set by ``deepspeed
    --sanitize`` / ``--no-sanitize``) overrides ``enabled`` in either
    direction — the monitor/comms-compression arming pattern."""

    def __init__(self, param_dict):
        from ..analysis.sanitize import resolve_enabled
        a = get_dict_param(param_dict, C.ANALYSIS, {}) or {}
        s = get_dict_param(a, C.ANALYSIS_SANITIZE, {}) or {}
        self.sanitize_config_enabled = bool(get_scalar_param(
            s, C.ANALYSIS_SANITIZE_ENABLED,
            C.ANALYSIS_SANITIZE_ENABLED_DEFAULT))
        self.sanitize_enabled = resolve_enabled(
            self.sanitize_config_enabled)
        self.sanitize_halt = bool(get_scalar_param(
            s, C.ANALYSIS_SANITIZE_HALT, C.ANALYSIS_SANITIZE_HALT_DEFAULT))
        unknown = set(s) - {C.ANALYSIS_SANITIZE_ENABLED,
                            C.ANALYSIS_SANITIZE_HALT}
        if unknown:
            raise DeepSpeedConfigError(
                f"analysis.sanitize: unknown key(s) {sorted(unknown)}; "
                f"valid: ['{C.ANALYSIS_SANITIZE_ENABLED}', "
                f"'{C.ANALYSIS_SANITIZE_HALT}']")

    def describe(self) -> dict:
        from ..analysis.sanitize import describe
        return describe(config_enabled=self.sanitize_config_enabled,
                        halt=self.sanitize_halt)


class DeepSpeedPipelineConfig:
    def __init__(self, param_dict):
        pipe_dict = get_dict_param(param_dict, C.PIPELINE, {})
        self.stages = get_scalar_param(pipe_dict, C.PIPELINE_STAGES, C.PIPELINE_STAGES_DEFAULT)
        self.partition = get_scalar_param(pipe_dict, C.PIPELINE_PARTITION,
                                          C.PIPELINE_PARTITION_DEFAULT)
        self.seed_layers = get_scalar_param(pipe_dict, C.PIPELINE_SEED_LAYERS,
                                            C.PIPELINE_SEED_LAYERS_DEFAULT)
        self.activation_checkpoint_interval = get_scalar_param(
            pipe_dict, C.PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL,
            C.PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL_DEFAULT)


class DeepSpeedCurriculumConfig:
    def __init__(self, param_dict):
        cl_dict = get_dict_param(param_dict, C.CURRICULUM_LEARNING, {})
        self.enabled = get_scalar_param(cl_dict, C.CURRICULUM_ENABLED,
                                        C.CURRICULUM_ENABLED_DEFAULT)
        self.params = {k: v for k, v in cl_dict.items()}


class DeepSpeedPLDConfig:
    def __init__(self, param_dict):
        pld_dict = get_dict_param(param_dict, C.PROGRESSIVE_LAYER_DROP, {})
        self.enabled = get_scalar_param(pld_dict, C.PLD_ENABLED, C.PLD_ENABLED_DEFAULT)
        self.theta = get_scalar_param(pld_dict, C.PLD_THETA, C.PLD_THETA_DEFAULT)
        self.gamma = get_scalar_param(pld_dict, C.PLD_GAMMA, C.PLD_GAMMA_DEFAULT)


class DeepSpeedEigenvalueConfig:
    def __init__(self, param_dict):
        ev = get_dict_param(param_dict, C.EIGENVALUE, {})
        self.enabled = get_scalar_param(ev, C.EIGENVALUE_ENABLED, C.EIGENVALUE_ENABLED_DEFAULT)
        self.verbose = get_scalar_param(ev, C.EIGENVALUE_VERBOSE, C.EIGENVALUE_VERBOSE_DEFAULT)
        self.max_iter = get_scalar_param(ev, C.EIGENVALUE_MAX_ITER, C.EIGENVALUE_MAX_ITER_DEFAULT)
        self.tol = get_scalar_param(ev, C.EIGENVALUE_TOL, C.EIGENVALUE_TOL_DEFAULT)
        self.stability = get_scalar_param(ev, C.EIGENVALUE_STABILITY,
                                          C.EIGENVALUE_STABILITY_DEFAULT)
        self.gas_boundary_resolution = get_scalar_param(
            ev, C.EIGENVALUE_GAS_BOUNDARY_RESOLUTION,
            C.EIGENVALUE_GAS_BOUNDARY_RESOLUTION_DEFAULT)
        self.layer_name = get_scalar_param(ev, C.EIGENVALUE_LAYER_NAME,
                                           C.EIGENVALUE_LAYER_NAME_DEFAULT)
        self.layer_num = get_scalar_param(ev, C.EIGENVALUE_LAYER_NUM,
                                          C.EIGENVALUE_LAYER_NUM_DEFAULT)


class DeepSpeedQuantizeTrainingConfig:
    """MoQ quantize-aware training knobs (reference ``config.py:275-330``)."""

    def __init__(self, param_dict):
        q = get_dict_param(param_dict, C.QUANTIZE_TRAINING, {})
        self.enabled = get_scalar_param(q, "enabled", False)
        groups = get_dict_param(q, "quantize_groups", {})
        self.quantize_groups = groups if isinstance(groups, int) else \
            get_scalar_param(q, "quantize_groups", 1)
        self.quantize_weight_in_forward = get_scalar_param(q, "quantize_weight_in_forward", False)
        self.quantize_verbose = get_scalar_param(q, "quantize_verbose", False)
        self.quantizer_kernel = get_scalar_param(q, "quantizer_kernel", False)
        sched = get_dict_param(q, "quantize_schedule", {})
        self.quantize_period = get_scalar_param(sched, "quantize_period", 1000)
        sched_offset = get_dict_param(sched, "schedule_offset", 1000)
        self.schedule_offset = sched_offset if isinstance(sched_offset, int) else 1000
        algo = get_dict_param(q, "quantize_algo", {})
        self.quantize_type = get_scalar_param(algo, "q_type", "symmetric")
        self.rounding = get_scalar_param(algo, "rounding", "nearest")
        self.fp16_mixed_quantize = get_scalar_param(
            get_dict_param(q, "fp16_mixed_quantize", {}), "enabled", False)
        self.quantize_change_ratio = get_scalar_param(
            get_dict_param(q, "fp16_mixed_quantize", {}), "quantize_change_ratio", 0.001)
        self.target_bits = get_scalar_param(q, "quantize_bits",
                                            {}).get("target_bits", 8) if isinstance(
                                                get_scalar_param(q, "quantize_bits", {}),
                                                dict) else 8
        bits = get_dict_param(q, "quantize_bits", {})
        self.start_bits = get_scalar_param(bits, "start_bits", 16)


class DeepSpeedCheckpointConfig:
    def __init__(self, param_dict):
        ckpt_dict = get_dict_param(param_dict, C.CHECKPOINT, {})
        self.tag_validation = get_scalar_param(ckpt_dict, C.CHECKPOINT_TAG_VALIDATION,
                                               C.CHECKPOINT_TAG_VALIDATION_DEFAULT)
        if self.tag_validation not in C.CHECKPOINT_TAG_VALIDATION_MODES:
            raise DeepSpeedConfigError(
                f"checkpoint.tag_validation must be one of {C.CHECKPOINT_TAG_VALIDATION_MODES}")
        self.load_universal = get_scalar_param(ckpt_dict, C.LOAD_UNIVERSAL_CHECKPOINT,
                                               C.LOAD_UNIVERSAL_CHECKPOINT_DEFAULT)
        # fault-tolerance layer (docs/fault-tolerance.md)
        self.keep_n = get_scalar_param(ckpt_dict, C.CHECKPOINT_KEEP_N,
                                       C.CHECKPOINT_KEEP_N_DEFAULT)
        if self.keep_n is None:
            self.keep_n = 0
        if int(self.keep_n) < 0:
            raise DeepSpeedConfigError("checkpoint.keep_n must be >= 0")
        self.keep_n = int(self.keep_n)
        self.verify = get_scalar_param(ckpt_dict, C.CHECKPOINT_VERIFY,
                                       C.CHECKPOINT_VERIFY_DEFAULT)
        if self.verify not in C.CHECKPOINT_VERIFY_MODES:
            raise DeepSpeedConfigError(
                f"checkpoint.verify must be one of {C.CHECKPOINT_VERIFY_MODES}")
        self.auto_resume = get_scalar_param(ckpt_dict, C.CHECKPOINT_AUTO_RESUME,
                                            C.CHECKPOINT_AUTO_RESUME_DEFAULT)
        self.dir = get_scalar_param(ckpt_dict, C.CHECKPOINT_DIR,
                                    C.CHECKPOINT_DIR_DEFAULT)
        self.fsync = get_scalar_param(ckpt_dict, C.CHECKPOINT_FSYNC,
                                      C.CHECKPOINT_FSYNC_DEFAULT)


class DeepSpeedIORetryConfig:
    """Bounded-backoff policy for checkpoint + NVMe-swap IO
    (``utils/retry.py``; docs/fault-tolerance.md)."""

    def __init__(self, param_dict):
        r = get_dict_param(param_dict, C.IO_RETRY, {}) or {}
        self.max_attempts = int(get_scalar_param(
            r, C.IO_RETRY_MAX_ATTEMPTS, C.IO_RETRY_MAX_ATTEMPTS_DEFAULT))
        self.base_delay_s = float(get_scalar_param(
            r, C.IO_RETRY_BASE_DELAY_S, C.IO_RETRY_BASE_DELAY_S_DEFAULT))
        self.max_delay_s = float(get_scalar_param(
            r, C.IO_RETRY_MAX_DELAY_S, C.IO_RETRY_MAX_DELAY_S_DEFAULT))
        self.jitter = float(get_scalar_param(
            r, C.IO_RETRY_JITTER, C.IO_RETRY_JITTER_DEFAULT))
        self.full_jitter = bool(get_scalar_param(
            r, C.IO_RETRY_FULL_JITTER, C.IO_RETRY_FULL_JITTER_DEFAULT))
        self.max_elapsed_s = get_scalar_param(
            r, C.IO_RETRY_MAX_ELAPSED_S, C.IO_RETRY_MAX_ELAPSED_S_DEFAULT)
        if self.max_attempts < 1:
            raise DeepSpeedConfigError("io_retry.max_attempts must be >= 1")
        if self.base_delay_s < 0 or self.max_delay_s < 0:
            raise DeepSpeedConfigError(
                "io_retry.base_delay_s/max_delay_s must be >= 0")
        if not (0.0 <= self.jitter < 1.0):
            raise DeepSpeedConfigError("io_retry.jitter must be in [0, 1)")
        if self.max_elapsed_s is not None:
            self.max_elapsed_s = float(self.max_elapsed_s)
            if self.max_elapsed_s <= 0:
                raise DeepSpeedConfigError(
                    "io_retry.max_elapsed_s must be > 0 (or absent)")

    def policy(self, **overrides):
        from ..utils.retry import RetryPolicy
        kw = dict(max_attempts=self.max_attempts,
                  base_delay_s=self.base_delay_s,
                  max_delay_s=self.max_delay_s, jitter=self.jitter,
                  jitter_mode="full" if self.full_jitter else "proportional",
                  max_elapsed_s=self.max_elapsed_s)
        kw.update(overrides)
        return RetryPolicy(**kw)


class DeepSpeedHealthCheckConfig:
    """Training health guardian knobs (``runtime/health.py``;
    docs/health-monitor.md).  The escalation ladder:

    - ``skip_nonfinite`` — branchless skip-step on any non-finite
      loss/grad/param sentinel (default on; the bf16/fp32 extension of the
      fp16 loss-scaler skip);
    - ``spike_zmax``/``spike_window``/``skip_on_spike`` — EMA loss-spike
      z-score sentinel (zmax 0 disables);
    - ``consecutive_skip_budget`` exhausted -> in-process rewind to the
      newest valid checkpoint + data fast-forward past the poison window;
    - ``rewind_limit`` exhausted -> ``on_exhausted`` (abort with a forensic
      JSON dump, or warn and continue unprotected).

    Env ``DSTPU_HEALTH_CHECK`` (set by ``deepspeed --health-check``)
    overrides ``enabled`` in either direction.
    """

    def __init__(self, param_dict):
        import os as _os
        h = get_dict_param(param_dict, C.HEALTH_CHECK, {}) or {}
        self.enabled = bool(get_scalar_param(h, C.HEALTH_ENABLED,
                                             C.HEALTH_ENABLED_DEFAULT))
        env = _os.environ.get("DSTPU_HEALTH_CHECK")
        if env:
            self.enabled = env.lower() in ("1", "true", "yes")
        self.skip_nonfinite = bool(get_scalar_param(
            h, C.HEALTH_SKIP_NONFINITE, C.HEALTH_SKIP_NONFINITE_DEFAULT))
        self.spike_window = int(get_scalar_param(
            h, C.HEALTH_SPIKE_WINDOW, C.HEALTH_SPIKE_WINDOW_DEFAULT))
        self.spike_zmax = float(get_scalar_param(
            h, C.HEALTH_SPIKE_ZMAX, C.HEALTH_SPIKE_ZMAX_DEFAULT))
        self.skip_on_spike = bool(get_scalar_param(
            h, C.HEALTH_SKIP_ON_SPIKE, C.HEALTH_SKIP_ON_SPIKE_DEFAULT))
        self.consecutive_skip_budget = int(get_scalar_param(
            h, C.HEALTH_SKIP_BUDGET, C.HEALTH_SKIP_BUDGET_DEFAULT))
        self.rewind_limit = int(get_scalar_param(
            h, C.HEALTH_REWIND_LIMIT, C.HEALTH_REWIND_LIMIT_DEFAULT))
        self.on_exhausted = get_scalar_param(
            h, C.HEALTH_ON_EXHAUSTED, C.HEALTH_ON_EXHAUSTED_DEFAULT)
        self.check_interval = int(get_scalar_param(
            h, C.HEALTH_CHECK_INTERVAL, C.HEALTH_CHECK_INTERVAL_DEFAULT))
        self.history = int(get_scalar_param(
            h, C.HEALTH_HISTORY, C.HEALTH_HISTORY_DEFAULT))
        self.forensic_dir = get_scalar_param(
            h, C.HEALTH_FORENSIC_DIR, C.HEALTH_FORENSIC_DIR_DEFAULT)
        if self.spike_window < 2:
            raise DeepSpeedConfigError("health_check.spike_window must be >= 2")
        if self.spike_zmax < 0:
            raise DeepSpeedConfigError("health_check.spike_zmax must be >= 0")
        if self.skip_on_spike and self.spike_zmax <= 0:
            raise DeepSpeedConfigError(
                "health_check.skip_on_spike needs spike_zmax > 0 (the "
                "spike sentinel is off at zmax=0)")
        if self.consecutive_skip_budget < 0:
            raise DeepSpeedConfigError(
                "health_check.consecutive_skip_budget must be >= 0")
        if self.rewind_limit < 0:
            raise DeepSpeedConfigError("health_check.rewind_limit must be >= 0")
        if self.on_exhausted not in C.HEALTH_ON_EXHAUSTED_MODES:
            raise DeepSpeedConfigError(
                f"health_check.on_exhausted must be one of "
                f"{C.HEALTH_ON_EXHAUSTED_MODES}")
        if self.check_interval < 1:
            raise DeepSpeedConfigError(
                "health_check.check_interval must be >= 1")
        if self.history < 1:
            raise DeepSpeedConfigError("health_check.history must be >= 1")


class DeepSpeedCompileCacheConfig:
    """Persistent compiled-step cache (``runtime/compile_cache.py``;
    docs/compile-cache.md).  Active when ``enabled`` (default) AND a
    directory resolves: an explicit ``dir`` wins, else env
    ``DSTPU_COMPILE_CACHE`` (set by ``deepspeed --compile-cache-dir``).
    An env value of ``0``/``off`` is the operator kill switch — it
    disables the cache even against a config-provided dir.  ``readonly``
    serves a shared CI cache (reads verify + deserialize; nothing is
    written, touched or evicted); ``max_entries`` bounds the store with
    LRU eviction (0 = unbounded)."""

    def __init__(self, param_dict):
        from .compile_cache import resolve_env_dir, env_disabled
        cc = get_dict_param(param_dict, C.COMPILE_CACHE, {}) or {}
        self.enabled = bool(get_scalar_param(
            cc, C.COMPILE_CACHE_ENABLED, C.COMPILE_CACHE_ENABLED_DEFAULT))
        self.dir = get_scalar_param(cc, C.COMPILE_CACHE_DIR,
                                    C.COMPILE_CACHE_DIR_DEFAULT)
        if self.dir is None:
            self.dir = resolve_env_dir()
        if env_disabled():
            self.enabled = False
        self.max_entries = int(get_scalar_param(
            cc, C.COMPILE_CACHE_MAX_ENTRIES,
            C.COMPILE_CACHE_MAX_ENTRIES_DEFAULT))
        if self.max_entries < 0:
            raise DeepSpeedConfigError(
                "compile_cache.max_entries must be >= 0")
        self.readonly = bool(get_scalar_param(
            cc, C.COMPILE_CACHE_READONLY, C.COMPILE_CACHE_READONLY_DEFAULT))


class DeepSpeedCommsCompressionConfig:
    """Quantized ZeRO collectives (ZeRO++-style; docs/comms-compression.md):
    qwZ int8/int4 parameter all-gathers, qgZ block-quantized gradient
    reduction with persistent error feedback, hierarchical two-level
    decomposition.  Default OFF — full-width wire, tier-1 numerics
    untouched.  Env ``DSTPU_COMMS_COMPRESSION`` (set by
    ``deepspeed --comms-compression``/``--no-comms-compression``)
    overrides ``enabled`` in either direction."""

    def __init__(self, param_dict):
        import os as _os
        cc = get_dict_param(param_dict, C.COMMS_COMPRESSION, {}) or {}
        self.enabled = bool(get_scalar_param(
            cc, C.COMMS_COMPRESSION_ENABLED,
            C.COMMS_COMPRESSION_ENABLED_DEFAULT))
        env = _os.environ.get("DSTPU_COMMS_COMPRESSION")
        if env:
            self.enabled = env.lower() in ("1", "true", "yes", "on")
        self.weights_bits = get_scalar_param(
            cc, C.COMMS_COMPRESSION_WEIGHTS_BITS,
            C.COMMS_COMPRESSION_WEIGHTS_BITS_DEFAULT)
        self.grads_bits = get_scalar_param(
            cc, C.COMMS_COMPRESSION_GRADS_BITS,
            C.COMMS_COMPRESSION_GRADS_BITS_DEFAULT)
        if self.weights_bits is not None and \
                int(self.weights_bits) not in (4, 8):
            raise DeepSpeedConfigError(
                "comms_compression.weights_bits must be 4, 8 or null "
                "(null = weights stay full-width)")
        if self.grads_bits is not None and int(self.grads_bits) != 8:
            raise DeepSpeedConfigError(
                "comms_compression.grads_bits must be 8 or null (the "
                "error-fed int8 reduce is the supported gradient scheme; "
                "null = gradients stay full-width)")
        self.weights_bits = (None if self.weights_bits is None
                             else int(self.weights_bits))
        self.grads_bits = (None if self.grads_bits is None
                           else int(self.grads_bits))
        self.block_size = int(get_scalar_param(
            cc, C.COMMS_COMPRESSION_BLOCK_SIZE,
            C.COMMS_COMPRESSION_BLOCK_SIZE_DEFAULT))
        if self.block_size < 2:
            raise DeepSpeedConfigError(
                "comms_compression.block_size must be >= 2")
        self.hierarchical = bool(get_scalar_param(
            cc, C.COMMS_COMPRESSION_HIERARCHICAL,
            C.COMMS_COMPRESSION_HIERARCHICAL_DEFAULT))
        self.min_tensor_bytes = int(get_scalar_param(
            cc, C.COMMS_COMPRESSION_MIN_TENSOR_BYTES,
            C.COMMS_COMPRESSION_MIN_TENSOR_BYTES_DEFAULT))
        if self.min_tensor_bytes < 0:
            raise DeepSpeedConfigError(
                "comms_compression.min_tensor_bytes must be >= 0")
        excluded = get_scalar_param(cc, C.COMMS_COMPRESSION_EXCLUDED,
                                    C.COMMS_COMPRESSION_EXCLUDED_DEFAULT)
        self.excluded = tuple(str(p).lower() for p in (excluded or []))
        routes = get_scalar_param(cc, C.COMMS_COMPRESSION_ROUTES,
                                  C.COMMS_COMPRESSION_ROUTES_DEFAULT)
        self.routes = tuple(routes or [])
        bad = [r for r in self.routes
               if r not in C.COMMS_COMPRESSION_ROUTES_VALID]
        if bad:
            raise DeepSpeedConfigError(
                f"comms_compression.routes {bad} unknown; valid: "
                f"{C.COMMS_COMPRESSION_ROUTES_VALID}")
        # per-route knobs: the MoE expert-dispatch wire (moe route)
        moe = get_dict_param(cc, C.COMMS_COMPRESSION_MOE, {}) or {}
        self.moe_bits = get_scalar_param(
            moe, C.COMMS_COMPRESSION_MOE_BITS,
            C.COMMS_COMPRESSION_MOE_BITS_DEFAULT)
        if self.moe_bits is not None and int(self.moe_bits) != 8:
            raise DeepSpeedConfigError(
                "comms_compression.moe.bits must be 8 or null (the "
                "int8-activation dispatch is the supported MoE scheme; "
                "null = the expert all_to_all stays full-width)")
        self.moe_bits = None if self.moe_bits is None else int(self.moe_bits)
        self.moe_block_size = get_scalar_param(
            moe, C.COMMS_COMPRESSION_MOE_BLOCK_SIZE,
            C.COMMS_COMPRESSION_MOE_BLOCK_SIZE_DEFAULT)
        if self.moe_block_size is None:
            self.moe_block_size = self.block_size
        else:
            self.moe_block_size = int(self.moe_block_size)
            if self.moe_block_size < 2:
                raise DeepSpeedConfigError(
                    "comms_compression.moe.block_size must be >= 2")

    def describe(self) -> dict:
        return {"enabled": self.enabled, "weights_bits": self.weights_bits,
                "grads_bits": self.grads_bits, "block_size": self.block_size,
                "hierarchical": self.hierarchical,
                "min_tensor_bytes": self.min_tensor_bytes,
                "excluded": list(self.excluded),
                "routes": list(self.routes),
                "moe": {"bits": self.moe_bits,
                        "block_size": self.moe_block_size}}


class DeepSpeedMeshConfig:
    """TPU-native extension: declared mesh axis sizes.

    ``{"axes": {"data": -1, "fsdp": 1, "tensor": 1, "expert": 1, "pipe": 1, "seq": 1}}``
    ``-1`` absorbs remaining devices. Replaces the reference's NCCL process-group
    construction (``deepspeed/utils/groups.py``, ``pipe/topology.py``).
    """

    AXES = ("data", "fsdp", "tensor", "expert", "pipe", "seq")

    def __init__(self, param_dict):
        mesh_dict = get_dict_param(param_dict, C.MESH, {})
        axes = get_dict_param(mesh_dict, "axes", {})
        self.axes = {name: axes.get(name, -1 if name == "data" else 1) for name in self.AXES}
        unknown = set(axes) - set(self.AXES)
        if unknown:
            raise DeepSpeedConfigError(f"Unknown mesh axes {unknown}; valid: {self.AXES}")


class DeepSpeedSequenceParallelConfig:
    """TPU-native extension (reference vintage has no SP — SURVEY.md §2.2)."""

    def __init__(self, param_dict):
        sp_dict = get_dict_param(param_dict, C.SEQUENCE_PARALLEL, {})
        self.enabled = get_scalar_param(sp_dict, "enabled", False)
        self.mode = get_scalar_param(sp_dict, "mode", "ring")  # "ring" | "ulysses"
        if self.mode not in ("ring", "ulysses"):
            raise DeepSpeedConfigError(f"sequence_parallel.mode must be ring|ulysses")


class DeepSpeedConfig:
    """Parse + validate the full JSON config document.

    Parity: reference ``runtime/config.py:791``. ``world_size`` here is the
    data-parallel extent (data×fsdp mesh axes product).
    """

    def __init__(self, config, world_size=None, mesh=None, elastic=None):
        # shallow-copy: _apply_elasticity (and the elastic override below)
        # write batch keys into the dict; a caller's config object must not
        # be mutated behind its back
        self._param_dict = dict(load_config_dict(config))

        if world_size is None:
            if mesh is not None:
                import numpy as _np
                world_size = int(_np.prod([mesh.shape.get("data", 1),
                                           mesh.shape.get("fsdp", 1)]))
            else:
                world_size = 1
        self.world_size = world_size

        # Elasticity may overwrite batch keys pre-parse (reference config.py:815-830).
        # ``elastic`` (initialize kwarg > env DSTPU_ELASTIC as set by
        # ``deepspeed --elastic`` > config) can force it on/off without
        # editing the JSON — the preempted-job restart path, where the
        # relaunch decides elasticity, not the original config author.
        self.elasticity_enabled = False
        self.elastic_record = None
        if elastic is None:
            import os as _os
            env = _os.environ.get("DSTPU_ELASTIC")
            if env:
                elastic = env.lower() in ("1", "true", "yes", "on")
        if elastic is not None:
            if elastic and C.ELASTICITY not in self._param_dict:
                raise DeepSpeedConfigError(
                    "--elastic/DSTPU_ELASTIC needs an `elasticity` config "
                    "block (micro_batch_sizes + max_train_batch_size) to "
                    "compute the batch schedule from (docs/elasticity.md)")
            if C.ELASTICITY in self._param_dict:
                self._param_dict[C.ELASTICITY] = dict(
                    self._param_dict[C.ELASTICITY], enabled=bool(elastic))
        if C.ELASTICITY in self._param_dict and \
                self._param_dict[C.ELASTICITY].get("enabled", False):
            self._apply_elasticity()

        self._initialize_params(self._param_dict)
        self._configure_train_batch_size()
        self._do_sanity_check()
        self._warn_noop_keys()

    # -- accepted-for-compatibility no-op keys -----------------------------
    # Every key the parser accepts must either change behavior or warn
    # loudly that it doesn't (VERDICT r3 weak #5: a user setting a dead key
    # must never get silence).  Section -> {key: why it is a no-op here}.
    NOOP_KEYS = {
        "zero_optimization": {
            "contiguous_gradients": "XLA's allocator packs gradient buffers",
            "reduce_scatter": "sharding constraints already emit "
                              "reduce-scatter at stage>=2",
            "reduce_bucket_size": "XLA schedules its own collective "
                                  "bucketing",
            "allgather_partitions": "stage-3 gathers come from the SPMD "
                                    "partitioner",
            "allgather_bucket_size": "XLA schedules its own collective "
                                     "bucketing",
            "overlap_comm": "XLA's latency-hiding scheduler overlaps "
                            "collectives with compute",
            "load_from_fp32_weights": "checkpoints always carry the fp32 "
                                      "master; loads restore it directly",
            "elastic_checkpoint": "checkpoints are always reshardable on "
                                  "this runtime",
            "ignore_unused_parameters": "jax.grad returns zeros for unused "
                                        "params; nothing hangs",
            "round_robin_gradients": "gradient placement is the fsdp "
                                     "sharding, not rank round-robin",
            "legacy_stage1": "single stage-1 implementation",
            "stage3_prefetch_bucket_size": "the scanned layer loop + XLA "
                                           "latency hiding do the prefetch",
            "prefetch_bucket_size": "the scanned layer loop + XLA latency "
                                    "hiding do the prefetch",
            "stage3_max_live_parameters": "XLA frees gathered params after "
                                          "last use inside the step",
            "max_live_parameters": "XLA frees gathered params after last "
                                   "use inside the step",
            "stage3_max_reuse_distance": "XLA's scheduler owns re-gather "
                                         "decisions",
            "max_reuse_distance": "XLA's scheduler owns re-gather decisions",
        },
        "fp16": {
            "master_weights_and_grads": "fp32 master is unconditional when "
                                        "training in 16-bit",
        },
        "activation_checkpointing": {
            "contiguous_memory_optimization": "XLA's allocator packs live "
                                              "buffers",
            "synchronize_checkpoint_boundary": "no stream boundaries under "
                                               "one jitted step",
            "profile": "use the flops profiler / jax.profiler instead",
        },
    }

    def _warn_noop_keys(self):
        """One rank-0 line naming every accepted-but-no-op key the user
        actually SET (the `prescale_gradients` pattern, engine.py)."""
        from ..utils.logging import log_dist
        hits = []
        for section, keys in self.NOOP_KEYS.items():
            sect = self._param_dict.get(section)
            if not isinstance(sect, dict):
                continue
            for k, why in keys.items():
                if k in sect:
                    hits.append(f"{section}.{k} ({why})")
        if hits:
            log_dist("config keys accepted for compatibility but NO-OPs on "
                     "this runtime: " + "; ".join(hits), ranks=[0])
        self.noop_keys_set = hits

    # -- elasticity hook ---------------------------------------------------
    def _apply_elasticity(self):
        from ..elasticity import (compute_elastic_config,
                                  ElasticityIncompatibleWorldSize)
        from ..elasticity.constants import ELASTICITY
        # raises ElasticityIncompatibleWorldSize here — at initialize —
        # when the current world size is not in the elastic schedule's
        # valid set (resuming a preempted job on an unschedulable chip
        # count must fail fast, not as a shard-shape mismatch mid-load)
        final_batch_size, valid_gpus, micro_batch_size = compute_elastic_config(
            ds_config=self._param_dict,
            target_deepspeed_version="any",
            world_size=self.world_size)
        self.elasticity_enabled = True
        ignore = self._param_dict[ELASTICITY].get("ignore_non_elastic_batch_info", False)
        if not ignore:
            for key in (C.TRAIN_BATCH_SIZE, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                        C.GRADIENT_ACCUMULATION_STEPS):
                if key in self._param_dict:
                    raise DeepSpeedConfigError(
                        f"Elasticity is enabled, but {key} is also set; set "
                        f"elasticity.ignore_non_elastic_batch_info to override.")
            self._param_dict[C.TRAIN_BATCH_SIZE] = final_batch_size
            if micro_batch_size is not None:
                self._param_dict[C.TRAIN_MICRO_BATCH_SIZE_PER_GPU] = micro_batch_size
                self._param_dict[C.GRADIENT_ACCUMULATION_STEPS] = \
                    final_batch_size // (micro_batch_size * self.world_size)
        else:
            # reference parity (config.py:815-830): with
            # ignore_non_elastic_batch_info the USER's batch keys stay
            # authoritative.  They must still be schedulable at THIS world
            # size — previously the overwrite hid any conflict and an
            # incompatible train_batch_size surfaced only later, as a
            # batch-stacking/shard-shape failure inside the engine.
            tb = self._param_dict.get(C.TRAIN_BATCH_SIZE)
            mb = self._param_dict.get(C.TRAIN_MICRO_BATCH_SIZE_PER_GPU)
            if tb is not None:
                if tb % self.world_size != 0:
                    raise ElasticityIncompatibleWorldSize(
                        f"elasticity (ignore_non_elastic_batch_info): "
                        f"train_batch_size {tb} is not divisible by the "
                        f"current world size {self.world_size}")
                if mb is not None and (tb // self.world_size) % mb != 0:
                    raise ElasticityIncompatibleWorldSize(
                        f"elasticity (ignore_non_elastic_batch_info): "
                        f"train_batch_size {tb} cannot be factored as "
                        f"micro_batch {mb} x gas x world_size "
                        f"{self.world_size}")
        self.elastic_record = {
            "train_batch_size": self._param_dict.get(C.TRAIN_BATCH_SIZE,
                                                     final_batch_size),
            "elastic_batch_size": final_batch_size,
            "micro_batch": self._param_dict.get(
                C.TRAIN_MICRO_BATCH_SIZE_PER_GPU),
            "world_size": self.world_size,
        }

    # -- param init --------------------------------------------------------
    def _initialize_params(self, pd):
        self.train_batch_size = get_scalar_param(pd, C.TRAIN_BATCH_SIZE,
                                                 C.TRAIN_BATCH_SIZE_DEFAULT)
        self.train_micro_batch_size_per_gpu = get_scalar_param(
            pd, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)
        self.gradient_accumulation_steps = get_scalar_param(
            pd, C.GRADIENT_ACCUMULATION_STEPS, C.GRADIENT_ACCUMULATION_STEPS_DEFAULT)
        self.steps_per_print = get_scalar_param(pd, C.STEPS_PER_PRINT,
                                                C.STEPS_PER_PRINT_DEFAULT)
        self.dump_state = get_scalar_param(pd, C.DUMP_STATE, C.DUMP_STATE_DEFAULT)
        self.disable_allgather = get_scalar_param(pd, C.DISABLE_ALLGATHER,
                                                  C.DISABLE_ALLGATHER_DEFAULT)
        self.communication_data_type = get_scalar_param(pd, C.COMMUNICATION_DATA_TYPE,
                                                        C.COMMUNICATION_DATA_TYPE_DEFAULT)
        self.prescale_gradients = get_scalar_param(pd, C.PRESCALE_GRADIENTS,
                                                   C.PRESCALE_GRADIENTS_DEFAULT)
        self.gradient_predivide_factor = get_scalar_param(
            pd, C.GRADIENT_PREDIVIDE_FACTOR, C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT)
        self.sparse_gradients_enabled = get_scalar_param(pd, C.SPARSE_GRADIENTS,
                                                         C.SPARSE_GRADIENTS_DEFAULT)
        self.gradient_clipping = get_scalar_param(pd, C.GRADIENT_CLIPPING,
                                                  C.GRADIENT_CLIPPING_DEFAULT)
        # reference "data_types": {"grad_accum_dtype": ...} — fp32 (default)
        # accumulates exactly; bf16 halves the accumulator bandwidth of the
        # gas scan (~9% step time at 350M/gas=2) at reduced summation
        # precision.  Only meaningful when gradient_accumulation_steps > 1.
        dt = get_dict_param(pd, C.DATA_TYPES, {}) or {}
        self.grad_accum_dtype = get_scalar_param(dt, C.GRAD_ACCUM_DTYPE,
                                                 C.GRAD_ACCUM_DTYPE_DEFAULT)
        assert self.grad_accum_dtype in ("fp32", "bf16"), \
            f"data_types.grad_accum_dtype must be fp32|bf16, got " \
            f"{self.grad_accum_dtype!r}"

        optimizer_dict = get_dict_param(pd, C.OPTIMIZER, None)
        self.optimizer_name = None
        self.optimizer_params = None
        self.optimizer_legacy_fusion = C.LEGACY_FUSION_DEFAULT
        if optimizer_dict is not None:
            self.optimizer_name = get_scalar_param(optimizer_dict, C.TYPE, None)
            if self.optimizer_name is not None:
                self.optimizer_name = self.optimizer_name.lower()
            self.optimizer_params = get_dict_param(optimizer_dict, C.OPTIMIZER_PARAMS, {})
            self.optimizer_legacy_fusion = get_scalar_param(optimizer_dict, C.LEGACY_FUSION,
                                                            C.LEGACY_FUSION_DEFAULT)

        scheduler_dict = get_dict_param(pd, C.SCHEDULER, None)
        self.scheduler_name = None
        self.scheduler_params = None
        if scheduler_dict is not None:
            self.scheduler_name = get_scalar_param(scheduler_dict, C.TYPE, None)
            self.scheduler_params = get_dict_param(scheduler_dict, C.SCHEDULER_PARAMS, {})

        self.zero_config = DeepSpeedZeroConfig(pd)
        self.fp16 = DeepSpeedFP16Config(pd)
        self.bf16 = DeepSpeedBF16Config(pd)
        amp_dict = get_dict_param(pd, C.AMP, {})
        self.amp_enabled = get_scalar_param(amp_dict, C.AMP_ENABLED, C.AMP_ENABLED_DEFAULT)
        self.amp_params = {k: v for k, v in amp_dict.items() if k != C.AMP_ENABLED}
        self.activation_checkpointing = DeepSpeedActivationCheckpointingConfig(pd)
        self.flops_profiler = DeepSpeedFlopsProfilerConfig(pd)
        self.tensorboard = DeepSpeedTensorboardConfig(pd)
        self.monitor_config = DeepSpeedMonitorConfig(pd)
        self.analysis_config = DeepSpeedAnalysisConfig(pd)
        self.pipeline = DeepSpeedPipelineConfig(pd)
        self.curriculum = DeepSpeedCurriculumConfig(pd)
        self.pld = DeepSpeedPLDConfig(pd)
        self.progressive_layer_drop = self.pld  # reference-facing alias
        self.eigenvalue = DeepSpeedEigenvalueConfig(pd)
        self.quantize_training = DeepSpeedQuantizeTrainingConfig(pd)
        self.checkpoint_config = DeepSpeedCheckpointConfig(pd)
        self.io_retry_config = DeepSpeedIORetryConfig(pd)
        self.health_check = DeepSpeedHealthCheckConfig(pd)
        self.compile_cache_config = DeepSpeedCompileCacheConfig(pd)
        self.comms_compression = DeepSpeedCommsCompressionConfig(pd)
        self.mesh_config = DeepSpeedMeshConfig(pd)
        self.sequence_parallel = DeepSpeedSequenceParallelConfig(pd)
        self.wall_clock_breakdown = get_scalar_param(pd, C.WALL_CLOCK_BREAKDOWN,
                                                     C.WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.memory_breakdown = get_scalar_param(pd, C.MEMORY_BREAKDOWN,
                                                 C.MEMORY_BREAKDOWN_DEFAULT)
        self.dataloader_drop_last = get_scalar_param(pd, C.DATALOADER_DROP_LAST,
                                                     C.DATALOADER_DROP_LAST_DEFAULT)
        self.sparse_attention = get_dict_param(pd, C.SPARSE_ATTENTION, None)
        self.aio_config = dict(C.AIO_DEFAULT_DICT)
        self.aio_config.update(get_dict_param(pd, C.AIO, {}))
        self.autotuning_config = get_dict_param(pd, C.AUTOTUNING, {})

    # -- batch arithmetic --------------------------------------------------
    def _configure_train_batch_size(self):
        """Solve for the missing one of (train_batch, micro_batch, gas).

        Parity: reference ``config.py:1049 _configure_train_batch_size`` and
        ``:980 _batch_assertion``.
        """
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps
        ws = self.world_size

        if train_batch is not None and micro_batch is not None and gas is not None:
            pass
        elif train_batch is not None and micro_batch is not None:
            gas = train_batch // micro_batch
            gas //= ws
        elif train_batch is not None and gas is not None:
            micro_batch = train_batch // ws
            micro_batch //= gas
        elif micro_batch is not None and gas is not None:
            train_batch = micro_batch * gas * ws
        elif train_batch is not None:
            gas = 1
            micro_batch = train_batch // ws
        elif micro_batch is not None:
            train_batch = micro_batch * ws
            gas = 1
        else:
            raise DeepSpeedConfigError(
                "Either train_batch_size or train_micro_batch_size_per_gpu needs to be provided")

        self.train_batch_size = train_batch
        self.train_micro_batch_size_per_gpu = micro_batch
        self.gradient_accumulation_steps = gas

        self._batch_assertion()

    def _batch_assertion(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps
        assert train_batch > 0, f"Train batch size: {train_batch} has to be greater than 0"
        assert micro_batch > 0, f"Micro batch size per gpu: {micro_batch} has to be greater than 0"
        assert gas > 0, f"Gradient accumulation steps: {gas} has to be greater than 0"
        assert train_batch == micro_batch * gas * self.world_size, (
            f"Check batch related parameters. train_batch_size is not equal to "
            f"micro_batch_per_gpu * gradient_acc_step * world_size "
            f"{train_batch} != {micro_batch} * {gas} * {self.world_size}")

    def _do_sanity_check(self):
        if self.fp16.enabled and self.bf16.enabled:
            raise DeepSpeedConfigError("fp16 and bf16 cannot both be enabled")
        if self.optimizer_name is not None and \
                self.optimizer_name not in C.DEEPSPEED_OPTIMIZERS:
            # torch-style names fall through to optax equivalents; only warn.
            logger.warning(f"Optimizer '{self.optimizer_name}' is not a DeepSpeed-native "
                           f"optimizer; resolving via the generic optax registry.")
        if self.zero_config.stage > 0 and self.amp_enabled:
            raise DeepSpeedConfigError("amp and ZeRO are not compatible (reference parity)")

    def print(self, name="DeepSpeedConfig"):
        import json
        from .config_utils import ScientificNotationEncoder
        logger.info(f"{name}:")
        logger.info(json.dumps(self._param_dict, cls=ScientificNotationEncoder, indent=4))

    @property
    def zero_enabled(self):
        return self.zero_config.stage > 0

    @property
    def zero_optimization_stage(self):
        return self.zero_config.stage

    @property
    def precision_dtype(self):
        """Compute dtype implied by the config ('bfloat16'|'float16'|'float32')."""
        if self.bf16.enabled:
            return "bfloat16"
        if self.fp16.enabled:
            return "float16"
        return "float32"
