"""Config key constants + defaults.

Mirrors the JSON config surface of the reference (``deepspeed/runtime/constants.py``,
453 LoC; key inventory in SURVEY.md §8.1) so that existing DeepSpeed JSON configs
parse unchanged.  Keys whose semantics are CUDA-specific (e.g. ``amp`` /
apex) are accepted and either mapped to a TPU equivalent or recorded as no-ops.
"""

#############################################
# Batch size / schedule
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None

TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None

GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

#############################################
# Optimizer / scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
LEGACY_FUSION_DEFAULT = False

SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"

MAX_GRAD_NORM = "max_grad_norm"

# Optimizer type names accepted by the reference (`engine.py:917-930`)
ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
ADAGRAD_OPTIMIZER = "adagrad"
SGD_OPTIMIZER = "sgd"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER, ADAMW_OPTIMIZER, LAMB_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER,
    ONEBIT_LAMB_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER, ADAGRAD_OPTIMIZER, SGD_OPTIMIZER
]

#############################################
# Precision: fp16 / bf16 / amp
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 16
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1
FP16_MASTER_WEIGHTS_AND_GRADS = "fp16_master_weights_and_grads"
FP16_MASTER_WEIGHTS_AND_GRADS_DEFAULT = False

BFLOAT16 = "bf16"
BFLOAT16_OLD = "bfloat16"  # reference accepts both spellings
BFLOAT16_ENABLED = "enabled"
BFLOAT16_ENABLED_DEFAULT = False

AMP = "amp"
AMP_ENABLED = "enabled"
AMP_ENABLED_DEFAULT = False

GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False

GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

SPARSE_GRADIENTS = "sparse_gradients"

# reference "data_types" section (grad accumulation dtype)
DATA_TYPES = "data_types"
GRAD_ACCUM_DTYPE = "grad_accum_dtype"
GRAD_ACCUM_DTYPE_DEFAULT = "fp32"
SPARSE_GRADIENTS_DEFAULT = False

COMMUNICATION_DATA_TYPE = "communication_data_type"
COMMUNICATION_DATA_TYPE_DEFAULT = None

DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False

#############################################
# ZeRO (`zero/config.py:18-42` in reference)
#############################################
ZERO_OPTIMIZATION = "zero_optimization"

#############################################
# Activation checkpointing
#############################################
ACTIVATION_CHECKPOINTING = "activation_checkpointing"

#############################################
# Misc engine behavior
#############################################
DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False

WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False

MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

#############################################
# Tensorboard / monitoring
#############################################
TENSORBOARD = "tensorboard"
TENSORBOARD_ENABLED = "enabled"
TENSORBOARD_ENABLED_DEFAULT = False
TENSORBOARD_OUTPUT_PATH = "output_path"
TENSORBOARD_OUTPUT_PATH_DEFAULT = ""
TENSORBOARD_JOB_NAME = "job_name"
TENSORBOARD_JOB_NAME_DEFAULT = "DeepSpeedJobName"

# unified runtime telemetry (deepspeed_tpu/monitor; docs/monitoring.md).
# Env DSTPU_MONITOR (set by `deepspeed --monitor`) overrides `enabled` in
# either direction; DSTPU_MONITOR_DIR (`--monitor-dir`) supplies the run
# dir when the config gives none.
MONITOR = "monitor"
MONITOR_ENABLED = "enabled"
MONITOR_ENABLED_DEFAULT = False
MONITOR_SINKS = "sinks"
MONITOR_SINKS_DEFAULT = ["jsonl", "ring"]
MONITOR_SINKS_VALID = ("jsonl", "csv", "ring", "tensorboard")
MONITOR_DIR = "dir"
MONITOR_DIR_DEFAULT = None             # None -> DSTPU_MONITOR_DIR or ./ds_monitor
MONITOR_INTERVAL = "interval"
MONITOR_INTERVAL_DEFAULT = 1           # emit every Nth step
MONITOR_TRACE_STEPS = "trace_steps"
MONITOR_TRACE_STEPS_DEFAULT = None     # [start, stop] -> jax.profiler window
MONITOR_RING_SIZE = "ring_size"
MONITOR_RING_SIZE_DEFAULT = 1024       # in-memory event ring length
MONITOR_MEMORY_INTERVAL = "memory_interval"
MONITOR_MEMORY_INTERVAL_DEFAULT = 50   # steps between memory-ledger `mem`
MONITOR_RUN_ID = "run_id"
MONITOR_RUN_ID_DEFAULT = None          # None -> DSTPU_RUN_ID or host-pid
MONITOR_ROTATE_MB = "rotate_mb"
MONITOR_ROTATE_MB_DEFAULT = 0          # 0 = no JSONL segment rotation
MONITOR_SLO = "slo"
MONITOR_SLO_DEFAULT = None             # None = SLO engine off; else the
#                                        monitor.slo block (monitor/slo.py)
#                                        events (0 disables the ledger)

# lifecycle shadow sanitizer (analysis/sanitize.py;
# docs/static-analysis.md#sanitizer).  Env DSTPU_SANITIZE (set by
# `deepspeed --sanitize` / `--no-sanitize`) overrides `enabled` in
# either direction, the monitor/comms-compression arming pattern.
ANALYSIS = "analysis"
ANALYSIS_SANITIZE = "sanitize"
ANALYSIS_SANITIZE_ENABLED = "enabled"
ANALYSIS_SANITIZE_ENABLED_DEFAULT = False   # OFF: zero cost by default
ANALYSIS_SANITIZE_HALT = "halt"
ANALYSIS_SANITIZE_HALT_DEFAULT = True  # raise at the first finding

#############################################
# Profiling
#############################################
FLOPS_PROFILER = "flops_profiler"
FLOPS_PROFILER_ENABLED = "enabled"
FLOPS_PROFILER_ENABLED_DEFAULT = False
FLOPS_PROFILER_PROFILE_STEP = "profile_step"
FLOPS_PROFILER_PROFILE_STEP_DEFAULT = 1
FLOPS_PROFILER_MODULE_DEPTH = "module_depth"
FLOPS_PROFILER_MODULE_DEPTH_DEFAULT = -1
FLOPS_PROFILER_TOP_MODULES = "top_modules"
FLOPS_PROFILER_TOP_MODULES_DEFAULT = 1
FLOPS_PROFILER_DETAILED = "detailed"
FLOPS_PROFILER_DETAILED_DEFAULT = True
FLOPS_PROFILER_OUTPUT_FILE = "output_file"
FLOPS_PROFILER_OUTPUT_FILE_DEFAULT = None

#############################################
# Sparse attention (`config.py:347-530` in reference)
#############################################
SPARSE_ATTENTION = "sparse_attention"
SPARSE_DENSE_MODE = "dense"
SPARSE_FIXED_MODE = "fixed"
SPARSE_VARIABLE_MODE = "variable"
SPARSE_BIGBIRD_MODE = "bigbird"
SPARSE_BSLONGFORMER_MODE = "bslongformer"
SPARSE_MODE = "mode"
SPARSE_MODE_DEFAULT = SPARSE_FIXED_MODE
SPARSE_BLOCK = "block"
SPARSE_BLOCK_DEFAULT = 16
SPARSE_DIFFERENT_LAYOUT_PER_HEAD = "different_layout_per_head"
SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT = False
SPARSE_NUM_LOCAL_BLOCKS = "num_local_blocks"
SPARSE_NUM_LOCAL_BLOCKS_DEFAULT = 4
SPARSE_NUM_GLOBAL_BLOCKS = "num_global_blocks"
SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT = 1
SPARSE_ATTENTION_TYPE = "attention"
SPARSE_ATTENTION_TYPE_DEFAULT = "bidirectional"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION = "horizontal_global_attention"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT = False
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS = "num_different_global_patterns"
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS_DEFAULT = 1
SPARSE_NUM_RANDOM_BLOCKS = "num_random_blocks"
SPARSE_NUM_RANDOM_BLOCKS_DEFAULT = 0
SPARSE_LOCAL_WINDOW_BLOCKS = "local_window_blocks"
SPARSE_LOCAL_WINDOW_BLOCKS_DEFAULT = [4]
SPARSE_GLOBAL_BLOCK_INDICES = "global_block_indices"
SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT = [0]
SPARSE_GLOBAL_BLOCK_END_INDICES = "global_block_end_indices"
SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT = None
SPARSE_NUM_SLIDING_WINDOW_BLOCKS = "num_sliding_window_blocks"
SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT = 3

#############################################
# Pipeline (`config.py:531-543` in reference)
#############################################
PIPELINE = "pipeline"
PIPELINE_STAGES = "stages"
PIPELINE_STAGES_DEFAULT = None
PIPELINE_PARTITION = "partition"
PIPELINE_PARTITION_DEFAULT = "best"
PIPELINE_SEED_LAYERS = "seed_layers"
PIPELINE_SEED_LAYERS_DEFAULT = False
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL = "activation_checkpoint_interval"
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL_DEFAULT = 0

#############################################
# Progressive layer drop
#############################################
PROGRESSIVE_LAYER_DROP = "progressive_layer_drop"
PLD_ENABLED = "enabled"
PLD_ENABLED_DEFAULT = False
PLD_THETA = "theta"
PLD_THETA_DEFAULT = 1.0
PLD_GAMMA = "gamma"
PLD_GAMMA_DEFAULT = 0.001

#############################################
# Curriculum learning
#############################################
CURRICULUM_LEARNING = "curriculum_learning"
CURRICULUM_ENABLED = "enabled"
CURRICULUM_ENABLED_DEFAULT = False

#############################################
# Eigenvalue (MoQ)
#############################################
EIGENVALUE = "eigenvalue"
EIGENVALUE_ENABLED = "enabled"
EIGENVALUE_ENABLED_DEFAULT = False
EIGENVALUE_VERBOSE = "verbose"
EIGENVALUE_VERBOSE_DEFAULT = False
EIGENVALUE_MAX_ITER = "max_iter"
EIGENVALUE_MAX_ITER_DEFAULT = 100
EIGENVALUE_TOL = "tol"
EIGENVALUE_TOL_DEFAULT = 1e-2
EIGENVALUE_STABILITY = "stability"
EIGENVALUE_STABILITY_DEFAULT = 1e-6
EIGENVALUE_GAS_BOUNDARY_RESOLUTION = "gas_boundary_resolution"
EIGENVALUE_GAS_BOUNDARY_RESOLUTION_DEFAULT = 1
EIGENVALUE_LAYER_NAME = "layer_name"
EIGENVALUE_LAYER_NAME_DEFAULT = "bert.encoder.layer"
EIGENVALUE_LAYER_NUM = "layer_num"
EIGENVALUE_LAYER_NUM_DEFAULT = 0

#############################################
# Quantize training (MoQ)
#############################################
QUANTIZE_TRAINING = "quantize_training"
QUANTIZE_TRAINING_ENABLED = "enabled"
QUANTIZE_TRAINING_ENABLED_DEFAULT = False

#############################################
# Checkpoint
#############################################
CHECKPOINT = "checkpoint"
CHECKPOINT_TAG_VALIDATION = "tag_validation"
CHECKPOINT_TAG_VALIDATION_DEFAULT = "Warn"
CHECKPOINT_TAG_VALIDATION_MODES = ["Warn", "Ignore", "Fail"]

LOAD_UNIVERSAL_CHECKPOINT = "load_universal"
LOAD_UNIVERSAL_CHECKPOINT_DEFAULT = False

# fault-tolerance layer: atomic-commit retention / validation / auto-resume
CHECKPOINT_KEEP_N = "keep_n"
CHECKPOINT_KEEP_N_DEFAULT = 0          # 0 = keep everything
CHECKPOINT_VERIFY = "verify"
CHECKPOINT_VERIFY_DEFAULT = "full"     # full | size | off
CHECKPOINT_VERIFY_MODES = ["full", "size", "off"]
CHECKPOINT_AUTO_RESUME = "auto_resume"
CHECKPOINT_AUTO_RESUME_DEFAULT = False
CHECKPOINT_DIR = "dir"
CHECKPOINT_DIR_DEFAULT = None
CHECKPOINT_FSYNC = "fsync"
CHECKPOINT_FSYNC_DEFAULT = True

#############################################
# IO retry (checkpoint + NVMe swap backoff)
#############################################
IO_RETRY = "io_retry"
IO_RETRY_MAX_ATTEMPTS = "max_attempts"
IO_RETRY_MAX_ATTEMPTS_DEFAULT = 5
IO_RETRY_BASE_DELAY_S = "base_delay_s"
IO_RETRY_BASE_DELAY_S_DEFAULT = 0.05
IO_RETRY_MAX_DELAY_S = "max_delay_s"
IO_RETRY_MAX_DELAY_S_DEFAULT = 2.0
IO_RETRY_JITTER = "jitter"
IO_RETRY_JITTER_DEFAULT = 0.25
IO_RETRY_FULL_JITTER = "full_jitter"
IO_RETRY_FULL_JITTER_DEFAULT = False   # True = AWS-style uniform(0, nominal)
IO_RETRY_MAX_ELAPSED_S = "max_elapsed_s"
IO_RETRY_MAX_ELAPSED_S_DEFAULT = None  # None = no overall wall-clock cap

#############################################
# Health guardian (divergence sentinels + skip/rewind/abort escalation)
#############################################
HEALTH_CHECK = "health_check"
HEALTH_ENABLED = "enabled"
HEALTH_ENABLED_DEFAULT = True
HEALTH_SKIP_NONFINITE = "skip_nonfinite"
HEALTH_SKIP_NONFINITE_DEFAULT = True
HEALTH_SPIKE_WINDOW = "spike_window"
HEALTH_SPIKE_WINDOW_DEFAULT = 50       # EMA horizon (steps) for loss stats
HEALTH_SPIKE_ZMAX = "spike_zmax"
HEALTH_SPIKE_ZMAX_DEFAULT = 0.0        # 0 = spike detection off
HEALTH_SKIP_ON_SPIKE = "skip_on_spike"
HEALTH_SKIP_ON_SPIKE_DEFAULT = False
HEALTH_SKIP_BUDGET = "consecutive_skip_budget"
HEALTH_SKIP_BUDGET_DEFAULT = 10        # 0 = never escalate past skipping
HEALTH_REWIND_LIMIT = "rewind_limit"
HEALTH_REWIND_LIMIT_DEFAULT = 4        # per poison episode (in-process, cheap)
HEALTH_ON_EXHAUSTED = "on_exhausted"
HEALTH_ON_EXHAUSTED_DEFAULT = "abort"
HEALTH_ON_EXHAUSTED_MODES = ["abort", "warn"]
HEALTH_CHECK_INTERVAL = "check_interval"
HEALTH_CHECK_INTERVAL_DEFAULT = 1      # monitor trails the device by N steps
HEALTH_HISTORY = "history"
HEALTH_HISTORY_DEFAULT = 64            # forensic ring-buffer length (steps)
HEALTH_FORENSIC_DIR = "forensic_dir"
HEALTH_FORENSIC_DIR_DEFAULT = None     # None -> checkpoint.dir or cwd

#############################################
# Compile cache (persistent AOT executables; runtime/compile_cache.py)
#############################################
COMPILE_CACHE = "compile_cache"
COMPILE_CACHE_ENABLED = "enabled"
COMPILE_CACHE_ENABLED_DEFAULT = True   # active iff a dir resolves
COMPILE_CACHE_DIR = "dir"
COMPILE_CACHE_DIR_DEFAULT = None       # None -> env DSTPU_COMPILE_CACHE
COMPILE_CACHE_MAX_ENTRIES = "max_entries"
COMPILE_CACHE_MAX_ENTRIES_DEFAULT = 0  # 0 = unbounded (no LRU eviction)
COMPILE_CACHE_READONLY = "readonly"
COMPILE_CACHE_READONLY_DEFAULT = False # True = shared CI cache, never writes

#############################################
# Quantized ZeRO collectives (runtime/comm/quantized.py + collective_router.py)
#############################################
COMMS_COMPRESSION = "comms_compression"
COMMS_COMPRESSION_ENABLED = "enabled"
COMMS_COMPRESSION_ENABLED_DEFAULT = False   # tier-1 numerics untouched
COMMS_COMPRESSION_WEIGHTS_BITS = "weights_bits"
COMMS_COMPRESSION_WEIGHTS_BITS_DEFAULT = 8  # qwZ: int8 param all-gather
COMMS_COMPRESSION_GRADS_BITS = "grads_bits"
COMMS_COMPRESSION_GRADS_BITS_DEFAULT = 8    # qgZ: int8 grad reduce
COMMS_COMPRESSION_BLOCK_SIZE = "block_size"
COMMS_COMPRESSION_BLOCK_SIZE_DEFAULT = 1024
COMMS_COMPRESSION_HIERARCHICAL = "hierarchical"
COMMS_COMPRESSION_HIERARCHICAL_DEFAULT = True
COMMS_COMPRESSION_MIN_TENSOR_BYTES = "min_tensor_bytes"
COMMS_COMPRESSION_MIN_TENSOR_BYTES_DEFAULT = 65536
COMMS_COMPRESSION_EXCLUDED = "excluded"
# norm/bias-style leaves keep the full-width wire (lossy delivery of
# scale/shift vectors is all pain, no bytes — they are tiny)
COMMS_COMPRESSION_EXCLUDED_DEFAULT = ["bias", "norm", "ln_", "layernorm",
                                      "/b"]
COMMS_COMPRESSION_ROUTES = "routes"
COMMS_COMPRESSION_ROUTES_DEFAULT = ["z1", "z2", "z3", "param_stream", "moe"]
COMMS_COMPRESSION_ROUTES_VALID = ["z1", "z2", "z3", "param_stream", "pipe",
                                  "moe"]
# per-route knobs for the expert-parallel dispatch wire (moe route):
# activations tolerate coarser blocks than weights, so the block size is
# independently tunable; bits=None keeps the route full-width even when
# listed in routes
COMMS_COMPRESSION_MOE = "moe"
COMMS_COMPRESSION_MOE_BITS = "bits"
COMMS_COMPRESSION_MOE_BITS_DEFAULT = 8      # int8 dispatch/combine payload
COMMS_COMPRESSION_MOE_BLOCK_SIZE = "block_size"
COMMS_COMPRESSION_MOE_BLOCK_SIZE_DEFAULT = None   # None -> global block_size

#############################################
# Dataloader
#############################################
DATALOADER_DROP_LAST = "dataloader_drop_last"
DATALOADER_DROP_LAST_DEFAULT = False

#############################################
# AIO (NVMe offload)
#############################################
AIO = "aio"
AIO_DEFAULT_DICT = {
    "block_size": 1048576,
    "queue_depth": 8,
    "thread_count": 1,
    "single_submit": False,
    "overlap_events": True,
}
AIO_BLOCK_SIZE = "block_size"
AIO_QUEUE_DEPTH = "queue_depth"
AIO_THREAD_COUNT = "thread_count"
AIO_SINGLE_SUBMIT = "single_submit"
AIO_OVERLAP_EVENTS = "overlap_events"

#############################################
# Elasticity (`elasticity/constants.py:12-25` in reference)
#############################################
ELASTICITY = "elasticity"

#############################################
# Autotuning
#############################################
AUTOTUNING = "autotuning"

#############################################
# TPU-specific extensions (new keys; absent keys keep DeepSpeed defaults)
#############################################
MESH = "mesh"  # {"axes": {"data": -1, "fsdp": 1, "tensor": 1, "expert": 1, "pipe": 1, "seq": 1}}
SEQUENCE_PARALLEL = "sequence_parallel"  # {"enabled": bool, "mode": "ring"|"ulysses", "degree": int}

#############################################
# Routing / gradient reduce
#############################################
ROUND_ROBIN_GRADIENTS = "round_robin_gradients"
