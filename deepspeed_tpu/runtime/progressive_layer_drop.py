"""Progressive Layer Drop (https://arxiv.org/pdf/2010.13369.pdf).

Parity: reference ``deepspeed/runtime/progressive_layer_drop.py`` —
``theta(t) = (1 - θ)·e^(−γ·t) + θ`` keep-probability schedule passed into the
model forward.  On TPU the model consumes ``pld_theta`` as a per-layer keep
probability drawn with the step rng (stochastic depth over the scanned layer
stack stays shape-static: dropped layers multiply by 0 through the residual).
"""

import numpy as np

from ..utils.logging import log_dist


class ProgressiveLayerDrop:
    def __init__(self, theta=0.5, gamma=0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0
        log_dist(f"Enabled progressive layer dropping (theta = {self.theta})",
                 ranks=[0])

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self):
        return self.current_theta

    def update_state(self, global_step):
        self.current_theta = (1.0 - self.theta) * np.exp(
            -self.gamma * global_step) + self.theta
        return self.current_theta
