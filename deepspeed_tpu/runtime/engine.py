"""DeepSpeedEngine: the train-loop wrapper, re-designed TPU-native.

Parity: reference ``deepspeed/runtime/engine.py:168`` (``DeepSpeedEngine``).
The reference wraps a torch ``nn.Module`` and exposes imperative
``forward/backward/step``; behavior (ZeRO stage, precision, optimizer,
schedule) is driven by the JSON config.  This engine keeps the config surface
and the API names, but the hot path is ONE jitted SPMD train step:

  - grad accumulation  = ``lax.scan`` over the microbatch axis
    (reference: per-micro-batch backward + bucketed hook reduction,
    ``engine.py:1684``)
  - DP grad averaging  = mean over the globally-sharded batch; XLA inserts the
    all-reduce (reference ``allreduce_gradients`` ``engine.py:1663``)
  - ZeRO 1/2/3         = sharding placement of master/opt/grads/params over
    the ``fsdp`` mesh axis (see ``runtime/zero/partition.py``)
  - fp16 loss scaling  = branchless skip-step with on-device scaler state
    (reference ``_take_model_step`` overflow path, ``engine.py:1819-1871``)
  - checkpoint save/load with the reference's directory layout
    (``engine.py:2797 save_checkpoint``, ``:2467 load_checkpoint``)

The imperative ``forward()/backward()/step()`` trio is provided as a
compatibility shim that stages microbatches and executes the fused step at the
gradient-accumulation boundary.
"""

import json
import os
import time
from functools import partial
from typing import Any, Callable, NamedTuple, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .config import DeepSpeedConfig
from . import constants as C
from . import health as hmod
from .fp16 import loss_scaler as ls
from .lr_schedules import get_lr_scheduler
from .dataloader import DeepSpeedDataLoader, RepeatingLoader
from .utils import (DummyOptim, clip_by_global_norm, global_norm, tree_cast,
                    see_memory_usage)
from .zero import partition as zpart
from ..ops.adam.fused_adam import FusedAdam, FusedAdamW
from ..ops.lamb.fused_lamb import FusedLamb
from ..parallel import mesh as M
from ..utils.logging import logger, log_dist
from ..utils.timer import SynchronizedWallClockTimer, ThroughputTimer

from ..checkpoint.constants import MODEL_FILE, OPTIM_FILE


class TrainState(NamedTuple):
    """Device-resident training state (one pytree, donated each step)."""
    global_steps: jnp.ndarray      # i32 — optimizer boundaries seen (incl. skipped)
    optimizer_steps: jnp.ndarray   # i32 — actual optimizer steps (Adam bias corr.)
    skipped_steps: jnp.ndarray     # i32 — overflow/health-skipped steps
    params: Any                    # compute-dtype params (sharded per ZeRO stage)
    master: Any                    # fp32 master params (None when training fp32)
    opt_state: Any
    scale: Any                     # LossScaleState (None unless fp16)
    health: Any = None             # health.HealthState (None when guardian off)
    comm_error: Any = None         # qgZ per-shard error feedback (None unless
    #                                comms_compression grads route is active)


def _resolve_model(model, loss_fn, params, apply_fn, rng_seed,
                   init_on_host=False):
    """Accept either a model object (``.init``/``.loss``[/``.apply``]) or an
    explicit (loss_fn, params) pair."""
    tp_specs = None
    if model is not None:
        if loss_fn is None:
            assert hasattr(model, "loss"), \
                "model must expose .loss(params, batch, rng) or pass loss_fn="
            loss_fn = model.loss
        if params is None:
            assert hasattr(model, "init"), "model must expose .init(rng) -> params"
            # jit the WHOLE init: eager per-leaf RNG ops are one device
            # dispatch each — on a remote-attached chip (~0.5-1 s round-trip
            # latency) a billion-param model's init takes tens of minutes
            # eagerly vs one compile + one dispatch jitted.
            # init_on_host (offload): create params on the HOST CPU backend —
            # the fp32 master then builds from local memory (no multi-GB d2h)
            # and only the 16-bit image crosses to the device.
            trace_errors = (jax.errors.TracerArrayConversionError,
                            jax.errors.TracerBoolConversionError,
                            jax.errors.TracerIntegerConversionError,
                            jax.errors.ConcretizationTypeError,
                            jax.errors.UnexpectedTracerError)
            try:
                if init_on_host:
                    with jax.default_device(jax.devices("cpu")[0]):
                        params = jax.jit(model.init)(
                            jax.random.PRNGKey(rng_seed))
                else:
                    params = jax.jit(model.init)(jax.random.PRNGKey(rng_seed))
            except trace_errors:
                # init closures that resist tracing (python-side state):
                # fall back to eager — but KEEP the host placement, or an
                # offload-sized model's init lands on (and OOMs) the device.
                # Any other error propagates; swallowing it here used to
                # hide real init bugs behind a minutes-slow eager retry.
                logger.warning("model.init does not trace (python-side "
                               "state?); falling back to eager init")
                if init_on_host:
                    with jax.default_device(jax.devices("cpu")[0]):
                        params = model.init(jax.random.PRNGKey(rng_seed))
                else:
                    params = model.init(jax.random.PRNGKey(rng_seed))
        if apply_fn is None and hasattr(model, "apply"):
            apply_fn = model.apply
        tp_specs = getattr(model, "partition_specs", None)
        if callable(tp_specs):
            tp_specs = tp_specs(params)
    assert loss_fn is not None and params is not None, \
        "Provide either model= (with .init/.loss) or loss_fn= and params="
    return loss_fn, params, apply_fn, tp_specs


class DeepSpeedEngine:
    """Config-driven training engine over a jitted SPMD step."""

    # The fused SPMD step's ZeRO wire routes through the collective
    # router (qwZ/qgZ); PipelineEngine schedules its own collectives and
    # opts out (the pipe route is accepted-but-full-width for now).
    _supports_comms_compression = True

    def __init__(self, model=None, optimizer=None, config=None, config_params=None,
                 training_data=None, lr_scheduler=None, mesh=None, collate_fn=None,
                 loss_fn=None, params=None, apply_fn=None, rng_seed=0, mpu=None,
                 dist_init_required=None, dont_change_device=False, elastic=None,
                 monitor=None):
        config = config if config is not None else config_params
        assert config is not None, "DeepSpeed requires --deepspeed_config to specify configuration file"

        # ---- mesh first (config batch math needs dp world size) ----------
        if mesh is None:
            from .config_utils import load_config_dict
            raw = load_config_dict(config)
            mesh = M.make_mesh(raw.get(C.MESH, {}).get("axes", None))
            config = raw
        self.mesh = mesh
        self.mesh_ctx = M.MeshContext(mesh)
        self.config = DeepSpeedConfig(config, world_size=self.mesh_ctx.dp_world_size,
                                      elastic=elastic)

        # ---- unified runtime telemetry (monitor/; docs/monitoring.md) -----
        # Event bus + monitor-side spans/gauges/counters.  The `monitor`
        # kwarg outranks env DSTPU_MONITOR outranks the config block
        # (the --elastic/--health-check precedence pattern).  All
        # instrumentation is host-side: an armed monitor leaves the
        # compiled step byte-identical (--audit-step monitor).
        from ..monitor import core as moncore
        self.monitor = moncore.from_config(
            self.config.monitor_config, override_enabled=monitor,
            retry=self.config.io_retry_config.policy(), role="train")
        if not self.monitor.armed and self.config.wall_clock_breakdown:
            # wall_clock_breakdown alone still needs measured spans: arm a
            # bus-less monitor (no sinks, nothing written) so the span
            # recorder feeds the named-timer breakdown log
            self.monitor = moncore.Monitor(run_dir=None, sinks=())
        self._mon_tokens_per_step = None   # lazy: first stacked batch
        self._mon_step_stats = None        # lazy: per-program flops/wire
        self._mon_example = None           # (batch, rng) for one-time pricing

        self.zero_stage = self.config.zero_optimization_stage
        self.compute_dtype = {"bfloat16": jnp.bfloat16, "float16": jnp.float16,
                              "float32": jnp.float32}[self.config.precision_dtype]
        self.fp16_enabled = self.config.fp16.enabled
        self.bfloat16_enabled = self.config.bf16.enabled

        # ---- training health guardian (runtime/health.py) ----------------
        # On-device divergence sentinels + branchless skip-step for EVERY
        # precision (the fp16 scaler covers only fp16 overflow; a NaN/Inf
        # gradient under bf16 — the TPU default — would otherwise be
        # written irrecoverably into params), plus the host-side
        # skip -> rewind -> abort escalation ladder.
        self._health_cfg = self.config.health_check
        self._health_enabled = self._health_cfg.enabled
        self.health_monitor = (
            hmod.HealthMonitor(self._health_cfg,
                               bus=(self.monitor.bus if self.monitor.armed
                                    else None))
            if self._health_enabled else None)
        self._stream_step = 0        # monotonic data-stream batch index
        self._last_batch_index = None  # stream index of the running step
        # True while _stream_step and the live iterator agree (fresh engine,
        # or a load that restored the loader state); loading a pre-guardian
        # checkpoint loses the correspondence and disables fast-forward
        self._stream_pos_known = True
        self._ff_stride = 1          # same-episode rewind fast-forward stride
        self._last_ckpt_dir = self.config.checkpoint_config.dir

        # ---- persistent compiled-step cache (runtime/compile_cache.py) ----
        # AOT warm-start: every jitted entry point below dispatches through
        # a CachedStep, so a process restart (bench rung, CI worker,
        # auto-resume, rewind-and-replay) deserializes yesterday's
        # executable instead of re-paying ~50s of XLA compilation.
        from . import compile_cache as ccache
        self.compile_cache = ccache.from_config(
            self.config.compile_cache_config)
        self._cc_key_slice = self._cache_key_slice()

        # ---- model ---------------------------------------------------------
        self.module = model
        if (self.zero_stage >= 3 and self.mesh_ctx.fsdp_size > 1
                and getattr(getattr(model, "config", None),
                            "unroll_layers", False)):
            log_dist(
                "unroll_layers with ZeRO-3 nearly doubles live memory: the "
                "unrolled program gathers layers less incrementally than the "
                "scanned one (measured 1.8x temp bytes on the fsdp mesh). "
                "Prefer the scanned layer loop (unroll_layers=False) at "
                "stage 3.", ranks=[0])
        # mirrors the _offload construction condition below: an eval-only
        # engine (DummyOptim) or a client-object optimizer never builds the
        # host tier, so its params must NOT be committed to the CPU backend
        offload_wanted = (self.config.zero_config.offload_optimizer_device()
                          in ("cpu", "nvme")
                          and optimizer is None
                          and self.config.optimizer_name is not None)
        # ---- ZeRO-3 parameter offload (streamed layer blocks) ------------
        # reference: stage3.py:656 _configure_offloading + the param tier of
        # swap_tensor/ — params live on host/NVMe, so offload_param REQUIRES
        # the host optimizer tier (there is nowhere on-device to keep a
        # master) and the decomposed-forward contract from the model.
        param_stream_wanted = (
            self.config.zero_config.offload_param_device() in ("cpu", "nvme"))
        if param_stream_wanted:
            if self.zero_stage != 3:
                raise ValueError(
                    "zero_optimization.offload_param requires stage 3 "
                    f"(got stage {self.zero_stage})")
            if not offload_wanted:
                raise ValueError(
                    "offload_param requires offload_optimizer (cpu or nvme) "
                    "with a config-specified Adam/AdamW: streamed parameters "
                    "have no device-resident master for an in-device "
                    "optimizer to update")
            if self.fp16_enabled:
                raise ValueError(
                    "offload_param does not support fp16 dynamic loss "
                    "scaling; use bf16 (TPU-native) or fp32")
            if not callable(getattr(model, "stream_fns", None)):
                raise ValueError(
                    "offload_param needs a model exposing stream_fns() "
                    "(decomposed embed/block/head forward) — GPT2 and "
                    "compatible families provide it")
            if self.mesh.size > 1:
                raise ValueError(
                    "offload_param streaming is single-chip scale-up "
                    "machinery; on a multi-chip mesh use ZeRO-3 sharding "
                    "(params shard over the fsdp axis) without offload_param")
        self._param_stream = None
        if param_stream_wanted and params is None and \
                self.config.zero_config.offload_param.fast_init:
            # host numpy init: the jitted XLA-CPU init costs minutes and
            # ~3x the tree in transient RAM at multi-billion params
            if not callable(getattr(model, "init_numpy", None)):
                raise ValueError(
                    "offload_param.fast_init requires the model to expose "
                    "init_numpy(seed) (a host-RAM init twin)")
            params = model.init_numpy(rng_seed)
        self._loss_fn, params0, self._apply_fn, self._tp_specs = _resolve_model(
            model, loss_fn, params, apply_fn, rng_seed,
            init_on_host=offload_wanted)
        # one jitted cast, not one dispatch per leaf (dispatch latency on a
        # remote-attached chip makes eager tree_map casts minutes-slow);
        # under offload the cast runs ON THE HOST backend — the default-
        # device jit would silently haul the tree to the accelerator
        f32 = lambda t: tree_cast(t, jnp.float32)
        if all(np.dtype(l.dtype) == np.float32
               for l in jax.tree_util.tree_leaves(params0)):
            pass      # already fp32: skip the cast (a copy of the whole
            # tree — prohibitive transient RAM at beyond-HBM param counts)
        elif offload_wanted:
            with jax.default_device(jax.devices("cpu")[0]):
                params0 = jax.jit(f32)(params0)
        else:
            params0 = jax.jit(f32)(params0)

        # ---- quantized-collectives router (runtime/comm/) ----------------
        # Per-route wire policy: qwZ int8 param gathers, qgZ error-fed
        # int8 grad reduction, 1-bit optimizer transport.  Default-off
        # policy => the router degrades to plain sharding constraints.
        from .comm.collective_router import CollectiveRouter
        self._router = CollectiveRouter(
            self.config.comms_compression, self.mesh, self.mesh_ctx,
            self.zero_stage,
            supports_zero_routes=self._supports_comms_compression)
        self._onebit_transport = None
        # quantized expert-parallel dispatch (moe route): the wire is
        # process-global so moe/layer.py finds it at trace time; install
        # it now AND before every step dispatch (_install_moe_wire) so a
        # retrace under THIS engine never sees another engine's policy
        self._moe_wire = self._router.moe_wire()
        self._install_moe_wire()
        if self._router.weights_active or self._router.grads_active \
                or self._router.moe_active:
            log_dist("comms_compression active: "
                     f"{self._router.describe()}", ranks=[0])

        # ---- optimizer -----------------------------------------------------
        self.optimizer = self._configure_optimizer(optimizer)
        # ---- lr scheduler --------------------------------------------------
        self.lr_scheduler = self._configure_lr_scheduler(lr_scheduler)

        # ---- shardings (ZeRO stages as placement; partition.py) -----------
        fsdp = self.mesh_ctx.fsdp_size
        self._param_specs = zpart.param_specs(
            params0, self.zero_stage, fsdp,
            persistence_threshold=self.config.zero_config.param_persistence_threshold,
            tp_specs=self._tp_specs)
        self._master_specs = zpart.master_specs(params0, self.zero_stage, fsdp,
                                                tp_specs=self._tp_specs)
        self._grad_specs = zpart.grad_specs(params0, self.zero_stage, fsdp,
                                            tp_specs=self._tp_specs)
        self._param_sh = zpart.to_named(self._param_specs, self.mesh)
        self._master_sh = zpart.to_named(self._master_specs, self.mesh)
        self._repl_sh = NamedSharding(self.mesh, P())

        # shape → master spec map: optimizer-state leaves that are param-shaped
        # (Adam moments etc.) inherit the master sharding.
        self._shape_spec_cache = {}
        for p, sp in zip(jax.tree_util.tree_leaves(params0),
                         jax.tree_util.tree_leaves(
                             self._master_specs, is_leaf=lambda x: isinstance(x, P))):
            self._shape_spec_cache.setdefault(np.shape(p), sp)

        # ---- host offload tier (ZeRO-Offload / -Infinity optimizer) -------
        # reference: stage_1_and_2.py cpu_offload path + stage3 swap tier
        self._offload = None
        offload_device = self.config.zero_config.offload_optimizer_device()
        if offload_device in ("cpu", "nvme") and not isinstance(self.optimizer, DummyOptim):
            if optimizer is not None:
                raise ValueError(
                    "offload_optimizer requires a config-specified Adam/AdamW "
                    "(the host tier runs its own fused step; a client "
                    "optimizer object cannot be offloaded)")
            name = self.config.optimizer_name or C.ADAM_OPTIMIZER
            assert name in (C.ADAM_OPTIMIZER, C.ADAMW_OPTIMIZER), \
                f"offload_optimizer requires Adam/AdamW (got {name!r}; " \
                "reference parity: DeepSpeedCPUAdam)"
            from .zero.offload_engine import HostOffloadOptimizer
            if param_stream_wanted:
                # layer-major flat layout: each streamed layer is one
                # contiguous host segment (zero-copy h2d views, contiguous
                # grad landing).  consume_params frees the init tree leaf
                # by leaf — at beyond-HBM scale the init tree, master and
                # moments cannot coexist in host RAM.
                from .zero import param_stream as ps
                stacked_key = model.stream_fns()["stacked_key"]
                stream_tree = ps.to_stream_tree(params0, stacked_key)
                # the per-layer slices copied the stacked leaves — free the
                # stacks now (nonblock leaves are SHARED with the stream
                # tree and get consumed by the host optimizer build)
                for leaf in jax.tree_util.tree_leaves(params0[stacked_key]):
                    if hasattr(leaf, "delete"):
                        leaf.delete()
                params0 = None
                self._offload = HostOffloadOptimizer(
                    stream_tree, self.config.zero_config,
                    self.config.aio_config, optimizer_name=name,
                    optimizer_params=self.config.optimizer_params,
                    compute_dtype_name=self.config.precision_dtype,
                    consume_params=True,
                    payload_in_ram=(self.config.zero_config
                                    .offload_param_device() == "cpu"),
                    retry=self.config.io_retry_config.policy())
                del stream_tree
                # init tree freed — NOW allocate grad buffer + RAM image
                self._offload.alloc_buffers()
                self._param_stream = ps.ParamStreamRunner(
                    model, self._offload, self.mesh, self.compute_dtype,
                    gas=self.config.gradient_accumulation_steps,
                    grad_clip=self.config.gradient_clipping,
                    zero_config=self.config.zero_config,
                    aio_config=self.config.aio_config,
                    retry=self.config.io_retry_config.policy(),
                    skip_nonfinite=(self._health_enabled
                                    and self._health_cfg.skip_nonfinite),
                    spike=((self._health_cfg.spike_window,
                            self._health_cfg.spike_zmax,
                            self._health_cfg.skip_on_spike)
                           if self._health_enabled else None),
                    compile_cache=self.compile_cache,
                    cache_key_extra=self._cc_key_slice,
                    comms_compression=self.config.comms_compression)
            else:
                self._offload = HostOffloadOptimizer(
                    params0, self.config.zero_config, self.config.aio_config,
                    optimizer_name=name,
                    optimizer_params=self.config.optimizer_params,
                    compute_dtype_name=self.config.precision_dtype,
                    retry=self.config.io_retry_config.policy())
        # one-step delayed parameter update (ZeRO-Offload DPU): device step
        # k+1 overlaps the host optimizer+transfers for step k
        off_cfg = self.config.zero_config.offload_optimizer
        self._dpu = (self._offload is not None and off_cfg is not None
                     and off_cfg.delayed_param_update)
        self._dpu_warmup = (off_cfg.delayed_param_update_warmup
                            if self._dpu else 0)
        self._pending_offload = None   # (grads, metrics) awaiting host apply
        self._pending_row_drop_checks = []   # device drop counters, read on
        # reporting steps only (no per-step host sync)
        self._jit_scatter_params = None   # flat h2d → param tree (lazy)
        self._scatter_nchunks = 0
        from .zero.wire import H2DUploader
        self._h2d = H2DUploader()

        # ---- sparse embedding gradients (reference engine.py:2227
        # sparse_allreduce_no_retain) -----------------------------------------
        # In-SPMD, gradient reduction is XLA's (sharding constraints), so the
        # wire where sparsity pays is the offload d2h transfer: declared
        # embedding leaves cross as (row indices, row values) instead of the
        # dense (vocab, dim) tensor.  Opt-in via the model's
        # ``sparse_grad_paths()`` — correctness requires the leaf to be used
        # ONLY as a lookup table (a tied LM head makes its grad dense).
        self._sparse_grad_paths = ()
        if self.config.sparse_gradients_enabled:
            declared = getattr(self.module, "sparse_grad_paths", None)
            if callable(declared):
                self._sparse_grad_paths = tuple(tuple(p) for p in declared())
            if not self._sparse_grad_paths:
                log_dist("sparse_gradients enabled but the model declares no "
                         "sparse_grad_paths(); gradients stay dense", ranks=[0])
            elif self._offload is None:
                log_dist("sparse_gradients: in-SPMD reduction is handled by "
                         "XLA sharding; the sparse wire format applies to the "
                         "offload d2h path only", ranks=[0])

        # ---- initial device state -----------------------------------------
        self.state = self._init_state(params0)
        self._needs_master = self.compute_dtype != jnp.float32

        # ---- data ----------------------------------------------------------
        self.training_dataloader = None
        self._data_iterator = None
        if training_data is not None:
            self.training_dataloader = self.deepspeed_io(training_data,
                                                         collate_fn=collate_fn)
            self._data_iterator = iter(RepeatingLoader(self.training_dataloader))

        # ---- compiled steps -------------------------------------------------
        # CachedStep wrappers: call-compatible with the jitted functions
        # (donation, .lower for the auditor/profiler) but warm-startable
        # from the persistent compile cache
        self._jit_train_step = self._wrap_step("train_step",
                                               self._train_step,
                                               donate_argnums=(0,))
        self._jit_grad_step = self._wrap_step("grad_only_step",
                                              self._grad_only_step)
        self._jit_eval = None

        # ---- curriculum learning / PLD ------------------------------------
        # (reference: engine injects curriculum_seqlen, engine.py:1596-1602;
        # PLD theta passed into model fwd, progressive_layer_drop.py)
        self.curriculum_scheduler = None
        if self.config.curriculum.enabled:
            from .data_pipeline.curriculum_scheduler import CurriculumScheduler
            self.curriculum_scheduler = CurriculumScheduler(self.config.curriculum.params)
        self.progressive_layer_drop = None
        if self.config.progressive_layer_drop.enabled:
            from .progressive_layer_drop import ProgressiveLayerDrop
            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=self.config.progressive_layer_drop.theta,
                gamma=self.config.progressive_layer_drop.gamma)

        # ---- misc parity state ---------------------------------------------
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_batch_size(),
            steps_per_output=self.config.steps_per_print,
            bus=self.monitor.bus if self.monitor.armed else None)
        self.micro_steps = 0
        self._global_steps_host = 0
        self._base_rng = jax.random.PRNGKey(rng_seed)
        self._pending_microbatches = []   # forward/backward/step shim buffer
        self._last_metrics = {}
        self.loaded_checkpoint_tag = None
        self.global_samples = 0
        if self.config.tensorboard.enabled:
            self._setup_tensorboard()
        # ---- memory ledger (monitor/memory_ledger.py) ---------------------
        # Host RSS HWM bracketed per wall-clock phase (init /
        # first-compile / steady-step) + periodic `mem` events; the
        # attribution is host-side reads only — the compiled step is
        # byte-identical ledger-on vs off (--audit-step mem).
        from ..monitor import memory_ledger as mled
        self._rss_phases = mled.RssPhases()
        self._rss_phases.mark(mled.PHASE_INIT)
        self._mem_interval = self.config.monitor_config.memory_interval
        self._oom_dumped = False
        if self.config.memory_breakdown:
            see_memory_usage("Engine initialized", force=True,
                             bus=self.monitor.bus if self.monitor.armed
                             else None)
        if self.config.prescale_gradients or \
                self.config.gradient_predivide_factor != 1.0:
            # reference: sum-allreduce with pre/post division to control
            # overflow (engine.py allreduce_gradients). Here the loss is a
            # mean over the GLOBAL batch, so XLA's reduction is already the
            # average — prescaling is implicit and numerically equivalent.
            log_dist("prescale_gradients/gradient_predivide_factor: XLA "
                     "mean-reduction already averages gradients; keys accepted "
                     "as no-ops", ranks=[0])
        log_dist(f"DeepSpeedEngine ready: zero_stage={self.zero_stage} "
                 f"dtype={self.config.precision_dtype} mesh={dict(self.mesh.shape)} "
                 f"micro_batch={self.train_micro_batch_size_per_gpu()} "
                 f"gas={self.gradient_accumulation_steps()}", ranks=[0])

    # ------------------------------------------------------------------ config
    def _configure_optimizer(self, client_optimizer):
        """Parity: reference ``engine.py:1079 _configure_optimizer`` /
        ``:1153 _configure_basic_optimizer`` (config name → optimizer)."""
        if client_optimizer is not None:
            assert hasattr(client_optimizer, "init") and hasattr(client_optimizer, "update"), \
                "client optimizer must expose .init(params) and .update(...)"
            if hasattr(client_optimizer, "set_world_size"):
                client_optimizer.set_world_size(self.mesh_ctx.dp_world_size)
            return client_optimizer
        name = self.config.optimizer_name
        if name is None:
            return DummyOptim()
        p = dict(self.config.optimizer_params or {})
        p.pop("torch_adam", None)  # accepted in reference configs; no-op here
        if name == C.ADAMW_OPTIMIZER:
            p.pop("adam_w_mode", None)  # implied by the optimizer type
        if name in (C.ADAM_OPTIMIZER,):
            opt = FusedAdam(**p)
        elif name == C.ADAMW_OPTIMIZER:
            opt = FusedAdamW(**p)
        elif name == C.LAMB_OPTIMIZER:
            opt = FusedLamb(**p)
        elif name == C.ONEBIT_ADAM_OPTIMIZER:
            from .fp16.onebit.adam import OnebitAdam
            opt = OnebitAdam(**p)
            # route the 1-bit compressed allreduce over the REAL dp mesh
            # axis (per-rank error feedback inside shard_map) — without
            # this, compressed_allreduce runs in its degenerate local
            # mode and is dead code from the engine's perspective
            self._onebit_transport = self._router.onebit_comm()
            if self._onebit_transport is not None:
                opt.set_comm(self._onebit_transport)
        elif name == C.ONEBIT_LAMB_OPTIMIZER:
            from .fp16.onebit.lamb import OnebitLamb
            opt = OnebitLamb(**p)
        elif name == C.ZERO_ONE_ADAM_OPTIMIZER:
            from .fp16.onebit.zoadam import ZeroOneAdam
            opt = ZeroOneAdam(**p)
        elif name == C.ADAGRAD_OPTIMIZER:
            from ..ops.adagrad.cpu_adagrad import DeepSpeedCPUAdagrad
            opt = DeepSpeedCPUAdagrad(**p)
        elif name == C.SGD_OPTIMIZER:
            from ..ops.sgd import SGD
            opt = SGD(**p)
        else:
            raise ValueError(f"Unknown optimizer type {name!r}")
        if hasattr(opt, "set_world_size"):
            opt.set_world_size(self.mesh_ctx.dp_world_size)
        return opt

    def _configure_lr_scheduler(self, client_scheduler):
        """Parity: reference ``engine.py:780``."""
        if client_scheduler is not None:
            return client_scheduler
        if self.config.scheduler_name is not None:
            return get_lr_scheduler(self.config.scheduler_name,
                                    self.config.scheduler_params,
                                    optimizer=self.optimizer)
        return None

    def _lr_at(self, step):
        """Traced lr as a function of the global step counter."""
        if self.lr_scheduler is not None and hasattr(self.lr_scheduler, "lr_fn"):
            return self.lr_scheduler.lr_fn(step)
        return jnp.asarray(getattr(self.optimizer, "lr", 0.0), jnp.float32)

    # ------------------------------------------------------------------- state
    def _init_state(self, params0):
        dtype = self.compute_dtype
        needs_master = dtype != jnp.float32

        if self._param_stream is not None:
            # streamed params: nothing model-sized lives on the device;
            # the runner owns the nonblock tree and the host owns the rest.
            # Health sentinels for this path are host-side (the runner's
            # metrics are host values already), so no device HealthState.
            self._scaler = None       # fp16 rejected for streamed mode
            z = lambda: jax.device_put(jnp.asarray(0, jnp.int32),
                                       self._repl_sh)
            return TrainState(global_steps=z(), optimizer_steps=z(),
                              skipped_steps=z(), params=None, master=None,
                              opt_state=None, scale=None, health=None)

        # one jitted cast: in the offload path ON THE HOST backend (only the
        # 16-bit image then crosses the wire, placed in a second step);
        # otherwise fused straight into the target sharding
        if self._offload is not None:
            with jax.default_device(jax.devices("cpu")[0]):
                p16 = jax.jit(lambda t: tree_cast(t, dtype))(params0)
            params = jax.device_put(p16, self._param_sh)
        else:
            params = jax.jit(lambda t: tree_cast(t, dtype),
                             out_shardings=self._param_sh)(params0)

        if self._offload is not None:
            # fp32 master + optimizer state live on the HOST (or NVMe); the
            # device holds only the compute-dtype params
            scale = None
            if self.fp16_enabled:
                scaler = ls.create_loss_scaler(self.config.fp16)
                self._scaler = scaler
                scale = jax.device_put(scaler.state, self._repl_sh)
            else:
                self._scaler = None
            z = lambda: jax.device_put(jnp.asarray(0, jnp.int32), self._repl_sh)
            return TrainState(global_steps=z(), optimizer_steps=z(),
                              skipped_steps=z(), params=params, master=None,
                              opt_state=None, scale=scale,
                              health=self._init_health_device(),
                              comm_error=self._init_comm_error(params))

        master = jax.device_put(params0, self._master_sh) if needs_master else None

        # opt state created under jit so it materializes directly sharded
        base = master if needs_master else params

        def mk_opt(p):
            return self.optimizer.init(p)
        opt_state = jax.jit(mk_opt)(base)
        # constrain opt-state leaves that mirror params to the master sharding
        opt_state = jax.device_put(
            opt_state, self._opt_shardings(opt_state))

        scale = None
        if self.fp16_enabled:
            scaler = ls.create_loss_scaler(self.config.fp16)
            self._scaler = scaler
            scale = jax.device_put(scaler.state, self._repl_sh)
        else:
            self._scaler = None

        z = lambda: jax.device_put(jnp.asarray(0, jnp.int32), self._repl_sh)
        return TrainState(global_steps=z(), optimizer_steps=z(), skipped_steps=z(),
                          params=params, master=master, opt_state=opt_state,
                          scale=scale, health=self._init_health_device(),
                          comm_error=self._init_comm_error(base))

    def _init_health_device(self):
        """Fresh (replicated) device HealthState, or None when the guardian
        is off.  Also the post-load reset: a restored run must not inherit
        the EMA statistics of the poisoned steps it just discarded."""
        if not self._health_enabled:
            return None
        return jax.device_put(hmod.init_state(), self._repl_sh)

    def _init_comm_error(self, base_like):
        """Fresh qgZ error-feedback state (``TrainState.comm_error``), or
        None when the grads compression route is inactive."""
        if base_like is None or not self._router.grads_active:
            return None
        return self._router.init_error_feedback(base_like, self._grad_specs)

    def _opt_shardings(self, opt_state):
        """Optimizer-state leaves that are param-shaped inherit the master
        sharding; anything else (scalars, counters) is replicated.  The
        1-bit transport's per-rank error buffers (leading ``(D, ...)``
        axis) shard over the dp axis — replicating them would cost a
        world-size multiple of the padded model."""
        onebit_fields = ("worker_error", "server_error")
        axis = (self._onebit_transport.axis
                if self._onebit_transport is not None else None)

        def sh_for(path, leaf):
            if axis is not None and any(
                    getattr(e, "name", getattr(e, "key", None))
                    in onebit_fields for e in path):
                return NamedSharding(self.mesh, P(axis))
            spec = self._shape_spec_cache.get(np.shape(leaf))
            return NamedSharding(self.mesh, spec if spec is not None else P())
        return jax.tree_util.tree_map_with_path(sh_for, opt_state)

    # ----------------------------------------------------- compile cache/AOT
    def _cache_key_slice(self):
        """The config slice of the compile-cache key: everything OUTSIDE
        the traced program that legally invalidates an executable (the
        lowering hash covers the program itself — docs/compile-cache.md)."""
        cfg = self.config
        h = self._health_cfg
        return {
            "engine": type(self).__name__,
            "zero_stage": self.zero_stage,
            "dtype": cfg.precision_dtype,
            "gas": cfg.gradient_accumulation_steps,
            "grad_accum_dtype": cfg.grad_accum_dtype,
            "gradient_clipping": cfg.gradient_clipping,
            "mesh": dict(self.mesh.shape),
            "fp16": ({"initial_scale_power": cfg.fp16.initial_scale_power,
                      "loss_scale": cfg.fp16.loss_scale,
                      "loss_scale_window": cfg.fp16.loss_scale_window,
                      "hysteresis": cfg.fp16.hysteresis,
                      "min_loss_scale": cfg.fp16.min_loss_scale}
                     if self.fp16_enabled else None),
            "health": {"enabled": h.enabled,
                       "skip_nonfinite": h.skip_nonfinite,
                       "spike_window": h.spike_window,
                       "spike_zmax": h.spike_zmax,
                       "skip_on_spike": h.skip_on_spike},
            "offload_optimizer": cfg.zero_config.offload_optimizer_device(),
            "offload_param": cfg.zero_config.offload_param_device(),
            "sparse_gradients": cfg.sparse_gradients_enabled,
            # the wire policy changes the traced program (quantize ops,
            # partial-grad layout) — part of the executable's identity
            "comms_compression": cfg.comms_compression.describe(),
        }

    def _wrap_step(self, name, fn, donate_argnums=()):
        """jit + CachedStep: the engine's dispatch path for a compiled
        entry point (AOT warm-start when the compile cache is on)."""
        from . import compile_cache as ccache
        return ccache.wrap_step(
            f"{type(self).__name__}.{name}", fn,
            cache=self.compile_cache, key_extra=self._cc_key_slice,
            donate_argnums=donate_argnums)

    def compile_report(self):
        """Compile-cache status + per-entry hit/miss/compile-ms events
        for this engine's cache (surfaced by bench.py and ds_report)."""
        from . import compile_cache as ccache
        return ccache.report(self.compile_cache)

    def _install_moe_wire(self):
        """Make THIS engine's quantized expert wire (or its absence) the
        process-global one ``moe/layer.py`` reads at trace time — called
        at init and before every step dispatch, so interleaved engines
        with different policies each retrace under their own."""
        from .comm import moe_wire as mw
        mw.set_active(self._moe_wire)

    def comms_budget(self):
        """Declared per-step wire ceiling for the compressed step's
        collective census (``analysis/comms.py CommsBudget``), computed
        from the compression policy — tight enough that the FULL-WIDTH
        step violates it.  None when no compression route is active or
        the engine streams params.  The moe route's component is
        trace-recorded, so budget-gated flows run one cold step first
        (docs/comms-compression.md)."""
        if self._param_stream is not None or self.state is None:
            return None
        if not (self._router.weights_active or self._router.grads_active
                or self._router.moe_active):
            return None
        base = (self.state.master if self.state.master is not None
                else self.state.params)
        return self._router.comms_budget(
            base, self._param_specs, self._grad_specs,
            np.dtype(self.compute_dtype).itemsize,
            moe_wire=self._moe_wire)

    def preflight_memory(self, batch, rng=None):
        """Peak-HBM preflight of the compiled step via the executable's
        ``memory_analysis()`` — available BEFORE any step executes (and
        nearly free when the compile cache is warm).  ``batch`` must be a
        stacked step batch (``_stack_microbatches`` output or matching
        shapes).  Returns byte counts with ``peak_bytes`` approximating
        execution-time live memory (arguments + outputs − donated
        aliases + temps + program), or None when the backend exposes no
        memory analysis (e.g. some CPU builds) or the engine streams
        params (``offload_param`` never materializes the model in HBM).

        Never consumes donated buffers — acquisition only lowers,
        deserializes or compiles."""
        if self._param_stream is not None:
            return None
        rng = rng if rng is not None else jax.random.fold_in(
            self._base_rng, 0)
        fn = (self._jit_grad_step if self._offload is not None
              else self._jit_train_step)
        with jax.set_mesh(self.mesh):
            exe = fn.executable(self.state, batch, rng)
        from .compile_cache import executable_memory_analysis
        return executable_memory_analysis(exe)

    def memory_ledger(self) -> dict:
        """One memory-ledger snapshot (``monitor/memory_ledger.py``):
        device HBM + host RSS attributed to named subsystems from the
        LIVE state (TrainState leaves, offload-tier buffers, H2D
        staging, NVMe swap pools, compiled programs, compile-cache
        disk), the measured gauges, the explicit residual, and the
        per-phase host-RSS high-water marks.  Host-side reads only."""
        from ..monitor import memory_ledger as mled
        return mled.attribute_engine(self).snapshot(
            phases=self._rss_phases)

    def _maybe_oom_forensics(self, exc):
        """RESOURCE_EXHAUSTED post-mortem (docs/monitoring.md
        #memory-explainability): dump the memory ledger + the capacity
        model's verdict — which subsystem blew the budget and which knob
        buys headroom — through the PR-3 ``write_forensics`` path, once,
        then let the original error propagate.  Only inspects; never
        swallows."""
        if self._oom_dumped or "RESOURCE_EXHAUSTED" not in str(exc):
            return
        self._oom_dumped = True
        from ..monitor import gauges as mg
        from ..monitor import memory_ledger as mled
        try:
            snap = self.memory_ledger()
            path = mled.oom_forensics(
                self._forensic_dir(), snap, reason=exc,
                budget_bytes=mg.hbm_limit_bytes(),
                filename=f"memory_forensics_step"
                         f"{self._global_steps_host}.json")
        except Exception as e:      # a dump failure must never mask the OOM
            logger.warning(f"memory forensics unavailable ({e})")
            return
        if path and self.monitor.armed:
            self.monitor.artifact("memory_forensics", path,
                                  step=self._global_steps_host)
            self.monitor.flush()

    def close(self):
        """Release device state, live compiled executables and staging
        buffers.  ``del engine`` alone does NOT free these (the r5 bench
        ladder leaked them across rungs until later configs died
        RESOURCE_EXHAUSTED); call ``close()`` between engine lifetimes
        sharing one process.  A pending delayed-param update is dropped,
        not applied — close is teardown, not a checkpoint boundary."""
        self._pending_offload = None
        self._pending_row_drop_checks = []
        self._data_iterator = None
        # release the global expert-wire slot iff this engine owns it
        from .comm import moe_wire as mw
        if mw.get_active() is not None and mw.get_active() is self._moe_wire:
            mw.set_active(None)
        self._moe_wire = None
        for wrapper in (self._jit_train_step, self._jit_grad_step,
                        self._jit_eval, self._jit_scatter_params):
            if hasattr(wrapper, "clear"):
                wrapper.clear()
        self._jit_eval = None
        self._jit_scatter_params = None
        self._h2d.close()
        state, self.state = self.state, None
        if state is not None:
            for leaf in jax.tree_util.tree_leaves(state):
                if hasattr(leaf, "delete") and hasattr(leaf, "is_deleted") \
                        and not leaf.is_deleted():
                    leaf.delete()
        ps, self._param_stream = self._param_stream, None
        if ps is not None:
            ps.close()
        self._offload = None
        if (self.monitor.armed and self.monitor.bus is not None
                and self.monitor.bus.sinks):
            # terminal hist flush: a run shorter than the timer's
            # emission cadence must still leave its whole-run step-time
            # distribution in the stream (what ds_fleet merges read)
            tt = getattr(self, "tput_timer", None)
            if tt is not None and getattr(tt, "step_time_hist", None):
                self.monitor.bus.hist("train_step_time_ms",
                                      tt.step_time_hist,
                                      step=self._global_steps_host,
                                      unit="ms")
        self.monitor.close()
        import gc
        gc.collect()

    # ------------------------------------------------------------- train step
    def _micro_loss_fn(self):
        """The ``(base_params, mb, r) -> (loss, aux)`` callable shared by
        the full-width ``_grad_fn`` and the qgZ partials path (the two
        must never drift): cast to the compute dtype, deliver params over
        the ZeRO-3 wire (quantized qwZ all-gather for routed leaves, the
        plain sharding constraint otherwise), then the model's OWN
        ``loss_with_metrics`` when the engine trains on the model's loss
        (MoE aux metrics, reference engine.py:1639) — a client ``loss_fn=``
        stays authoritative and is never silently displaced."""
        dtype = self.compute_dtype
        needs_master = dtype != jnp.float32
        own_loss = (getattr(self._loss_fn, "__self__", None)
                    is self.module
                    and getattr(self._loss_fn, "__name__", "") == "loss")
        lwm = (getattr(self.module, "loss_with_metrics", None)
               if own_loss else None)

        def fn(base_params, mb, r):
            p = tree_cast(base_params, dtype) if needs_master else base_params
            p = self._router.gather_params(p, self._param_specs)
            if lwm is not None:
                return lwm(p, mb, r)
            return self._loss_fn(p, mb, r), {}

        return fn

    @staticmethod
    def _acc_aux_fn(gas):
        """Aux-metric accumulation rule of the gas scan, shared by both
        gradient paths: losses/ratios average over microbatches; COUNTS
        (keys ending in "_dropped") sum — "tokens dropped this step" must
        mean the step's total, not a per-microbatch mean."""
        def acc_aux(acc_tree, aux_tree):
            return {k: acc_tree[k] + (v if k.endswith("_dropped")
                                      else v / gas)
                    for k, v in aux_tree.items()}
        return acc_aux

    def _grad_fn(self, base, batch, rng, cur_scale):
        """Gradient computation inside the jitted step.

        Default: scan over the gas microbatch axis accumulating fp32 grads
        (reference per-micro-batch backward + bucketed hook reduction,
        ``engine.py:1684``).  ``PipelineEngine`` overrides this with the
        pipelined forward/backward.  Returns ``(grads, scaled_loss_sum)``
        where ``scaled_loss_sum == mean_loss * cur_scale``.
        """
        if self._router.grads_active:
            # qgZ: gradients leave this function as per-dp-slice PARTIALS
            # (leading (D, ...) axis); _grads_and_metrics routes them
            # through the quantized reduction
            return self._grad_fn_partials(base, batch, rng, cur_scale)
        gas = self.gradient_accumulation_steps()
        loss_fn = self._micro_loss_fn()

        def micro_loss(base_params, mb, r):
            loss, aux = loss_fn(base_params, mb, r)
            return loss * cur_scale / gas, aux

        vgrad = jax.value_and_grad(micro_loss, has_aux=True)

        if gas == 1:
            # no accumulation loop: the scan wrapper would zero-init and
            # add-into a full fp32 grad tree (1.4GB at 350M) per step for
            # nothing
            mb = jax.tree_util.tree_map(lambda a: a[0], batch)
            (scaled_loss, aux), grads = vgrad(base, mb,
                                              jax.random.fold_in(rng, 0))
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), grads)
            return grads, scaled_loss, aux

        acc_dtype = (jnp.bfloat16 if self.config.grad_accum_dtype == "bf16"
                     else jnp.float32)
        acc_aux = self._acc_aux_fn(gas)

        def body(carry, xs):
            gacc, lacc, aacc, idx = carry
            mb = xs
            r = jax.random.fold_in(rng, idx)
            (scaled_loss, aux), grads = vgrad(base, mb, r)
            grads = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(acc_dtype), gacc, grads)
            aacc = acc_aux(aacc, aux)
            return (grads, lacc + scaled_loss, aacc, idx + 1), None

        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, acc_dtype), base)
        mb0 = jax.tree_util.tree_map(lambda a: a[0], batch)
        # zero-init the aux accumulator with the right structure
        aux_zeros = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype),
            jax.eval_shape(lambda b, m, r: micro_loss(b, m, r)[1],
                           base, mb0, rng))
        (grads, scaled_loss_sum, aux, _), _ = jax.lax.scan(
            body, (zeros, jnp.float32(0.0), aux_zeros, jnp.int32(0)), batch)
        grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32), grads)
        return grads, scaled_loss_sum, aux

    def _grad_fn_partials(self, base, batch, rng, cur_scale):
        """qgZ gradient computation: PARTIAL gradients per data-parallel
        slice instead of XLA's implicit full-width reduction.

        The global microbatch reshapes to ``(D, micro_per_rank, ...)``
        (a shard-local reshape: sharding already splits axis 0 into D
        contiguous chunks) and a vmapped ``value_and_grad`` produces one
        gradient slice per dp rank — each device computes exactly the
        backward it computed before, but the cross-device sum is now OURS
        to schedule, so the reduction wire can move int8 with error
        feedback (``comm/quantized.py reduce_partials_quantized``).
        Returns ``(partial_grads (D, *shape), scaled_loss_sum, aux)``;
        also note the reduction now happens ONCE per step (after the gas
        scan) rather than per microbatch.

        Normalization: each slice loss is a mean over ``micro/D`` rows,
        so the per-slice loss is scaled by ``1/D`` here — the SUMMED
        partial gradients then equal the gradient of the global-batch
        mean exactly.  (Without it the summed partials are D× the
        full-width gradient — invisible under Adam, an effective-lr
        explosion under any scale-sensitive optimizer.)
        """
        gas = self.gradient_accumulation_steps()
        D = self.mesh_ctx.dp_world_size
        loss_fn = self._micro_loss_fn()
        lead = NamedSharding(self.mesh, P(M.BATCH_AXES))

        def slice_loss(base_params, mb, r):
            loss, aux = loss_fn(base_params, mb, r)
            return loss * cur_scale / (gas * D), aux

        vgrad = jax.vmap(jax.value_and_grad(slice_loss, has_aux=True),
                         in_axes=(None, 0, 0))

        def split_dp(mb):
            def r(a):
                a = jnp.reshape(a, (D, a.shape[0] // D) + a.shape[1:])
                return jax.lax.with_sharding_constraint(a, lead)
            return jax.tree_util.tree_map(r, mb)

        def one_micro(mb, r):
            rs = jax.random.split(r, D)
            (sl, aux), pg = vgrad(base, split_dp(mb), rs)
            pg = jax.tree_util.tree_map(
                lambda g: jax.lax.with_sharding_constraint(g, lead), pg)
            # per-slice aux -> microbatch aux (counts sum, ratios average)
            aux = {k: (jnp.sum(v, axis=0) if k.endswith("_dropped")
                       else jnp.mean(v, axis=0)) for k, v in aux.items()}
            # per-slice losses carry 1/D, so the sum IS the scaled mean
            return pg, jnp.sum(sl), aux

        if gas == 1:
            mb = jax.tree_util.tree_map(lambda a: a[0], batch)
            pg, scaled_loss, aux = one_micro(mb, jax.random.fold_in(rng, 0))
            pg = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), pg)
            return pg, scaled_loss, aux

        acc_dtype = (jnp.bfloat16 if self.config.grad_accum_dtype == "bf16"
                     else jnp.float32)
        acc_aux = self._acc_aux_fn(gas)

        def body(carry, xs):
            gacc, lacc, aacc, idx = carry
            pg, sl, aux = one_micro(xs, jax.random.fold_in(rng, idx))
            gacc = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(acc_dtype), gacc, pg)
            return (gacc, lacc + sl, acc_aux(aacc, aux), idx + 1), None

        zeros = jax.tree_util.tree_map(
            lambda p: jax.lax.with_sharding_constraint(
                jnp.zeros((D,) + p.shape, acc_dtype), lead), base)
        mb0 = jax.tree_util.tree_map(lambda a: a[0], batch)
        aux_zeros = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype),
            jax.eval_shape(lambda m, r: one_micro(m, r)[2], mb0, rng))
        (pg, scaled_loss_sum, aux, _), _ = jax.lax.scan(
            body, (zeros, jnp.float32(0.0), aux_zeros, jnp.int32(0)), batch)
        pg = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), pg)
        return pg, scaled_loss_sum, aux

    def _grads_and_metrics(self, state: TrainState, base, batch, rng):
        """Shared gradient post-processing contract, used by the fused
        in-device step AND the offload grad-only step: scan microbatches,
        unscale, overflow check, clip, constrain to ZeRO-2 sharding
        (reference clip order: unscale → clip → step,
        ``stage_1_and_2.py:1736 unscale_and_clip``).

        With the qgZ route active the grad function returns PARTIALS;
        overflow/non-finite sentinels run on the partials (quantization
        would launder an Inf into finite garbage) and the reduction goes
        through the router's error-fed int8 wire.  Returns
        ``(grads, overflow, lr, metrics, new_comm_error)`` — the last is
        None on the full-width path."""
        cur_scale = (state.scale.cur_scale if state.scale is not None
                     else jnp.float32(1.0))
        out = self._grad_fn(base, batch, rng, cur_scale)
        # uniform (grads, loss, aux) contract; a 2-tuple from a legacy
        # client override still unpacks
        grads, scaled_loss_sum, aux = out if len(out) == 3 else (*out, {})
        # unscale (fp16); loss for reporting is the true mean loss
        grads = jax.tree_util.tree_map(lambda g: g / cur_scale, grads)
        loss = scaled_loss_sum / cur_scale
        overflow = (ls.has_overflow(grads) if self.fp16_enabled
                    else jnp.asarray(False))
        new_ef = None
        wire_nf = None
        if self._router.grads_active:
            # non-finite flags come from the RAW partials: the quantizer
            # sanitizes NaN/Inf to 0 (the int cast is undefined on them),
            # so without this a poisoned gradient would silently train as
            # zeros.  Re-injecting NaN into the reduced grads restores
            # full-width semantics exactly — the post-reduce sentinels
            # catch it when the guardian is armed, and with the guardian
            # OFF (numerics debugging) the NaN propagates visibly, as it
            # would on the lossless wire.  (fp16 needs no twin: its
            # overflow scan below already runs on the partials and the
            # scaler skip-step is unconditional.)
            wire_nf = (None if self.fp16_enabled
                       else hmod.tree_nonfinite(grads))
            grads, new_ef = self._router.reduce_grads(
                grads, state.comm_error, self._grad_specs)
            if wire_nf is not None:
                grads = jax.tree_util.tree_map(
                    lambda g: jnp.where(
                        wire_nf, jnp.full(g.shape, jnp.nan, g.dtype), g),
                    grads)
        if self.config.gradient_clipping > 0:
            grads, gnorm = clip_by_global_norm(grads, self.config.gradient_clipping)
        else:
            gnorm = global_norm(grads)
        # ZeRO-2: constrain grads to fsdp sharding → reduce-scatter
        grads = zpart.constrain(grads, self._grad_specs, self.mesh)
        lr = self._lr_at(state.global_steps)
        metrics = {"loss": loss, "grad_norm": gnorm, "overflow": overflow,
                   "lr": lr, "loss_scale": cur_scale}
        if wire_nf is not None:
            metrics["nonfinite_wire"] = wire_nf
        metrics.update(aux)
        return grads, overflow, lr, metrics, new_ef

    def _health_sentinels(self, state, loss, grads, overflow):
        """On-device divergence sentinels (traced into the step; pure jnp,
        no host callbacks — the DSTPU201 audit stays clean).

        Returns ``(skip, new_health, sentinel_metrics)`` where ``skip``
        gates the branchless skip-step.  For fp16 the grad flag reuses the
        scaler's overflow scan (one reduction, not two)."""
        cfg = self._health_cfg
        nf_grads = (overflow if self.fp16_enabled
                    else hmod.tree_nonfinite(grads))
        nf_loss = jnp.logical_not(jnp.isfinite(loss))
        new_health, z, spike = hmod.update_ema(
            state.health, loss, window=cfg.spike_window,
            zmax=cfg.spike_zmax)
        skip = overflow
        if cfg.skip_nonfinite:
            skip = skip | nf_grads | nf_loss
        if cfg.skip_on_spike:
            skip = skip | spike
        sm = {"nonfinite_grads": nf_grads, "nonfinite_loss": nf_loss,
              "health_z": z, "loss_spike": spike}
        return skip, new_health, sm

    def _train_step(self, state: TrainState, batch, rng):
        """One full optimizer step: scan over gas microbatches, reduce, update.

        ``batch`` leaves are shaped (gas, global_micro_batch, ...) with the
        second axis sharded over the batch axes (data, fsdp, expert).

        With the health guardian enabled (default), the fp16 scaler's
        branchless skip-step generalizes to EVERY precision: a step whose
        loss, gradients, or updated parameters are non-finite (or whose
        loss z-score spikes, when ``skip_on_spike`` is set) is a ``where``-
        selected no-op on params and optimizer state — no data-dependent
        control flow, donation honored, no host round-trip.
        """
        dtype = self.compute_dtype
        needs_master = dtype != jnp.float32
        base = state.master if needs_master else state.params

        grads, overflow, lr, metrics, new_ef = self._grads_and_metrics(
            state, base, batch, rng)
        if self._health_enabled:
            skip, new_health, sm = self._health_sentinels(
                state, metrics["loss"], grads, overflow)
            metrics.update(sm)
        else:
            skip, new_health = overflow, state.health
        new_base, new_opt = self.optimizer.update(
            grads, state.opt_state, base, step=state.optimizer_steps + 1, lr=lr)
        new_base = zpart.constrain(new_base, self._master_specs if needs_master
                                   else self._param_specs, self.mesh)

        if self._health_enabled and self._health_cfg.skip_nonfinite:
            # optimizer-minted non-finites (e.g. an Inf moment) are caught
            # on the UPDATED base, before anything is committed
            nf_params = hmod.tree_nonfinite(new_base)
            skip = skip | nf_params
            metrics["nonfinite_params"] = nf_params

        gate = self.fp16_enabled or (
            self._health_enabled and (self._health_cfg.skip_nonfinite
                                      or self._health_cfg.skip_on_spike))
        if gate:
            # branchless skip-step: the unhealthy step is a no-op on
            # params/optimizer state (reference _take_model_step overflow
            # path, engine.py:1819-1871 — extended beyond fp16)
            sel = lambda new, old: jax.tree_util.tree_map(
                lambda n, o: jnp.where(skip, o, n), new, old)
            new_base = sel(new_base, base)
            new_opt = sel(new_opt, state.opt_state)
            if new_ef is not None:
                # error feedback computed from a skipped step's garbage
                # gradients must not poison future compensation
                new_ef = sel(new_ef, state.comm_error)
        if self.fp16_enabled:
            # the loss scale reacts to OVERFLOW only — a health skip (loss
            # spike, optimizer NaN) is not a scale-is-too-big signal
            new_scale = ls.update_scale(
                state.scale, overflow, dynamic=self._scaler.dynamic,
                scale_factor=self._scaler.scale_factor,
                scale_window=self._scaler.scale_window,
                min_scale=self._scaler.min_scale,
                delayed_shift=self._scaler.delayed_shift,
                consecutive_hysteresis=self._scaler.consecutive_hysteresis)
        else:
            new_scale = state.scale

        if needs_master:
            new_params = zpart.constrain(tree_cast(new_base, dtype),
                                         self._param_specs, self.mesh)
            new_master = new_base
        else:
            new_params = new_base
            new_master = None

        metrics["skip"] = skip
        skip_i = skip.astype(jnp.int32)
        new_state = TrainState(
            global_steps=state.global_steps + 1,
            optimizer_steps=state.optimizer_steps + (1 - skip_i),
            skipped_steps=state.skipped_steps + skip_i,
            params=new_params, master=new_master, opt_state=new_opt,
            scale=new_scale, health=new_health,
            comm_error=(new_ef if new_ef is not None
                        else state.comm_error))
        return new_state, metrics

    def _grad_only_step(self, state: TrainState, batch, rng):
        """Device half of the offload step: grads (unscaled, clipped, sharded)
        + metrics + the UPDATED loss-scale state; the optimizer update happens
        on the host (reference: backward populates the fp32 cpu partition,
        ``stage_1_and_2.py:1008-1160``).  Grads cross to the host in the
        16-bit compute dtype — the reference also moves 16-bit grads over
        PCIe and upcasts on the CPU (half the transfer bytes).

        The dynamic loss scale updates IN-GRAPH (eagerly), not host-side
        with the delayed param apply: under DPU the next step dispatches
        before the previous host apply, and a host-side scale update would
        reach it one step late — one overflow would then cost two skipped
        steps and two halvings.  In-graph, the halved scale flows to the
        next dispatch through device state with no host sync."""
        grads, overflow, _, metrics, new_ef = self._grads_and_metrics(
            state, state.params, batch, rng)
        if self._health_enabled:
            # the host half reads metrics["skip"] and makes the skipped
            # step a no-op on the host master/moments — the offload
            # spelling of the branchless skip-step.  (No nonfinite_params
            # sentinel here: the update happens on the host.)
            skip, new_health, sm = self._health_sentinels(
                state, metrics["loss"], grads, overflow)
            metrics.update(sm)
        else:
            skip, new_health = overflow, state.health
        metrics["skip"] = skip
        if new_ef is not None:
            # the error feedback advances in-graph (like scale/health);
            # a skipped step must leave it untouched
            new_ef = jax.tree_util.tree_map(
                lambda n, o: jnp.where(skip, o, n), new_ef,
                state.comm_error)
        if self.fp16_enabled:
            new_scale = ls.update_scale(
                state.scale, overflow, dynamic=self._scaler.dynamic,
                scale_factor=self._scaler.scale_factor,
                scale_window=self._scaler.scale_window,
                min_scale=self._scaler.min_scale,
                delayed_shift=self._scaler.delayed_shift,
                consecutive_hysteresis=self._scaler.consecutive_hysteresis)
        else:
            new_scale = state.scale
        if self.compute_dtype == jnp.bfloat16:
            # bf16 spans the fp32 exponent range so no new inf can appear
            # after the overflow check; fp16 (max 65504) must stay fp32 —
            # casting could mint inf that bypasses the skip-step logic
            grads = tree_cast(grads, jnp.bfloat16)
        if self._sparse_grad_paths:
            grads, rows_dropped = self._sparsify_grads(grads, batch)
            # surfaced so an under-declared sparse_grad_row_bound is an
            # ERROR (checked host-side in _host_offload_update), never a
            # silent truncation of embedding gradients
            metrics["sparse_rows_dropped"] = rows_dropped
        elif self.mesh.size == 1:
            # ONE flat buffer for the wire: a per-leaf d2h pays one
            # round-trip latency per leaf (~minutes per step for a
            # billion-param tree on a remote-attached chip); the in-graph
            # concatenate costs one HBM copy.  Single-device only — on a
            # mesh the concatenate would gather sharded grads whole.
            grads = jnp.concatenate(
                [g.reshape(-1) for g in jax.tree_util.tree_leaves(grads)])
        return grads, metrics, new_scale, new_health, new_ef

    def _sparsify_grads(self, grads, batch):
        """Replace declared embedding-grad leaves with row-sparse
        (indices, values) pairs for the d2h wire.

        The static row bound defaults to the TOTAL integer-id count in the
        batch — safe (a lookup touches at most one row per id) but counts
        non-lookup int leaves like labels too (2× buffers for
        (inputs, labels) batches).  A model can tighten it by declaring
        ``sparse_grad_row_bound(batch) -> int`` (count only the ids that
        actually feed its lookups).  Under-declaring would drop gradient
        rows, so the true nonzero-row count is checked per leaf and
        returned as ``rows_dropped`` — the engine raises on any nonzero
        value rather than corrupting embedding training silently."""
        from .sparse_tensor import SparseTensor
        bound_fn = getattr(self.module, "sparse_grad_row_bound", None)
        if callable(bound_fn):
            tokens = int(bound_fn(batch))
        else:
            tokens = sum(int(np.prod(l.shape)) for l in
                         jax.tree_util.tree_leaves(batch)
                         if jnp.issubdtype(jnp.asarray(l).dtype, jnp.integer))
        if tokens == 0:
            return grads, jnp.int32(0)
        dropped = [jnp.int32(0)]

        def replace(tree, path):
            key = path[0]
            sub = tree[key]
            if len(path) == 1:
                assert np.ndim(sub) == 2, \
                    f"sparse_grad_paths leaf {path} must be 2-D (rows, dim)"
                rows = sub.shape[0]
                if tokens >= rows:
                    return tree  # dense is smaller; keep it
                nz = jnp.any(sub != 0, axis=1)
                nz_rows = jnp.sum(nz.astype(jnp.int32))
                dropped[0] = dropped[0] + jnp.maximum(nz_rows - tokens, 0)
                st = SparseTensor.from_dense(sub, max_rows=tokens, nz=nz)
                out = dict(tree)
                out[key] = {"sparse_indices": st.indices,
                            "sparse_values": st.values}
                return out
            out = dict(tree)
            out[key] = replace(sub, path[1:])
            return out

        for path in self._sparse_grad_paths:
            grads = replace(grads, path)
        return grads, dropped[0]

    def _host_offload_update(self, grads, metrics):
        """Host half of the offload step: d2h grads → native fused Adam on
        the flat fp32 master (moments on host RAM or streamed from NVMe) →
        h2d of the 16-bit payload."""
        state = self.state
        # "skip" unifies fp16 overflow with the health guardian's
        # non-finite/spike sentinels (all device scalars computed in
        # _grad_only_step); the bool() read syncs, but this host path
        # synchronizes on the grads right below anyway
        if "skip" in metrics:
            overflow = bool(metrics["skip"])
        else:
            overflow = bool(metrics["overflow"]) if self.fp16_enabled else False
        ovf = jnp.asarray(int(overflow), jnp.int32)
        # NOTE: checked only on non-overflow steps — a NaN/inf grad step makes
        # every row "nonzero" through the NaN-propagating clip; that path must
        # reach the skip-step logic below, not die here.  The per-step
        # counters ACCUMULATE host-side (device scalars, no sync) and are
        # read only on reporting steps: int() forces a host-device sync,
        # which would shrink the DPU overlap window on every step, while
        # the accumulated check still catches a drop on ANY step of the
        # interval.
        if not overflow and "sparse_rows_dropped" in metrics:
            self._pending_row_drop_checks.append(
                metrics["sparse_rows_dropped"])
            # flush on reporting steps OR every 50 steps — steps_per_print
            # is often set huge to silence logs, which must not disable
            # the guard (or grow the pending list without bound).  Checkpoint
            # save, eval and state-dict export flush unconditionally
            # (_flush_row_drop_checks) so a short run or a mid-interval save
            # can never skip the check.
            if (self._global_steps_host + 1) % \
                    self.config.steps_per_print == 0 or \
                    len(self._pending_row_drop_checks) >= 50:
                self._flush_row_drop_checks()
        if not overflow:
            from .zero.offload_engine import FlatWireHandle
            t0 = time.time()
            if isinstance(grads, FlatWireHandle):
                # flat wire format: land the chunked d2h start_d2h began
                flat = self._offload.land_flat(grads)
            else:
                flat = self._offload.flatten_grads(grads)
            t1 = time.time()
            lr = float(metrics["lr"])
            self._offload.step(flat, int(state.optimizer_steps) + 1, lr)
            t2 = time.time()
            # h2d dispatch is async; its cost surfaces as next-step wait
            params = self._upload_offload_params()
            self._offload.last_host_times = {
                "grad_d2h_flatten_s": t1 - t0, "host_adam_s": t2 - t1}
        else:
            # the skipped step's grads are never landed; dropping the wire
            # handle (or tree) frees the device buffers
            params = state.params
        # scale/health already advanced in-graph by _grad_only_step (kept
        # as-is: under DPU `state` may carry newer values than this
        # pending step)
        self.state = TrainState(
            global_steps=state.global_steps + 1,
            optimizer_steps=state.optimizer_steps + (1 - ovf),
            skipped_steps=state.skipped_steps + ovf,
            params=params, master=None, opt_state=None, scale=state.scale,
            health=state.health, comm_error=state.comm_error)

    # ------------------------------------------------------------- public API
    def train_batch(self, data_iter=None):
        """Run one full training step (gas microbatches → one optimizer step).

        Parity: ``PipelineEngine.train_batch`` naming; for the non-pipeline
        engine this replaces the forward/backward/step trio with one call.
        """
        from .. import fault
        fault.site("engine.step")    # host-side only; never traced
        self._install_moe_wire()
        self.monitor.begin_step()    # root "step" span (host wall-clock)
        it = data_iter if data_iter is not None else self._data_iterator
        assert it is not None, "train_batch needs training_data or a data_iter"
        if it is not self._data_iterator:
            # training is fed by an EXTERNAL iterator: the engine-owned
            # loader no longer tracks the real stream, so a rewind must
            # not "fast-forward" it (the warning path in rewind())
            self._stream_pos_known = False
        gas = self.gradient_accumulation_steps()
        with self.monitor.span("data_fetch"):
            micro_batches = [next(it) for _ in range(gas)]
        # data-stream position of THIS step (monotonic; checkpointed with
        # the data-pipeline state, advanced by rewind's fast-forward) —
        # also the index the value-corruption fault sites key on, so an
        # injected grad_nan/loss_spike window rides the data deterministically
        self._last_batch_index = self._stream_step
        self._stream_step += 1
        if fault.is_enabled():
            micro_batches = [fault.corrupt_batch(mb, self._last_batch_index)
                             for mb in micro_batches]
        if self.curriculum_scheduler is not None:
            micro_batches = [self._apply_curriculum(mb) for mb in micro_batches]
        try:
            if self._param_stream is not None:
                return self._run_stream_step(micro_batches)
            batch = self._stack_microbatches(micro_batches)
            return self._run_fused_step(batch)
        except Exception as e:
            # an allocator OOM gets its post-mortem pre-written (ledger +
            # capacity verdict); the error itself always propagates
            self._maybe_oom_forensics(e)
            raise

    def _apply_curriculum(self, mb):
        """Crop token sequences to the scheduled difficulty (reference:
        ``curriculum_seqlen`` kwarg injection, ``engine.py:1596-1602``; here
        the seq axis itself is cropped — same tokens seen, shorter program)."""
        seqlen = self.curriculum_scheduler.update_difficulty(
            self._global_steps_host + 1)

        def crop(x):
            if hasattr(x, "ndim") and x.ndim >= 2 and x.shape[1] > seqlen:
                return x[:, :seqlen + 1] if np.issubdtype(
                    np.asarray(x).dtype, np.integer) else x[:, :seqlen]
            return x
        return jax.tree_util.tree_map(crop, mb)

    def curriculum_seqlen(self):
        if self.curriculum_scheduler is None:
            return None
        return self.curriculum_scheduler.get_current_difficulty()

    def _stack_microbatches(self, micro_batches):
        # spanned as one phase: host collation + the H2D placement (the
        # device_put dispatch; the DMA itself overlaps the step)
        with self.monitor.span("h2d_upload"):
            batch = jax.tree_util.tree_map(lambda *xs: np.stack(xs),
                                           *micro_batches)
            if self.monitor.armed and self._mon_tokens_per_step is None:
                from ..monitor import gauges as mg
                self._mon_tokens_per_step = mg.tokens_in_batch(batch)
            sh = jax.tree_util.tree_map(
                lambda x: NamedSharding(self.mesh, P(None, M.BATCH_AXES)),
                batch)
            return jax.device_put(batch, sh)

    def _run_fused_step(self, batch):
        self.tput_timer.start()
        rng = jax.random.fold_in(self._base_rng, self.micro_steps)
        # FLOPS profiler: profile the step program BEFORE the donated buffers
        # are consumed (reference: engine.py:1583-1588 profile_step bracket)
        if (self.config.flops_profiler.enabled
                and self._global_steps_host + 1 == self.config.flops_profiler.profile_step):
            self._profile_train_step(batch, rng)
        # trace with the mesh in context so bare-PartitionSpec sharding
        # constraints inside models (MoE expert axis, SP) bind to it
        if self.monitor.armed and self.monitor.bus.sinks \
                and self._mon_step_stats is None:
            self._mon_example = (batch, rng)   # freed once stats price
        self.monitor.trace_before_step(self._global_steps_host + 1)
        with jax.set_mesh(self.mesh):
            if self._offload is not None:
                with self.monitor.span("dispatch"):
                    grads, metrics, new_scale, new_health, new_ef = \
                        self._jit_grad_step(self.state, batch, rng)
                # loss scale + health EMA + qgZ error feedback advance
                # eagerly (device-graph dependency): the NEXT dispatch
                # sees a post-overflow halving / updated loss baseline /
                # compensated error with no host sync
                self.state = self.state._replace(
                    scale=new_scale, health=new_health,
                    comm_error=(new_ef if new_ef is not None
                                else self.state.comm_error))
                # queue grad d2h behind the device compute (async copy
                # engine; overlaps the host work below).  For the flat
                # wire this swaps `grads` for a chunk handle — the
                # original flat array's buffer is then freed as soon as
                # the chunk slices are computed, instead of being pinned
                # through the DPU delay window.
                with self.monitor.span("grad_d2h"):
                    grads = self._offload.start_d2h(grads)
                if self._dpu and self._global_steps_host >= self._dpu_warmup:
                    # DPU steady state: while the device computes THIS
                    # step's grads, the host applies the PREVIOUS step's —
                    # params are one step stale (ZeRO-Offload paper §DPU;
                    # the reference's overlap-centric design,
                    # docs/_posts/2021-03-08-zero3-offload.md:72)
                    if self._pending_offload is not None:
                        with self.monitor.span("host_adam"):
                            self._host_offload_update(*self._pending_offload)
                    self._pending_offload = (grads, metrics)
                else:
                    with self.monitor.span("host_adam"):
                        self._host_offload_update(grads, metrics)
            else:
                with self.monitor.span("dispatch"):
                    self.state, metrics = self._jit_train_step(
                        self.state, batch, rng)
        return self._finish_step(metrics)

    def _run_stream_step(self, micro_batches):
        """ZeRO-3 param-offload step: the runner streams layer blocks
        through the device (``zero/param_stream.py``); the engine keeps
        counters/schedules/reporting identical to the fused path."""
        self.tput_timer.start()
        rng = jax.random.fold_in(self._base_rng, self.micro_steps)
        lr = float(self._lr_at(self.state.global_steps))
        if self.monitor.armed and self._mon_tokens_per_step is None:
            from ..monitor import gauges as mg
            self._mon_tokens_per_step = mg.tokens_in_batch(micro_batches)
        self.monitor.trace_before_step(self._global_steps_host + 1)
        with jax.set_mesh(self.mesh):
            # the runner's layer loop (streamed gathers, NVMe swaps, host
            # Adam) runs inside this bracket; its own phase timings land
            # as child spans in _monitor_finish when it reports them
            with self.monitor.span("dispatch"):
                metrics = self._param_stream.train_step(
                    micro_batches, rng, lr=lr,
                    step_no=int(self.state.optimizer_steps) + 1)
        # the runner's skip-step (non-finite loss/grad-norm -> host Adam
        # not applied) reports through metrics["skip"]; counters mirror
        # the fused path's skipped-step accounting
        skip = bool(metrics.get("skip", False))
        one = jnp.asarray(1, jnp.int32)
        zero = jnp.asarray(0, jnp.int32)
        self.state = self.state._replace(
            global_steps=self.state.global_steps + one,
            optimizer_steps=self.state.optimizer_steps + (zero if skip
                                                          else one),
            skipped_steps=self.state.skipped_steps + (one if skip
                                                      else zero))
        return self._finish_step(metrics)

    def _finish_step(self, metrics):
        """Post-step bookkeeping shared by the fused and streamed paths."""
        self._last_metrics = metrics
        self.micro_steps += self.gradient_accumulation_steps()
        self.global_samples += self.train_batch_size()
        self._global_steps_host += 1
        if self.lr_scheduler is not None and hasattr(self.lr_scheduler, "step"):
            self.lr_scheduler.step()
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self._global_steps_host)
        if self._scaler is not None and self.state.scale is not None:
            self._scaler.state = self.state.scale
        # host sync (float()/block) only on steps that actually report — keeps
        # the hot path async so input prep overlaps device compute
        step_no = self._global_steps_host
        # RSS HWM phase brackets (one getrusage read): init ended at
        # __init__, first-compile ends with step 1, steady re-marks at
        # the ledger cadence
        from ..monitor import memory_ledger as mled
        if step_no == 1:
            self._rss_phases.mark(mled.PHASE_FIRST_COMPILE)
        elif self._mem_interval and step_no % self._mem_interval == 0:
            self._rss_phases.mark_latest(mled.PHASE_STEADY)
        reporting = step_no % self.config.steps_per_print == 0
        if reporting:
            self._report_progress(step_no, metrics)
        self.tput_timer.stop(global_step=True,
                             sync_obj=metrics["loss"] if reporting else None)
        self._monitor_finish(step_no, metrics, reporting)
        if self.health_monitor is not None:
            # trails the device by health_check.check_interval steps (the
            # sentinel read then blocks only on already-finished work) and
            # may rewind (in-process) or abort (with forensics)
            self._health_observe(step_no, metrics)
        return metrics["loss"]

    # ------------------------------------------------------------- telemetry
    _MON_SCALAR_KEYS = ("loss", "lr", "grad_norm", "loss_scale", "skip",
                        "moe_aux_loss", "moe_tokens_dropped")

    def _monitor_finish(self, step_no, metrics, reporting):
        """Per-step telemetry emission (monitor/; docs/monitoring.md).

        Closes the step's root span and hands the monitor (a) the span
        tree measured around this step's dispatch path, (b) the step's
        scalar metrics as DEVICE REFERENCES — synced one step late by
        the monitor, never here — and (c) host-side gauges/counters
        (memory, compile-cache, health counters, per-step wire bytes).
        With ``wall_clock_breakdown`` the same spans feed the named-timer
        registry and its log line on reporting steps."""
        mon = self.monitor
        if not mon.armed:
            return
        scalars = gauges = counters = None
        if mon.should_emit(step_no):
            scalars = {k: metrics[k] for k in self._MON_SCALAR_KEYS
                       if k in metrics}
            gauges, counters = self._monitor_gauges_counters()
        spans = mon.end_step(step_no, scalars=scalars, gauges=gauges,
                             counters=counters)
        if (self._mem_interval and mon.bus is not None and mon.bus.sinks
                and step_no % self._mem_interval == 0):
            # the memory ledger's periodic `mem` event (host-side reads
            # only — the compiled step never sees this; --audit-step
            # mem).  Gated on live sinks but NOT on monitor.interval:
            # memory_interval alone sets this cadence, as documented —
            # an interval-thinned monitor must not push it to the lcm.
            from ..monitor import memory_ledger as mled
            mled.attribute_engine(self).emit(mon, step=step_no,
                                             phases=self._rss_phases)
        if self.config.wall_clock_breakdown and spans:
            for s in spans:
                self.timers.record_span(s["name"], s["dur_s"])
            if reporting:
                self.timers.log(
                    sorted({s["name"] for s in spans}),
                    memory_breakdown=self.config.memory_breakdown)

    def _monitor_gauges_counters(self):
        """Host-side gauge/counter payload for one emitted step: rate
        denominators (tokens, flops — set once, the monitor divides by
        measured wall), device memory (live stats, or the executable's
        ``memory_analysis()`` projection where the backend exposes
        none), compile-cache hit/miss, and health skip/rewind state."""
        from ..monitor import gauges as mg
        stats = self._monitor_step_stats()
        self.monitor.set_rates(
            tokens_per_step=self._mon_tokens_per_step or None,
            samples_per_step=self.train_batch_size(),
            flops_per_step=stats.get("flops"),
            peak_flops=stats.get("peak_flops"))
        gauges = {}
        mem = mg.device_memory()
        if mem:
            gauges.update(mem)
        elif stats.get("hbm_projected"):
            gauges["hbm_peak_projected"] = stats["hbm_projected"]
        if self.compile_cache is not None:
            gauges["compile_cache_hits"] = self.compile_cache.stats["hits"]
            gauges["compile_cache_misses"] = \
                self.compile_cache.stats["misses"]
        if self.health_monitor is not None:
            hc = self.health_monitor.counters()
            gauges["health_skipped_total"] = hc["total_skips"]
            gauges["health_rewinds"] = hc["rewinds"]
        return gauges, dict(stats.get("wire") or {})

    def _monitor_step_stats(self):
        """Per-program telemetry constants, priced from the DISPATCHING
        compiled step (no extra lowering/compile): XLA cost-analysis
        FLOPs (the flops-profiler reading — live MFU divides them by
        measured wall), the HLO collective census priced as wire
        bytes/step (``analysis/comms.py``), and the projected peak bytes.
        Cached per live-signature count: a retrace under a new batch
        shape (curriculum cropping) re-prices, so the gauges follow the
        program that is actually executing."""
        from ..monitor import gauges as mg
        fn = (self._jit_grad_step if self._offload is not None
              else self._jit_train_step)
        n_sigs = mg.live_signature_count(fn)
        if self._mon_step_stats is not None:
            cached_n, out = self._mon_step_stats
            if cached_n == n_sigs:
                return out
            self._mon_step_stats = None    # new program: re-price
        if not getattr(fn, "_exes", None) and self._mon_example is not None:
            # no live executable recorded (compile cache off -> CachedStep
            # passthrough): acquire one, once, so the per-program gauges
            # exist anyway.  One extra compile on monitored no-cache
            # engines — enabling the compile cache avoids it.
            example, self._mon_example = self._mon_example, None
            try:
                with jax.set_mesh(self.mesh):
                    fn.executable(self.state, *example)
            except Exception as e:
                logger.warning(f"monitor: could not price the compiled "
                               f"step ({e}); MFU/wire gauges unavailable")
        self._mon_example = None
        out = {}
        flops = mg.executable_flops(fn)
        if flops:
            out["flops"] = flops
            out["peak_flops"] = mg.peak_flops_per_chip() * len(jax.devices())
        wire = mg.executable_wire_report(fn)
        if wire:
            out["wire"] = wire
        peak = mg.executable_peak_bytes(fn)
        if peak:
            out["hbm_projected"] = peak
        hbm_bytes = mg.executable_bytes_accessed(fn)
        if flops or hbm_bytes:
            # one `exe_cost` event per priced program: the ds_explain
            # (analysis/roofline.py) feed — XLA FLOPs + memory-traffic
            # bytes + census wire bytes + the producing chip, so an
            # offline stream carries everything the roofline needs
            self.monitor.gauge(
                "exe_cost", float(flops), exe="train_step", flops=flops,
                hbm_bytes=hbm_bytes,
                wire_bytes=(wire or {}).get("wire_bytes_per_step", 0),
                device_kind=jax.devices()[0].device_kind,
                n_chips=len(jax.devices()))
        n_sigs = mg.live_signature_count(fn)
        if n_sigs:
            # cache against the signature count: stable program = priced
            # once; a retrace invalidates (see the check above)
            self._mon_step_stats = (n_sigs, out)
        return out

    # ------------------------------------------------- health guardian (host)
    def _health_observe(self, step_no, metrics):
        """Feed the step's sentinels to the monitor and execute the action
        it escalates to (docs/health-monitor.md)."""
        action = self.health_monitor.observe(
            step_no, self._last_batch_index, metrics)
        if action == "rewind":
            self._health_rewind()
        elif action == "abort":
            self._health_abort("consecutive-skip budget exhausted and "
                               "rewind limit spent")

    def _health_abort(self, reason):
        # drain the monitor's lag window first: the newest steps —
        # including the ones that tripped the abort — must reach the
        # forensic history (their escalation verdict is moot now)
        self.health_monitor.flush()
        path = self.health_monitor.forensic_dump(
            self._forensic_dir(), reason,
            last_good_tag=self.loaded_checkpoint_tag)
        raise hmod.TrainingHealthError(
            f"training health: {reason}; "
            f"counters={self.health_monitor.counters()}"
            + (f"; forensics at {path}" if path else ""),
            forensic_path=path)

    def _forensic_dir(self):
        return (self._health_cfg.forensic_dir
                or self.config.checkpoint_config.dir
                or self._last_ckpt_dir or os.getcwd())

    def _health_rewind(self):
        """Monitor-driven escalation: in-process rewind to the newest valid
        checkpoint, then fast-forward the data stream past the last
        observed poison batch.  A rewind that cannot run (no checkpoint
        dir / no loadable tag) falls through to ``on_exhausted``.

        When a rewind's replay runs STRAIGHT back into skips (no clean
        step applied since the previous rewind — we are provably still
        inside the same poison window), the fast-forward stride doubles:
        a W-batch window is crossed in O(log W) rewinds instead of one
        skip-budget's width per rewind, at the cost of over-skipping at
        most W clean batches."""
        mon = self.health_monitor
        same_episode = mon.episode_rewinds > 0 and mon.clean_since_rewind == 0
        self._ff_stride = self._ff_stride * 2 if same_episode else 1
        target = mon.last_bad_stream_step
        if target is not None:
            target += self._ff_stride - 1
        try:
            self.rewind(replay_past=target)
        except Exception as e:
            # any ordinary failure (no dir, no valid tag, checkpoint IO
            # errors after retry exhaustion) ends the ladder here;
            # InjectedCrash/SIGKILL-like BaseExceptions still propagate
            if self._health_cfg.on_exhausted == "warn":
                logger.warning(f"health: rewind unavailable ({e}); "
                               "on_exhausted=warn — continuing without it")
                mon.consecutive_skips = 0
                return
            self._health_abort(f"rewind failed: {e}")
        mon.record_rewind(tag=self.loaded_checkpoint_tag)

    def rewind(self, load_dir=None, tag=None, replay_past=None):
        """In-process rewind-and-replay: reload the newest *valid* (manifest-
        verified) checkpoint without a process restart, then fast-forward
        the restored data stream past ``replay_past`` (a data-stream batch
        index, e.g. the last step poisoned by a bad batch) so replay
        resumes on clean data instead of re-feeding the poison window.

        Used by the health guardian's escalation ladder; also callable
        directly (operator-driven rollback)."""
        load_dir = load_dir or self._rewind_dir()
        if load_dir is None:
            raise ValueError(
                "rewind needs a checkpoint directory: set checkpoint.dir "
                "in the config or save/load a checkpoint first")
        path, _ = self.load_checkpoint(load_dir, tag=tag)
        if replay_past is not None:
            if self._data_iterator is None:
                logger.warning(
                    "rewind: no engine-owned data iterator to fast-forward "
                    "(external data_iter?); replay will re-feed the stream "
                    "from the checkpointed position")
            elif not self._stream_pos_known:
                logger.warning(
                    "rewind: data-stream position unknown (the checkpoint "
                    "carried no data-pipeline state); fast-forward skipped "
                    "— replay may re-feed already-seen batches")
            else:
                gas = self.gradient_accumulation_steps()
                skipped = max(replay_past - self._stream_step + 1, 0)
                loader = self.training_dataloader
                if (skipped and isinstance(self._data_iterator,
                                           RepeatingLoader)
                        and self._data_iterator.loader is loader
                        and hasattr(loader, "load_state_dict")):
                    # O(1) jump: advance the loader's (epoch, batch_index)
                    # arithmetic instead of collating every discarded batch
                    # (a W-step window at model-scale batch sizes would
                    # otherwise stall recovery on throwaway numpy stacking)
                    per_epoch = max(len(loader), 1)
                    sd = loader.state_dict()
                    pos = sd["epoch"] * per_epoch + sd["batch_index"] \
                        + skipped * gas
                    loader.load_state_dict({
                        "seed": sd["seed"], "epoch": pos // per_epoch,
                        "batch_index": pos % per_epoch})
                    self._data_iterator = iter(RepeatingLoader(loader))
                    self._stream_step += skipped
                else:
                    while self._stream_step <= replay_past:
                        for _ in range(gas):
                            next(self._data_iterator)
                        self._stream_step += 1
                log_dist("rewind fast-forward: " + json.dumps(
                    {"event": "health_fast_forward", "batches": skipped,
                     "resume_stream_step": self._stream_step}), ranks=[0])
        return path

    def _rewind_dir(self):
        return self.config.checkpoint_config.dir or self._last_ckpt_dir

    def _upload_offload_params(self):
        """Host master → device params as CHUNKED flat h2d transfers + a
        jitted concat/scatter (per-leaf device_put pays one round-trip
        latency per leaf; one monolithic transfer serializes the
        transport — ``zero/wire.py``).  Chunks are staged through
        reusable host buffers so the next host optimizer step can mutate
        the 16-bit payload while the previous upload is still in flight
        (the DPU overlap makes that race live otherwise).

        Single-device fast path only: on a multi-chip mesh the flat image
        would land whole on one device before resharding (OOM for models
        that only fit sharded) — there the per-leaf placement puts each
        leaf directly into its sharding."""
        if self._sparse_grad_paths or self.mesh.size > 1:
            # sparse wire keeps the tree format end-to-end.  Under DPU the
            # payload leaves are live views of the host 16-bit image, which
            # the NEXT host step mutates while this device_put may still be
            # reading — stage copies first (same race the flat branch
            # stages against).
            tree = self._offload.payload_tree()
            if self._dpu:
                # copy into ALTERNATING pre-faulted staging trees (a fresh
                # tree_map(np.array) would allocate + first-touch the full
                # payload every step; a single reused tree could itself be
                # overwritten while its upload is in flight — two buffers
                # give a full upload cycle of slack, and the grad landing
                # between reuses proves the older transfer completed)
                stages = getattr(self, "_tree_stages", None)
                if stages is None:
                    stages = self._tree_stages = [
                        jax.tree_util.tree_map(np.array, tree), None]
                    self._tree_stage_idx = 0
                idx = self._tree_stage_idx
                if stages[idx] is None:
                    stages[idx] = jax.tree_util.tree_map(np.array, tree)
                else:
                    jax.tree_util.tree_map(np.copyto, stages[idx], tree)
                self._tree_stage_idx = 1 - idx
                tree = stages[idx]
            return jax.device_put(tree, self._param_sh)
        with self.monitor.span("param_h2d"):
            payload = self._offload.payload_flat()
            chunks = self._h2d.upload_flat(payload, stage=self._dpu)
        if self._jit_scatter_params is None or \
                self._scatter_nchunks != len(chunks):
            from .zero.wire import make_chunk_scatter
            self._scatter_nchunks = len(chunks)
            self._jit_scatter_params = make_chunk_scatter(
                self._offload.shapes, self._offload.treedef,
                int(chunks[0].shape[0]), len(chunks),
                out_shardings=self._param_sh)
        params = self._jit_scatter_params(*chunks)
        # staging buffers recycle once the scatter OUTPUT is ready (the
        # donated chunks' is_deleted cannot prove the h2d DMA finished)
        self._h2d.settle_on(jax.tree_util.tree_leaves(params)[0])
        return params

    def _flush_row_drop_checks(self):
        """Read the accumulated device drop counters (syncs) and raise if any
        sparse-gradient row was silently dropped since the last flush."""
        pending, self._pending_row_drop_checks = \
            self._pending_row_drop_checks, []
        n_dropped = sum(int(x) for x in pending)
        if n_dropped > 0:
            raise RuntimeError(
                f"sparse_grad_row_bound under-declared: {n_dropped} "
                "nonzero gradient row(s) exceeded the declared bound "
                "within the last reporting interval and were "
                "dropped; raise the bound (or remove "
                "sparse_grad_row_bound to use the safe default)")

    def _flush_offload(self):
        """Apply a pending delayed-param update so exported / evaluated
        parameters reflect every batch seen (DPU holds one step in flight).
        Also the unconditional flush point for the sparse row-drop guard:
        every state-export boundary (checkpoint save, eval, state_dict)
        routes through here, so corrupted-gradient errors cannot be skipped
        by run length or checkpoint timing."""
        self._flush_row_drop_checks()
        if self._pending_offload is not None:
            pending, self._pending_offload = self._pending_offload, None
            self._host_offload_update(*pending)
            # the just-applied in-flight step appended its own drop counter
            # (DPU holds one step back) — check it too before any export
            self._flush_row_drop_checks()

    def eval_batch(self, batch, rng=None):
        """Loss without gradient/update (jitted separately)."""
        self._install_moe_wire()
        self._flush_offload()
        if self._param_stream is not None:
            rng = rng if rng is not None else jax.random.PRNGKey(0)
            with jax.set_mesh(self.mesh):
                return self._param_stream.eval_loss(batch, rng)
        if self._jit_eval is None:
            def eval_fn(params, mb, r):
                return self._loss_fn(params, mb, r)
            self._jit_eval = self._wrap_step("eval_step", eval_fn)
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        batch = self._device_batch(batch)
        with jax.set_mesh(self.mesh):
            return self._jit_eval(self.state.params, batch, rng)

    def _device_batch(self, batch):
        sh = jax.tree_util.tree_map(
            lambda x: NamedSharding(self.mesh, P(M.BATCH_AXES)), batch)
        return jax.device_put(batch, sh)

    # --- forward/backward/step compatibility shim -------------------------
    def forward(self, batch, rng=None):
        """Compatibility shim: computes the (eval) loss AND stages the batch
        for the fused step executed at the gas boundary in :meth:`step`."""
        self._staged_batch = batch
        return self.eval_batch(batch, rng)

    def backward(self, loss=None):
        """Compatibility shim: queue the staged microbatch.  The actual
        gradient computation happens fused inside :meth:`step` at the
        accumulation boundary (reference semantics: grads materialize during
        backward; here XLA fuses them into the optimizer step)."""
        assert getattr(self, "_staged_batch", None) is not None, \
            "call forward(batch) before backward()"
        self._pending_microbatches.append(self._staged_batch)
        self._staged_batch = None
        return loss

    def is_gradient_accumulation_boundary(self):
        """Parity: reference ``engine.py:1267``."""
        return len(self._pending_microbatches) >= self.gradient_accumulation_steps()

    def step(self):
        """Compatibility shim: at the gas boundary, run the fused train step
        over the queued microbatches."""
        if not self.is_gradient_accumulation_boundary():
            return None
        # a retrace here must see THIS engine's expert-wire policy, not
        # whichever engine dispatched last (same rule as train_batch)
        self._install_moe_wire()
        self.monitor.begin_step()
        micro_batches, self._pending_microbatches = \
            self._pending_microbatches, []
        if self._param_stream is not None:
            return self._run_stream_step(micro_batches)
        return self._run_fused_step(self._stack_microbatches(micro_batches))

    # ------------------------------------------------------------ data/loader
    def deepspeed_io(self, dataset, batch_size=None, route=None, data_sampler=None,
                     collate_fn=None, num_local_io_workers=None):
        """Build the config-driven loader (parity: reference ``engine.py:1493``).

        One process feeds the whole mesh, so the loader yields GLOBAL
        micro-batches of ``micro_batch × dp_world`` samples; the engine shards
        them over the (data, fsdp) axes on device_put.
        """
        if batch_size is None:
            batch_size = self.train_micro_batch_size_per_gpu() * self.mesh_ctx.dp_world_size
        return DeepSpeedDataLoader(dataset, batch_size=batch_size,
                                   collate_fn=collate_fn,
                                   drop_last=self.config.dataloader_drop_last)

    def _profile_train_step(self, batch, rng):
        """Print the FLOPS profile of the compiled train step (parity:
        reference flops-profiler engine integration, ``engine.py:1583-1588``)."""
        from ..profiling.flops_profiler.profiler import FlopsProfiler
        prof = FlopsProfiler(ds_engine=self)
        prof.start_profile()
        try:
            step_fn = (self._jit_grad_step if self._offload is not None
                       else self._jit_train_step)
            with jax.set_mesh(self.mesh):
                lowered = step_fn.lower(self.state, batch, rng)
                ca = lowered.compile().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            prof._flops = int(ca.get("flops", 0) or 0)
            prof._macs = prof._flops // 2
            prof._bytes = ca.get("bytes accessed")
            prof._duration = self.tput_timer.avg_step_time()
            if self.config.flops_profiler.detailed:
                # per-module tree via named_scope attribution (the model's
                # scopes; optimizer/infra ops stay at the root)
                from ..profiling.flops_profiler.profiler import module_tree
                raw_fn = (self._grad_only_step if self._offload is not None
                          else self._train_step)
                try:
                    with jax.set_mesh(self.mesh):
                        jaxpr = jax.make_jaxpr(raw_fn)(self.state, batch, rng)
                    prof._tree = module_tree(jaxpr)
                except Exception:
                    prof._tree = None
        except Exception as e:
            logger.warning(f"flops profiler cost analysis failed: {e}")
        prof.print_model_profile(
            profile_step=self.config.flops_profiler.profile_step,
            detailed=self.config.flops_profiler.detailed,
            output_file=self.config.flops_profiler.output_file)
        prof.end_profile()

    # ------------------------------------------------------------- reporting
    def _report_progress(self, step, metrics):
        lr = float(metrics["lr"])
        loss = float(metrics["loss"])
        msg = f"step={step}, loss={loss:.6f}, lr={lr:.3e}"
        if self.fp16_enabled:
            msg += (f", loss_scale={float(metrics['loss_scale']):.1f}"
                    f", skipped={int(self.state.skipped_steps)}")
        elif self._health_enabled and bool(metrics.get("skip", False)):
            msg += (f", SKIPPED (health sentinel; total "
                    f"{int(self.state.skipped_steps)})")
        if "moe_aux_loss" in metrics:
            msg += f", moe_aux={float(metrics['moe_aux_loss']):.4f}"
        log_dist(msg, ranks=[0])
        dropped = float(metrics.get("moe_tokens_dropped", 0.0))
        if dropped > 0:
            log_dist(f"WARNING: MoE dropped {dropped:.0f} token-slots this "
                     "step (capacity overflow) — raise capacity_factor / "
                     "max_capacity or enable drop-free gating "
                     "(drop_tokens=False)", ranks=[0])

    def _setup_tensorboard(self):
        """Tensorboard as a monitor-bus sink.

        The old path imported ``torch.utils.tensorboard`` — a torch
        dependency a JAX framework must not carry, and dead in any
        torch-less container.  Now ``tensorboard.enabled`` attaches a
        :class:`monitor.sinks.TensorboardSink` (tensorboardX / flax
        writer) to the engine's event bus, so the scalars it exports are
        the SAME step/gauge events every other sink sees; when no
        non-torch writer is importable it degrades to one warning
        (JSONL/CSV always work)."""
        from ..monitor import core as moncore
        from ..monitor.sinks import TensorboardSink, SinkUnavailable
        if not moncore._is_rank0():
            # same rank-0 gate Monitor.__init__ applies to export sinks:
            # every process writing the same tfevents dir would conflict
            return
        path = os.path.join(self.config.tensorboard.output_path or ".",
                            self.config.tensorboard.job_name)
        try:
            sink = TensorboardSink(path)
        except (SinkUnavailable, OSError) as e:
            logger.warning(f"tensorboard unavailable: {e}")
            return
        if not self.monitor.armed:
            # arm a bus-only monitor so the tensorboard sink has events
            # to consume; no file sinks, nothing else changes
            self.monitor = moncore.Monitor(run_dir=None, sinks=())
        self.monitor.bus.attach(sink)
        # a late-armed (tensorboard-only) monitor must reach the other
        # bus consumers built before it
        self.tput_timer.bus = self.monitor.bus
        if self.health_monitor is not None:
            self.health_monitor.bus = self.monitor.bus

    # ------------------------------------------------------------ properties
    @property
    def global_steps(self):
        return int(self.state.global_steps)

    @property
    def skipped_steps(self):
        return int(self.state.skipped_steps)

    def train_batch_size(self):
        return self.config.train_batch_size

    def train_micro_batch_size_per_gpu(self):
        return self.config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self):
        return self.config.gradient_accumulation_steps

    def zero_optimization_stage(self):
        return self.zero_stage

    def loss_scale(self):
        if self.state.scale is None:
            return 1.0
        return float(self.state.scale.cur_scale)

    def get_lr(self):
        return [float(self._lr_at(self.state.global_steps))]

    def get_global_grad_norm(self):
        m = self._last_metrics.get("grad_norm")
        return float(m) if m is not None else None

    def module_state_dict(self):
        """Full (gathered) params as a host pytree of numpy arrays."""
        self._flush_offload()
        if self._param_stream is not None:
            return self._param_stream.full_params_host()
        return jax.tree_util.tree_map(np.asarray, self.state.params)

    # ----------------------------------------------------------- checkpoints
    def _get_ckpt_name(self, checkpoints_path, tag):
        return os.path.join(checkpoints_path, str(tag))

    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True):
        """Parity: reference ``engine.py:2797``.  Layout:
        ``<dir>/<tag>/{model,optim}_states.msgpack`` + ``<dir>/latest``.
        Arrays are gathered to host; ZeRO-sharded state is saved in full so
        checkpoints reshard freely across mesh-size changes (the reference
        needs ``elastic_checkpoint`` machinery for this; here resharding is a
        device_put).

        Crash-consistent (docs/fault-tolerance.md): every file goes into a
        ``<tag>.tmp`` staging dir, a SHA-256 manifest is recorded, and the
        checkpoint is published by one ``os.rename``; the ``latest`` pointer
        is updated write-temp-then-rename only after commit.  A kill at any
        instant leaves either the previous checkpoint set intact or the new
        tag fully committed — never a torn tag that ``latest`` points at."""
        from ..checkpoint.serialization import save_tree
        from ..checkpoint import atomic
        from .. import fault
        self._flush_offload()
        if self.health_monitor is not None:
            # drain the monitor's lag window so the saved run's history is
            # complete; the returned action is intentionally discarded —
            # if the drained steps warrant escalation, the still-elevated
            # counters re-trigger it on the next training step, not from
            # inside a save
            self.health_monitor.flush()
        tag = tag or f"global_step{self.global_steps}"
        retry = self.config.io_retry_config.policy()
        fsync = self.config.checkpoint_config.fsync
        os.makedirs(save_dir, exist_ok=True)
        # drop staging leftovers of killed saves (any tag) and restore an
        # orphaned `.replaced` before staging anew
        atomic.clean_stale_staging(save_dir)
        path = atomic.stage_path(save_dir, tag)
        os.makedirs(path)

        engine_meta = {
            "global_steps": self.global_steps,
            "optimizer_steps": int(self.state.optimizer_steps),
            "skipped_steps": self.skipped_steps,
            "micro_steps": self.micro_steps,
            "global_samples": self.global_samples,
            "zero_stage": self.zero_stage,
            "dtype": self.config.precision_dtype,
            # elastic-resume record (docs/elasticity.md): the mesh this
            # state was partitioned on + the global batch it was trained
            # at, so a resume on a DIFFERENT mesh can verify the resize is
            # a pure re-partition (global batch preserved) and log the
            # re-layout instead of silently changing training semantics
            "mesh": {k: int(v) for k, v in dict(self.mesh.shape).items()},
            "dp_world_size": self.mesh_ctx.dp_world_size,
            "train_batch_size": self.train_batch_size(),
            "elasticity": self.config.elastic_record,
            "client_state": client_state or {},
            "lr_scheduler": (self.lr_scheduler.state_dict()
                             if self.lr_scheduler is not None and
                             hasattr(self.lr_scheduler, "state_dict") else None),
            # data-pipeline state: sampler (seed, epoch, batch index) +
            # monotonic stream position, so load/auto_resume/rewind resume
            # the EXACT batch stream (docs/health-monitor.md)
            "data_state": {
                "stream_step": self._stream_step,
                "loader": (self.training_dataloader.state_dict()
                           if self.training_dataloader is not None and
                           hasattr(self.training_dataloader, "state_dict")
                           else None),
            },
        }
        params_out = (self._param_stream.full_params_host()
                      if self._param_stream is not None
                      else self.state.params)
        # fsync deferred to commit_staged: one durability pass per file,
        # not two (the manifest hash reads the page cache either way)
        save_tree(os.path.join(path, MODEL_FILE),
                  {"params": params_out}, meta=engine_meta,
                  fsync=False, retry=retry)
        fault.site("ckpt.after_model_file")
        if self._offload is not None:
            # host-resident state saved in the SAME layout as the in-device
            # AdamState (param-shaped moment pytrees + full master pytree),
            # so offload/non-offload runs can load each other's checkpoints
            # and zero_to_fp32 consolidation works unchanged.  Streamed mode
            # converts its layer-major trees back to the stacked model tree.
            moments = self._offload.moments_tree()
            master = self._offload.master_tree()
            if self._param_stream is not None:
                from .zero.param_stream import from_stream_tree
                key = self._param_stream.sf["stacked_key"]
                moments = {k: from_stream_tree(v, key)
                           for k, v in moments.items()}
                master = from_stream_tree(master, key)
            optim_tree = {"opt_state": moments, "master": master}
        else:
            optim_tree = {"opt_state": self.state.opt_state}
            if self.state.master is not None:
                optim_tree["master"] = self.state.master
        if self.state.scale is not None:
            optim_tree["scale"] = self.state.scale
        if self.state.comm_error is not None:
            # qgZ error feedback: without it a resumed run would re-pay
            # the compensation warm-up (rewind-safe like `health`)
            optim_tree["comm_error"] = self.state.comm_error
        save_tree(os.path.join(path, OPTIM_FILE), optim_tree,
                  fsync=False, retry=retry)
        fault.site("ckpt.after_optim_file")

        # everything that belongs to the tag — recovery script and gathered
        # 16-bit weights included — is staged and manifested BEFORE commit
        self._copy_recovery_script(path)
        if self.config.zero_config.gather_16bit_weights_on_model_save:
            self.save_16bit_model(path, fsync=False, retry=retry)
        atomic.write_manifest(path, meta={
            "tag": tag,
            "global_steps": self.global_steps,
            "format_version": 1,
        })
        fault.site("ckpt.before_commit")
        with self.monitor.standalone_span("checkpoint_commit"):
            final = atomic.commit_staged(save_dir, tag, fsync=fsync)
        self.monitor.artifact("checkpoint", final, tag=tag,
                              global_steps=self.global_steps)
        fault.site("ckpt.after_commit")
        if save_latest:
            atomic.write_latest(save_dir, tag)
        keep_n = self.config.checkpoint_config.keep_n
        if keep_n:
            # rotation's newest-valid probe uses the cheap size level: the
            # retained tags were hash-verified at commit, and re-hashing
            # them all on every save would put O(keep_n · ckpt_bytes) of
            # SHA-256 on the training hot path
            atomic.rotate_checkpoints(save_dir, keep_n)
        self._last_ckpt_dir = save_dir   # rewind target of last resort
        log_dist(f"saved checkpoint {final}", ranks=[0])
        return True

    def _copy_recovery_script(self, save_path):
        """Drop zero_to_fp32.py beside the checkpoint so weights can be
        extracted without this framework installed (parity: reference
        ``engine.py:3095 _copy_recovery_script``)."""
        import shutil
        from ..utils import zero_to_fp32 as z2f
        src = z2f.__file__
        dst = os.path.join(save_path, "zero_to_fp32.py")
        try:
            shutil.copy2(src, dst)
            os.chmod(dst, 0o755)
        except OSError as e:
            logger.warning(f"could not copy recovery script: {e}")

    def save_16bit_model(self, save_dir, save_filename="model_16bit.msgpack",
                         fsync=True, retry=None):
        """Save the full (gathered) params in the 16-bit compute dtype
        (parity: reference ``engine.py:3194 save_16bit_model`` /
        ``_zero3_consolidated_16bit_state_dict`` :3118 — with sharded state
        the gather here is just the host transfer in ``save_tree``)."""
        from ..checkpoint.serialization import save_tree
        self._flush_offload()
        os.makedirs(save_dir, exist_ok=True)
        path = os.path.join(save_dir, save_filename)
        params_out = (self._param_stream.full_params_host()
                      if self._param_stream is not None
                      else self.state.params)
        save_tree(path, {"params": params_out},
                  meta={"dtype": self.config.precision_dtype},
                  fsync=fsync, retry=retry)
        log_dist(f"saved 16-bit model to {path}", ranks=[0])
        return True

    def _resolve_checkpoint_tag(self, load_dir, tag):
        """Validating, self-healing tag resolution (docs/fault-tolerance.md):

        - explicit ``tag``: manifest must verify, else raise
          ``CheckpointValidationError`` (the caller asked for *that* state);
        - ``latest`` pointer: verify; on mismatch, a missing pointer, or a
          pointer at a torn/uncommitted tag, fall back to the newest valid
          tag with one structured warning;
        - a tag without a manifest (pre-fault-tolerance layout) loads with a
          warning instead of failing — old checkpoints stay readable.
        """
        from ..checkpoint import atomic
        # restore an orphaned `.replaced` (killed same-tag re-commit) on
        # EVERY load path, not just auto_resume.  `.tmp` cleanup is age-
        # guarded here: a reader sharing a live trainer's dir must not
        # delete an in-flight save's staging dir (loads never need the
        # cleanup for correctness; the next save sweeps the garbage)
        atomic.clean_stale_staging(load_dir,
                                   min_age_s=atomic.LOAD_STAGING_MIN_AGE_S)
        verify = self.config.checkpoint_config.verify
        explicit = tag is not None
        problems = []
        if tag is None:
            tag = atomic.read_latest(load_dir)
            if tag is None:
                problems.append(f"no `latest` pointer in {load_dir}")
        if tag is not None:
            path = self._get_ckpt_name(load_dir, tag)
            # legacy = the manifest FILE is absent but state files are
            # there; an unparseable manifest is a torn checkpoint, not a
            # pre-fault-tolerance one
            if atomic.is_legacy_checkpoint(path):
                logger.warning(
                    f"checkpoint {path} has no manifest (pre-fault-tolerance "
                    f"layout); loading without integrity verification")
                return tag
            ok, tag_problems = atomic.verify_checkpoint(path, level=verify)
            if ok:
                return tag
            if explicit:
                raise atomic.CheckpointValidationError(
                    f"checkpoint {path} failed validation: {tag_problems}")
            problems.extend(tag_problems)
        fallback = atomic.find_latest_valid(
            load_dir, exclude=(tag,) if tag else (), level=verify)
        if fallback is None:
            # last resort: a pre-fault-tolerance tag the validity scan
            # cannot vouch for is still better than refusing restorable
            # state (manifested-but-invalid tags never land here — a
            # manifest file, even a corrupt one, means post-upgrade)
            legacy = [t for t in atomic.find_legacy_tags(load_dir)
                      if t != tag]
            if legacy:
                logger.warning("checkpoint fallback engaged: " + json.dumps({
                    "event": "checkpoint_fallback", "load_dir": load_dir,
                    "unusable_tag": tag, "problems": problems,
                    "fallback_tag": legacy[0], "legacy": True}))
                return legacy[0]
            raise FileNotFoundError(
                f"no loadable checkpoint in {load_dir}: {problems}")
        logger.warning("checkpoint fallback engaged: " + json.dumps({
            "event": "checkpoint_fallback", "load_dir": load_dir,
            "unusable_tag": tag, "problems": problems,
            "fallback_tag": fallback}))
        return fallback

    def _check_mesh_transition(self, meta):
        """Elastic resume-on-resize gate (docs/elasticity.md): compare the
        checkpoint's recorded mesh with the current one.

        - identical mesh: nothing to do (the common restart).
        - no record: pre-elastic checkpoint — reshard anyway (the on-disk
          form is full arrays), but warn that global-batch preservation
          cannot be verified.
        - different mesh: a reshard-on-resize event.  The resize is a pure
          re-partition only when the GLOBAL batch is preserved (ZeRO shard
          layout is a function of world size — arXiv 1910.02054 — but the
          optimizer trajectory is a function of the batch): with
          elasticity enabled a changed global batch means the elasticity
          block itself changed (it is a pure function of that block), so
          raise; without elasticity, warn loudly and continue.  The
          re-layout of the ZeRO placements is logged as one structured
          event (``relayout_report``).
        """
        cur_mesh = {k: int(v) for k, v in dict(self.mesh.shape).items()}
        saved_mesh = meta.get("mesh")
        if saved_mesh is None:
            logger.warning(
                "pre-elastic checkpoint: no mesh/batch record in the "
                f"checkpoint meta; resharding onto mesh {cur_mesh} "
                "proceeds, but global-batch preservation cannot be "
                "verified — if the device count changed, loss-curve "
                "continuity is not guaranteed (enable `elasticity` and "
                "re-save to make checkpoints resize-aware)")
            return
        saved_mesh = {k: int(v) for k, v in saved_mesh.items()}
        if saved_mesh == cur_mesh:
            return
        saved_tb = meta.get("train_batch_size")
        cur_tb = self.train_batch_size()
        event = {"event": "elastic_resume",
                 "from_mesh": saved_mesh, "to_mesh": cur_mesh,
                 "from_dp_world": meta.get("dp_world_size"),
                 "to_dp_world": self.mesh_ctx.dp_world_size,
                 "global_batch": {"from": saved_tb, "to": cur_tb,
                                  "preserved": saved_tb == cur_tb},
                 "elastic": bool(self.config.elasticity_enabled)}
        if saved_tb is not None and saved_tb != cur_tb:
            if self.config.elasticity_enabled:
                # the elastic final batch is a pure function of the
                # elasticity block — a mismatch means the block changed
                # between save and resume, which silently changes the
                # optimizer trajectory; refuse rather than drift
                from ..elasticity import ElasticityConfigError
                raise ElasticityConfigError(
                    f"elastic resume would change the global batch "
                    f"{saved_tb} -> {cur_tb}: the `elasticity` block does "
                    f"not match the one the checkpoint was trained with "
                    f"(saved record: {meta.get('elasticity')})")
            logger.warning(
                "resuming on a different mesh WITHOUT elasticity: the "
                f"global batch changes {saved_tb} -> {cur_tb}, which "
                "changes training semantics (lr schedule, convergence). "
                "Enable `elasticity` (or `deepspeed --elastic`) to pick a "
                "(micro_batch, gas) pair that preserves it.")
        old_fsdp = int(saved_mesh.get("fsdp", 1))
        new_fsdp = self.mesh_ctx.fsdp_size
        if self.state is not None and self.state.params is not None \
                and old_fsdp != new_fsdp:
            event["relayout"] = zpart.relayout_report(
                self.state.params, self.zero_stage, old_fsdp, new_fsdp,
                persistence_threshold=(self.config.zero_config
                                       .param_persistence_threshold),
                tp_specs=self._tp_specs)
        log_dist("elastic resume: " + json.dumps(event), ranks=[0])
        if self.monitor.armed:
            # the same record on the telemetry stream (one schema)
            self.monitor.counter(
                "elastic_resume", 1,
                from_mesh=json.dumps(saved_mesh),
                to_mesh=json.dumps(cur_mesh),
                global_batch_preserved=bool(saved_tb == cur_tb))

    def load_checkpoint(self, load_dir, tag=None, load_module_only=False,
                        load_optimizer_states=True, load_lr_scheduler_states=True):
        """Parity: reference ``engine.py:2467``. Returns (path, client_state).

        Loads only manifest-verified checkpoints; see
        ``_resolve_checkpoint_tag`` for the fallback policy."""
        from ..checkpoint.serialization import load_tree
        # a pending delayed update is superseded by the loaded state —
        # and so are its drop counters (they describe discarded steps)
        self._pending_offload = None
        self._pending_row_drop_checks = []
        tag = self._resolve_checkpoint_tag(load_dir, tag)
        path = self._get_ckpt_name(load_dir, tag)
        self.loaded_checkpoint_tag = tag
        retry = self.config.io_retry_config.policy()

        from ..checkpoint.serialization import reshard_put, restore_like
        model_tree, meta = load_tree(os.path.join(path, MODEL_FILE),
                                     with_meta=True, retry=retry)
        # elastic resume (docs/elasticity.md): validate a mesh change
        # BEFORE restoring anything — the checkpoint stores full (gathered)
        # arrays, so re-partitioning onto this mesh is the reshard_put
        # below, but the resize is only training-equivalent when the
        # global batch is preserved
        self._check_mesh_transition(meta)
        state = self.state
        if self._offload is None:
            # (offload path uploads once from the restored host master below)
            state = state._replace(params=reshard_put(
                model_tree["params"], self.state.params, self._param_sh))
        if state.master is not None:
            # keep the fp32 master coherent with the loaded params NOW; if
            # optimizer states are loaded below this is overwritten with the
            # checkpointed master, otherwise (load_module_only) the train step
            # would silently resume from the stale master.
            state = state._replace(master=reshard_put(
                model_tree["params"], state.master, self._master_sh,
                cast=np.float32))

        loaded_ef = None
        if self._offload is not None:
            # host tier: master/moments restored into the offload buffers;
            # the device payload is refreshed from the loaded master.
            # Streamed mode converts checkpoint (stacked) trees into its
            # layer-major layout first.
            if self._param_stream is not None:
                from .zero.param_stream import to_stream_tree
                skey = self._param_stream.sf["stacked_key"]
                conv = lambda t: (to_stream_tree(t, skey)
                                  if t is not None else None)
            else:
                conv = lambda t: t
            self._offload.load_state(master_tree=conv(model_tree["params"]))
            if load_optimizer_states and not load_module_only:
                optim_tree, _ = load_tree(os.path.join(path, OPTIM_FILE),
                                          with_meta=True, retry=retry)
                opt = optim_tree.get("opt_state", {})
                self._offload.load_state(
                    master_tree=conv(optim_tree.get("master")),
                    m=conv(opt.get("exp_avg")), v=conv(opt.get("exp_avg_sq")))
                if "scale" in optim_tree and state.scale is not None:
                    state = state._replace(scale=jax.device_put(
                        restore_like(state.scale, optim_tree["scale"]),
                        self._repl_sh))
                loaded_ef = optim_tree.get("comm_error")
            if self._param_stream is not None:
                self._param_stream.reload_from_host()
            else:
                state = state._replace(params=jax.device_put(
                    self._offload.payload_tree(), self._param_sh))
        elif load_optimizer_states and not load_module_only:
            optim_tree, _ = load_tree(os.path.join(path, OPTIM_FILE),
                                      with_meta=True, retry=retry)
            opt_state = reshard_put(optim_tree["opt_state"],
                                    self.state.opt_state,
                                    self._opt_shardings(self.state.opt_state))
            master = state.master
            if "master" in optim_tree and master is not None:
                master = reshard_put(optim_tree["master"], master,
                                     self._master_sh)
            scale = state.scale
            if "scale" in optim_tree and scale is not None:
                scale = jax.device_put(
                    restore_like(scale, optim_tree["scale"]), self._repl_sh)
            state = state._replace(opt_state=opt_state, master=master, scale=scale)
            loaded_ef = optim_tree.get("comm_error")

        if state.comm_error is not None:
            # qgZ error feedback: reset, then restore when the checkpoint
            # carries a matching state (a pre-compression checkpoint, or
            # one from a different mesh/policy, restarts compensation
            # from zero — EF is an accumulator, resetting is always safe)
            def _ef_leaf(cur, new):
                new = np.asarray(new)
                if new.shape != cur.shape:
                    raise ValueError(
                        f"comm_error leaf shape {new.shape} != {cur.shape}")
                return jax.device_put(new.astype(cur.dtype), cur.sharding)

            ef = jax.tree_util.tree_map(
                lambda cur: jax.device_put(
                    np.zeros(cur.shape, cur.dtype), cur.sharding),
                state.comm_error)
            if loaded_ef is not None:
                try:
                    ef = jax.tree_util.tree_map(
                        _ef_leaf, state.comm_error,
                        restore_like(state.comm_error, loaded_ef))
                except Exception as e:
                    logger.warning(
                        "checkpoint comm_error does not match the current "
                        f"compression policy/mesh ({e}); error feedback "
                        "reset to zero")
            state = state._replace(comm_error=ef)

        mk = lambda v: jax.device_put(jnp.asarray(v, jnp.int32), self._repl_sh)
        self._global_steps_host = int(meta["global_steps"])
        state = state._replace(global_steps=mk(meta["global_steps"]),
                               optimizer_steps=mk(meta["optimizer_steps"]),
                               skipped_steps=mk(meta["skipped_steps"]),
                               # fresh EMA: the loaded run must not inherit
                               # loss statistics of the steps just discarded
                               health=self._init_health_device()
                               if state.health is not None else None)
        self.state = state
        self.micro_steps = meta.get("micro_steps", 0)
        self.global_samples = meta.get("global_samples", 0)
        # data-pipeline state: restore the sampler position so replay
        # resumes the exact batch stream (pre-guardian checkpoints carry
        # none — the stream then restarts, as before)
        data_state = meta.get("data_state") or {}
        self._stream_step = int(data_state.get("stream_step", 0))
        self._last_batch_index = None
        if (data_state.get("loader") is not None
                and self.training_dataloader is not None
                and hasattr(self.training_dataloader, "load_state_dict")):
            exact = self.training_dataloader.load_state_dict(
                data_state["loader"])
            # rebuild the engine-owned iterator over the restored position
            self._data_iterator = iter(
                RepeatingLoader(self.training_dataloader))
            # a mesh resize changes the loader's global micro-batch; the
            # position converts through rows (loader state carries its
            # batch_size) and stays EXACT at optimizer-step boundaries —
            # only an off-boundary conversion (floored, rows replay)
            # degrades the stream position to unknown for fast-forward
            self._stream_pos_known = exact is not False
        else:
            # pre-guardian checkpoint (or no engine-owned loader): the live
            # iterator's position no longer matches _stream_step, so a
            # rewind must not fast-forward against it
            self._stream_pos_known = False
        if self.health_monitor is not None:
            self.health_monitor.on_checkpoint_load()
        if self._param_stream is not None:
            self._param_stream.reset_health_ema()
        self._last_ckpt_dir = load_dir
        if (load_lr_scheduler_states and self.lr_scheduler is not None
                and meta.get("lr_scheduler") is not None
                and hasattr(self.lr_scheduler, "load_state_dict")):
            self.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        log_dist(f"loaded checkpoint {path} at global_step={meta['global_steps']}",
                 ranks=[0])
        return path, meta.get("client_state", {})
