"""Persistent compiled-step cache with AOT warm-start.

The reference DeepSpeed amortizes kernel build cost once per install
(``op_builder/`` JIT compiles + prebuilt wheels); this XLA port instead
paid full tracing+compilation on EVERY process start — ~50s of
engine-ready time per bench rung, per CI test worker, per auto-resume and
per rewind-and-replay.  This module makes that a cached cost:

- every jitted entry point (the fused ``_train_step``, the offload
  ``_grad_only_step``, eval steps, the pipe-engine schedule step, the
  ``param_stream`` per-layer programs, the inference prefill/decode
  steps) is dispatched through a :class:`CachedStep` wrapper;
- on first use the wrapper lowers the function (cheap tracing), builds a
  content-addressed key, and either DESERIALIZES a previously compiled
  executable (``jax.experimental.serialize_executable`` — donation
  aliasing is baked into the serialized artifact, so DSTPU204 holds for
  warm starts too) or compiles and writes the entry;
- entries are committed with the PR-1 atomic stage/manifest/rename
  protocol (``checkpoint/atomic.py``): SHA-256-manifested payloads, one
  publishing ``os.rename`` — a corrupt, truncated or unpicklable entry
  is a MISS that falls back to a fresh compile, never a crash.

Cache key anatomy (see docs/compile-cache.md) — everything that legally
invalidates an executable:

- jax/jaxlib versions, backend, device kind + count;
- the entry point's name and the engine's config slice (dtype, zero
  stage, gas, grad-accum dtype, clipping, scaler + health flags, mesh
  axes, offload devices — passed in by the caller as ``key_extra``);
- per-argument abstract avals (shape/dtype/weak_type) and shardings;
- the donation spec;
- the DSTPU205 recompile-hazard fingerprint (the weak-typed-scalar
  argument surface of the PR-2 auditor; the baked-constant hazard class
  is covered by the lowering hash below — a closure-captured constant
  changes the StableHLO text);
- a SHA-256 of the lowered StableHLO itself — the belt-and-braces term
  that also captures remat policy, sharding constraints, and any model
  code change.

NOTE: this is NOT jax's ``jax_compilation_cache_dir``.  That cache was
measured returning executables whose donated-buffer aliasing mismatched
the new trace on this container's jax 0.4.37 (see tests/conftest.py);
``serialize_executable`` round-trips the executable object itself, so
the alias map travels with the payload and is re-audited (DSTPU204) on
warm-started engines.
"""

import hashlib
import json
import os
import pickle
import shutil
import time

import numpy as np
import jax
import jax.numpy as jnp

from ..checkpoint import atomic
from ..utils.logging import logger, log_dist

PAYLOAD_FILE = "payload.bin"
KEY_FILE = "key_anatomy.json"
STATS_FILE = "last_run_stats.json"
FORMAT_VERSION = 1
ENV_DIR = "DSTPU_COMPILE_CACHE"
_ENV_OFF = ("0", "off", "false", "no", "disabled")
_MAX_EVENTS = 64

# process-wide counters aggregated across every CompileCache instance —
# the pytest terminal summary and ds_report read these to show the
# cold-vs-warm trend of a whole run
GLOBAL_STATS = {"hits": 0, "misses": 0, "corrupt": 0, "puts": 0,
                "put_errors": 0, "lower_ms": 0.0, "compile_ms": 0.0,
                "deserialize_ms": 0.0}


def reset_global_stats():
    for k in GLOBAL_STATS:
        GLOBAL_STATS[k] = 0.0 if k.endswith("_ms") else 0


def resolve_env_dir():
    """The env-configured cache dir, or None (incl. explicit-off values)."""
    v = os.environ.get(ENV_DIR, "").strip()
    if not v or v.lower() in _ENV_OFF:
        return None
    return v


def env_disabled():
    """True when the env var explicitly turns the cache OFF (overrides a
    config-provided dir — the operator's kill switch)."""
    v = os.environ.get(ENV_DIR, "").strip()
    return bool(v) and v.lower() in _ENV_OFF


# --------------------------------------------------------------------- keys
def _leaf_sig(leaf):
    """(shape, dtype, weak_type) — the per-dispatch signature term.  No
    string formatting of shardings here: this runs on EVERY call."""
    aval = getattr(leaf, "aval", None)
    if aval is not None:
        return (tuple(getattr(aval, "shape", ())),
                str(getattr(aval, "dtype", "")),
                bool(getattr(aval, "weak_type", False)))
    if isinstance(leaf, (bool, int, float, complex)):
        # Python scalars are weak-typed by definition — the DSTPU205
        # hazard class; they key separately from explicit-dtype arrays
        return ("pyscalar", type(leaf).__name__, True)
    a = np.asarray(leaf)
    return (tuple(a.shape), str(a.dtype), False)


def _leaf_fingerprint(leaf):
    """_leaf_sig + the sharding repr — the once-per-signature key term."""
    sharding = getattr(leaf, "sharding", None)
    return _leaf_sig(leaf) + (str(sharding) if sharding is not None
                              else None,)


def args_signature(args, kwargs=None):
    """Hashable structural signature of a call: treedef + per-leaf
    (shape, dtype, weak_type).  One executable per signature."""
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs or {}))
    return (treedef, tuple(map(_leaf_sig, leaves)))


def _being_traced(args, kwargs):
    """True while any jax trace is in progress (jax.make_jaxpr, an outer
    jit).  One global flag read — no per-leaf scan on the hot path; a
    tracer can only reach us while a trace is live.  Falls back to a
    leaf scan on jax versions without ``trace_state_clean``."""
    try:
        return not jax.core.trace_state_clean()
    except AttributeError:
        return any(isinstance(l, jax.core.Tracer)
                   for l in jax.tree_util.tree_leaves((args, kwargs)))


def build_key_material(name, args, lowered, key_extra=None, kwargs=None):
    """The documented key anatomy (docs/compile-cache.md), or None when
    program identity cannot be established (then nothing is cached)."""
    import jaxlib
    leaves = jax.tree_util.tree_leaves((args, kwargs or {}))
    fps = [_leaf_fingerprint(l) for l in leaves]
    # DSTPU205 fingerprint, argument half: weak-typed scalar positions
    # (a Python int/float leaked into the step).  The closure-constant
    # half of DSTPU205 is covered by lowering_sha256 — baked consts are
    # dense attributes in the StableHLO text.
    weak_scalars = [i for i, (shape, _, weak, _) in enumerate(fps)
                    if weak and shape in ((), "pyscalar")]
    try:
        low_text = lowered.as_text()
    except Exception as e:  # lowering dialects vary across jax versions
        # WITHOUT the program hash, two lowerings that differ only in
        # content (a baked constant, a remat policy, model code) would
        # collide on avals+config and a warm start would dispatch a
        # stale executable — refuse to key at all: the caller compiles
        # fresh and skips the cache for this entry point
        logger.warning(f"compile cache: lowered.as_text failed ({e}); "
                       f"NOT caching {name} (program identity unavailable)")
        return None
    devices = jax.devices()
    material = {
        "v": FORMAT_VERSION,
        "name": name,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
        "devices": {"kind": devices[0].device_kind, "count": len(devices)},
        "args": [list(map(str, fp)) for fp in fps],
        "dstpu205_weak_scalars": weak_scalars,
        "config": key_extra or {},
        "lowering_sha256": hashlib.sha256(low_text.encode()).hexdigest(),
    }
    return material


def key_from_material(material):
    blob = json.dumps(material, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()


# -------------------------------------------------------------------- cache
class CompileCache:
    """Content-addressed on-disk store of serialized compiled executables.

    Entry layout: ``<dir>/<key>/{payload.bin, key_anatomy.json,
    manifest.json}``, committed via the atomic stage/manifest/rename
    protocol and validated (SHA-256) on every read.  ``readonly=True``
    serves a shared CI cache: reads verify and deserialize, but nothing
    is written, touched, or evicted.
    """

    def __init__(self, dir, max_entries=0, readonly=False):
        self.dir = dir
        self.max_entries = int(max_entries or 0)
        self.readonly = bool(readonly)
        self.stats = {k: (0.0 if k.endswith("_ms") else 0)
                      for k in GLOBAL_STATS}
        self.events = []
        if not self.readonly:
            os.makedirs(self.dir, exist_ok=True)
            # age-guarded sweep: unlike a checkpoint dir, a compile cache
            # is SHARED BY DESIGN (CI workers, concurrent engines) — a
            # young `.tmp` may be another process's in-flight put, not a
            # killed writer's leftover
            atomic.clean_stale_staging(
                self.dir, min_age_s=atomic.LOAD_STAGING_MIN_AGE_S)

    # -------------------------------------------------------------- storage
    def _entry_dir(self, key):
        return os.path.join(self.dir, key)

    def get(self, key):
        """Verified payload bytes, or None.  A torn/corrupt entry is
        removed (unless readonly) and reported as a miss."""
        path = self._entry_dir(key)
        if not os.path.isdir(path):
            return None
        ok, problems = atomic.verify_checkpoint(path, level="full")
        if not ok:
            self._count("corrupt")
            logger.warning(
                "compile cache: entry %s failed validation (%s); "
                "falling back to a fresh compile" % (key[:16], problems))
            self.invalidate(key)
            return None
        try:
            with open(os.path.join(path, PAYLOAD_FILE), "rb") as f:
                payload = f.read()
        except OSError as e:
            self._count("corrupt")
            logger.warning(f"compile cache: entry {key[:16]} unreadable "
                           f"({e}); falling back to a fresh compile")
            self.invalidate(key)
            return None
        self._touch(path)
        return payload

    def put(self, key, payload, meta=None):
        """Atomically commit an entry; returns True on success.  Failures
        (disk full, permissions, races) degrade to not-cached.

        Staging is PER-PROCESS (``<key>.<pid>.tmp``): the cache is shared
        by design, and two workers compiling the same program must not
        clobber each other's in-flight staging (the same-content entry
        either writer commits is valid — first rename wins)."""
        if self.readonly:
            return False
        staged = atomic.stage_path(self.dir, f"{key}.{os.getpid()}")
        final = self._entry_dir(key)
        try:
            if os.path.isdir(staged):        # leftover of our own killed run
                shutil.rmtree(staged, ignore_errors=True)
            os.makedirs(staged)
            with open(os.path.join(staged, PAYLOAD_FILE), "wb") as f:
                f.write(payload)
            with open(os.path.join(staged, KEY_FILE), "w") as f:
                # key anatomy beside the payload, not a metric stream
                json.dump(meta or {}, f, indent=2,  # dstpu: disable=DSTPU104
                          sort_keys=True, default=str)
            atomic.write_manifest(staged, meta={
                "key": key, "format_version": FORMAT_VERSION,
                "payload_bytes": len(payload)})
            try:
                os.rename(staged, final)
            except OSError:
                if not os.path.isdir(final):
                    raise
                # a concurrent writer committed the same key first; its
                # entry is equivalent — drop ours
                shutil.rmtree(staged, ignore_errors=True)
            atomic.fsync_dir(self.dir)
        except OSError as e:
            shutil.rmtree(staged, ignore_errors=True)
            self._count("put_errors")
            logger.warning(f"compile cache: could not write entry "
                           f"{key[:16]} ({e}); executable stays in-memory "
                           "only for this process")
            return False
        self._count("puts")
        self._evict_lru()
        return True

    def invalidate(self, key):
        if self.readonly:
            return
        try:
            shutil.rmtree(self._entry_dir(key))
        except OSError as e:
            logger.warning(f"compile cache: could not remove invalid entry "
                           f"{key[:16]}: {e}")

    def _touch(self, path):
        """LRU recency marker (entry-dir mtime).  Readonly caches skip it."""
        if self.readonly:
            return
        try:
            os.utime(path, None)
        except OSError as e:
            logger.debug(f"compile cache: utime failed on {path}: {e}")

    def entries(self):
        """Committed entries as (key, bytes, mtime), oldest first."""
        out = []
        if not os.path.isdir(self.dir):
            return out
        for name in os.listdir(self.dir):
            full = os.path.join(self.dir, name)
            if not os.path.isdir(full) or \
                    name.endswith(atomic.STAGE_SUFFIX) or \
                    name.endswith(".replaced"):
                continue
            if not os.path.isfile(os.path.join(full, PAYLOAD_FILE)):
                continue
            try:
                size = os.path.getsize(os.path.join(full, PAYLOAD_FILE))
                mtime = os.path.getmtime(full)
            except OSError:
                continue     # entry vanished mid-scan (concurrent evict)
            out.append((name, size, mtime))
        out.sort(key=lambda t: t[2])
        return out

    def _evict_lru(self):
        if self.readonly or self.max_entries < 1:
            return
        ent = self.entries()
        excess = len(ent) - self.max_entries
        for key, _, _ in ent[:max(excess, 0)]:
            self.invalidate(key)
            logger.info(f"compile cache: evicted LRU entry {key[:16]} "
                        f"(max_entries={self.max_entries})")

    # ------------------------------------------------------------ accounting
    def _count(self, k, ms=None):
        self.stats[k] += 1 if ms is None else ms
        GLOBAL_STATS[k] += 1 if ms is None else ms

    def record_event(self, name, key, source, ms, payload_bytes=0):
        self.events.append({"name": name, "key": key[:16], "source": source,
                            "ms": round(ms, 1),
                            "payload_bytes": payload_bytes})
        del self.events[:-_MAX_EVENTS]
        self.write_last_run_stats()

    def write_last_run_stats(self):
        """Small JSON beside the entries so ``ds_report`` can show the
        last run's hit/miss counters without importing jax state."""
        if self.readonly:
            return
        try:
            atomic.atomic_write_text(
                os.path.join(self.dir, STATS_FILE),
                json.dumps({"pid": os.getpid(), "ts": time.time(),
                            "stats": self.stats,
                            "events": self.events[-16:]}, indent=2))
        except OSError as e:
            logger.debug(f"compile cache: stats write failed: {e}")

    def report(self):
        ent = self.entries()
        return {
            "enabled": True,
            "dir": self.dir,
            "readonly": self.readonly,
            "max_entries": self.max_entries,
            "entries": len(ent),
            "total_bytes": sum(s for _, s, _ in ent),
            **{k: (round(v, 1) if isinstance(v, float) else v)
               for k, v in self.stats.items()},
            "events": list(self.events),
        }


# ----------------------------------------------------------- the AOT wrapper
class CachedStep:
    """Dispatch wrapper for one jitted entry point.

    Call-compatible with the wrapped ``jax.jit`` function (including
    donation and tracing through ``jax.make_jaxpr``); exposes ``lower``
    for the auditor/profiler.  With a cache attached, the first call per
    argument signature lowers the function, resolves the content key, and
    either deserializes the stored executable (warm start) or compiles
    and stores it; subsequent calls dispatch straight into the compiled
    executable.  Without a cache it is a transparent passthrough.
    """

    def __init__(self, name, jit_fn, cache=None, key_extra=None,
                 donate_argnums=()):
        self.name = name
        self._jit = jit_fn
        self.cache = cache
        self.key_extra = key_extra or {}
        self.donate_argnums = tuple(donate_argnums)
        self._exes = {}        # args_signature -> (Compiled, key, source)

    # jax.jit API surface used elsewhere in the repo
    def lower(self, *args, **kwargs):
        return self._jit.lower(*args, **kwargs)

    def clear(self):
        """Drop live executables (frees their device programs)."""
        self._exes.clear()

    def live_executable(self, *args, **kwargs):
        """The already-acquired Compiled for these avals, or None.  Used
        by the auditor to check THE executable that is dispatching —
        including a deserialized (warm-started) one."""
        hit = self._exes.get(args_signature(args, kwargs))
        return hit[0] if hit else None

    def executable(self, *args, **kwargs):
        """Acquire (cache-or-compile) without calling.  Never consumes
        donated buffers.  Works with no cache attached (plain AOT
        compile) — the bench memory preflight path."""
        sig = args_signature(args, kwargs)
        hit = self._exes.get(sig)
        if hit is None:
            hit = self._acquire(args, kwargs, sig)
        return hit[0]

    def keys(self):
        """Content keys of every acquired signature (test hook)."""
        return [k for _, k, _ in self._exes.values()]

    def __call__(self, *args, **kwargs):
        if _being_traced(args, kwargs):
            # being traced (jax.make_jaxpr / an outer jit): stage the
            # underlying jit call, never the dispatch machinery
            return self._jit(*args, **kwargs)
        if self.cache is None and not self._exes:
            return self._jit(*args, **kwargs)
        hit = None
        if len(self._exes) == 1:
            # steady-state fast path: nearly every wrapper only ever sees
            # one signature, so skip the per-call pytree flatten + sig
            # build.  Safe optimistically: Compiled.call validates avals
            # BEFORE executing (donated buffers are not consumed on a
            # mismatch), so a new signature surfaces as TypeError and
            # falls through to the full acquire below.
            (hit,) = self._exes.values()
            try:
                return self._dispatch(hit, args, kwargs)
            except TypeError:
                hit = None
        sig = args_signature(args, kwargs)
        hit = self._exes.get(sig)
        if hit is None:
            if self.cache is None:
                return self._jit(*args, **kwargs)
            hit = self._acquire(args, kwargs, sig)
        return self._dispatch(hit, args, kwargs)

    def _dispatch(self, hit, args, kwargs):
        exe, _, source = hit
        if source == "cache" and self.donate_argnums and \
                jax.default_backend() == "cpu":
            # DESERIALIZED executables on this jaxlib donate
            # UNCONDITIONALLY (must-alias semantics), where normal jit
            # dispatch — and, measured, a freshly `lowered.compile()`d
            # Compiled — backs off to a copy when a zero-copy host view
            # of the buffer is alive (np.asarray of a CPU jax array is
            # such a view; without this the view mutates in place
            # mid-step, the exact corruption jax's own compilation cache
            # shows on this container, tests/conftest.py).  Restore
            # copy-on-donate semantics by donating a COPY on backends
            # with zero-copy host views; device-backed arrays (TPU) have
            # none, so real donation is preserved where the memory win
            # matters.
            args = list(args)
            for i in self.donate_argnums:
                if i < len(args):
                    args[i] = jax.tree_util.tree_map(
                        lambda l: (jnp.copy(l) if isinstance(l, jax.Array)
                                   else l), args[i])
            args = tuple(args)
        return exe(*args, **kwargs)

    # ----------------------------------------------------------- internals
    def _acquire(self, args, kwargs, sig):
        t0 = time.monotonic()
        lowered = self._jit.lower(*args, **kwargs)
        lower_ms = (time.monotonic() - t0) * 1000
        cache = self.cache
        material = None
        if cache is not None:
            cache._count("lower_ms", lower_ms)
            material = build_key_material(self.name, args, lowered,
                                          self.key_extra, kwargs=kwargs)
        if material is not None:
            key = key_from_material(material)
            exe = self._try_deserialize(cache, key)
            if exe is not None:
                hit = (exe, key, "cache")
                self._exes[sig] = hit
                return hit
        else:
            key = "<uncached>"
        t1 = time.monotonic()
        compiled = lowered.compile()
        compile_ms = (time.monotonic() - t1) * 1000
        if material is not None:
            cache._count("misses")
            cache._count("compile_ms", compile_ms)
            self._try_serialize(cache, key, compiled, material)
            cache.record_event(self.name, key, "compile", compile_ms)
        hit = (compiled, key, "compile")
        self._exes[sig] = hit
        return hit

    def _try_deserialize(self, cache, key):
        payload = cache.get(key)
        if payload is None:
            return None
        from jax.experimental import serialize_executable as se
        t0 = time.monotonic()
        try:
            ser, in_tree, out_tree = pickle.loads(payload)
            exe = se.deserialize_and_load(ser, in_tree, out_tree)
        except Exception as e:
            # unpicklable/incompatible payload (jaxlib drift the version
            # key missed, foreign-topology artifact): a miss, not a crash
            cache._count("corrupt")
            cache.invalidate(key)
            logger.warning(f"compile cache: could not deserialize entry "
                           f"{key[:16]} ({type(e).__name__}: {e}); "
                           "falling back to a fresh compile")
            return None
        ms = (time.monotonic() - t0) * 1000
        cache._count("hits")
        cache._count("deserialize_ms", ms)
        cache.record_event(self.name, key, "cache", ms, len(payload))
        log_dist(f"compile cache HIT {self.name} [{key[:12]}] "
                 f"({ms:.0f} ms deserialize)", ranks=[0])
        return exe

    def _try_serialize(self, cache, key, compiled, material):
        from jax.experimental import serialize_executable as se
        try:
            ser, in_tree, out_tree = se.serialize(compiled)
            payload = pickle.dumps((ser, in_tree, out_tree))
        except Exception as e:
            # e.g. a treedef holding a test-local class pickle refuses;
            # the executable still runs, it just is not persisted
            cache._count("put_errors")
            logger.warning(f"compile cache: could not serialize "
                           f"{self.name} ({type(e).__name__}: {e}); "
                           "entry not persisted")
            return
        cache.put(key, payload, meta=material)


def executable_memory_analysis(exe):
    """One shared reading of an executable's ``memory_analysis()`` for
    every preflight gate (train engine, serving, bench): byte-count dict
    with ``peak_bytes`` approximating execution-time live memory
    (arguments + outputs − donated aliases + temps + program), or None
    when the backend exposes no analysis.  Backend quirks (list-wrapped
    results, missing fields) are handled HERE so the gates cannot
    drift."""
    try:
        ma = exe.memory_analysis()
    except Exception as e:
        logger.warning(f"memory preflight unavailable: {e}")
        return None
    if isinstance(ma, (list, tuple)):
        ma = ma[0] if ma else None
    if ma is None:
        return None
    g = lambda k: int(getattr(ma, k, 0) or 0)
    out = {
        "argument_bytes": g("argument_size_in_bytes"),
        "output_bytes": g("output_size_in_bytes"),
        "temp_bytes": g("temp_size_in_bytes"),
        "alias_bytes": g("alias_size_in_bytes"),
        "generated_code_bytes": g("generated_code_size_in_bytes"),
    }
    out["peak_bytes"] = (out["argument_bytes"] + out["output_bytes"]
                         - out["alias_bytes"] + out["temp_bytes"]
                         + out["generated_code_bytes"])
    return out


def wrap_step(name, fn, cache=None, key_extra=None, donate_argnums=()):
    """jit + CachedStep in one place — the factory every engine's
    ``_wrap_step`` delegates to, so dispatch-policy changes land once."""
    return CachedStep(name, jax.jit(fn, donate_argnums=donate_argnums),
                      cache=cache, key_extra=key_extra,
                      donate_argnums=donate_argnums)


def report(cache):
    """Engine-facing compile report: the cache's report, or the disabled
    marker when no cache is attached."""
    if cache is None:
        return {"enabled": False}
    return cache.report()


# ------------------------------------------------------------- construction
def from_config(cfg):
    """Build the engine's CompileCache from its parsed ``compile_cache``
    config block (None when disabled / no directory resolved)."""
    if cfg is None or not cfg.enabled or not cfg.dir:
        return None
    return CompileCache(cfg.dir, max_entries=cfg.max_entries,
                        readonly=cfg.readonly)


def from_dir(dir=None, max_entries=0, readonly=False):
    """Cache from an explicit dir, or the env default (None if neither)."""
    if env_disabled():
        return None
    dir = dir or resolve_env_dir()
    if not dir:
        return None
    return CompileCache(dir, max_entries=max_entries, readonly=readonly)


def disk_report(dir=None):
    """What ``ds_report`` prints: entry count, bytes, last-run counters.
    Read-only — safe on a cache owned by another (live) process."""
    dir = dir or resolve_env_dir()
    if not dir:
        return {"configured": False}
    out = {"configured": True, "dir": dir, "exists": os.path.isdir(dir)}
    if not out["exists"]:
        return out
    n, total = 0, 0
    for name in os.listdir(dir):
        if name.endswith(atomic.STAGE_SUFFIX) or name.endswith(".replaced"):
            continue     # in-flight/stale staging is not a committed entry
        payload = os.path.join(dir, name, PAYLOAD_FILE)
        if os.path.isfile(payload):
            n += 1
            try:
                total += os.path.getsize(payload)
            except OSError:  # dstpu: disable=DSTPU002
                pass  # entry evicted mid-scan; the count stays best-effort
    out["entries"] = n
    out["total_bytes"] = total
    try:
        with open(os.path.join(dir, STATS_FILE)) as f:
            out["last_run"] = json.load(f)
    except (OSError, ValueError):
        out["last_run"] = None
    return out
