"""MoQ: Mixed-precision quantize-aware training.

Parity: reference ``deepspeed/runtime/quantize.py:12`` (``Quantizer``):
weights are progressively quantized during training — starting at
``q_start_bits`` and dropping one bit every ``q_period`` steps (the period
doubling each drop) until ``q_target_bits``; groupwise symmetric or
asymmetric quantize→dequantize; optional stochastic rounding; optional
fp16-mixing ramp (``mixed_fp16_quantize`` :123); eigenvalue-paced periods
(``factor = 1 + floor(λ·4)`` :78).

TPU re-design: the schedule/bookkeeping stays host-side (it changes every
few hundred steps), while the quantize-dequantize math is the jitted
groupwise kernel from ``ops/quantizer`` (Pallas/XLA) applied to the whole
pytree.  2-D+ parameters only, like the reference (:75 ``len(p.size())>1``).
"""

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..ops.quantizer.quantizer import quantize as q_op, dequantize as dq_op
from ..utils.logging import logger

TWO_D_PARAMS = 6  # ≈ 2-D params per transformer layer (reference quantize.py:9)


class Quantizer:
    def __init__(self, q_target_bits=8, q_start_bits=16, q_period=100,
                 q_offset=100, q_groups=1, q_mixed_fp16=False,
                 q_change_ratio=0.01, q_type=0, q_rounding=0, q_verbose=False,
                 q_eigenvalue=False, use_quantizer_kernel=False, layer_num=0):
        self.q_target_bits = q_target_bits
        n = layer_num if layer_num != 0 else 1
        self.q_start_bits = [q_start_bits] * n
        self.q_period = [q_period] * n
        self.q_offset = q_offset
        self.q_groups = q_groups
        self.q_mixed_fp16 = q_mixed_fp16
        self.q_change_ratio = q_change_ratio
        self.q_type = q_type          # 0 = symmetric, 1 = asymmetric
        self.q_rounding = q_rounding  # 0 = nearest, 1 = stochastic
        self.qsteps = 0
        self.q_init_period = q_period
        self.quantize_real_ratio = 1.0
        self.q_verbose = q_verbose
        self.q_eigenvalue = q_eigenvalue
        self.use_quantizer_kernel = use_quantizer_kernel
        self.layer_num = layer_num
        self._rng = jax.random.PRNGKey(17)

    # ----------------------------------------------------------- scheduling
    def any_precision_switch(self):
        """Parity: reference :46 — would the next quantize() change bits?"""
        if self.layer_num == 0:
            return True
        for index in range(self.layer_num):
            if self.q_start_bits[index] != self.q_target_bits:
                next_step = self.qsteps + TWO_D_PARAMS * self.layer_num
                if next_step >= self.q_period[index]:
                    return True
        return False

    def step(self):
        self.qsteps += TWO_D_PARAMS * (self.layer_num if self.layer_num != 0 else 1)

    def update_fp16_ratio(self):
        if self.q_mixed_fp16 and self.quantize_real_ratio > 0:
            self.quantize_real_ratio -= self.q_change_ratio
            self.quantize_real_ratio = max(0.0, self.quantize_real_ratio)

    # -------------------------------------------------------------- compute
    def _maybe_advance_bits(self, index, factor):
        if self.q_offset > 0:
            if self.qsteps >= self.q_offset:
                self.q_offset = 0
                self.qsteps = 0
            else:
                return False  # still in offset warmup: no quantization
        if self.q_start_bits[index] != self.q_target_bits:
            if self.qsteps >= self.q_period[index]:
                self.quantize_real_ratio = 1.0
                if self.q_eigenvalue:
                    self.q_period[index] <<= 1
                    self.q_period[index] *= factor
                    self.q_start_bits[index] -= 1
                else:
                    for i in range(len(self.q_start_bits)):
                        self.q_start_bits[i] -= 1
                        self.q_period[i] <<= 1
                if self.q_verbose:
                    logger.info(
                        f"Quantization settings: current bit-precision = "
                        f"{self.q_start_bits[index]}, step = {self.qsteps}, "
                        f"quantization period = {self.q_period[index]}, "
                        f"index = {index}")
        assert self.q_start_bits[index] >= self.q_target_bits, \
            "Quantization bit is lower than target precision bits!"
        return True

    def compute_quantization(self, x, index=0, factor=1):
        """Quantize→dequantize one tensor at the current bit width."""
        if not self._maybe_advance_bits(index, factor):
            return x
        bits = self.q_start_bits[index]
        self._rng, sub = jax.random.split(self._rng)
        q, scale, zero = q_op(jnp.asarray(x), groups=self.q_groups, bits=bits,
                              symmetric=(self.q_type == 0),
                              stochastic=(self.q_rounding == 1), rng=sub)
        xq = dq_op(q, scale, zero, groups=self.q_groups).reshape(np.shape(x)) \
            .astype(x.dtype)
        return self.mixed_fp16_quantize(x, xq, index)

    def mixed_fp16_quantize(self, x, x_q, index):
        """Ramp between full-precision and quantized (reference :123)."""
        if self.q_mixed_fp16 and self.q_start_bits[index] >= self.q_target_bits - 1:
            return x * self.quantize_real_ratio + \
                (1 - self.quantize_real_ratio) * x_q
        return x_q

    def quantize(self, params, overflow=False, eigenvalue_enabled=False,
                 block_eigenvalue=None):
        """Quantize all ≥2-D leaves of ``params`` in place of the reference's
        parameter-group walk (:60-82).  ``block_eigenvalue``: per-layer λ in
        [0,1] (see :class:`~deepspeed_tpu.runtime.eigenvalue.Eigenvalue`) —
        stacked block leaves (leading layer axis) use their layer's λ-scaled
        factor.  Returns the quantized pytree.
        """
        if overflow and not eigenvalue_enabled:
            return params
        self.step()
        self.update_fp16_ratio()

        def one(path, p):
            if not hasattr(p, "ndim") or p.ndim <= 1:
                return p
            index, factor = 0, 1
            if block_eigenvalue:
                lam = block_eigenvalue[0] if len(block_eigenvalue) else None
                if lam is not None:
                    factor = 1 + math.floor(lam * 4)
            return self.compute_quantization(p, index, factor)

        return jax.tree_util.tree_map_with_path(one, params)
