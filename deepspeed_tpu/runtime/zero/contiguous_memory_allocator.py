"""Contiguous memory allocator with defragmentation.

Parity: reference ``runtime/zero/contiguous_memory_allocator.py`` (283 LoC):
a flat pre-allocated buffer handing out tensor-sized sub-views, with
``release`` + assignment tracking and a compaction pass (``defragment``)
that migrates live tensors to the front so large requests never fail from
fragmentation.

TPU placement note: device HBM is managed by XLA (arena allocation inside
compiled programs — the reference's device-side fragmentation problem does
not exist under jit).  This allocator manages HOST arenas: the offload
tier's pinned staging buffers and NVMe swap pools, which have exactly the
reference's lifetime/fragmentation pattern (many differently-sized
sub-buffers with interleaved release).
"""

import numpy as np

from ...utils.logging import logger


class ContiguousMemoryAllocator:
    def __init__(self, size, dtype=np.float32, name="host_arena"):
        self.buffer = np.zeros(size, dtype)
        self.size = size
        self.name = name

        # address → numel of free/allocated blocks (reference keeps the same
        # two maps plus tensor-id indirection so defrag can move live views)
        self.contiguous_sizes = {0: size}          # free blocks
        self.tensor_addresses = {}                  # tensor_id → address
        self.tensor_sizes = {}                      # tensor_id → numel
        self.tensor_map = {}                        # tensor_id → ndarray view
        self.total_free = size
        self._next_id = 0

    # ---------------------------------------------------------------- alloc
    def allocate_tensor(self, numel):
        """A view of ``numel`` elements; defragments when no single free
        block fits but the total free space does (reference behavior)."""
        assert numel <= self.total_free, \
            f"{self.name}: requested {numel} > free {self.total_free}"
        if self._largest_free() < numel:
            logger.info(f"{self.name}: defragmenting "
                        f"(free={self.total_free}, need={numel})")
            self.defragment()
        addr = self._find_block(numel)
        assert addr is not None
        self._carve(addr, numel)
        tid = self._next_id
        self._next_id += 1
        view = self.buffer[addr:addr + numel]
        self.tensor_addresses[tid] = addr
        self.tensor_sizes[tid] = numel
        self.tensor_map[tid] = view
        self.total_free -= numel
        return tid, view

    def release_tensor(self, tid):
        addr = self.tensor_addresses.pop(tid)
        numel = self.tensor_sizes.pop(tid)
        self.tensor_map.pop(tid)
        self.total_free += numel
        self._free(addr, numel)

    def get_tensor(self, tid):
        return self.tensor_map[tid]

    # ------------------------------------------------------------- defrag
    def defragment(self):
        """Compact live tensors to the front (copies preserve contents; the
        returned views are refreshed in ``tensor_map``)."""
        order = sorted(self.tensor_addresses.items(), key=lambda kv: kv[1])
        cursor = 0
        for tid, addr in order:
            numel = self.tensor_sizes[tid]
            if addr != cursor:
                # memmove-safe: destination is always left of source
                self.buffer[cursor:cursor + numel] = self.buffer[addr:addr + numel]
                self.tensor_addresses[tid] = cursor
                self.tensor_map[tid] = self.buffer[cursor:cursor + numel]
            cursor += numel
        self.contiguous_sizes = ({cursor: self.size - cursor}
                                 if cursor < self.size else {})

    # ------------------------------------------------------------- helpers
    def _largest_free(self):
        return max(self.contiguous_sizes.values(), default=0)

    def _find_block(self, numel):
        for addr in sorted(self.contiguous_sizes):
            if self.contiguous_sizes[addr] >= numel:
                return addr
        return None

    def _carve(self, addr, numel):
        block = self.contiguous_sizes.pop(addr)
        if block > numel:
            self.contiguous_sizes[addr + numel] = block - numel

    def _free(self, addr, numel):
        self.contiguous_sizes[addr] = numel
        # merge adjacent free blocks
        merged = {}
        for a in sorted(self.contiguous_sizes):
            n = self.contiguous_sizes[a]
            if merged:
                last = max(merged)
                if last + merged[last] == a:
                    merged[last] += n
                    continue
            merged[a] = n
        self.contiguous_sizes = merged

    def print_allocation(self, resolution=200):
        """ASCII map of the arena (reference debugging helper)."""
        cell = max(1, self.size // resolution)
        marks = ["."] * (self.size // cell + 1)
        for tid, addr in self.tensor_addresses.items():
            for i in range(addr // cell,
                           (addr + self.tensor_sizes[tid]) // cell + 1):
                if i < len(marks):
                    marks[i] = "x"
        line = "".join(marks)
        logger.info(f"{self.name}: [{line}] free={self.total_free}/{self.size}")
        return line
