"""Chunked host<->device wire for the offload tiers.

Parity role: the reference moves offload traffic through pinned CUDA
buffers with async copies overlapping compute
(``zero/stage_1_and_2.py:1008-1160`` pinned d2h grad buckets;
``swap_tensor/partitioned_param_swapper.py`` pinned swap buffers).  The
TPU-runtime analogue: one monolithic transfer serializes on a single
stream, while splitting the flat payload into ~64 MB chunks and issuing
every chunk's ``copy_to_host_async`` / ``device_put`` before consuming
any pipelines the transport (measured ~8x d2h on the shared dev tunnel;
on real PCIe the chunking is free and preserves overlap with compute).

All offload wire traffic (grad d2h, param h2d, streamed layer blocks)
goes through these helpers so the chunking policy lives in one place.
"""

import numpy as np
import jax

# 64 MB: large enough to amortize per-transfer dispatch, small enough to
# pipeline (and to bound the staging copy used to avoid mutate-in-flight
# races on the h2d payload)
DEFAULT_CHUNK_BYTES = 64 << 20


def _chunk_bounds(n, itemsize, chunk_bytes):
    per = max(1, chunk_bytes // max(1, itemsize))
    bounds = list(range(0, n, per)) + [n]
    return [(bounds[i], bounds[i + 1]) for i in range(len(bounds) - 1)]


def d2h_flat_start(dev_flat, *, chunk_bytes=DEFAULT_CHUNK_BYTES):
    """Slice a flat device array into chunks and start EVERY chunk's async
    device-to-host copy.  Returns the (spans, parts) handle for
    :func:`d2h_flat_land`.  Starting all transfers before consuming any
    pipelines the transport; starting them right after the grad step is
    dispatched overlaps them with host work (DPU)."""
    n = int(dev_flat.shape[0])
    spans = _chunk_bounds(n, dev_flat.dtype.itemsize, chunk_bytes)
    parts = ([dev_flat] if len(spans) <= 1
             else [dev_flat[a:b] for a, b in spans])
    for p in parts:
        if hasattr(p, "copy_to_host_async"):
            p.copy_to_host_async()
    return spans, parts


def d2h_flat_land(handle, host_out):
    """Land started chunks into a preallocated host buffer (upcasts on
    copy: fp32 landing buffer for 16-bit grads, into pre-faulted memory)."""
    spans, parts = handle
    for (a, b), p in zip(spans, parts):
        host_out[a:b] = np.asarray(p)
    return host_out


def d2h_flat_into(dev_flat, host_out, *, chunk_bytes=DEFAULT_CHUNK_BYTES):
    """Start + land in one call (non-overlapped path)."""
    assert host_out.shape[0] == int(dev_flat.shape[0]), \
        (host_out.shape, dev_flat.shape)
    return d2h_flat_land(d2h_flat_start(dev_flat, chunk_bytes=chunk_bytes),
                         host_out)


def d2h_tree_start(tree):
    """Begin async d2h for every leaf of a pytree (non-blocking)."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "copy_to_host_async"):
            leaf.copy_to_host_async()


def gather_span(parts, per, start, end):
    """Concatenate the flat range ``[start, end)`` out of equally-sized
    chunks (``per`` elements each, last chunk may be short) — no
    full-size concatenate of the whole buffer."""
    import jax.numpy as jnp
    pieces = []
    s = start
    while s < end:
        c = s // per
        base = c * per
        e = min(end, base + int(parts[c].shape[0]))
        pieces.append(parts[c][s - base:e - base])
        s = e
    return pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)


def make_chunk_scatter(shapes, treedef, per, nchunks, *, out_shardings=None):
    """Build the jitted chunks→pytree scatter shared by every h2d upload
    path: each leaf is sliced straight out of the chunk(s) covering it —
    no full-size concatenate (that would double peak HBM) and per-chunk
    donation stays usable (XLA reuses chunk memory for the leaf outputs).

    ``shapes``: leaf shapes in treedef order (leaves tile the flat buffer
    contiguously); ``per``: elements per chunk (all chunks but the last).
    """

    def scatter(*parts):
        leaves = []
        o = 0
        for s in shapes:
            n = int(np.prod(s or (1,)))
            leaves.append(gather_span(parts, per, o, o + n).reshape(s))
            o += n
        return jax.tree_util.tree_unflatten(treedef, leaves)

    kw = {"out_shardings": out_shardings} if out_shardings is not None else {}
    return jax.jit(scatter, donate_argnums=tuple(range(nchunks)), **kw)


def make_quantized_chunk_scatter(shapes, treedef, plan, per_q, nq,
                                 per_fw, nfw, *, bits, block,
                                 out_dtype):
    """Chunks→pytree scatter for the QUANTIZED layer wire
    (docs/comms-compression.md, the ``param_stream`` route): quantized
    leaves are sliced out of the int8 image chunks and dequantized
    per-leaf on device; excluded/full-width leaves come from the
    (possibly empty) full-width image.

    ``plan``: per-leaf ``("q", q_off, n, npad)`` or ``("fw", fw_off, n)``
    entries in treedef order — offsets in ELEMENTS of the respective
    image (quantized leaves are block-aligned so each leaf owns whole
    scale blocks; the int4 image packs two elements per byte).
    Call: ``scatter(scales, *q_chunks, *fw_chunks)`` (chunks donated).
    """
    from ..comm.quantized import dequantize_flat_jnp
    pack = 2 if bits == 4 else 1

    def scatter(scales, *parts):
        q_parts, fw_parts = parts[:nq], parts[nq:]
        leaves = []
        for entry, shape in zip(plan, shapes):
            if entry[0] == "fw":
                _, off, n = entry
                flat = gather_span(fw_parts, per_fw, off, off + n)
            else:
                _, off, n, npad = entry
                qflat = gather_span(q_parts, per_q, off // pack,
                                    (off + npad) // pack)
                sc = scales[off // block:(off + npad) // block]
                flat = dequantize_flat_jnp(qflat, sc, bits=bits,
                                           out_dtype=out_dtype)[:n]
            leaves.append(flat.reshape(shape))
        return jax.tree_util.tree_unflatten(treedef, leaves)

    return jax.jit(scatter,
                   donate_argnums=tuple(range(1, 1 + nq + nfw)))


class H2DUploader:
    """Chunked host->device upload with an optional staging copy.

    ``upload_flat`` returns a list of device chunks covering the host
    array.  With ``stage=True`` each chunk is copied into a reusable
    staging buffer before ``device_put`` so the caller may mutate the
    source immediately (the delayed-param-update overlap mutates the
    16-bit payload while the previous upload may still be in flight —
    the staging copy is the pinned-buffer double-buffering the reference
    gets from its CUDA pinned pool).  Staging buffers are recycled only
    after the transfer they feed is committed.
    """

    def __init__(self, chunk_bytes=DEFAULT_CHUNK_BYTES):
        self.chunk_bytes = chunk_bytes
        self._staging = []        # reusable host buffers
        # fresh: chunk pairs from upload_flat awaiting their settle_on
        # (their arrays are donated into the consuming scatter, so they
        # MUST re-key to its output).  settled: pairs keyed to a settle
        # target; once THAT is deleted downstream they are parked until
        # release_parked() — a later settle_on must NOT re-key them (it
        # would hide their deletion and defeat the recycling barrier).
        # Every pair carries the DISPATCH EPOCH of its upload_flat call:
        # release_parked(epoch) recycles only pairs dispatched at or
        # before the caller's proven barrier, so an upload dispatched
        # AFTER the barrier value was computed (prefetch racing the
        # throttle read) can never have its staging buffer reused while
        # its h2d DMA may still be reading it.
        self._fresh = []          # (device_array, staging_buf, epoch)
        self._settled = []        # (settle_target, staging_buf, epoch)
        self._epoch = 0           # bumped once per upload_flat call

    def _get_staging(self, nbytes):
        for i, buf in enumerate(self._staging):
            if buf.nbytes >= nbytes:
                return self._staging.pop(i)
        return np.empty(nbytes, np.uint8)

    @property
    def dispatch_epoch(self):
        """Epoch of the latest ``upload_flat`` dispatch.  Capture this
        BEFORE dispatching compute whose later value-read will serve as
        the completion barrier, and hand it to :meth:`release_parked` —
        uploads dispatched after the capture are excluded."""
        return self._epoch

    def _reclaim(self, block=False):
        def sweep(pairs):
            still = []
            for arr, buf, epoch in pairs:
                # is_deleted (e.g. donated downstream) does NOT mean the
                # h2d DMA finished reading the staging buffer — donation
                # marks deletion at dispatch.  Only an observed is_ready()
                # proves the transfer landed.  A deleted-but-never-
                # observed-ready pair stays PARKED (buffer referenced)
                # until release_parked() at a caller-proven barrier.
                deleted = arr.is_deleted()
                done = (not deleted) and arr.is_ready()
                if block and not done and not deleted:
                    arr.block_until_ready()
                    done = True
                if done:
                    if buf is not None:
                        self._staging.append(buf)
                else:
                    still.append((arr, buf, epoch))
            return still
        self._settled = sweep(self._settled)
        self._fresh = sweep(self._fresh)

    def upload_flat(self, host_flat, *, device=None, stage=False,
                    chunk_bytes=None):
        """host flat array -> list of device chunk arrays (async).
        ``chunk_bytes`` overrides the uploader default for payloads with
        alignment needs (the quantized layer wire keeps chunks on scale-
        block boundaries so each chunk dequantizes independently)."""
        host_flat = host_flat.reshape(-1)
        spans = _chunk_bounds(host_flat.shape[0], host_flat.dtype.itemsize,
                              chunk_bytes or self.chunk_bytes)
        self._reclaim()
        self._epoch += 1
        out = []
        for a, b in spans:
            src = host_flat[a:b]
            buf = None
            if stage:
                buf = self._get_staging(src.nbytes)
                view = buf[:src.nbytes].view(host_flat.dtype)
                np.copyto(view, src)
                src = view
            arr = (jax.device_put(src, device) if device is not None
                   else jax.device_put(src))
            out.append(arr)
            self._fresh.append((arr, buf, self._epoch))
        return out

    def settle_on(self, arr):
        """Re-key the FRESH (just-uploaded, donated-into-the-scatter)
        chunk pairs onto ``arr`` — a downstream array whose readiness
        implies their DMAs completed (the compute that overwrites a
        donated chunk cannot run before its h2d transfer lands).
        Already-settled pairs are NOT re-keyed: once their own target is
        deleted downstream they are parked, and re-keying them onto ever-
        newer targets would hide the deletion and defeat
        :meth:`release_parked` (the r5 6.7B probe leaked a staging buffer
        per layer fetch exactly this way)."""
        self._settled += [(arr, buf, epoch) for _, buf, epoch in self._fresh]
        self._fresh = []

    def release_parked(self, epoch=None):
        """Recycle parked pairs after the CALLER has executed a true
        completion barrier (a VALUE READ of a downstream result — on
        remote-attached runtimes ``is_ready``/``block_until_ready`` may
        never observe donated-then-deleted settle targets).

        ``epoch`` scopes the barrier's proof: only pairs whose upload was
        dispatched at or before that :attr:`dispatch_epoch` capture are
        eligible.  A pair dispatched AFTER the barrier value was computed
        (the next layer's prefetch races the throttle read) can be
        settled-and-deleted — its scatter was dispatched and donated its
        chunks — while its h2d DMA has not provably read the staging
        buffer yet; recycling it would hand a buffer still on the wire to
        the next upload.  ``epoch=None`` keeps the legacy behavior
        (recycle every deleted pair) for callers whose barrier, by
        construction, postdates every dispatch (e.g. final-step flush)."""
        def eligible(pair_epoch):
            return epoch is None or pair_epoch <= epoch
        for arr, buf, pair_epoch in self._settled:
            if eligible(pair_epoch) and arr.is_deleted() \
                    and buf is not None:
                self._staging.append(buf)
        self._settled = [(a, b, e) for a, b, e in self._settled
                         if not (eligible(e) and a.is_deleted())]

    def wait(self):
        self._reclaim(block=True)

    def close(self):
        """Engine shutdown: drop every staging buffer and tracked pair.
        The r5 bench ladder leaked these across configs (`del engine`
        does not free buffers still referenced here) until later rungs
        died RESOURCE_EXHAUSTED."""
        self._fresh = []
        self._settled = []
        self._staging = []
