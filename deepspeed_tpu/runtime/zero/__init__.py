"""deepspeed.zero namespace (reference ``deepspeed/runtime/zero/__init__.py``):
``Init``, ``GatheredParameters``, ``TiledLinear``, stage configs and the
sharding-placement rules that replace the reference's hook machinery."""

from .config import DeepSpeedZeroConfig
from .init_context import (Init, GatheredParameters,
                           register_external_parameter,
                           unregister_external_parameter)
from .tiling import TiledLinear, TiledLinearReturnBias
from . import partition
