"""ZeRO configuration.

Parity: reference ``deepspeed/runtime/zero/config.py:14`` (``DeepSpeedZeroConfig``)
and ``zero/offload_config.py``.  Same JSON keys; TPU semantics documented per field.

On TPU, ZeRO stages map to sharding placement over the ``fsdp`` mesh axis
(SURVEY.md §7): stage 1 shards optimizer state, stage 2 additionally
reduce-scatters gradients, stage 3 additionally shards parameters.  Bucket-size
knobs are accepted for config compatibility; XLA's SPMD partitioner performs
its own collective scheduling, so they inform (but do not dictate) chunking.
"""

from ..config_utils import get_scalar_param, get_dict_param

ZERO_FORMAT = """
ZeRO optimization should be enabled as:
"zero_optimization": {
  "stage": [0|1|2|3],
  "overlap_comm": [true|false],
  "reduce_scatter": [true|false],
  "reduce_bucket_size": 500000000,
  "allgather_bucket_size": 500000000,
  "offload_param": {...},
  "offload_optimizer": {...},
  ...
}
"""

ZERO_OPTIMIZATION = "zero_optimization"

ZERO_OPTIMIZATION_DISABLED = 0
ZERO_OPTIMIZATION_OPTIMIZER_STATES = 1
ZERO_OPTIMIZATION_GRADIENTS = 2
ZERO_OPTIMIZATION_WEIGHTS = 3
MAX_STAGE_ZERO_OPTIMIZATION = ZERO_OPTIMIZATION_WEIGHTS

# Offload devices
OFFLOAD_DEVICE_NONE = "none"
OFFLOAD_DEVICE_CPU = "cpu"
OFFLOAD_DEVICE_NVME = "nvme"


class DeepSpeedZeroOffloadParamConfig:
    """``zero_optimization.offload_param`` — reference ``zero/offload_config.py``."""

    def __init__(self, param_dict=None):
        param_dict = param_dict or {}
        self.device = get_scalar_param(param_dict, "device", OFFLOAD_DEVICE_NONE)
        self.nvme_path = get_scalar_param(param_dict, "nvme_path", None)
        self.buffer_count = get_scalar_param(param_dict, "buffer_count", 5)
        self.buffer_size = int(get_scalar_param(param_dict, "buffer_size", 1e8))
        self.max_in_cpu = int(get_scalar_param(param_dict, "max_in_cpu", 1e9))
        self.pin_memory = get_scalar_param(param_dict, "pin_memory", False)
        # host-side numpy init for the streamed tier (reference:
        # offload fast_init): skips the jitted XLA-CPU init, which at
        # multi-billion params costs minutes and ~3x the tree in RAM.
        # Values come from the model's numpy init twin, so runs are NOT
        # bit-identical to the jitted init — off by default.
        self.fast_init = get_scalar_param(param_dict, "fast_init", False)

    def repr_dict(self):
        return dict(device=self.device, nvme_path=self.nvme_path,
                    buffer_count=self.buffer_count, buffer_size=self.buffer_size,
                    max_in_cpu=self.max_in_cpu, pin_memory=self.pin_memory,
                    fast_init=self.fast_init)


class DeepSpeedZeroOffloadOptimizerConfig:
    """``zero_optimization.offload_optimizer`` — reference ``zero/offload_config.py``."""

    def __init__(self, param_dict=None):
        param_dict = param_dict or {}
        self.device = get_scalar_param(param_dict, "device", OFFLOAD_DEVICE_NONE)
        self.nvme_path = get_scalar_param(param_dict, "nvme_path", None)
        self.buffer_count = get_scalar_param(param_dict, "buffer_count", 4)
        self.pin_memory = get_scalar_param(param_dict, "pin_memory", False)
        self.pipeline_read = get_scalar_param(param_dict, "pipeline_read", False)
        self.pipeline_write = get_scalar_param(param_dict, "pipeline_write", False)
        self.fast_init = get_scalar_param(param_dict, "fast_init", False)
        # One-step delayed parameter update (the ZeRO-Offload paper's DPU;
        # the reference's "communication overlap centric design",
        # docs/_posts/2021-03-08-zero3-offload.md:72): the device computes
        # step k+1's gradients with step k's parameters while the host runs
        # step k's optimizer and uploads — hiding the full d2h/step/h2d
        # latency behind device compute at the cost of one-step-stale
        # parameters after the warmup window.
        self.delayed_param_update = get_scalar_param(
            param_dict, "delayed_param_update", False)
        self.delayed_param_update_warmup = int(get_scalar_param(
            param_dict, "delayed_param_update_warmup", 20))

    @property
    def pipeline(self):
        return self.pipeline_read or self.pipeline_write

    def repr_dict(self):
        return dict(device=self.device, nvme_path=self.nvme_path,
                    buffer_count=self.buffer_count, pin_memory=self.pin_memory,
                    pipeline_read=self.pipeline_read, pipeline_write=self.pipeline_write,
                    fast_init=self.fast_init,
                    delayed_param_update=self.delayed_param_update,
                    delayed_param_update_warmup=self.delayed_param_update_warmup)


class DeepSpeedZeroConfig:
    """Parsed ``zero_optimization`` section.

    Field inventory mirrors reference ``zero/config.py:18-42``.
    """

    def __init__(self, param_dict=None):
        if param_dict is None:
            param_dict = {}
        zero_dict = param_dict.get(ZERO_OPTIMIZATION, {})
        if isinstance(zero_dict, bool):
            # legacy: "zero_optimization": true meant stage 1
            zero_dict = {"stage": 1 if zero_dict else 0}

        self.stage = get_scalar_param(zero_dict, "stage", 0)
        if self.stage not in (0, 1, 2, 3):
            raise ValueError(f"Invalid ZeRO stage {self.stage}. {ZERO_FORMAT}")
        self.contiguous_gradients = get_scalar_param(zero_dict, "contiguous_gradients", True)
        self.reduce_scatter = get_scalar_param(zero_dict, "reduce_scatter", True)
        self.reduce_bucket_size = int(get_scalar_param(zero_dict, "reduce_bucket_size", 5e8))
        self.allgather_partitions = get_scalar_param(zero_dict, "allgather_partitions", True)
        self.allgather_bucket_size = int(get_scalar_param(zero_dict, "allgather_bucket_size", 5e8))
        self.overlap_comm = get_scalar_param(
            zero_dict, "overlap_comm", True if self.stage == 3 else False)
        self.load_from_fp32_weights = get_scalar_param(zero_dict, "load_from_fp32_weights", True)
        self.elastic_checkpoint = get_scalar_param(zero_dict, "elastic_checkpoint", False)
        self.cpu_offload = get_scalar_param(zero_dict, "cpu_offload", False)
        self.cpu_offload_params = get_scalar_param(zero_dict, "cpu_offload_params", False)

        offload_param_dict = get_dict_param(zero_dict, "offload_param", None)
        self.offload_param = (DeepSpeedZeroOffloadParamConfig(offload_param_dict)
                              if offload_param_dict is not None else None)
        offload_opt_dict = get_dict_param(zero_dict, "offload_optimizer", None)
        if offload_opt_dict is None and self.cpu_offload:
            offload_opt_dict = {"device": OFFLOAD_DEVICE_CPU}
        self.offload_optimizer = (DeepSpeedZeroOffloadOptimizerConfig(offload_opt_dict)
                                  if offload_opt_dict is not None else None)

        self.sub_group_size = int(get_scalar_param(zero_dict, "sub_group_size", 1e9))
        self.prefetch_bucket_size = int(get_scalar_param(
            zero_dict, "stage3_prefetch_bucket_size",
            get_scalar_param(zero_dict, "prefetch_bucket_size", 5e7)))
        self.param_persistence_threshold = int(get_scalar_param(
            zero_dict, "stage3_param_persistence_threshold",
            get_scalar_param(zero_dict, "param_persistence_threshold", 1e5)))
        self.max_live_parameters = int(get_scalar_param(
            zero_dict, "stage3_max_live_parameters",
            get_scalar_param(zero_dict, "max_live_parameters", 1e9)))
        self.max_reuse_distance = int(get_scalar_param(
            zero_dict, "stage3_max_reuse_distance",
            get_scalar_param(zero_dict, "max_reuse_distance", 1e9)))
        self.gather_16bit_weights_on_model_save = get_scalar_param(
            zero_dict, "stage3_gather_16bit_weights_on_model_save",
            get_scalar_param(zero_dict, "gather_16bit_weights_on_model_save", False))
        self.ignore_unused_parameters = get_scalar_param(
            zero_dict, "ignore_unused_parameters", True)
        self.round_robin_gradients = get_scalar_param(zero_dict, "round_robin_gradients", False)
        self.legacy_stage1 = get_scalar_param(zero_dict, "legacy_stage1", False)

    def offload_optimizer_device(self):
        return self.offload_optimizer.device if self.offload_optimizer else OFFLOAD_DEVICE_NONE

    def offload_param_device(self):
        return self.offload_param.device if self.offload_param else OFFLOAD_DEVICE_NONE

    def repr_dict(self):
        d = dict(stage=self.stage,
                 contiguous_gradients=self.contiguous_gradients,
                 reduce_scatter=self.reduce_scatter,
                 reduce_bucket_size=self.reduce_bucket_size,
                 allgather_partitions=self.allgather_partitions,
                 allgather_bucket_size=self.allgather_bucket_size,
                 overlap_comm=self.overlap_comm,
                 load_from_fp32_weights=self.load_from_fp32_weights,
                 elastic_checkpoint=self.elastic_checkpoint,
                 sub_group_size=self.sub_group_size,
                 prefetch_bucket_size=self.prefetch_bucket_size,
                 param_persistence_threshold=self.param_persistence_threshold,
                 max_live_parameters=self.max_live_parameters,
                 max_reuse_distance=self.max_reuse_distance,
                 gather_16bit_weights_on_model_save=self.gather_16bit_weights_on_model_save,
                 ignore_unused_parameters=self.ignore_unused_parameters,
                 round_robin_gradients=self.round_robin_gradients)
        d["offload_param"] = self.offload_param.repr_dict() if self.offload_param else None
        d["offload_optimizer"] = (self.offload_optimizer.repr_dict()
                                  if self.offload_optimizer else None)
        return d

    def __repr__(self):
        return f"DeepSpeedZeroConfig({self.repr_dict()})"
