"""ZeRO-Offload / ZeRO-Infinity host optimizer tier.

Parity: reference stage-1/2 ``cpu_offload`` path
(``zero/stage_1_and_2.py:1008-1160``: fp32 master partition + Adam state on
host, ``DeepSpeedCPUAdam.step(fp16_param_groups=...)`` with fused copy-back)
and the stage-3 NVMe tier (``stage3.py:2339`` per-sub-group swap-in → step →
swap-out over ``swap_tensor/``).

TPU-native shape: the device step computes fp32 gradients (sharded, clipped,
unscaled); this object owns the flat fp32 master + Adam moments on the HOST,
runs the native fused step (``csrc/adam/ds_cpu_adam.cpp``) sub-group by
sub-group, and hands back the 16-bit payload for ``device_put`` — one host
memory sweep per step, PCIe-analogous transfers at the step boundary only.
With ``device == "nvme"`` the Adam moments live on NVMe between steps and are
streamed through the aio op (prefetch of group g+1 overlaps compute of g via
``PipelinedOptimizerSwapper``).
"""

import numpy as np
import jax

from ...ops.adam.cpu_adam import DeepSpeedCPUAdam
from ...utils.logging import logger, log_dist
from . import wire

OUT_DTYPE = {"bfloat16": "bfloat16", "float16": "float16",
             "float32": None}


class FlatWireHandle:
    """In-flight chunked d2h of one flat grad array (see
    :meth:`HostOffloadOptimizer.start_d2h`); holds only the chunk slices,
    so dropping it frees the device memory."""

    def __init__(self, handle):
        self.handle = handle


class HostOffloadOptimizer:
    def __init__(self, params0, zero_config, aio_config, *, optimizer_name,
                 optimizer_params, compute_dtype_name, rank=0,
                 consume_params=False, payload_in_ram=True, retry=None):
        p = dict(optimizer_params or {})
        p.pop("torch_adam", None)
        # same default as FusedAdam (adam_w_mode=True): identical update rule
        # with and without offload for the same config
        adam_w_mode = p.pop("adam_w_mode", True)
        adamw = True if optimizer_name == "adamw" else adam_w_mode
        self.opt = DeepSpeedCPUAdam(adamw_mode=adamw, **p)
        self.out_dtype = OUT_DTYPE[compute_dtype_name]

        # ---- flat layout of the fp32 master --------------------------------
        leaves, self.treedef = jax.tree_util.tree_flatten(params0)
        self.shapes = [np.shape(l) for l in leaves]
        sizes = [int(np.prod(s or (1,))) for s in self.shapes]
        self.offsets = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)
        self.numel = int(self.offsets[-1])
        self.master = np.empty(self.numel, np.float32)
        # start every d2h before consuming any: per-leaf sequential
        # np.asarray pays one transfer LATENCY per leaf (~minutes for a
        # billion-param tree on a remote-attached chip)
        self.start_d2h(leaves)
        for i, (leaf, off, n) in enumerate(zip(leaves, self.offsets, sizes)):
            self.master[off:off + n] = np.asarray(leaf, np.float32).ravel()
            if consume_params and hasattr(leaf, "delete"):
                # free each source leaf as it is absorbed — at billions of
                # params the init tree + master together would not fit RAM
                leaf.delete()
                leaves[i] = None

        # ---- sub-groups (reference sub_group_size elements) ----------------
        sg = int(zero_config.sub_group_size)
        bounds = list(range(0, self.numel, sg)) + [self.numel]
        self.sub_groups = [(bounds[i], bounds[i + 1])
                           for i in range(len(bounds) - 1)]

        # ---- moments: host RAM or NVMe -------------------------------------
        off_cfg = zero_config.offload_optimizer
        self.nvme = off_cfg is not None and off_cfg.device == "nvme"
        if self.nvme:
            from ..swap_tensor.partitioned_optimizer_swapper import (
                PartitionedOptimizerSwapper, PipelinedOptimizerSwapper)
            cls = (PipelinedOptimizerSwapper if off_cfg.pipeline
                   else PartitionedOptimizerSwapper)
            assert off_cfg.nvme_path, \
                "offload_optimizer.device=nvme requires nvme_path"
            self.swapper = cls(off_cfg, aio_config, off_cfg.nvme_path, rank,
                               retry=retry)
            for g, (s, e) in enumerate(self.sub_groups):
                z = np.zeros(e - s, np.float32)
                self.swapper.swap_out_group(
                    g, {"exp_avg": z, "exp_avg_sq": z}, async_op=False)
            self.m = self.v = None
        else:
            self.swapper = None
            self.m, self.v = self.opt.init_buffers(self.numel)
            # first-touch the moment pages NOW: lazily-faulted zeros add
            # minutes to the FIRST optimizer step of a billion-param model
            self.m.fill(0.0)
            self.v.fill(0.0)
        # reusable fp32 gradient landing buffer (the flat wire upcasts into
        # it in place — no per-step multi-GB allocation/fault)
        self._flat32 = None
        self._out16 = None
        self._payload_in_ram = payload_in_ram
        if not consume_params:
            self.alloc_buffers()
        # consume_params callers (the streamed tier) free the init tree
        # FIRST and then call alloc_buffers() — at multi-billion params the
        # init tree, master, grad buffer and image cannot coexist in RAM

    def alloc_buffers(self):
        """Allocate + pre-fault the flat gradient buffer and (if configured)
        the 16-bit RAM image.  Separated from __init__ so the streamed tier
        can free the init tree between the master build and these
        allocations (peak-RAM control)."""
        if self._flat32 is None:
            self._flat32 = np.empty(self.numel, np.float32)
            self._flat32.fill(0.0)
        if self.out_dtype is not None and self._payload_in_ram \
                and self._out16 is None:
            self._out16 = np.empty(self.numel, np.uint16)
            self._out16.fill(0)
            self.refresh_payload()
        log_dist(f"host offload optimizer: {self.numel} params, "
                 f"{len(self.sub_groups)} sub-group(s), "
                 f"moments on {'nvme' if self.nvme else 'cpu'}, "
                 f"native={self.opt.is_native}", ranks=[0])

    # ------------------------------------------------------- payload encode
    def encode_range(self, lo, hi, out_buf):
        """master[lo:hi] → compute-dtype payload bytes in ``out_buf``
        (uint16 view for 16-bit dtypes, fp32 otherwise).  The param-stream
        NVMe tier uses this to materialize per-layer payloads without a
        whole-model RAM image."""
        n = hi - lo
        if self.out_dtype is None:
            np.copyto(out_buf[:n], self.master[lo:hi])
        elif self.out_dtype == "bfloat16":
            import ml_dtypes
            out_buf[:n] = self.master[lo:hi].astype(
                ml_dtypes.bfloat16).view(np.uint16)
        else:
            out_buf[:n] = self.master[lo:hi].astype(np.float16).view(np.uint16)

    def refresh_payload(self):
        """Re-encode the full 16-bit RAM image from the fp32 master (init
        and checkpoint-load; steady-state steps update it incrementally
        through the fused op's 16-bit copy-back)."""
        if self._out16 is not None:
            self.encode_range(0, self.numel, self._out16)

    def drop_payload(self):
        """Release the RAM image (NVMe param tier keeps payloads on disk)."""
        self._out16 = None

    # ------------------------------------------------------------ flattening
    def start_d2h(self, grads_tree):
        """Kick off the device→host DMA for every gradient leaf WITHOUT
        blocking, and return the wire object the caller should hold IN
        PLACE OF the grads.  Called right after the grad step is
        dispatched, so the transfers queue behind the device compute and
        run while the host does other work (the reference overlaps
        per-bucket pinned d2h copies with backward,
        ``stage_1_and_2.py:1008-1160``).

        A flat grad array is CHUNKED first (``zero/wire.py``; one
        monolithic transfer serializes the transport, ~8x measured) and a
        :class:`FlatWireHandle` over the chunk slices is returned — the
        caller drops its reference to the original flat array so only the
        chunks stay live (dropping the handle, e.g. on an fp16 overflow
        skip, frees everything).  Pytree grads start per-leaf transfers
        and pass through unchanged."""
        if isinstance(grads_tree, jax.Array):
            return FlatWireHandle(wire.d2h_flat_start(grads_tree))
        wire.d2h_tree_start(grads_tree)
        return grads_tree

    def land_flat(self, handle):
        """Land a :class:`FlatWireHandle`'s chunks into the reusable fp32
        host buffer (upcasts into preallocated, pre-faulted memory)."""
        return wire.d2h_flat_land(handle.handle, self._flat32)

    def flatten_grads(self, grads_tree):
        """Device grads pytree → flat host fp32 (the d2h transfer).

        A leaf may arrive row-sparse as ``{"sparse_indices", "sparse_values"}``
        (engine ``sparse_gradients`` wire format, reference
        ``sparse_allreduce_no_retain`` engine.py:2227): only the touched rows
        cross the wire; the host scatters them into the flat buffer."""
        leaves = self.treedef.flatten_up_to(grads_tree)
        flat = self._flat32          # reuse: no multi-GB alloc/fault per step
        for leaf, off, shape in zip(leaves, self.offsets, self.shapes):
            n = int(np.prod(shape or (1,)))
            if isinstance(leaf, dict) and "sparse_indices" in leaf:
                seg = flat[off:off + n].reshape(shape)
                seg[...] = 0.0
                np.add.at(seg, np.asarray(leaf["sparse_indices"]),
                          np.asarray(leaf["sparse_values"], np.float32))
            else:
                flat[off:off + n] = np.asarray(leaf, np.float32).ravel()
        return flat

    def payload_flat(self):
        """Master as ONE flat compute-dtype numpy array (single h2d)."""
        import jax.numpy as jnp
        if self.out_dtype is None:
            return self.master
        assert self._out16 is not None, \
            "payload image dropped (NVMe param tier); use encode_range"
        return self._out16.view(
            jnp.bfloat16 if self.out_dtype == "bfloat16" else np.float16)

    def payload_tree(self):
        """Master as a pytree of compute-dtype numpy arrays (h2d payload)."""
        src = self.payload_flat()
        leaves = [src[off:off + int(np.prod(s or (1,)))].reshape(s)
                  for off, s in zip(self.offsets, self.shapes)]
        return self.treedef.unflatten(leaves)

    # ------------------------------------------------------------------ step
    def step(self, flat_grads: np.ndarray, step_no: int, lr: float):
        """One fused host Adam step over all sub-groups (in place)."""
        out16 = self._out16          # None for fp32 or external payload
        # no RAM image -> skip the fused op's 16-bit copy-back entirely
        # (the NVMe tier re-encodes per layer from the master instead)
        kind = self.out_dtype if out16 is not None else None

        if not self.nvme:
            self._step_range(0, self.numel, flat_grads, self.m, self.v,
                             step_no, lr, out16, kind)
            return

        pipelined = hasattr(self.swapper, "prefetch_group")
        names = ("exp_avg", "exp_avg_sq")
        if pipelined and self.sub_groups:
            self.swapper.prefetch_group(0, names)
        for g, (s, e) in enumerate(self.sub_groups):
            if pipelined:
                bufs = self.swapper.get_group(g, names)
                if g + 1 < len(self.sub_groups):
                    self.swapper.prefetch_group(g + 1, names)
            else:
                bufs = self.swapper.swap_in_group(g, names)
            self._step_range(s, e, flat_grads, bufs["exp_avg"],
                             bufs["exp_avg_sq"], step_no, lr, out16, kind,
                             moment_offset=s)
            self.swapper.swap_out_group(g, bufs,
                                        async_op=pipelined)
        self.swapper.wait()

    def _step_range(self, s, e, flat_grads, m, v, step_no, lr, out16, kind,
                    moment_offset=0):
        ms, mv = (m[s - moment_offset:e - moment_offset],
                  v[s - moment_offset:e - moment_offset])
        self.opt.step_flat(
            self.master[s:e], flat_grads[s:e], ms, mv, step_no, lr=lr,
            out16=out16[s:e] if out16 is not None else None, out_dtype=kind)

    # ----------------------------------------------------------- checkpoints
    def master_tree(self):
        leaves = [self.master[off:off + int(np.prod(s or (1,)))].reshape(s).copy()
                  for off, s in zip(self.offsets, self.shapes)]
        return self.treedef.unflatten(leaves)

    def moments(self):
        """(exp_avg, exp_avg_sq) flat fp32 — gathered from NVMe if needed."""
        if not self.nvme:
            return self.m, self.v
        m = np.empty(self.numel, np.float32)
        v = np.empty(self.numel, np.float32)
        for g, (s, e) in enumerate(self.sub_groups):
            bufs = self.swapper.swap_in_group(g, ("exp_avg", "exp_avg_sq"))
            m[s:e] = bufs["exp_avg"]
            v[s:e] = bufs["exp_avg_sq"]
        return m, v

    def _unflatten(self, flat):
        leaves = [flat[off:off + int(np.prod(s or (1,)))].reshape(s).copy()
                  for off, s in zip(self.offsets, self.shapes)]
        return self.treedef.unflatten(leaves)

    def moments_tree(self):
        """Moments as param-shaped pytrees — the SAME checkpoint layout as
        the in-device AdamState, so offload and non-offload runs can load
        each other's checkpoints (leaves match by ``exp_avg/...`` paths)."""
        m, v = self.moments()
        return {"exp_avg": self._unflatten(m),
                "exp_avg_sq": self._unflatten(v)}

    def _to_flat(self, x):
        """Accept a flat array OR a param-shaped pytree of moments."""
        if x is None:
            return None
        leaves = jax.tree_util.tree_leaves(x)
        if len(leaves) == 1 and np.ndim(leaves[0]) == 1 \
                and np.size(leaves[0]) == self.numel:
            return np.asarray(leaves[0], np.float32)
        return np.concatenate(
            [np.asarray(l, np.float32).ravel() for l in leaves])

    def load_state(self, master_tree=None, m=None, v=None):
        if master_tree is not None:
            leaves = self.treedef.flatten_up_to(master_tree)
            for leaf, off, shape in zip(leaves, self.offsets, self.shapes):
                n = int(np.prod(shape or (1,)))
                self.master[off:off + n] = np.asarray(leaf, np.float32).ravel()
        m, v = self._to_flat(m), self._to_flat(v)
        if m is not None and v is not None:
            if self.nvme:
                for g, (s, e) in enumerate(self.sub_groups):
                    self.swapper.swap_out_group(
                        g, {"exp_avg": m[s:e], "exp_avg_sq": v[s:e]},
                        async_op=False)
            else:
                np.copyto(self.m, m)
                np.copyto(self.v, v)
        # refresh the device payload for the next upload
        self.refresh_payload()
