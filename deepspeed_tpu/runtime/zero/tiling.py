"""TiledLinear — split a huge linear into tiles to cap working memory.

Parity: reference ``runtime/zero/tiling.py:27`` (``TiledLinear``): a Linear
with ``in_splits × out_splits`` sub-linears so ZeRO-3 only gathers one tile
at a time, bounding live memory for layers too big to materialize whole
(e.g. embedding projections of very large vocabularies).

TPU re-design: params are stored pre-tiled as a stacked (in_splits,
out_splits, tile_in, tile_out) array and the forward is a ``lax.scan`` over
tiles with ``jax.checkpoint`` — under fsdp sharding XLA gathers one tile per
scan iteration (the same bounded-live-memory guarantee the reference gets
from per-tile ds params), and remat keeps only tile boundaries for backward.
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


class TiledLinear:
    """Layer-protocol tiled linear: ``(.., in_features) → (.., out_features)``."""

    def __init__(self, in_features, out_features, bias=True, in_splits=1,
                 out_splits=1, input_is_already_split=False, combine_out_splits=True,
                 linear_cls=None, init_linear=None, **kw):
        assert in_features % in_splits == 0, \
            f"in_features {in_features} not divisible by in_splits {in_splits}"
        assert out_features % out_splits == 0, \
            f"out_features {out_features} not divisible by out_splits {out_splits}"
        self.in_features = in_features
        self.out_features = out_features
        self.in_splits = in_splits
        self.out_splits = out_splits
        self.tile_in = in_features // in_splits
        self.tile_out = out_features // out_splits
        self.use_bias = bias
        self.input_is_already_split = input_is_already_split
        self.combine_out_splits = combine_out_splits
        self.init_linear = init_linear  # optional full (in, out) weight to copy

    def init(self, rng):
        k1, _ = jax.random.split(rng)
        std = 1.0 / np.sqrt(self.in_features)
        if self.init_linear is not None:
            w = np.asarray(self.init_linear, np.float32)
            assert w.shape == (self.in_features, self.out_features)
            w = (w.reshape(self.in_splits, self.tile_in,
                           self.out_splits, self.tile_out)
                  .transpose(0, 2, 1, 3))
            w = jnp.asarray(w)
        else:
            w = jax.random.uniform(
                k1, (self.in_splits, self.out_splits, self.tile_in, self.tile_out),
                jnp.float32, -std, std)
        params = {"w": w}
        if self.use_bias:
            params["b"] = jnp.zeros((self.out_splits, self.tile_out), jnp.float32)
        return params

    def partition_specs(self, params=None):
        """fsdp shards the tile grid's input axis; tensor TP can take out."""
        specs = {"w": P(None, None, "tensor", None)}
        if self.use_bias:
            specs["b"] = P()
        return specs

    def apply(self, params, x, rng=None):
        """Scan over in-tiles (outer) and out-tiles (inner): live memory is
        one (tile_in, tile_out) weight + one (.., tile_out) partial."""
        lead = x.shape[:-1]
        xs = x.reshape(*lead, self.in_splits, self.tile_in)
        xs = jnp.moveaxis(xs, -2, 0)               # (in_splits, .., tile_in)

        w = params["w"]                            # (is, os, ti, to)

        @jax.checkpoint
        def in_tile(carry, inputs):
            w_row, x_tile = inputs                 # (os, ti, to), (.., ti)
            # contribution of this in-tile to every out-tile
            part = jnp.einsum("...i,oij->o...j", x_tile,
                              w_row.astype(x_tile.dtype))
            return carry + part, None

        zeros = jnp.zeros((self.out_splits, *lead, self.tile_out), x.dtype)
        acc, _ = jax.lax.scan(in_tile, zeros, (w, xs))

        if self.use_bias:
            b = params["b"].astype(x.dtype)        # (os, to)
            acc = acc + b.reshape(self.out_splits,
                                  *(1,) * len(lead), self.tile_out)
        if not self.combine_out_splits:
            return acc
        out = jnp.moveaxis(acc, 0, -2)             # (.., os, to)
        return out.reshape(*lead, self.out_features)

    def __call__(self, params, x, **kw):
        return self.apply(params, x, **kw)

    def full_weight(self, params):
        """Reassemble the (in, out) weight (testing/checkpoint export)."""
        w = np.asarray(params["w"])
        return (w.transpose(0, 2, 1, 3)
                 .reshape(self.in_features, self.out_features))


class TiledLinearReturnBias(TiledLinear):
    """Variant returning (out, bias) unadded (reference
    ``tiling.py TiledLinearReturnBias`` used by Megatron layers)."""

    def apply(self, params, x, rng=None):
        bias = params.get("b")
        saved = self.use_bias
        self.use_bias = False
        try:
            out = super().apply({"w": params["w"]}, x, rng=rng)
        finally:
            self.use_bias = saved
        if bias is not None:
            bias = bias.reshape(self.out_features) if self.combine_out_splits \
                else bias
        return out, bias
