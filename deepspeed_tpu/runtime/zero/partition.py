"""ZeRO stages as sharding placement rules.

TPU-native re-design of the reference's ZeRO machinery (SURVEY.md §7
"sharding, not hooks"):

- reference stage 1 (``zero/stage_1_and_2.py:92``: flattened fp16 groups +
  per-rank fp32 partition) → optimizer state + fp32 master params sharded on
  the ``fsdp`` mesh axis; compute params stay replicated.
- reference stage 2 (bucketed reduce-scatter fired by grad hooks,
  ``stage_1_and_2.py:777,1198``) → gradients constrained to the same fsdp
  sharding BEFORE the optimizer update; XLA's SPMD partitioner then emits a
  reduce-scatter instead of an all-reduce — the entire hook/bucket/stream
  apparatus disappears into one sharding constraint.
- reference stage 3 (``zero/stage3.py:228`` + ``partition_parameters.py:555``
  ``zero.Init`` param interception + ``partitioned_param_coordinator.py``
  fetch/prefetch/release state machine) → parameters themselves sharded on
  ``fsdp`` everywhere; XLA all-gathers them per-use inside the step and frees
  the gathered copies after use (prefetch/release ≈ XLA latency hiding +
  scan-over-layers; ``param_persistence_threshold`` keeps small params
  replicated exactly like the reference's persistence threshold).

The sharding rule for a single array: shard the LARGEST axis divisible by the
fsdp extent (falls back to replicated if none divides), composing with any
tensor-parallel spec the model declares.
"""

from typing import Optional

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shardable_axis(shape, extent: int, taken_axes=()) -> Optional[int]:
    """Largest axis divisible by ``extent``, excluding axes already sharded."""
    if extent <= 1 or not shape:
        return None
    best = None
    for i, dim in enumerate(shape):
        if i in taken_axes:
            continue
        if dim % extent == 0:
            if best is None or dim > shape[best]:
                best = i
    return best


def fsdp_spec(shape, fsdp_size: int, *, persistence_threshold: int = 0,
              base_spec: Optional[P] = None) -> P:
    """PartitionSpec sharding one array over the fsdp axis.

    ``base_spec`` carries tensor-parallel axes already assigned by the model;
    fsdp composes onto a remaining axis.  Arrays with fewer elements than
    ``persistence_threshold`` stay replicated (parity: reference
    ``param_persistence_threshold``, ``zero/config.py``).
    """
    base = tuple(base_spec) if base_spec is not None else ()
    base = base + (None,) * (len(shape) - len(base))
    if int(np.prod(shape or (1,))) < persistence_threshold:
        return P(*base)
    taken = tuple(i for i, s in enumerate(base) if s is not None)
    axis = shardable_axis(shape, fsdp_size, taken_axes=taken)
    if axis is None:
        return P(*base)
    new = list(base)
    existing = new[axis]
    if existing is None:
        new[axis] = "fsdp"
    elif isinstance(existing, str):
        new[axis] = (existing, "fsdp")
    else:
        new[axis] = tuple(existing) + ("fsdp",)
    return P(*new)


def _spec_tree(params, fn):
    return jax.tree_util.tree_map(lambda p: fn(np.shape(p)), params)


def param_specs(params, stage: int, fsdp_size: int, *,
                persistence_threshold: int = 0, tp_specs=None):
    """Sharding specs for the COMPUTE parameters by ZeRO stage.

    Stage 0/1/2: replicated (modulo tensor-parallel specs).
    Stage 3:     fsdp-sharded (reference param partitioning).
    """
    def one(shape, base):
        if stage >= 3:
            return fsdp_spec(shape, fsdp_size, persistence_threshold=persistence_threshold,
                             base_spec=base)
        return base if base is not None else P()

    if tp_specs is None:
        return _spec_tree(params, lambda s: one(s, None))
    return jax.tree_util.tree_map(lambda p, sp: one(np.shape(p), sp), params, tp_specs)


def master_specs(params, stage: int, fsdp_size: int, *, tp_specs=None):
    """Sharding specs for fp32 master params + optimizer moments.

    Stage >= 1: fsdp-sharded (reference per-rank fp32 partition,
    ``stage_1_and_2.py:228-270``).  Stage 0: replicated.
    """
    def one(shape, base):
        if stage >= 1:
            return fsdp_spec(shape, fsdp_size, base_spec=base)
        return base if base is not None else P()

    if tp_specs is None:
        return _spec_tree(params, lambda s: one(s, None))
    return jax.tree_util.tree_map(lambda p, sp: one(np.shape(p), sp), params, tp_specs)


def grad_specs(params, stage: int, fsdp_size: int, *, tp_specs=None):
    """Sharding constraint applied to gradients before the update.

    Stage >= 2: fsdp-sharded → XLA emits reduce-scatter (reference stage-2
    bucketed reduce-scatter).  Stage < 2: same placement as params → plain
    all-reduce (reference allreduce_bucket).
    """
    if stage >= 2:
        return master_specs(params, 1, fsdp_size, tp_specs=tp_specs)
    return param_specs(params, min(stage, 2), fsdp_size, tp_specs=tp_specs)


def _has_fsdp(spec: P) -> bool:
    for entry in spec:
        axes = (entry,) if isinstance(entry, str) else (entry or ())
        if "fsdp" in axes:
            return True
    return False


def relayout_report(params, stage: int, old_fsdp: int, new_fsdp: int, *,
                    persistence_threshold: int = 0, tp_specs=None) -> dict:
    """Summarize how ZeRO placements change across an fsdp-extent change
    (the elastic reshard-on-resize path, docs/elasticity.md).

    The placement rules are pure functions of (shape, stage, fsdp extent)
    — arXiv 1910.02054's observation that a ZeRO shard layout is derivable
    from the world size alone — so a resize is a deterministic
    re-partition: recompute the specs at the new extent and ``device_put``
    the full (gathered) checkpoint arrays under them.  This report names
    what that re-partition does: how many leaves change their spec, and
    how many lose their fsdp sharding entirely because no axis divides the
    new extent (they fall back to replicated — still correct, but
    memory-relevant, so the resume path logs it).
    """
    def counts(old_specs, new_specs):
        olds = jax.tree_util.tree_leaves(
            old_specs, is_leaf=lambda x: isinstance(x, P))
        news = jax.tree_util.tree_leaves(
            new_specs, is_leaf=lambda x: isinstance(x, P))
        changed = sum(1 for o, n in zip(olds, news) if tuple(o) != tuple(n))
        fallback = sum(1 for o, n in zip(olds, news)
                       if _has_fsdp(o) and not _has_fsdp(n))
        return {"leaves": len(news), "respec": changed,
                "replicated_fallback": fallback}

    report = {"old_fsdp": old_fsdp, "new_fsdp": new_fsdp}
    report["params"] = counts(
        param_specs(params, stage, old_fsdp,
                    persistence_threshold=persistence_threshold,
                    tp_specs=tp_specs),
        param_specs(params, stage, new_fsdp,
                    persistence_threshold=persistence_threshold,
                    tp_specs=tp_specs))
    report["master"] = counts(
        master_specs(params, stage, old_fsdp, tp_specs=tp_specs),
        master_specs(params, stage, new_fsdp, tp_specs=tp_specs))
    return report


def to_named(specs, mesh: Mesh):
    return jax.tree_util.tree_map(lambda sp: NamedSharding(mesh, sp), specs,
                                  is_leaf=lambda x: isinstance(x, P))


def constrain(tree, specs, mesh: Optional[Mesh] = None):
    """with_sharding_constraint over a pytree of PartitionSpecs.

    ``mesh`` is required unless a mesh context is already set (jax.set_mesh);
    with it, specs are bound into NamedShardings.
    """
    if mesh is not None:
        bind = lambda sp: NamedSharding(mesh, sp)
    else:
        bind = lambda sp: sp
    return jax.tree_util.tree_map(
        lambda x, sp: jax.lax.with_sharding_constraint(x, bind(sp)), tree, specs)
