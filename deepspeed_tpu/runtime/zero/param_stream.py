"""ZeRO-3 parameter offload: params live on the HOST (or NVMe) and layer
blocks stream through the device during forward/backward.

Parity: the reference's ZeRO-3 offload / ZeRO-Infinity param tier —
``zero/stage3.py:656 _configure_offloading`` +
``zero/partition_parameters.py:555`` (``remote_device``) +
``swap_tensor/partitioned_param_swapper.py:37`` — the machinery behind
"13B trainable on one V100-32GB, 40B with NVMe"
(``docs/_posts/2020-09-09-ZeRO-Offload.md:9``,
``docs/_posts/2021-03-08-zero3-offload.md:49``).

TPU-native shape (NOT a hook translation): the reference intercepts
per-submodule fwd/bwd with gather/release hooks; here the model exposes
its forward DECOMPOSED (``model.stream_fns()``: embed / per-layer block /
head) and a Python-driven loop runs one jitted block program per layer:

  - the host optimizer's flat buffers are built over a LAYER-MAJOR tree
    (``{"layers": [per-layer dicts], "nonblock": {...}}``) so each
    layer's parameters and gradients are CONTIGUOUS flat segments —
    per-layer h2d uploads are zero-copy views of the 16-bit image and
    per-layer grad d2h lands with one contiguous accumulate;
  - forward streams layer l+1's params (chunked async ``device_put``,
    ``zero/wire.py``) while layer l's block computes — the double-
    buffered prefetch the reference's param coordinator does with CUDA
    streams;
  - backward IS the rematerialization: each layer's params stream in
    again (reverse order), ``jax.vjp`` re-runs the block forward, the
    layer's bf16 grads stream out chunked+async and accumulate into the
    host fp32 gradient buffer while the next layer's backward runs;
  - small "nonblock" params (embeddings, final LN) stay device-resident
    (the reference's ``param_persistence_threshold`` idea) with their
    grads accumulated on device and transferred once per step;
  - the host fused Adam then runs over the same flat buffers
    (``offload_engine.HostOffloadOptimizer``) — parameters are never
    materialized whole on the device, so trainable model size is bounded
    by HOST memory, not HBM.

With ``offload_param.device == "nvme"`` the 16-bit layer payloads live
in per-layer files serviced by the kernel-AIO op (no host-RAM image);
reads prefetch ahead of the layer loop.
"""

import os
import time

import numpy as np
import jax
import jax.numpy as jnp

from . import wire
from ...utils.logging import logger, log_dist


def to_stream_tree(params, stacked_key):
    """Model tree (stacked blocks) -> layer-major stream tree."""
    blocks = params[stacked_key]
    L = jax.tree_util.tree_leaves(blocks)[0].shape[0]
    layers = [jax.tree_util.tree_map(lambda a: a[l], blocks)
              for l in range(L)]
    nonblock = {k: v for k, v in params.items() if k != stacked_key}
    return {"layers": layers, "nonblock": nonblock}


def from_stream_tree(tree, stacked_key):
    """Layer-major stream tree -> model tree (stacked blocks).

    Used on the checkpoint boundary so streamed and monolithic runs can
    load each other's checkpoints unchanged."""
    layers = tree["layers"]
    blocks = jax.tree_util.tree_map(lambda *ls: np.stack(ls), *layers)
    out = dict(tree["nonblock"])
    out[stacked_key] = blocks
    return out


class ParamStreamRunner:
    """Drives the streamed train/eval step for one engine.

    The engine owns config parsing, LR schedules, counters and
    checkpoint I/O; this object owns the device loop and the layout
    bookkeeping between the host optimizer's flat buffers and the
    per-layer jitted programs.
    """

    def __init__(self, model, host_opt, mesh, compute_dtype, *,
                 gas, grad_clip, zero_config, aio_config, retry=None,
                 skip_nonfinite=True, spike=None, compile_cache=None,
                 cache_key_extra=None, comms_compression=None):
        assert mesh.size == 1, (
            "offload_param streaming is single-chip (scale-up) machinery; "
            "on a multi-chip mesh use ZeRO-3 sharding (stage 3 without "
            "offload_param) — params then shard over the fsdp axis")
        self.model = model
        self.host = host_opt
        self.mesh = mesh
        self.dtype = compute_dtype
        self.gas = int(gas)
        self.grad_clip = float(grad_clip or 0.0)
        # health guardian skip-step: a non-finite step must be a no-op on
        # the host master/moments (runtime/health.py; the streamed twin of
        # the engine's branchless in-graph skip).  ``spike`` is the
        # (window, zmax, skip_on_spike) tuple of the loss-spike sentinel —
        # this path has no device HealthState, so the EMA runs host-side
        # with the same formula (health.HostEma).
        self.skip_nonfinite = bool(skip_nonfinite)
        self._spike_ema = None
        self._skip_on_spike = False
        if spike is not None:
            from ..health import HostEma
            window, zmax, skip_on_spike = spike
            self._spike_ema = HostEma(window, zmax)
            self._skip_on_spike = bool(skip_on_spike)
        sf = model.stream_fns()
        self.sf = sf
        self.L = int(sf["n_layer"])
        self.local_flags = np.asarray(sf["local_flags"], bool)

        # ---- flat-layout bookkeeping (layer-major stream tree) -----------
        # host_opt was built over to_stream_tree(params); dict keys sort as
        # "layers" < "nonblock", so the flat buffer is
        #   [layer 0 | layer 1 | ... | layer L-1 | nonblock]
        # with every segment contiguous.  Verify by index rather than
        # assuming: unflattening leaf positions shows where each leaf sits.
        numel = host_opt.numel
        idx_tree = host_opt.treedef.unflatten(
            list(range(len(host_opt.shapes))))
        layer0 = idx_tree["layers"][0]
        layer_idx = jax.tree_util.tree_leaves(layer0)   # any nesting
        self.layer_shapes = [host_opt.shapes[i] for i in layer_idx]
        per_layer = sum(int(np.prod(s or (1,))) for s in self.layer_shapes)
        self.layer_bounds = []
        for l in range(self.L):
            ids = jax.tree_util.tree_leaves(idx_tree["layers"][l])
            lo = int(host_opt.offsets[min(ids)])
            self.layer_bounds.append((lo, lo + per_layer))
            assert lo == l * per_layer, "layer segments must tile the front"
        self.nb_lo = self.L * per_layer
        self.nb_hi = numel
        self.per_layer = per_layer
        self.layer_treedef = jax.tree_util.tree_structure(
            jax.tree_util.tree_map(lambda x: 0, layer0))
        nb_ids = jax.tree_util.tree_leaves(idx_tree["nonblock"])
        assert min(nb_ids, default=len(host_opt.shapes)) >= self.L * \
            len(layer_idx), "nonblock leaves must follow the layer segments"
        self._nb_shapes = [host_opt.shapes[i] for i in nb_ids]
        self._nonblock_treedef = jax.tree_util.tree_structure(
            jax.tree_util.tree_map(lambda x: 0, idx_tree["nonblock"]))

        # ---- NVMe param tier ---------------------------------------------
        off_p = zero_config.offload_param
        self.nvme = off_p is not None and off_p.device == "nvme"
        if self.nvme:
            from ..swap_tensor.partitioned_param_swapper import (
                AsyncPartitionedParameterSwapper)
            assert off_p.nvme_path, "offload_param.device=nvme needs nvme_path"
            itemsize = 2 if host_opt.out_dtype is not None else 4
            self.swapper = AsyncPartitionedParameterSwapper(
                aio_config, off_p.nvme_path,
                dtype=np.uint16 if itemsize == 2 else np.float32,
                buffer_count=max(4, int(off_p.buffer_count)),
                buffer_numel=per_layer, retry=retry)
            self._flush_layers_to_nvme(range(self.L))
            host_opt.drop_payload()
        else:
            self.swapper = None

        # ---- quantized layer wire (docs/comms-compression.md) ------------
        # qwZ for the h2d hop: the 16-bit layer payload crosses as a
        # block-quantized int8/int4 image + fp32 scales, dequantized
        # inside the jitted scatter (half / quarter the wire bytes — the
        # route that matters on the slow host<->device tunnel).  The
        # fp32 master and host optimizer stay exact; only the COMPUTE
        # copy is lossy, exactly like the fused engine's qwZ gathers.
        # Quantized images are cached per host-payload version (one host
        # quantization pass per optimizer step, ~numel/2 extra host RAM);
        # excluded leaves ride a separate full-width image.  The NVMe
        # tier keeps the full-width wire (its payload lives on disk).
        cc = comms_compression
        self._quant = bool(
            cc is not None and cc.enabled and "param_stream" in cc.routes
            and cc.weights_bits is not None and not self.nvme
            and host_opt.out_dtype is not None)
        if self._quant:
            self._q_bits = int(cc.weights_bits)
            self._q_block = int(cc.block_size)
            if self._q_bits == 4 and self._q_block % 2:
                self._q_block += 1
            self._q_plan = self._build_quant_plan(cc)
            self._q_cache = {}
            self._payload_version = 0
            if self._q_plan["q_total"] == 0:
                self._quant = False     # policy excluded every layer leaf
        if self._quant:
            log_dist("param_stream comms_compression: layer wire "
                     f"int{self._q_bits} block={self._q_block} "
                     f"(q {self._q_plan['q_total']} / fw "
                     f"{self._q_plan['fw_total']} elems per layer)",
                     ranks=[0])

        # ---- device-resident nonblock params + jitted programs -----------
        self._h2d = wire.H2DUploader()
        self._jit_cache = {}
        # persistent compiled-step cache: the per-layer programs (embed /
        # block fwd+bwd / head / nonblock reductions) are the streamed
        # path's compile cost — L layers × two directions re-compiled on
        # every process start without it (runtime/compile_cache.py)
        self._compile_cache = compile_cache
        self._cache_key_extra = dict(cache_key_extra or {},
                                     n_layer=self.L, nvme=self.nvme)
        self._nonblock_dev = None
        self._upload_nonblock()
        self.last_times = {}

    # ------------------------------------------------------------- layout
    def _payload_seg(self, lo, hi):
        """16-bit (or fp32) host view of flat range [lo, hi)."""
        return self.host.payload_flat()[lo:hi]

    # ------------------------------------------- quantized layer wire
    def _build_quant_plan(self, cc):
        """Per-leaf wire plan for one layer block: quantized leaves get
        block-ALIGNED ranges of the int8 image (a shared block would mix
        a weight tail with e.g. an LN vector and ruin the block scale);
        excluded / sub-threshold leaves ride a full-width image."""
        from ..comm.collective_router import _path_str
        dummy = jax.tree_util.tree_unflatten(
            self.layer_treedef, list(range(len(self.layer_shapes))))
        paths = [p for p, _ in
                 jax.tree_util.tree_flatten_with_path(dummy)[0]]
        B = self._q_block
        entries, q_off, fw_off = [], 0, 0
        for path, shape in zip(paths, self.layer_shapes):
            n = int(np.prod(shape or (1,)))
            ps = _path_str(path)
            if n * 2 < cc.min_tensor_bytes or \
                    any(pat in ps for pat in cc.excluded):
                entries.append(("fw", fw_off, n))
                fw_off += n
            else:
                npad = ((n + B - 1) // B) * B
                entries.append(("q", q_off, n, npad))
                q_off += npad
        return {"entries": tuple(entries), "q_total": q_off,
                "fw_total": fw_off}

    def _wire_dtype_np(self):
        import ml_dtypes
        return (ml_dtypes.bfloat16 if self.host.out_dtype == "bfloat16"
                else np.float16)

    def _quant_images(self, l, lo, hi):
        """(q_img u8, scales f32, fw_img 16-bit) for layer ``l``, cached
        per host-payload version (one host quantization pass per applied
        optimizer step, not per fetch — fetches run L×gas×2 per step)."""
        hit = self._q_cache.get(l)
        if hit is not None and hit[0] == self._payload_version:
            return hit[1]
        from ..comm.quantized import quantize_flat_np
        seg16 = self._payload_seg(lo, hi)
        if seg16.dtype == np.uint16:
            seg16 = seg16.view(self._wire_dtype_np())
        pl = self._q_plan
        B = self._q_block
        pack = 2 if self._q_bits == 4 else 1
        q_img = np.empty(pl["q_total"] // pack, np.uint8)
        scales = np.empty(pl["q_total"] // B, np.float32)
        fw_img = np.empty(pl["fw_total"], seg16.dtype)
        off = 0
        for entry, shape in zip(pl["entries"], self.layer_shapes):
            n = int(np.prod(shape or (1,)))
            leaf = seg16[off:off + n]
            off += n
            if entry[0] == "fw":
                fw_img[entry[1]:entry[1] + n] = leaf
            else:
                _, qo, _, npad = entry
                q, s = quantize_flat_np(leaf, block_size=B,
                                        bits=self._q_bits)
                q_img[qo // pack:(qo + npad) // pack] = q
                scales[qo // B:(qo + npad) // B] = s
        imgs = (q_img, scales, fw_img)
        self._q_cache[l] = (self._payload_version, imgs)
        return imgs

    def _upload_layer_quantized(self, l, lo, hi):
        q_img, scales, fw_img = self._quant_images(l, lo, hi)
        B = self._q_block
        pack = 2 if self._q_bits == 4 else 1
        bpb = B // pack                     # packed bytes per block
        cb = max(bpb, (wire.DEFAULT_CHUNK_BYTES // bpb) * bpb)
        q_chunks = self._h2d.upload_flat(q_img, chunk_bytes=cb)
        fw_chunks = (self._h2d.upload_flat(fw_img) if fw_img.size else [])
        sc_dev = jax.device_put(scales)     # tiny; ref held by _q_cache
        key = ("layerq", len(q_chunks), len(fw_chunks))
        if key not in self._jit_cache:
            out_dtype = (jnp.bfloat16 if self.host.out_dtype == "bfloat16"
                         else jnp.float16)
            per_fw = (int(fw_chunks[0].shape[0]) if fw_chunks else 1)
            self._jit_cache[key] = wire.make_quantized_chunk_scatter(
                tuple(self.layer_shapes), self.layer_treedef,
                self._q_plan["entries"], int(q_chunks[0].shape[0]),
                len(q_chunks), per_fw, len(fw_chunks),
                bits=self._q_bits, block=B, out_dtype=out_dtype)
        tree = self._jit_cache[key](sc_dev, *q_chunks, *fw_chunks)
        self._h2d.settle_on(jax.tree_util.tree_leaves(tree)[0])
        return tree

    # ---------------------------------------------------------- NVMe tier
    def _flush_layers_to_nvme(self, layer_ids):
        enc = self.host.encode_range
        buf = np.empty(self.per_layer,
                       np.uint16 if self.host.out_dtype is not None
                       else np.float32)
        for l in layer_ids:
            lo, hi = self.layer_bounds[l]
            enc(lo, hi, buf)
            self.swapper.swap_out(l, buf)
        self.swapper.synchronize_writes()

    # ------------------------------------------------------------ uploads
    def _scatter_jit(self, name, shapes, nchunks, per):
        key = (name, nchunks)
        if key not in self._jit_cache:
            treedef = (self.layer_treedef if name == "layer"
                       else self._nonblock_treedef)
            self._jit_cache[key] = wire.make_chunk_scatter(
                shapes, treedef, per, nchunks)
        return self._jit_cache[key]

    def _upload_segment(self, seg16, name, shapes, stage=False):
        """Host flat 16-bit segment -> device pytree (chunked, async)."""
        if seg16.dtype == np.uint16:
            import ml_dtypes
            seg16 = seg16.view(ml_dtypes.bfloat16 if self.host.out_dtype ==
                               "bfloat16" else np.float16)
        chunks = self._h2d.upload_flat(seg16, stage=stage)
        per = int(chunks[0].shape[0])
        tree = self._scatter_jit(name, tuple(shapes), len(chunks),
                                 per)(*chunks)
        self._h2d.settle_on(jax.tree_util.tree_leaves(tree)[0])
        return tree

    def fetch_layer(self, l):
        """Start layer l's h2d; returns the device layer-param tree (the
        consuming jit waits on the transfers, so calling this one layer
        AHEAD gives double-buffered prefetch for free)."""
        if self.nvme:
            self.swapper.swap_in([l])
            seg = self.swapper.get_buffer(l)
            # staged: the swap buffer returns to the pool immediately (the
            # staging copy decouples it from the in-flight h2d DMA)
            tree = self._upload_segment(seg, "layer", self.layer_shapes,
                                        stage=True)
            self.swapper.release([l])
            return tree
        lo, hi = self.layer_bounds[l]
        if self._quant:
            return self._upload_layer_quantized(l, lo, hi)
        seg = self._payload_seg(lo, hi)
        return self._upload_segment(seg, "layer", self.layer_shapes)

    def prefetch_layer_nvme(self, l):
        """Begin the NVMe read for layer l (overlaps the current layer's
        compute; no-op on the cpu tier where fetch is a RAM view).  Skips
        (rather than fails) only on the one benign condition — no free pool
        buffer, in which case the blocking fetch_layer picks the read up —
        so genuine AIO errors surface HERE with their real context instead
        of resurfacing later mislabeled."""
        if self.nvme and 0 <= l < self.L:
            if self.swapper.available_swap_in_buffers() < 1:
                return                # pool busy; fetch_layer will block
            try:
                self.swapper.swap_in([l], async_op=True)
            except RuntimeError as e:
                # the availability check above races in-flight release/
                # acquire (swap_out's drain, a concurrent prefetch): the
                # pool can empty between check and acquire.  Same benign
                # condition as the guarded return — fall back to the
                # blocking fetch.  Anything else (AIO submit failures
                # arrive as their own error types) still raises.
                if "no free swap buffer" not in str(e):
                    raise
                logger.debug(
                    f"prefetch_layer_nvme({l}): swap buffer pool drained "
                    "between availability check and acquire; falling back "
                    "to the blocking fetch")

    def _upload_nonblock(self):
        nb_shapes = self._nb_shapes
        if self.nvme:
            buf = np.empty(self.nb_hi - self.nb_lo,
                           np.uint16 if self.host.out_dtype is not None
                           else np.float32)
            self.host.encode_range(self.nb_lo, self.nb_hi, buf)
            seg = buf
        else:
            seg = self._payload_seg(self.nb_lo, self.nb_hi)
        self._nonblock_dev = self._upload_segment(seg, "nonblock", nb_shapes)

    # ------------------------------------------------------- jitted pieces
    def _jits(self, deterministic):
        key = ("step", bool(deterministic))
        if key in self._jit_cache:
            return self._jit_cache[key]
        sf = self.sf
        dtype = self.dtype
        inv_gas = 1.0 / self.gas
        wire_dtype = (jnp.bfloat16 if self.host.out_dtype == "bfloat16"
                      else jnp.float32)

        def embed(nb, tokens, rng):
            return sf["embed"](nb, tokens, rng, deterministic)

        def block_fwd(p, x, rng, is_local):
            return sf["block"](p, x, rng, is_local, deterministic)

        def block_bwd(p, x, rng, is_local, dy):
            _, vjp = jax.vjp(
                lambda pp, xx: sf["block"](pp, xx, rng, is_local,
                                           deterministic), p, x)
            dp, dx = vjp(dy)
            leaves = jax.tree_util.tree_leaves(dp)
            dp_flat = jnp.concatenate(
                [l.astype(jnp.float32).reshape(-1) for l in leaves])
            return dx, (dp_flat * inv_gas).astype(wire_dtype)

        def head(nb, x, labels):
            def f(nb_, x_):
                return sf["head_loss"](nb_, x_, labels)
            loss, (d_nb, dx) = jax.value_and_grad(f, argnums=(0, 1))(nb, x)
            return loss, d_nb, dx

        def embed_bwd(nb, tokens, rng, dx):
            _, vjp = jax.vjp(lambda nb_: embed(nb_, tokens, rng), nb)
            (d_nb,) = vjp(dx)
            return d_nb

        def nb_add(a, b):
            return jax.tree_util.tree_map(
                lambda x, y: x.astype(jnp.float32) + y.astype(jnp.float32),
                a, b)

        def nb_flat(d_nb):
            leaves = jax.tree_util.tree_leaves(d_nb)
            flat = jnp.concatenate(
                [l.astype(jnp.float32).reshape(-1) for l in leaves])
            return (flat * inv_gas).astype(wire_dtype)

        def head_eval(nb, x, labels):
            return sf["head_loss"](nb, x, labels)

        from ..compile_cache import wrap_step

        def wrap(nm, fn, donate=()):
            return wrap_step(
                f"param_stream.{nm}", fn, cache=self._compile_cache,
                key_extra=dict(self._cache_key_extra,
                               deterministic=bool(deterministic)),
                donate_argnums=donate)

        out = {
            "embed": wrap("embed", embed),
            "block_fwd": wrap("block_fwd", block_fwd),
            "block_bwd": wrap("block_bwd", block_bwd, donate=(0, 4)),
            "head": wrap("head", head),
            "head_eval": wrap("head_eval", head_eval),
            "embed_bwd": wrap("embed_bwd", embed_bwd),
            "nb_add": wrap("nb_add", nb_add),
            "nb_flat": wrap("nb_flat", nb_flat),
            "layer_rngs": wrap("layer_rngs", sf["layer_rngs"]),
        }
        self._jit_cache[key] = out
        return out

    # ------------------------------------------------------------ training
    def train_step(self, micro_batches, rng, *, lr, step_no):
        """One optimizer step over ``gas`` microbatches.  Returns metrics."""
        J = self._jits(deterministic=False)
        host = self.host
        flat = host._flat32
        t0 = time.time()
        flat[:] = 0.0
        losses = []
        nb_grads = None
        t_dev = 0.0
        t_d2h = 0.0

        for mi, mb in enumerate(micro_batches):
            mb_rng = jax.random.fold_in(rng, mi)
            tokens, labels = self.sf["split_batch"](mb)
            tokens = jnp.asarray(tokens)
            labels = jnp.asarray(labels)
            rngs = J["layer_rngs"](mb_rng)

            # ---------- forward: stream layers up ----------
            td = time.time()
            x = J["embed"](self._nonblock_dev, tokens, mb_rng)
            self.prefetch_layer_nvme(0)
            xs = []
            p_next = self.fetch_layer(0)
            for l in range(self.L):
                p = p_next
                self.prefetch_layer_nvme(l + 1)
                xs.append(x)
                x = J["block_fwd"](p, x, rngs[l],
                                   jnp.asarray(self.local_flags[l]))
                # dispatch epoch BEFORE the next fetch: reading x proves
                # only uploads consumed by layers <= l completed — the
                # l+1 fetch below postdates that proof
                ep_proved = self._h2d.dispatch_epoch
                # prefetch next layer's params while this block computes
                p_next = (self.fetch_layer(l + 1) if l + 1 < self.L
                          else None)
                self._throttle(l, x, ep_proved)
            del p, p_next

            # ---------- head: loss + gradients ----------
            loss, d_nb, dx = J["head"](self._nonblock_dev, x, labels)
            losses.append(loss)

            # ---------- backward: stream layers down, grads out ----------
            self.prefetch_layer_nvme(self.L - 1)
            p_next = self.fetch_layer(self.L - 1)
            pending = None    # (handle, lo, hi, epoch) grad d2h in flight
            for l in range(self.L - 1, -1, -1):
                p = p_next
                self.prefetch_layer_nvme(l - 1)
                dx, dp_flat = J["block_bwd"](
                    p, xs[l], rngs[l], jnp.asarray(self.local_flags[l]), dx)
                # epoch proven once THIS layer's grads land (its bwd
                # consumed p's upload); the l-1 fetch below postdates it
                ep = self._h2d.dispatch_epoch
                p_next = self.fetch_layer(l - 1) if l > 0 else None
                handle = wire.d2h_flat_start(dp_flat)
                del dp_flat
                if pending is not None:
                    ph, plo, phi, pep = pending
                    t1 = time.time()
                    self._land_add(ph, plo, phi, flat)
                    t_d2h += time.time() - t1
                    # landing reads the bwd outputs — a barrier proving
                    # the param uploads dispatched up to that layer's
                    # bwd (epoch pep) completed; later fetches excluded
                    self._h2d.release_parked(pep)
                lo, hi = self.layer_bounds[l]
                pending = (handle, lo, hi, ep)
                xs[l] = None          # free the saved activation
            if pending is not None:
                ph, plo, phi, pep = pending
                t1 = time.time()
                self._land_add(ph, plo, phi, flat)
                t_d2h += time.time() - t1
                self._h2d.release_parked(pep)
            del p, p_next, xs

            # ---------- nonblock grads (device-accumulated) ----------
            d_nb_e = J["embed_bwd"](self._nonblock_dev, tokens, mb_rng, dx)
            d_nb = J["nb_add"](d_nb, d_nb_e)
            nb_grads = d_nb if nb_grads is None else J["nb_add"](nb_grads,
                                                                 d_nb)
            t_dev += time.time() - td

        # land nonblock grads: one chunked d2h into the nonblock segment
        t1 = time.time()
        nb_flat_dev = J["nb_flat"](nb_grads)
        self._land_add(wire.d2h_flat_start(nb_flat_dev),
                       self.nb_lo, self.nb_hi, flat)
        t_d2h += time.time() - t1
        del nb_grads, nb_flat_dev

        # ---------- clip + host Adam + payload refresh ----------
        t1 = time.time()
        gnorm = self._host_global_norm(flat)
        loss = float(np.mean([float(l) for l in losses]))
        # health-guardian skip-step, streamed spelling: the grads are
        # already host-side (the wire crossed either way), so the no-op is
        # simply not applying the host optimizer — master, moments, NVMe
        # image and the device payload all stay at the pre-step state
        z, spiked = (self._spike_ema.update(loss)
                     if self._spike_ema is not None else (0.0, False))
        skip = (self.skip_nonfinite and not (np.isfinite(gnorm)
                                             and np.isfinite(loss))) \
            or (self._skip_on_spike and spiked)
        if skip:
            logger.warning(
                f"param-stream step {step_no}: unhealthy sentinels "
                f"(loss={loss}, grad_norm={gnorm}, z={z:.2f}); host "
                "optimizer step SKIPPED — params/optimizer state untouched")
            t_adam = time.time() - t1
        else:
            if self.grad_clip > 0 and gnorm > self.grad_clip:
                np.multiply(flat, self.grad_clip / (gnorm + 1e-6), out=flat)
            host.step(flat, step_no, lr)
            t_adam = time.time() - t1
            if self.nvme:
                t2 = time.time()
                self._flush_layers_to_nvme(range(self.L))
                t_adam += time.time() - t2
            self._upload_nonblock()
            if self._quant:
                # payload changed: next fetch of each layer re-quantizes
                # (a SKIPPED step leaves the payload — and the cached
                # quantized images — untouched)
                self._payload_version += 1

        self.last_times = {
            "device_plus_wire_s": round(t_dev, 3),
            "grad_d2h_land_s": round(t_d2h, 3),
            "host_adam_s": round(t_adam, 3),
            "step_wall_s": round(time.time() - t0, 3),
        }
        metrics = {"loss": jnp.asarray(loss), "grad_norm": jnp.asarray(gnorm),
                   "overflow": jnp.asarray(False), "lr": jnp.asarray(lr),
                   "loss_scale": jnp.asarray(1.0), "skip": jnp.asarray(skip)}
        if self._spike_ema is not None:
            # carried so the monitor uses THIS ema (no double accounting)
            metrics["health_z"] = jnp.asarray(z)
            metrics["loss_spike"] = jnp.asarray(spiked)
        return metrics

    def close(self):
        """Engine shutdown: drop the jitted per-layer programs (and their
        live executables), the device nonblock tree, parked H2D staging
        buffers, and the NVMe swapper's pinned buffer pool.  ``del
        engine`` frees none of these — the r5 bench ladder's cross-rung
        leak class (VERDICT r5 weak #1)."""
        for entry in self._jit_cache.values():
            fns = entry.values() if isinstance(entry, dict) else (entry,)
            for fn in fns:
                if hasattr(fn, "clear"):
                    fn.clear()
        self._jit_cache.clear()
        self._nonblock_dev = None
        if self._quant:
            self._q_cache.clear()
        self._h2d.close()
        swapper, self.swapper = self.swapper, None
        if swapper is not None:
            try:
                swapper.synchronize_writes()
                swapper.synchronize_reads()
            except (OSError, RuntimeError) as e:
                logger.warning(f"param-stream close: AIO drain failed "
                               f"({e}); dropping buffers anyway")
            swapper.release(list(swapper._id_to_buffer))

    def reset_health_ema(self):
        """Post-checkpoint-load reset: the restored run must not inherit
        loss statistics of the steps it just discarded."""
        if self._spike_ema is not None:
            self._spike_ema.reset()

    @property
    def THROTTLE_EVERY(self):
        """Forward-loop sync cadence (layers); tighter = smaller in-flight
        upload window (host RAM) at the cost of more syncs — the
        max-params probe sets 2 via env to squeeze under the 125 GB
        host.  Read per-use so setting the env after import still works;
        clamped to >= 1 (0 would divide by zero in the layer loop)."""
        try:
            return max(1, int(os.environ.get("DS_TPU_STREAM_THROTTLE", "4")))
        except ValueError:
            logger.warning("DS_TPU_STREAM_THROTTLE is not an int; using 4")
            return 4

    @property
    def GC_AT_THROTTLE(self):
        return os.environ.get("DS_TPU_STREAM_GC", "0") == "1"

    def _throttle(self, l, x, proved_epoch=None):
        """Backpressure for the forward stream: without it the Python loop
        dispatches EVERY layer's upload before any compute finishes, and
        the runtime buffers up to the whole model's bytes in host RAM
        (observed: the 2.7B probe OOM'd a 125 GB host).  A tiny VALUE READ
        of the current activation every few layers bounds the in-flight
        window to ~THROTTLE_EVERY layers (``jax.block_until_ready`` does
        not actually wait on this remote-attached runtime — only a value
        read synchronizes)."""
        if (l + 1) % self.THROTTLE_EVERY == 0:
            np.asarray(jax.device_get(x[0, 0, 0]))
            # the value read above transitively proves every upload
            # consumed by layers <= l completed — recycle their staging
            # buffers (parked pairs never self-observe ready on this
            # runtime once their settle target is donated downstream).
            # proved_epoch was captured BEFORE the l+1 fetch dispatched,
            # so that fetch's pairs (settled, possibly deleted, DMA not
            # provably landed) stay parked until their own barrier.
            self._h2d.release_parked(proved_epoch)
            if self.GC_AT_THROTTLE:
                import gc
                gc.collect()      # drop cyclic refs pinning transfer state

    @staticmethod
    def _land_add(handle, lo, hi, flat):
        """Land a started chunked d2h and ACCUMULATE (+=) into the flat
        fp32 segment (upcasts 16-bit wire grads on the add)."""
        spans, parts = handle
        for (a, b), p in zip(spans, parts):
            seg = flat[lo + a:lo + b]
            seg += np.asarray(p, np.float32)

    @staticmethod
    def _host_global_norm(flat):
        # chunked np.dot: one pass, no temporary the size of the buffer
        total = 0.0
        step = 1 << 24
        for a in range(0, flat.shape[0], step):
            seg = flat[a:a + step]
            total += float(np.dot(seg, seg))
        return float(np.sqrt(total))

    # ------------------------------------------------------------ eval path
    def eval_loss(self, batch, rng):
        J = self._jits(deterministic=True)
        tokens, labels = self.sf["split_batch"](batch)
        tokens = jnp.asarray(tokens)
        labels = jnp.asarray(labels)
        rngs = J["layer_rngs"](rng)
        x = J["embed"](self._nonblock_dev, tokens, rng)
        self.prefetch_layer_nvme(0)
        p_next = self.fetch_layer(0)
        for l in range(self.L):
            p = p_next
            self.prefetch_layer_nvme(l + 1)
            x = J["block_fwd"](p, x, rngs[l],
                               jnp.asarray(self.local_flags[l]))
            ep_proved = self._h2d.dispatch_epoch
            p_next = self.fetch_layer(l + 1) if l + 1 < self.L else None
            self._throttle(l, x, ep_proved)
        return J["head_eval"](self._nonblock_dev, x, labels)

    # --------------------------------------------------------- checkpoints
    def full_params_host(self):
        """Model-tree (stacked) params from the host payload — numpy."""
        if self.nvme:
            tree = self._host_tree_from_master()
        else:
            tree = self.host.payload_tree()
        return from_stream_tree(tree, self.sf["stacked_key"])

    def _host_tree_from_master(self):
        # nvme mode has no RAM image; derive the compute-dtype tree from
        # the fp32 master (identical values to the on-disk payload)
        import jax.numpy as jnp
        master = self.host.master
        out16 = self.host.out_dtype
        leaves = []
        for off, s in zip(self.host.offsets, self.host.shapes):
            n = int(np.prod(s or (1,)))
            seg = master[off:off + n].reshape(s)
            if out16 == "bfloat16":
                seg = np.asarray(jnp.asarray(seg, jnp.bfloat16))
            elif out16 == "float16":
                seg = seg.astype(np.float16)
            leaves.append(seg)
        return self.host.treedef.unflatten(leaves)

    def reload_from_host(self):
        """After the engine restores the host master (checkpoint load),
        refresh the NVMe payload files and the device nonblock tree."""
        if self.nvme:
            self._flush_layers_to_nvme(range(self.L))
        self._upload_nonblock()
        if self._quant:
            self._payload_version += 1
