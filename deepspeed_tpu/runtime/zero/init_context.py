"""zero.Init / GatheredParameters — sharded construction & gathered access.

Parity: reference ``runtime/zero/partition_parameters.py`` —

- ``Init`` (:555): a context that intercepts ``nn.Module.__init__`` so every
  parameter is partitioned the moment it is created (a 100B model never
  materializes replicated).  TPU re-design: parameter *creation* is a pure
  ``init(rng)`` function, so interception becomes compilation — ``Init.
  initialize(model, rng)`` jits the init function with fsdp ``out_shardings``;
  XLA materializes every leaf directly as its shard on its device.  No hook
  machinery, same memory guarantee.
- ``GatheredParameters`` (:1529): gather the full values of (some) partitioned
  params for reading or in-place modification, re-partitioning on exit.  Here
  the gather is a host fetch (numpy copies, writable) and the re-partition is
  a ``device_put`` back to the original shardings on exit.
- ``register_external_parameter`` (:115): the reference needs this because its
  hooks only see the owning module's own params; with whole-pytree sharding
  there is nothing to register — kept as a no-op for API compatibility.
"""

import contextlib

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from . import partition as zpart
from ...utils.logging import logger


class Init:
    """Construct model parameters directly sharded over the fsdp axis.

    Usage (reference: ``with deepspeed.zero.Init(): model = MyModel()``)::

        ctx = zero.Init(mesh=mesh)
        params = ctx.initialize(model, jax.random.PRNGKey(0))

    or as a context manager wrapping explicit init calls::

        with zero.Init(mesh=mesh) as zinit:
            params = zinit.initialize(model, rng)
    """

    def __init__(self, module=None, data_parallel_group=None, mem_efficient_linear=True,
                 remote_device=None, pin_memory=False, config_dict_or_path=None,
                 config=None, enabled=True, dtype=None, mpu=None, mesh=None,
                 persistence_threshold=0):
        from ...parallel import mesh as M
        if mesh is None:
            gm = M.get_global_mesh()
            mesh = gm.mesh if gm is not None else M.make_mesh()
        self.mesh = mesh
        self.enabled = enabled
        self.dtype = dtype
        self.persistence_threshold = persistence_threshold
        self.remote_device = remote_device  # "cpu"/"nvme" → host-resident init
        self._mesh_ctx = M.MeshContext(self.mesh)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def specs_for(self, params_shape_tree, tp_specs=None):
        """fsdp PartitionSpecs for an init shape tree (stage-3 placement)."""
        return zpart.param_specs(
            params_shape_tree, stage=3, fsdp_size=self._mesh_ctx.fsdp_size,
            persistence_threshold=self.persistence_threshold, tp_specs=tp_specs)

    def initialize(self, model_or_init_fn, rng):
        """Run ``init`` under jit with sharded outputs: each param leaf
        materializes as its shard — the full model never exists replicated
        (the reference's whole ``InsertPostInitMethodToModuleSubClasses``
        apparatus, done by the compiler)."""
        init_fn = (model_or_init_fn.init if hasattr(model_or_init_fn, "init")
                   else model_or_init_fn)
        if not self.enabled:
            return init_fn(rng)
        tp_specs = getattr(model_or_init_fn, "partition_specs", None)
        shapes = jax.eval_shape(init_fn, rng)
        if callable(tp_specs):
            # spec fns usually accept the (shape) pytree or nothing
            try:
                tp_specs = tp_specs(shapes)
            except TypeError:
                tp_specs = tp_specs()
        specs = self.specs_for(shapes, tp_specs=tp_specs)
        shardings = zpart.to_named(specs, self.mesh)
        if self.remote_device in ("cpu", "nvme"):
            # host-resident construction (ZeRO-Infinity remote_device): init
            # on host, never touching device HBM
            with jax.default_device(jax.local_devices(backend="cpu")[0]):
                params = jax.jit(init_fn)(rng)
            return jax.tree_util.tree_map(np.asarray, params)
        with jax.set_mesh(self.mesh):
            return jax.jit(init_fn, out_shardings=shardings)(rng)


class GatheredParameters:
    """Gather shards to writable host arrays; re-shard on exit.

    Reference semantics (``partition_parameters.py:1529``): inside the
    context the full parameter values are visible; with ``modifier_rank``
    set, in-place modifications are re-partitioned on exit.

    Usage::

        gp = zero.GatheredParameters(params, mesh=mesh)
        with gp as full:           # full: pytree of writable numpy arrays
            full["wte"][:] = 0.0
        params = gp.result         # re-sharded device pytree
    """

    def __init__(self, params, modifier_rank=0, fwd_module=None, enabled=True,
                 mesh=None):
        self.params = params
        self.enabled = enabled
        self.modifier_rank = modifier_rank
        self.mesh = mesh
        self.result = params
        self._shardings = jax.tree_util.tree_map(
            lambda x: getattr(x, "sharding", None), params)

    def __enter__(self):
        if not self.enabled:
            return self.params
        self._host = jax.tree_util.tree_map(
            lambda x: np.array(x), self.params)  # gathered + writable copies
        return self._host

    def __exit__(self, exc_type, exc, tb):
        if not self.enabled or exc_type is not None:
            return False
        if self.modifier_rank is None:
            # read-only context: nothing to write back
            self.result = self.params
            return False
        def put(h, x, sh):
            arr = np.asarray(h, dtype=np.asarray(x).dtype)
            return jax.device_put(arr, sh) if sh is not None else arr
        self.result = jax.tree_util.tree_map(
            put, self._host, self.params, self._shardings)
        return False


def register_external_parameter(module, parameter):
    """No-op (reference ``partition_parameters.py:115``): with whole-pytree
    sharding every parameter is visible to the step function; there is no
    per-module hook scope to escape."""
    return parameter


def unregister_external_parameter(module, parameter):
    return parameter
