"""Collective router: per-route compression policy for the ZeRO wire.

One object owns every "should this tensor move compressed, and how"
decision (ZeRO++-style policy, arXiv:2306.10209):

- **qwZ** (``gather_params``): ZeRO-3 parameter all-gathers move per-block
  int8 (or packed int4) + fp32 scales instead of the compute dtype;
- **qgZ** (``reduce_grads``): the gradient reduction consumes per-rank
  PARTIAL gradients, quantizes them with persistent per-shard error
  feedback, and lands the reduced gradient on the ZeRO-2/3 sharding —
  two-level (intra full-width / inter quantized) when the mesh and leaf
  shape allow it;
- **1-bit transport** (``onebit_comm``): the error-compensated 1-bit
  allreduce (``comm/compressed.py``) wired onto a real mesh axis via
  ``shard_map`` for the 1-bit optimizers — policy-independent (the 1-bit
  algorithm is the optimizer's own semantics; the router only provides
  the wire).

Per-leaf policy: a leaf compresses iff its route is enabled, it is at
least ``min_tensor_bytes``, and its path matches none of ``excluded``
(norm/bias-style leaves train badly through a lossy wire and are tiny
anyway).  Leaves that do not fit a scheme (odd int4 dims, no axis
divisible by the dp world for the two-level reduce) fall back to the
full-width wire — compression must never be a correctness cliff.

The router's ``describe()`` dict is part of the compile-cache key: the
compression policy is part of the executable's identity.
"""

from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from . import quantized as Q
from .moe_wire import MoEWire
from ...parallel import mesh as M
from ...utils.logging import logger, warning_once

EF_DTYPE = jnp.bfloat16      # error-feedback storage (docs/comms-compression.md)


def _path_str(path) -> str:
    parts = []
    for e in path:
        for attr in ("key", "name", "idx"):
            if hasattr(e, attr):
                parts.append(str(getattr(e, attr)))
                break
        else:
            parts.append(str(e))
    return "/".join(parts).lower()


def _spec_entries(spec: Optional[P], ndim: int):
    ent = tuple(spec) if spec is not None else ()
    return ent + (None,) * (ndim - len(ent))


def _entry_axes(entry):
    if entry is None:
        return ()
    return (entry,) if isinstance(entry, str) else tuple(entry)


class CollectiveRouter:
    def __init__(self, policy, mesh, mesh_ctx, zero_stage: int, *,
                 supports_zero_routes: bool = True):
        self.policy = policy                  # DeepSpeedCommsCompressionConfig
        self.mesh = mesh
        self.mesh_ctx = mesh_ctx
        self.zero_stage = int(zero_stage)
        self.dp_world = mesh_ctx.dp_world_size
        self.fsdp = mesh_ctx.fsdp_size
        enabled = bool(policy is not None and policy.enabled)
        route = f"z{min(max(zero_stage, 0), 3)}"
        self._zero_route_on = (enabled and supports_zero_routes
                               and route in policy.routes)
        if enabled and not supports_zero_routes:
            # fires ONCE per process, at ANY stage: an engine that
            # schedules its own collectives opts every compressed route
            # out, and the operator who enabled the policy must hear it
            # even at zero_stage 0 (where the old stage-gated warning
            # stayed silent)
            warning_once(
                "comms_compression: this engine's wire does not support "
                "compression (pipeline schedules its own collectives); "
                "gradients/params/expert dispatch stay full-width")
        self.weights_active = (self._zero_route_on and zero_stage >= 3
                               and self.fsdp > 1
                               and policy.weights_bits is not None)
        self.grads_active = (self._zero_route_on and self.dp_world > 1
                             and policy.grads_bits is not None)
        # moe route: the quantized expert-parallel dispatch/combine wire
        # (moe_wire.py) — active only when there IS an expert wire to
        # compress (expert axis extent > 1)
        self.moe_active = (enabled and supports_zero_routes
                           and "moe" in (policy.routes if policy else ())
                           and getattr(policy, "moe_bits", None) is not None
                           and mesh_ctx.expert_size > 1)
        # batch axes actually present on the mesh; fsdp-major ordering so
        # the two-level regather (mid -> out) is a pure outer-axis move
        self.batch_axes = tuple(M.BATCH_AXES)
        self.mid_axes = ("fsdp",) + tuple(a for a in M.BATCH_AXES
                                          if a != "fsdp")

    # ----------------------------------------------------------- plumbing
    def _ns(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def _constrain_leaf(self, x, spec: Optional[P]):
        return jax.lax.with_sharding_constraint(
            x, self._ns(spec if spec is not None else P()))

    def _excluded(self, path_str: str) -> bool:
        return any(pat in path_str for pat in self.policy.excluded)

    def _big_enough(self, shape, itemsize) -> bool:
        return (int(np.prod(shape or (1,))) * itemsize
                >= self.policy.min_tensor_bytes)

    # -------------------------------------------------------- qwZ weights
    def _weight_plan(self, path_str, shape, itemsize, spec) -> Optional[int]:
        """bits for this parameter's gather, or None (full width)."""
        if not self.weights_active or not shape or shape[-1] == 0:
            return None
        # exactly one sharded dim, and it must be plain fsdp (any
        # tensor-parallel composition — composed entry OR a separate
        # tp-sharded dim — keeps the full-width wire: the explicit fsdp
        # all-gather would not reassemble it and the full-manual region
        # would silently treat the tp dim as replicated)
        ent = _spec_entries(spec, len(shape))
        sharded = [i for i, e in enumerate(ent) if e is not None]
        if len(sharded) != 1 or ent[sharded[0]] not in ("fsdp", ("fsdp",)):
            return None               # replicated (persistence threshold)
        dims = sharded
        if not self._big_enough(shape, itemsize) or self._excluded(path_str):
            return None
        bits = int(self.policy.weights_bits)
        if bits == 4:
            if Q.pick_block(shape[-1], self.policy.block_size,
                            even=True) % 2 != 0:
                bits = 8              # no even block: int4 cannot pack
            elif dims[0] == len(shape) - 1 and \
                    (shape[-1] // 2) % self.fsdp != 0:
                bits = 8              # packed last dim no longer shards
        return bits

    def gather_params(self, params, specs):
        """The ZeRO-3 parameter wire: quantized all-gather for planned
        leaves, the plain sharding constraint for everything else.  With
        the weights route inactive this IS ``zpart.constrain``."""
        mesh = self.mesh

        def one(path, leaf, spec):
            bits = self._weight_plan(_path_str(path), np.shape(leaf),
                                     np.dtype(leaf.dtype).itemsize, spec)
            if bits is None:
                return self._constrain_leaf(leaf, spec)
            return Q.gather_quantized(
                leaf, mesh, spec, block_size=self.policy.block_size,
                bits=bits, out_dtype=leaf.dtype, ste=True)

        return jax.tree_util.tree_map_with_path(one, params, specs)

    # --------------------------------------------------------- qgZ grads
    def _grad_block(self, shape, dim) -> int:
        """Effective quantization block for a leaf scattered D-ways on
        ``dim``: when that is the LAST dim, blocks must tile the
        per-device chunk so the level-1 scale side-channel splits along
        (a smaller block, never a full-width fallback)."""
        K = shape[-1]
        if dim < len(shape) - 1:
            return Q.pick_block(K, self.policy.block_size)
        return Q.pick_block(K // self.dp_world, self.policy.block_size)

    def _grad_plan(self, path_str, shape, out_spec):
        """(bits, chunk_dim, lvl2_axes, block) for this gradient's
        reduction — chunk_dim None means the single-level constraint
        reshard (``hierarchical: false``) — or None (full-width)."""
        if not self.grads_active or not shape or shape[-1] == 0:
            return None
        if not self._big_enough(shape, 4) or self._excluded(path_str):
            return None
        bits = int(self.policy.grads_bits)
        D = self.dp_world
        ent = _spec_entries(out_spec, len(shape))
        sharded = [i for i, e in enumerate(ent) if e is not None]
        if len(sharded) > 1 or (sharded and ent[sharded[0]] not in
                                ("fsdp", ("fsdp",))):
            return None               # tensor-parallel composition: full width
        if not self.policy.hierarchical:
            return (bits, None, (),
                    Q.pick_block(shape[-1], self.policy.block_size))
        # two-level: the scatter axis must be divisible by the dp world
        # AND be the axis the output sharding owns (level 2 is then a
        # pure outer-axis regather landing exactly on out_spec);
        # ZeRO-1's replicated output frees the choice to any axis.
        if sharded:
            a = sharded[0]
            if shape[a] % D != 0:
                return None
            lvl2 = tuple(x for x in self.mid_axes if x != "fsdp")
            return (bits, a, lvl2, self._grad_block(shape, a))
        cands = [i for i in range(len(shape)) if shape[i] % D == 0]
        if not cands:
            return None
        a = max(cands, key=lambda i: shape[i])
        # regather over EVERY dp axis (replicated ZeRO-1 gradients)
        return (bits, a, self.mid_axes, self._grad_block(shape, a))

    def init_error_feedback(self, base_like, out_specs):
        """Persistent per-shard error-feedback state: one ``(D, *shape)``
        buffer (bf16, axis 0 sharded over the batch axes) per gradient
        leaf the policy compresses; a ``(1,)`` placeholder otherwise.
        Lives in ``TrainState.comm_error`` — donated each step,
        checkpointed, rewind-safe (docs/comms-compression.md)."""
        if not self.grads_active:
            return None
        D = self.dp_world
        lead = self._ns(P(self.batch_axes))
        repl = self._ns(P())
        flat, treedef = jax.tree_util.tree_flatten(base_like)
        paths = [p for p, _ in
                 jax.tree_util.tree_flatten_with_path(base_like)[0]]
        specs = treedef.flatten_up_to(out_specs)

        def one(path, leaf, spec):
            if self._grad_plan(_path_str(path), np.shape(leaf),
                               spec) is None:
                return jax.device_put(jnp.zeros((1,), EF_DTYPE), repl)
            return jax.device_put(
                jnp.zeros((D,) + tuple(np.shape(leaf)), EF_DTYPE), lead)

        return treedef.unflatten(
            [one(p, l, s) for p, l, s in zip(paths, flat, specs)])

    def reduce_grads(self, partials, ef, out_specs):
        """The gradient wire: partial ``(D, *shape)`` grads → reduced
        grads on the ZeRO sharding.  Returns ``(grads, new_ef)``."""
        mesh = self.mesh

        def one(path, pg, e, spec):
            plan = self._grad_plan(_path_str(path), pg.shape[1:], spec)
            if plan is None:
                red = jnp.sum(pg.astype(jnp.float32), axis=0)
                return self._constrain_leaf(red, spec), e
            bits, chunk_dim, lvl2, block = plan
            red, new_e = Q.reduce_partials_quantized(
                pg, e, mesh, spec if spec is not None else P(),
                batch_axes=self.batch_axes,
                block_size=block, bits=bits,
                chunk_dim=chunk_dim, lvl2_axes=lvl2,
                out_dtype=jnp.float32)
            return red, (new_e if new_e is not None else e)

        flat_p, treedef = jax.tree_util.tree_flatten(partials)
        paths = [p for p, _ in
                 jax.tree_util.tree_flatten_with_path(partials)[0]]
        flat_e = treedef.flatten_up_to(ef)
        flat_s = treedef.flatten_up_to(out_specs)
        outs = [one(pp, pg, e, s) for pp, pg, e, s in
                zip(paths, flat_p, flat_e, flat_s)]
        grads = treedef.unflatten([o[0] for o in outs])
        new_ef = treedef.unflatten([o[1] for o in outs])
        return grads, new_ef

    # ----------------------------------------------------- moe dispatch
    def moe_wire(self) -> Optional[MoEWire]:
        """The quantized expert-parallel dispatch wire for this policy,
        or None (full-width constraint dispatch).  The engine installs
        the returned wire via ``moe_wire.set_active`` so ``moe/layer.py``
        finds it at trace time (docs/comms-compression.md)."""
        if not self.moe_active:
            return None
        return MoEWire(self.mesh, bits=int(self.policy.moe_bits),
                       block_size=int(self.policy.moe_block_size),
                       hierarchical=bool(self.policy.hierarchical))

    # ------------------------------------------------ budget + reporting
    def describe(self) -> dict:
        """Stable policy fingerprint (compile-cache key, ds_report)."""
        pol = self.policy
        return {
            "enabled": bool(pol is not None and pol.enabled),
            "weights_active": self.weights_active,
            "grads_active": self.grads_active,
            "moe_active": self.moe_active,
            "weights_bits": getattr(pol, "weights_bits", None),
            "grads_bits": getattr(pol, "grads_bits", None),
            "moe_bits": getattr(pol, "moe_bits", None),
            "moe_block_size": getattr(pol, "moe_block_size", None),
            "block_size": getattr(pol, "block_size", None),
            "hierarchical": getattr(pol, "hierarchical", None),
            "min_tensor_bytes": getattr(pol, "min_tensor_bytes", None),
            "excluded": tuple(getattr(pol, "excluded", ())),
            "routes": tuple(getattr(pol, "routes", ())),
        }

    def expected_wire_bytes(self, params, param_specs, grad_specs,
                            compute_itemsize: int) -> dict:
        """Approximate per-kind wire ceilings for the compressed step's
        static census (one count per program site; loops count once —
        the same accounting ``analysis/comms.py`` uses).  Components:

        - all_gather: quantized param payloads + full-width leaves +
          scale/mask side-channels + the level-2 grad regathers;
        - all_to_all: the level-1 quantized partial-grad exchange;
        - all_reduce: leaves whose gradients stay full-width (excluded /
          unplannable) — the partitioner reduces those as f32 all-reduce
          (this bucket also hosts the MoE wire's outer int8 psums, added
          by :meth:`comms_budget`).
        """
        ag = ata = ar = 0
        leaves = jax.tree_util.tree_flatten_with_path(params)[0]
        p_specs = jax.tree_util.tree_leaves(
            param_specs, is_leaf=lambda x: isinstance(x, P))
        g_specs = jax.tree_util.tree_leaves(
            grad_specs, is_leaf=lambda x: isinstance(x, P))
        for (path, leaf), psp, gsp in zip(leaves, p_specs, g_specs):
            shape = np.shape(leaf)
            n = int(np.prod(shape or (1,)))
            ps = _path_str(path)
            wbits = self._weight_plan(ps, shape, compute_itemsize, psp)
            if self.zero_stage >= 3:
                if wbits is not None:
                    B = Q.pick_block(shape[-1], self.policy.block_size,
                                     even=(wbits == 4))
                    ag += n * wbits // 8 + 3 * 4 * (n // max(B, 1))
                else:
                    ag += n * compute_itemsize
            gplan = self._grad_plan(ps, shape, gsp)
            if gplan is None:
                # full-width reduction: all-reduce/reduce-scatter of f32
                ar += 4 * n
            else:
                bits, chunk_dim, lvl2, B = gplan
                nb = n // max(B, 1)
                if chunk_dim is None:
                    # single-level: every chunk owner receives all D slices
                    ag += self.dp_world * n * bits // 8 + 12 * nb
                else:
                    O = int(np.prod([M.mesh_axis_size(self.mesh, x)
                                     for x in lvl2]))
                    ata += n * bits // 8 + 4 * nb        # q + scales
                    ag += ((n * bits // 8) * O // self.dp_world
                           + 4 * nb * O // self.dp_world + 4 * nb)
        return {"all_gather": ag, "all_to_all": ata, "all_reduce": ar}

    def comms_budget(self, params, param_specs, grad_specs,
                     compute_itemsize: int, *, slack: float = 1.6,
                     floor: int = 1 << 16, moe_wire=None):
        """A :class:`analysis.comms.CommsBudget` for the compressed step:
        per-kind ceilings at ``slack`` over the expected quantized wire
        (+ a small floor for loss/norm reductions).  Declared tight
        enough that the FULL-WIDTH step violates it — the budget is an
        accounting statement, not a formality.

        ``moe_wire``: the engine's active :class:`MoEWire`; its
        trace-recorded expert-route expectation (int8 all_to_all +
        outer psum + combine all_gather, both directions) joins the
        ceilings — available after the first cold trace."""
        from ...analysis.comms import CommsBudget
        exp = self.expected_wire_bytes(params, param_specs, grad_specs,
                                       compute_itemsize)
        if moe_wire is not None:
            for kind, b in moe_wire.expected_wire_bytes().items():
                exp[kind] = exp.get(kind, 0) + b
        per_kind = {
            "all_gather": {"max_bytes": int(exp["all_gather"] * slack)
                           + floor},
            "all_to_all": {"max_bytes": int(exp["all_to_all"] * slack)
                           + floor},
            # full-width fallback reductions + the moe wire's outer int8
            # psums; the 4x floor also absorbs loss/norm scalar psums
            "all_reduce": {"max_bytes": int(exp["all_reduce"] * slack)
                           + 4 * floor},
        }
        total = int(sum(exp.values()) * slack) + 8 * floor
        return CommsBudget(per_kind=per_kind, total_max_bytes=total)

    # -------------------------------------------------- 1-bit transport
    def onebit_comm(self):
        """A transport for the 1-bit optimizers' compressed allreduce:
        per-rank error feedback inside ``shard_map`` on the (single)
        data-parallel mesh axis.  Returns None when the mesh gives the
        compression nothing to do (dp world of 1) or the dp extent spans
        multiple named axes (the two-phase wire wants one ring).
        Policy-independent: 1-bit is the optimizer's own algorithm."""
        live = [a for a in M.BATCH_AXES
                if M.mesh_axis_size(self.mesh, a) > 1]
        if len(live) != 1:
            if len(live) > 1:
                logger.warning(
                    "1-bit allreduce: dp world spans multiple mesh axes "
                    f"{live}; falling back to the local (no-wire) path")
            return None
        return OnebitTransport(self.mesh, live[0])


class OnebitTransport:
    """Engine-provided wire for ``fp16/onebit`` optimizers: runs
    ``compressed_allreduce`` with true per-rank error buffers (leading
    ``(D, ...)`` axis sharded over the dp axis) inside ``shard_map``."""

    def __init__(self, mesh, axis: str):
        self.mesh = mesh
        self.axis = axis
        self.world_size = M.mesh_axis_size(mesh, axis)

    def init_error_buffers(self, params):
        from .compressed import padded_size, server_chunk_size
        D = self.world_size

        def werr(p):
            return jnp.zeros(
                (D, padded_size(int(np.prod(np.shape(p))), D)), jnp.float32)

        def serr(p):
            return jnp.zeros(
                (D, server_chunk_size(int(np.prod(np.shape(p))), D)),
                jnp.float32)

        return (jax.tree_util.tree_map(werr, params),
                jax.tree_util.tree_map(serr, params))

    def __call__(self, x, werr, serr):
        """x: replicated tensor; werr/serr: (D, ...) per-rank buffers.
        Returns (allreduced x, new werr, new serr)."""
        from .compressed import compressed_allreduce
        axis = self.axis
        D = self.world_size

        def per_rank(m, we, se):
            out, we_n, se_n = compressed_allreduce(
                m, we[0], se[0], axis_name=axis, world_size=D)
            return out, we_n[None], se_n[None]

        fn = jax.shard_map(per_rank, mesh=self.mesh,
                           in_specs=(P(), P(axis), P(axis)),
                           out_specs=(P(), P(axis), P(axis)),
                           check_vma=False)
        return fn(x, werr, serr)
