"""Quantized ZeRO collectives: block quantization + the SPMD wire ops.

Parity role: ZeRO++ (arXiv:2306.10209) — qwZ (quantized weight
all-gather), qgZ (block-quantized gradient reduce-scatter) and the
hierarchical two-level decomposition; "Scaling LLM Training on Frontier
with Low-Bandwidth Partitioning" (arXiv:2501.04266) confirms shrinking
bytes-on-wire is THE lever on slow interconnects.  The reference ships
these as custom CUDA kernels + hand-scheduled NCCL; this runtime's ZeRO
wire is XLA's SPMD partitioner (SURVEY.md §7 "sharding, not hooks"), so
the quantized collectives are spelled as *sharding-constraint-pinned
quantize → reshard → dequantize* sequences:

- the tensor is pinned to its sharded placement, quantized SHARD-LOCALLY
  (block scales along the last axis), and the int8 payload is pinned to
  the target placement — the partitioner then has no choice but to move
  the int8 bytes (plus the tiny fp32 scales) on the wire;
- dequantization happens after the reshard, in the compute dtype.

Everything here is pure jnp traced into the jitted step: no host
callbacks (DSTPU201 stays clean), donation-compatible, and visible to
the DSTPU203 comms census as u8/s8 collectives (the census classifies
those as quantized wire traffic — ``analysis/comms.py``).

Gradient flow: the weight gather is wrapped in a straight-through
estimator (``custom_vjp`` with identity cotangent) — differentiating
through ``convert_element_type(f32→s8)`` would silently return zero
gradients, and re-touching the full-width tensor in the forward (the
``x + stop_grad(deq - x)`` spelling) would re-gather it full-width,
destroying the wire win.
"""
# dstpu: disable-file=DSTPU102 (reviewed: this IS a comms-layer module --
# the quantized wire schedules its own collectives by design, exactly
# like the 1-bit protocol in compressed.py)

from typing import Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


def pick_block(n: int, block_size: int, *, even: bool = False) -> int:
    """Largest divisor of ``n`` that is <= ``block_size`` (>= 1).

    Block scales must tile the axis exactly — padding a *sharded* array
    would itself insert collectives.  ``even`` additionally requires an
    even block (int4 packs two values per byte within a block)."""
    n = int(n)
    if n <= 0:
        return 1
    b = min(int(block_size), n)
    while b > 1:
        if n % b == 0 and (not even or b % 2 == 0):
            return b
        b -= 1
    return 1


def _sanitize(x):
    """Zero out non-finite values so the int cast is defined.  Callers
    carry a separate pre-quantization non-finite flag (the health
    sentinels / fp16 overflow scan run on the UN-quantized values), so a
    poisoned step is skipped rather than trained on laundered zeros."""
    return jnp.where(jnp.isfinite(x), x, jnp.zeros_like(x))


def quantize_blockwise(x, *, block_size: int = 1024, bits: int = 8,
                       zero_scale: float = 1.0):
    """Symmetric per-block quantization along the LAST axis.

    Returns ``(q, scales)``:
      bits=8 → ``q`` int8, same shape as ``x``;
      bits=4 → ``q`` uint8 of shape ``(..., K//2)`` (two nibbles/byte,
               packed within blocks so shard alignment is preserved).
    ``scales`` is fp32 of shape ``(..., K//B)`` with ``B`` the largest
    divisor of K <= block_size.  Guards: all-zero blocks quantize with
    scale ``zero_scale`` (no 0/0; the default 1 keeps dequant exact,
    the MoE wire passes 0 so disjoint-row partial buffers SUM exactly
    across devices — ``moe_wire.py``), non-finite inputs are zeroed
    (see ``_sanitize``), zero-size tensors round-trip as empty.
    """
    assert bits in (4, 8), f"bits must be 4 or 8, got {bits}"
    assert np.ndim(x) >= 1, "quantize_blockwise needs ndim >= 1"
    K = x.shape[-1]
    B = pick_block(K, block_size, even=(bits == 4))
    if x.size == 0 or K == 0:
        qdt = jnp.int8 if bits == 8 else jnp.uint8
        qshape = x.shape if bits == 8 else x.shape[:-1] + (K // 2,)
        return (jnp.zeros(qshape, qdt),
                jnp.zeros(x.shape[:-1] + (K // B if K else 0,), jnp.float32))
    if bits == 4 and B % 2 != 0:
        raise ValueError(
            f"int4 quantization needs an even block; last dim {K} has no "
            "even divisor <= block_size (use bits=8 for this tensor)")
    nb = K // B
    xb = _sanitize(x.astype(jnp.float32)).reshape(x.shape[:-1] + (nb, B))
    amax = jnp.max(jnp.abs(xb), axis=-1)
    qmax = 127.0 if bits == 8 else 7.0
    scales = jnp.where(amax > 0, amax / qmax,
                       jnp.full_like(amax, jnp.float32(zero_scale)))
    # divide by a safe scale: an all-zero block (scale possibly 0) must
    # yield q=0, not 0/0 NaNs cast to int
    safe = jnp.where(scales > 0, scales, jnp.ones_like(scales))
    q = jnp.clip(jnp.round(xb / safe[..., None]), -qmax, qmax)
    if bits == 8:
        return q.astype(jnp.int8).reshape(x.shape), scales
    # int4: pack value pairs into one byte, pairs never cross a block
    qi = (q + 8.0).astype(jnp.uint8).reshape(x.shape[:-1] + (K // 2, 2))
    packed = qi[..., 0] | (qi[..., 1] << 4)
    return packed, scales


def dequantize_blockwise(q, scales, *, bits: int = 8, out_dtype=jnp.float32):
    """Inverse of :func:`quantize_blockwise` (block size inferred from
    the q/scales shapes)."""
    assert bits in (4, 8)
    if q.size == 0:
        K = q.shape[-1] * (2 if bits == 4 else 1)
        return jnp.zeros(q.shape[:-1] + (K,), out_dtype)
    if bits == 4:
        lo = (q & 0xF).astype(jnp.int32) - 8
        hi = (q >> 4).astype(jnp.int32) - 8
        vals = jnp.stack([lo, hi], axis=-1).reshape(q.shape[:-1]
                                                    + (q.shape[-1] * 2,))
    else:
        vals = q.astype(jnp.int32)
    K = vals.shape[-1]
    nb = scales.shape[-1]
    B = K // nb
    x = vals.astype(jnp.float32).reshape(vals.shape[:-1] + (nb, B))
    x = x * scales[..., None]
    return x.reshape(vals.shape[:-1] + (K,)).astype(out_dtype)


# --------------------------------------------------------------- numpy twins
def quantize_flat_np(flat, *, block_size: int = 1024, bits: int = 8):
    """Host-side quantizer for the ``param_stream`` h2d wire: a FLAT
    numpy array padded up to a block multiple (device side slices leaves
    by offset, so the pad tail is never read).  Returns ``(q, scales)``
    with ``q`` uint8 (int4 packed / int8 two's-complement bytes)."""
    assert bits in (4, 8)
    flat = np.asarray(flat)
    n = flat.shape[0]
    B = int(block_size)
    if bits == 4:
        assert B % 2 == 0, "int4 needs an even block_size"
    npad = ((n + B - 1) // B) * B
    x = np.zeros((npad,), np.float32)
    x[:n] = flat.astype(np.float32, copy=False)
    np.nan_to_num(x, copy=False, nan=0.0, posinf=0.0, neginf=0.0)
    xb = x.reshape(-1, B)
    amax = np.max(np.abs(xb), axis=1)
    qmax = 127.0 if bits == 8 else 7.0
    scales = np.where(amax > 0, amax / qmax, 1.0).astype(np.float32)
    q = np.clip(np.round(xb / scales[:, None]), -qmax, qmax)
    if bits == 8:
        return q.astype(np.int8).reshape(-1).view(np.uint8), scales
    qi = (q + 8.0).astype(np.uint8).reshape(-1, 2)
    return (qi[:, 0] | (qi[:, 1] << 4)), scales


def dequantize_flat_jnp(q, scales, *, bits: int = 8, out_dtype=jnp.float32):
    """Device-side inverse of :func:`quantize_flat_np` for one flat
    segment (or one upload chunk whose element count is a block
    multiple; ``scales`` must be the matching block slice)."""
    if bits == 4:
        lo = (q & 0xF).astype(jnp.int32) - 8
        hi = (q >> 4).astype(jnp.int32) - 8
        vals = jnp.stack([lo, hi], axis=-1).reshape(-1)
    else:
        vals = q.view(jnp.int8).astype(jnp.int32)
    B = vals.shape[0] // scales.shape[0]
    x = vals.astype(jnp.float32).reshape(-1, B) * scales[:, None]
    return x.reshape(-1).astype(out_dtype)


# ------------------------------------------------------------- SPMD wire ops
def _ns(mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def _pin(x, mesh, spec: P):
    return jax.lax.with_sharding_constraint(x, _ns(mesh, spec))


def _spec_fits(shape, spec: P, mesh) -> bool:
    """True when every sharded dim of ``shape`` divides its axis extents
    (a reshaped/packed tensor may no longer fit the original spec)."""
    for dim, entry in zip(shape, tuple(spec)):
        if entry is None:
            continue
        names = (entry,) if isinstance(entry, str) else tuple(entry)
        ext = int(np.prod([mesh.shape.get(a, 1) for a in names]))
        if ext > 1 and dim % ext != 0:
            return False
    return True


def _sharded_dim(spec: P, ndim: int, axis: str = "fsdp"):
    """Index of the (single) dim ``spec`` shards over ``axis``, or None."""
    ent = tuple(spec) + (None,) * (ndim - len(tuple(spec)))
    dims = [i for i, e in enumerate(ent)
            if e is not None and axis in
            ((e,) if isinstance(e, str) else tuple(e))]
    return dims[0] if len(dims) == 1 else None


def gather_quantized(x, mesh, shard_spec: P, *, block_size: int = 1024,
                     bits: int = 8, out_dtype=jnp.bfloat16,
                     ste: bool = True):
    """qwZ leaf op: quantize the local shard, move int8 (+ fp32 scales)
    on the all-gather wire, dequantize to the compute dtype.

    ``x`` carries sharding ``shard_spec`` (the fsdp placement from
    ``zero/partition.py``); the result is replicated in ``out_dtype``.
    Quantization math runs SPMD (shard-local by sharding propagation),
    but the gather itself is an EXPLICIT ``lax.all_gather`` of the int8
    payload inside a ``shard_map`` region — a sharding-constraint-only
    spelling leaves the partitioner free to sink the gather past the
    dequantize (observed with int4 packing: it re-materialized the
    f32 value and gathered THAT, silently un-compressing the wire).

    With ``ste`` the op is wrapped in a straight-through estimator so
    gradients w.r.t. ``x`` flow as identity (see module docstring)."""
    a = _sharded_dim(shard_spec, np.ndim(x))
    assert a is not None, "gather_quantized needs a single fsdp-sharded dim"

    def value(xv):
        xv = _pin(xv, mesh, shard_spec)
        q, s = quantize_blockwise(xv, block_size=block_size, bits=bits)
        q = _pin(q, mesh, shard_spec)        # always valid: packing (int4)
        # halves the LAST dim, sharding rides dim `a` (see _weight_plan)
        s_spec = shard_spec if _spec_fits(s.shape, shard_spec, mesh) \
            else P()
        s = _pin(s, mesh, s_spec)
        s_manual = tuple(s_spec) != ()

        def body(q_l, s_l):
            qf = jax.lax.all_gather(q_l, "fsdp", axis=a, tiled=True)
            sf = (jax.lax.all_gather(s_l, "fsdp", axis=a, tiled=True)
                  if s_manual else s_l)
            return dequantize_blockwise(qf, sf, bits=bits,
                                        out_dtype=out_dtype)

        return jax.shard_map(body, mesh=mesh, in_specs=(shard_spec, s_spec),
                             out_specs=P(), check_vma=False)(q, s)

    if not ste:
        return value(x)

    @jax.custom_vjp
    def ste_gather(xv):
        return value(xv)

    def fwd(xv):
        return value(xv), None

    def bwd(_, g):
        # identity cotangent in x's dtype: downstream constraints decide
        # the (full-width or qgZ-quantized) gradient wire
        return (g.astype(x.dtype),)

    ste_gather.defvjp(fwd, bwd)
    return ste_gather(x)


def reduce_partials_quantized(pg, ef, mesh, out_spec: P, *,
                              batch_axes: Sequence[str],
                              block_size: int = 1024, bits: int = 8,
                              chunk_dim: Optional[int] = None,
                              lvl2_axes: Sequence[str] = (),
                              out_dtype=jnp.float32) -> Tuple:
    """qgZ leaf op: error-compensated block-quantized reduction of
    per-rank partial gradients.

    ``pg``: ``(D, *shape)`` partial grads, axis 0 sharded over
    ``batch_axes`` (one slice per data-parallel rank).  ``ef``: the
    persistent per-shard error-feedback buffer (same shape, any float
    dtype) or None.  ``out_spec`` is the PartitionSpec of the REDUCED
    gradient (``zero/partition.py grad_specs``).

    **Two-level** (``chunk_dim`` given — the hierarchical default): runs
    inside a ``shard_map`` region with EXPLICIT collectives (the
    constraint-resharding spelling left the partitioner free to lower
    the exchange as alltoall+permute double-hops and to gather scale
    side-channels replicated):

      level 1: quantize the compensated local slice, ``all_to_all`` the
      int8 payload + fp32 scales over the fsdp-MAJOR dp axes splitting
      ``chunk_dim`` into D per-device chunks, dequantize + sum — each
      device receives exactly 1 byte/element and owns its reduced chunk;

      level 2: re-quantize the reduced chunk and ``all_gather`` it over
      ``lvl2_axes`` (the outer, DCN-crossing axes — or every dp axis for
      the ZeRO-1 replicated-gradient layout), landing on ``out_spec``.
      Only quantized traffic crosses the outer hop; the second-stage
      quantization error is not error-fed (it compresses the
      already-reduced gradient once; ZeRO++ does the same).

    **Single-level** (``chunk_dim=None``, ``hierarchical: false``): one
    constraint-based reshard of the int8 partials straight to
    ``P(None, *out_spec)`` + local dequant-sum.  Simpler schedule, but
    each chunk owner receives all D quantized slices — more wire.

    Returns ``(reduced, new_ef)`` with ``reduced`` in ``out_dtype``
    sharded per ``out_spec``.
    """
    lead = P(tuple(batch_axes))
    nd = pg.ndim - 1                  # leaf rank
    if chunk_dim is None:
        # ---- single-level, constraint-based --------------------------
        pg = _pin(pg, mesh, lead)
        comp = pg.astype(jnp.float32)
        if ef is not None:
            comp = comp + ef.astype(jnp.float32)
        comp = _pin(comp, mesh, lead)
        q, s = quantize_blockwise(comp, block_size=block_size, bits=bits)
        new_ef = None
        if ef is not None:
            local = dequantize_blockwise(q, s, bits=bits,
                                         out_dtype=jnp.float32)
            new_ef = _pin((comp - local).astype(ef.dtype), mesh, lead)
        q = _pin(q, mesh, lead)
        s = _pin(s, mesh, lead)
        s = _pin(s, mesh, P())        # one replicated f32 side-channel
        q = _pin(q, mesh, P(None, *tuple(out_spec)))   # u8 reduce wire
        red = jnp.sum(dequantize_blockwise(q, s, bits=bits,
                                           out_dtype=jnp.float32), axis=0)
        red = _pin(red, mesh, out_spec)
        return red.astype(out_dtype), new_ef

    # ---- two-level, explicit collectives -----------------------------
    a = int(chunk_dim)
    a2a_axes = ("fsdp",) + tuple(x for x in batch_axes if x != "fsdp")
    lvl2_axes = tuple(lvl2_axes)
    ef_dtype = None if ef is None else ef.dtype

    def body(pg_l, ef_l):
        comp = pg_l[0].astype(jnp.float32)
        if ef_l is not None:
            comp = comp + ef_l[0].astype(jnp.float32)
        q, s = quantize_blockwise(comp, block_size=block_size, bits=bits)
        new_ef = None
        if ef_l is not None:
            local = dequantize_blockwise(q, s, bits=bits,
                                         out_dtype=jnp.float32)
            new_ef = (comp - local).astype(ef_dtype)[None]
        # level 1: int8 + scales ride the same alltoall split
        s_dim = a if a < nd - 1 else s.ndim - 1
        qx = jax.lax.all_to_all(q[None], a2a_axes, split_axis=1 + a,
                                concat_axis=0, tiled=True)
        sx = jax.lax.all_to_all(s[None], a2a_axes, split_axis=1 + s_dim,
                                concat_axis=0, tiled=True)
        red = jnp.sum(dequantize_blockwise(qx, sx, bits=bits,
                                           out_dtype=jnp.float32), axis=0)
        # level 2: quantized regather of the reduced chunk
        q2, s2 = quantize_blockwise(red, block_size=block_size, bits=bits)
        if lvl2_axes:
            q2 = jax.lax.all_gather(q2, lvl2_axes, axis=a, tiled=True)
            s2_dim = a if a < red.ndim - 1 else s2.ndim - 1
            s2 = jax.lax.all_gather(s2, lvl2_axes, axis=s2_dim, tiled=True)
        out = dequantize_blockwise(q2, s2, bits=bits, out_dtype=out_dtype)
        return out, new_ef

    pg = _pin(pg, mesh, lead)
    if ef is None:
        fn = jax.shard_map(lambda p: body(p, None)[0], mesh=mesh,
                           in_specs=lead, out_specs=out_spec,
                           check_vma=False)
        return fn(pg), None
    fn = jax.shard_map(body, mesh=mesh, in_specs=(lead, lead),
                       out_specs=(out_spec, lead), check_vma=False)
    return fn(pg, ef)
