"""Error-compensated 1-bit compressed allreduce.

Parity: reference ``deepspeed/runtime/comm/nccl.py:52``
(``NcclBackend.compressed_allreduce``) and ``comm/mpi.py:170`` — the custom
allreduce used by the 1-bit optimizers: each rank sends only the SIGN of the
(error-compensated) tensor plus one fp32 scale, in two phases (worker →
server chunk owners → broadcast), with per-rank worker/server error feedback
buffers accumulating what the quantization dropped.

TPU re-design:

- The cupy bit-packing + NCCL alltoall/allgather pipeline becomes pure jnp:
  signs pack to uint8 via ``jnp.packbits`` (32× smaller than fp32 on the
  wire) and ride ``lax.all_to_all`` / ``lax.all_gather`` on a named mesh
  axis inside ``shard_map``.  This matters only for DCN-spanning axes; over
  ICI a plain psum is usually faster (reference docs say the same about
  NVLink vs Ethernet, ``docs/_pages/features.md:179``).
- ``sign(0) → +1`` exactly like the reference's ``sign().add_(1).bool()``
  trick (``nccl.py:74``).
- Scale = ||x||₂ / √numel (``nccl.py:73 worker_scale``).
- When no axis is given (or the axis extent is 1) the same two-phase
  quantization runs locally — the degenerate single-rank case.

Called inside ``shard_map``; all shapes static.  Returns
``(result, new_worker_error, new_server_error)``.
"""
# dstpu: disable-file=DSTPU102 (reviewed: this IS a comms-layer module --
# the 1-bit wire protocol schedules its own collectives by design)

from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def padded_size(numel: int, world_size: int) -> int:
    """Flat size padded so each of ``world_size`` chunks packs to whole bytes
    (parity: reference ``corrected_tensor_size`` divider math,
    ``onebit/adam.py:172-180``)."""
    mult = world_size * 8
    return int(int(np.ceil(numel / mult)) * mult)


def server_chunk_size(numel: int, world_size: int) -> int:
    return padded_size(numel, world_size) // world_size


def _sign(x):
    """sign with sign(0) = +1 (reference ``sign().add_(1).bool()`` mapping)."""
    return jnp.where(x >= 0, 1.0, -1.0).astype(jnp.float32)


def _scale(x):
    """||x||₂ / √numel with a zero-size guard: an empty (or fully padded
    away) tensor must produce scale 0, not 0/0 = NaN — the NaN would ride
    the scale all-gather and poison every rank's chunk."""
    if x.size == 0:
        return jnp.float32(0.0)
    return jnp.linalg.norm(x) / np.sqrt(x.size)


def _quantize(x):
    """One error-feedback quantization: x → (scale, sign, residual)."""
    s = _scale(x)
    sg = _sign(x)
    return s, sg, x - s * sg


def compressed_allreduce(x, worker_error, server_error,
                         axis_name: Optional[str] = None,
                         world_size: int = 1) -> Tuple:
    """Two-phase error-compensated 1-bit allreduce of ``x``.

    ``x``: any-shape fp32 tensor (same shape on every rank, different values).
    ``worker_error``: (padded_size,) fp32; ``server_error``: (chunk,) fp32.
    Inside ``shard_map`` pass ``axis_name``; ``world_size`` must equal the
    axis extent (static).  Returns (averaged_x, new_worker_err, new_server_err).
    """
    shape = x.shape
    flat = x.astype(jnp.float32).reshape(-1)
    n = world_size
    L = worker_error.shape[0]
    if L == 0:
        # zero-length tensor: nothing on the wire; errors stay zero-size
        return (jnp.zeros(shape, jnp.float32), worker_error, server_error)
    if flat.size != L:
        flat = jnp.pad(flat, (0, L - flat.size))

    # ---- worker phase (reference nccl.py:71-84) -------------------------
    compensated = flat + worker_error
    w_scale, w_sign, new_worker_error = _quantize(compensated)

    if axis_name is None or n <= 1:
        # degenerate single-rank path: same two-phase math, no wire; the
        # server "chunk" is the full tensor (init buffers with world_size=1)
        assert server_error.shape[0] == L, \
            "single-rank mode needs full-size server_error (init with world_size=1)"
        s_scale, s_sign, new_server_error = _quantize(w_scale * w_sign + server_error)
        result = s_scale * s_sign
        return result[:x.size].reshape(shape), new_worker_error, new_server_error

    # ---- wire format: packed sign bits + one fp32 scale ------------------
    bits = jnp.packbits((w_sign > 0).reshape(n, -1), axis=1)       # (n, L/n/8) u8
    # alltoall: rank j receives chunk j of every rank's sign vector
    recv_bits = lax.all_to_all(bits, axis_name, split_axis=0,
                               concat_axis=0, tiled=False)          # (n, chunk/8)
    scales = lax.all_gather(w_scale, axis_name)                     # (n,)

    signs = jnp.unpackbits(recv_bits, axis=1).astype(jnp.float32) * 2.0 - 1.0
    # server phase: exact average of the compressed values of my chunk
    # (reference nccl.py:126-135)
    avg_chunk = jnp.einsum("rc,r->c", signs, scales) / n            # (chunk,)
    comp_server = avg_chunk + server_error
    s_scale, s_sign, new_server_error = _quantize(comp_server)

    # phase 2: broadcast my compressed chunk to everyone
    s_bits = jnp.packbits(s_sign > 0)                               # (chunk/8,) u8
    all_bits = lax.all_gather(s_bits, axis_name)                    # (n, chunk/8)
    all_scales = lax.all_gather(s_scale, axis_name)                 # (n,)
    all_signs = jnp.unpackbits(all_bits, axis=1).astype(jnp.float32) * 2.0 - 1.0
    result = (all_signs * all_scales[:, None]).reshape(-1)          # (L,)
    return result[:x.size].reshape(shape), new_worker_error, new_server_error


def init_error_buffers(params, world_size: int):
    """Per-leaf (worker_error, server_error) zero buffers (reference
    ``state['worker_error']/['server_error']`` init, ``onebit/adam.py:181-186``)."""
    def werr(p):
        return jnp.zeros((padded_size(int(np.prod(p.shape)), world_size),),
                         jnp.float32)

    def serr(p):
        return jnp.zeros((server_chunk_size(int(np.prod(p.shape)), world_size),),
                         jnp.float32)

    return (jax.tree_util.tree_map(werr, params),
            jax.tree_util.tree_map(serr, params))
