"""Quantized expert-parallel dispatch wire: int8 all_to_all for MoE.

Parity role: the reference's ``deepspeed/moe/sharded_moe.py:85 _AllToAll``
autograd op — the expert-parallel dispatch/combine exchange — upgraded
per ZeRO++ (arXiv:2306.10209) block quantization and the Frontier
low-bandwidth-partitioning result (arXiv:2501.04266): once the
``expert`` mesh axis spans a slow wire (DCN), the full-width
dispatch/combine all_to_all is THE dominant distributed cost, and
shrinking its bytes-on-wire is the lever.

The constraint-only spelling in ``moe/layer.py`` (pin the ``(E, C, M)``
buffer to ``P('expert', ...)`` and let the SPMD partitioner insert the
exchange) moves compute-dtype bytes and leaves the schedule to the
partitioner.  This module replaces it with an EXPLICIT ``shard_map``
exchange whose payload is int8 codes + per-block f32 scales:

- **dispatch** (tokens → expert shards): each device quantizes its
  LOCAL token rows once (the gate runs full-width OUTSIDE the wire, so
  routing/capacity numerics are untouched), replicates them masked per
  destination chunk, and exchanges COMPACT payloads — tokens, never
  the ``cf``×-padded capacity buffer:

  * level 1 (intra): ``lax.all_to_all`` over the ``expert`` axis of
    ``(e, k, S/shards, M)`` int8 codes + per-block f32 scales + int32
    slot addresses — source i's block d holds exactly i's tokens
    routed to chunk d (others masked to the drop sentinel), so this IS
    the reference ``_AllToAll``'s permutation traffic at 1
    byte/element; each receiver then scatters the dequantized rows
    into its own ``(E/e·C, M)`` chunk at their local addresses;
  * level 2 (inter): when tokens are also sharded over outer,
    DCN-crossing axes (``data``; ``fsdp`` rides the fast wire between
    them), the scattered 1/e-size chunk re-quantizes — all-zero blocks
    carry scale **0** so the per-device partials, whose nonzero rows
    are globally DISJOINT (every capacity slot is owned by exactly one
    token), sum EXACTLY in int8 — and ``psum``-reduces over those
    axes: the slow wire sees e× fewer, 4×-narrower bytes.
    ``hierarchical: false`` is the single-level baseline: the old
    full-buffer spelling (scatter locally, quantize the ``(E*C, M)``
    partial, psum it over the outer axes FIRST, then the expert
    all_to_all + segment sum of buffer chunks).

- **combine** (expert shards → tokens): the inverse permutation.  A
  tiny ``all_gather`` of the slot addresses tells each chunk owner
  which rows every peer's tokens claimed; the owner gathers those rows
  from its shard (zeros for rows it does not own), quantizes with
  zero-scale blocks, and the same compact ``all_to_all`` returns
  ``(e, k, S/shards, M)`` per-source partials — at most ONE source is
  non-zero per row (each slot lives in exactly one chunk), so the
  int8 partials sum exactly and each device dequantizes only ITS
  tokens' rows.  No full-buffer broadcast in either direction.

Both directions are wrapped in ``custom_vjp`` pairs: the backward of
dispatch IS the combine-direction exchange of the cotangent and vice
versa, so the backward wire is quantized too.  Differentiating through
``convert_element_type(f32→s8)`` would silently yield zero gradients
(the qwZ lesson, ``quantized.py``); the pair spelling keeps gradients
flowing straight-through while never re-touching the full-width tensor.

The ACTIVE wire is process-global (``set_active``/``get_active``),
installed by the engine from its ``comms_compression`` policy before
each step dispatch and cleared on ``engine.close()`` — mirroring
``parallel/mesh.set_global_mesh``.  The policy is part of the
compile-cache key (``CollectiveRouter.describe``), so flipping it can
never silently reuse a stale executable.
"""
# dstpu: disable-file=DSTPU102 (reviewed: this IS a comms-layer module --
# the MoE wire schedules its own collectives by design, exactly like
# quantized.py's qwZ/qgZ ops)

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import quantized as Q
from ...parallel import mesh as M


class MoEWire:
    """One engine's expert-exchange policy: mesh + quantization knobs.

    ``dispatch``/``combine`` are the only entry points ``moe/layer.py``
    calls; both are trace-time no-ops when :meth:`supports` rejects the
    shape (the layer falls back to the constraint-only full-width
    path — compression must never be a correctness cliff)."""

    def __init__(self, mesh, *, bits: int = 8, block_size: int = 1024,
                 hierarchical: bool = True):
        assert bits == 8, "the MoE wire is an int8 scheme (bits=8)"
        self.mesh = mesh
        self.bits = int(bits)
        self.block_size = int(block_size)
        self.hierarchical = bool(hierarchical)
        self.expert_size = M.mesh_axis_size(mesh, "expert")
        # token-sharding axes OTHER than expert, ordered inner → outer so
        # the hierarchical reduce crosses the slow (outer) wire last and
        # smallest; extent-1 axes emit no collective and are dropped
        self._outer_axes = tuple(
            a for a in ("fsdp", "data")
            if a in mesh.shape and M.mesh_axis_size(mesh, a) > 1)
        # per-step expected wire bytes, recorded at trace time (one entry
        # per traced exchange site+direction) — feeds the engine's
        # CommsBudget after the first cold trace (docs/comms-compression.md)
        self.trace_log = []

    # ------------------------------------------------------------ policy
    def supports(self, E: int, C: int, Mdim: int) -> bool:
        """True when this (E, C, M) exchange can ride the int8 wire:
        the expert dim must tile the ``expert`` axis (the all_to_all
        splits it into per-rank chunks) and there must be a wire to
        compress at all (expert extent > 1)."""
        e = self.expert_size
        return e > 1 and E % e == 0 and Mdim > 0 and C > 0

    def describe(self) -> dict:
        return {"bits": self.bits, "block_size": self.block_size,
                "hierarchical": self.hierarchical,
                "expert_size": self.expert_size}

    # --------------------------------------------------------- accounting
    def _record(self, tag: str, direction: str, E: int, C: int, Mdim: int,
                S: int, k: int, site: int):
        """Trace-time census expectation for one exchange site.

        ``direction`` is the WIRE direction, not the autodiff pass:
        ``"scatter"`` (tokens → expert shards: the compact token
        all_to_all + outer chunk psums — the forward dispatch AND the
        combine backward) or ``"gather"`` (expert shards → tokens: the
        address all_gather + the inverse compact all_to_all — the
        forward combine AND the dispatch backward).  Bytes follow the
        census convention (``analysis/comms.py``: an entry's bytes =
        its OUTPUT aval bytes): the compact all_to_all conserves the
        ``k*S*M`` int8 token payload (+ ``4/B`` f32 scales + 4-byte
        int32 addresses), the outer psums move the scattered ``E/e``
        chunk, the single-level (``hierarchical: false``) baseline
        moves the full ``E*C*M`` buffer instead.  One record per
        unique (tag, site, shape): a retrace (eval twin, warm
        re-specialization) must not inflate the per-step expectation,
        but distinct call sites — two same-shaped MoE layers in one
        model — each emit their own exchanges, so ``site`` (the
        layer's wire id) is part of the identity."""
        key = (tag, site, (E, C, Mdim, S, k))
        if any(ev["site"] == key for ev in self.trace_log):
            return
        e = self.expert_size
        B = Q.pick_block(Mdim, self.block_size)
        n_buf = E * C * Mdim                  # full-buffer int8 elements
        ns_buf = 4 * (n_buf // B)
        n_tok = k * S * Mdim                  # compact token payload
        ns_tok = 4 * k * S * (Mdim // B)
        n_pos = 4 * k * S                     # int32 slot addresses
        if direction == "scatter":            # tokens → expert shards
            if self.hierarchical:
                ev = {"all_to_all": n_tok + ns_tok + n_pos}
                outer = sum(n_buf // e + ns_buf // e
                            for _ in self._outer_axes)
                if outer:
                    ev["all_reduce"] = outer
            else:                             # full-buffer baseline
                ev = {"all_to_all": n_buf + ns_buf}
                outer = sum(n_buf + ns_buf for _ in self._outer_axes)
                if outer:
                    ev["all_reduce"] = outer
        else:                                 # expert shards → tokens
            ev = {"all_gather": n_pos,
                  "all_to_all": n_tok + ns_tok}
        self.trace_log.append({"site": key, "tag": tag,
                               "shape": (E, C, Mdim, S, k), "bytes": ev})

    def expected_wire_bytes(self) -> dict:
        """Per-kind int8-wire byte expectation summed over every traced
        exchange (both directions, forward AND backward).  Empty until
        the first cold trace — a compile-cache warm start skips tracing,
        so budget-driven flows (``--audit-step moe``, the bench rung)
        run one cold step first.  A (tag, site) pair recorded at several
        SHAPES is the same exchange re-specialized (an eval twin at a
        different batch shape, a warm re-specialization) — one compiled
        program runs one variant per step, so the expectation keeps the
        largest variant per pair instead of summing them; distinct
        sites (layers) still sum."""
        per_pair = {}
        for ev in self.trace_log:
            pair = (ev["tag"], ev["site"][1])
            best = per_pair.get(pair)
            if best is None or sum(ev["bytes"].values()) > \
                    sum(best["bytes"].values()):
                per_pair[pair] = ev
        out = {}
        for ev in per_pair.values():
            for kind, b in ev["bytes"].items():
                out[kind] = out.get(kind, 0) + b
        return out

    # ------------------------------------------------------ wire internals
    def _specs(self):
        tok = P(tuple(M.BATCH_AXES))
        return tok, P("expert", None, None)

    def _scatter_reduce(self, vals, pos, E: int, C: int, *, tag: str,
                        site: int = 0):
        """(k, S, M) route payloads + (k, S) global slot addresses →
        ``(E, C, M)`` buffer sharded ``P('expert')``: the quantized
        dispatch-direction exchange (also the combine backward)."""
        mesh = self.mesh
        k, S, Mdim = vals.shape
        e = self.expert_size
        block = Q.pick_block(Mdim, self.block_size)
        out_dtype = vals.dtype
        self._record(tag, "scatter", E, C, Mdim, S, k, site)
        tok, buf_spec = self._specs()
        chunk = (E // e) * C
        vals = M.maybe_constrain(vals, P(None, tuple(M.BATCH_AXES), None))
        pos = M.maybe_constrain(pos, P(None, tuple(M.BATCH_AXES)))

        def a2a(t):
            return jax.lax.all_to_all(t, "expert", split_axis=0,
                                      concat_axis=0, tiled=True)

        def body_compact(v_l, pos_l):
            # compact permutation traffic (module docstring): quantize
            # the LOCAL token rows once, replicate masked per
            # destination chunk — block d of the a2a payload holds
            # exactly this rank's tokens routed to chunk d
            s_l = v_l.shape[1]
            q, s = Q.quantize_blockwise(v_l.astype(jnp.float32),
                                        block_size=block, bits=8,
                                        zero_scale=0.0)
            dest = pos_l // chunk             # >= e for dropped routes
            sel = dest[None] == jnp.arange(e, dtype=dest.dtype)[:, None,
                                                                None]
            qd = jnp.where(sel[..., None], q[None], jnp.int8(0))
            sd = jnp.where(sel[..., None], s[None], jnp.float32(0))
            pd = jnp.where(sel, pos_l[None], E * C)   # drop sentinel
            qd, sd, pd = a2a(qd), a2a(sd), a2a(pd)
            rows = Q.dequantize_blockwise(
                qd.reshape(-1, Mdim), sd.reshape(e * k * s_l, -1),
                bits=8, out_dtype=jnp.float32)
            # every received row is addressed to THIS chunk (or the
            # sentinel, whose rel lands >= chunk and drops)
            rel = pd.reshape(-1) - jax.lax.axis_index("expert") * chunk
            flat = jnp.zeros((chunk, Mdim), jnp.float32)
            flat = flat.at[rel].add(rows, mode="drop")
            if self._outer_axes:
                # level 2: only the 1/e-size chunk crosses the outer
                # (DCN-class) axes; zero-scale blocks keep the
                # (globally disjoint) partials summing exactly in int8
                q2, s2 = Q.quantize_blockwise(flat, block_size=block,
                                              bits=8, zero_scale=0.0)
                for a in self._outer_axes:
                    q2 = jax.lax.psum(q2, a)
                    s2 = jax.lax.psum(s2, a)
                flat = Q.dequantize_blockwise(q2, s2, bits=8,
                                              out_dtype=jnp.float32)
            return flat.astype(out_dtype).reshape(E // e, C, Mdim)

        def body_fullbuf(v_l, pos_l):
            # single-level baseline: scatter locally into the FULL
            # (E*C, M) buffer, quantize, cross the outer axes first,
            # then the expert all_to_all + segment sum of buffer chunks
            flat = jnp.zeros((E * C, Mdim), jnp.float32)
            for r in range(k):
                flat = flat.at[pos_l[r]].add(v_l[r].astype(jnp.float32),
                                             mode="drop")
            q, s = Q.quantize_blockwise(flat, block_size=block, bits=8,
                                        zero_scale=0.0)
            q = q.reshape(E, C, Mdim)
            s = s.reshape(E, C, -1)

            def expert_a2a(t):
                # cast back to the wire dtype: jnp.sum promotes int8 →
                # int32, and disjoint rows (at most one non-zero
                # source per element) mean the cast never clips
                dt = t.dtype
                t = a2a(t)
                return t.reshape((e, E // e) + t.shape[1:]) \
                        .sum(axis=0).astype(dt)

            for ax in self._outer_axes:
                q = jax.lax.psum(q, ax)
                s = jax.lax.psum(s, ax)
            q, s = expert_a2a(q), expert_a2a(s)
            return Q.dequantize_blockwise(
                q.reshape(-1, Mdim), s.reshape(-1, s.shape[-1]),
                bits=8, out_dtype=out_dtype).reshape(E // e, C, Mdim)

        body = body_compact if self.hierarchical else body_fullbuf
        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(None, tok[0], None), P(None, tok[0])),
            out_specs=buf_spec, check_vma=False)(vals, pos)

    def _gather_rows(self, buf, pos, *, tag: str, site: int = 0):
        """``(E, C, M)`` expert-sharded buffer + (k, S) addresses →
        (k, S, M) token-sharded rows: the quantized combine-direction
        exchange (also the dispatch backward).  OOB addresses (dropped
        routes) return exact-zero rows — callers additionally weight
        them by the gate's 0."""
        mesh = self.mesh
        E, C, Mdim = buf.shape
        e = self.expert_size
        k, S = pos.shape
        block = Q.pick_block(Mdim, self.block_size)
        out_dtype = buf.dtype
        self._record(tag, "gather", E, C, Mdim, S, k, site)
        tok, buf_spec = self._specs()
        chunk = (E // e) * C
        pos = M.maybe_constrain(pos, P(None, tuple(M.BATCH_AXES)))

        def body(b_l, pos_l):
            s_l = pos_l.shape[1]
            # tiny int32 side-channel: owners learn every peer's
            # claimed slots (the data-dependent return addresses)
            pall = jax.lax.all_gather(pos_l, "expert", axis=1,
                                      tiled=True)          # (k, e*s_l)
            rel = pall - jax.lax.axis_index("expert") * chunk
            own = (rel >= 0) & (rel < chunk)
            flat = b_l.reshape(chunk, Mdim).astype(jnp.float32)
            rows = flat[jnp.clip(rel, 0, chunk - 1).reshape(-1)]
            rows = jnp.where(own.reshape(-1, 1), rows, jnp.float32(0))
            q, s = Q.quantize_blockwise(rows, block_size=block, bits=8,
                                        zero_scale=0.0)
            # (k·e·s_l, ·) → (e, k, s_l, ·): block j = rank j's tokens'
            # rows from THIS chunk; the inverse a2a routes them home
            q = q.reshape(k, e, s_l, Mdim).transpose(1, 0, 2, 3)
            s = s.reshape(k, e, s_l, -1).transpose(1, 0, 2, 3)
            q = jax.lax.all_to_all(q, "expert", split_axis=0,
                                   concat_axis=0, tiled=True)
            s = jax.lax.all_to_all(s, "expert", split_axis=0,
                                   concat_axis=0, tiled=True)
            # per-source partials for MY tokens: each slot lives in
            # exactly one chunk, so at most one source is non-zero per
            # row and the int8 sum is exact (never clips)
            q = q.sum(axis=0, dtype=jnp.int32).astype(jnp.int8)
            s = s.sum(axis=0)
            out = Q.dequantize_blockwise(
                q.reshape(-1, Mdim), s.reshape(k * s_l, -1),
                bits=8, out_dtype=out_dtype)
            return out.reshape(k, s_l, Mdim)

        return jax.shard_map(
            body, mesh=mesh, in_specs=(buf_spec, P(None, tok[0])),
            out_specs=P(None, tok[0], None), check_vma=False)(buf, pos)

    # ------------------------------------------------------- entry points
    def dispatch(self, x, pos, E: int, C: int, site: int = 0):
        """Token activations ``x (S, M)`` + per-route global slot
        addresses ``pos (k, S)`` (``E*C`` = dropped) → the dispatched
        ``(E, C, M)`` buffer sharded over the ``expert`` axis, int8 on
        every wire hop.  Backward: the cotangent rides the quantized
        combine-direction gather."""
        EC = E * C

        def value(v):
            b = jnp.broadcast_to(v[None], (pos.shape[0],) + v.shape)
            return self._scatter_reduce(b, pos, E, C, tag="dispatch",
                                        site=site)

        @jax.custom_vjp
        def go(v):
            return value(v)

        def fwd(v):
            return value(v), None

        def bwd(_, g):
            rows = self._gather_rows(g, pos, tag="dispatch_bwd", site=site)
            keep = (pos < EC)[..., None].astype(rows.dtype)
            return ((rows * keep).sum(axis=0).astype(x.dtype),)

        go.defvjp(fwd, bwd)
        return go(x)

    def combine(self, buf, pos, site: int = 0):
        """Expert outputs ``buf (E, C, M)`` (expert-sharded) + addresses
        ``pos (k, S)`` → per-route token rows ``(k, S, M)``; callers
        weight them by the gate (0 for dropped routes).  Backward: the
        cotangent rides the quantized dispatch-direction reduce."""
        E, C = buf.shape[0], buf.shape[1]

        @jax.custom_vjp
        def go(b):
            return self._gather_rows(b, pos, tag="combine", site=site)

        def fwd(b):
            return self._gather_rows(b, pos, tag="combine", site=site), None

        def bwd(_, g):
            return (self._scatter_reduce(g, pos, E, C, tag="combine_bwd",
                                         site=site).astype(buf.dtype),)

        go.defvjp(fwd, bwd)
        return go(buf)


# ------------------------------------------------------ active-wire registry
_ACTIVE: Optional[MoEWire] = None


def set_active(wire: Optional[MoEWire]):
    """Install (or clear, with None) the process-global MoE wire.  The
    engine calls this from ``initialize`` and again before each step
    dispatch (a retrace must see the OWNING engine's policy, not the
    most recently built engine's), and clears it in ``close()``."""
    global _ACTIVE
    _ACTIVE = wire


def get_active() -> Optional[MoEWire]:
    return _ACTIVE
