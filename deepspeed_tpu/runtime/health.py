"""Training health guardian: on-device divergence sentinels + host escalation.

Production training on preemptible TPU fleets needs a defense ladder against
*numerical* faults, not just infrastructure ones (docs/health-monitor.md):

    detect -> skip -> rewind to last good checkpoint -> replay past the
    poison window -> abort with forensics

The pieces here split cleanly across the device/host boundary:

- **Device sentinels** (:class:`HealthState`, :func:`tree_nonfinite`,
  :func:`update_ema`): cheap scalar metrics computed INSIDE the jitted train
  step — global non-finite flags over loss/grads/params, and an EMA
  loss-spike z-score carried in ``TrainState``.  Pure ``jnp`` ops, no host
  callbacks, so the DSTPU201/DSTPU204 audits (``deepspeed_tpu/analysis``)
  stay clean and state donation stays honored.  The engine combines the
  sentinels into one ``skip`` flag and gates the parameter/optimizer update
  branchlessly (``jnp.where``) — the generalization of the fp16 loss-scaler
  skip-step to the bf16/fp32 paths, where a single NaN gradient would
  otherwise be written irrecoverably into the params.

- **Host monitor** (:class:`HealthMonitor`): reads the per-step sentinel
  scalars (every ``check_interval`` steps — each read is one device sync)
  and implements the configurable escalation policy of the ``health_check``
  config block: a run of ``consecutive_skip_budget`` skipped steps triggers
  an in-process ``engine.rewind()`` (manifest-verified checkpoint reload +
  data-stream fast-forward past the poison window); ``rewind_limit``
  exhaustion triggers ``on_exhausted`` (``abort`` with a forensic JSON dump,
  or ``warn``).

Nothing in this module runs under ``jit`` except the pure functions the
engine traces into its step.
"""

import json
import os
import time
from typing import Any, NamedTuple, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..monitor.ring import RingBuffer
from ..utils.logging import logger, log_dist


class TrainingHealthError(RuntimeError):
    """Raised when the escalation ladder is exhausted (``on_exhausted:
    "abort"``) — training cannot make progress without intervention.  The
    forensic dump path is carried in ``.forensic_path`` when one was
    written."""

    def __init__(self, message, forensic_path=None):
        super().__init__(message)
        self.forensic_path = forensic_path


# ---------------------------------------------------------------------------
# device-side sentinels (traced into the jitted step; pure jnp only)
# ---------------------------------------------------------------------------

class HealthState(NamedTuple):
    """Device-resident EMA statistics of the training loss (all scalars,
    carried in ``TrainState`` and donated with it each step)."""
    ema_loss: jnp.ndarray   # f32 — EMA of the (finite) loss
    ema_sq: jnp.ndarray     # f32 — EMA of the squared (finite) loss
    count: jnp.ndarray      # i32 — finite-loss observations absorbed


def init_state() -> HealthState:
    return HealthState(ema_loss=jnp.float32(0.0), ema_sq=jnp.float32(0.0),
                       count=jnp.int32(0))


def tree_nonfinite(tree) -> jnp.ndarray:
    """Global any-non-finite flag over the inexact leaves of a pytree.

    The generalization of ``fp16.loss_scaler.has_overflow`` to arbitrary
    state trees (params, grads): integer/bool leaves are skipped, an empty
    tree is finite.  One scalar per leaf, OR-reduced — XLA fuses the whole
    scan into the step for free under SPMD (sharded leaves reduce
    cross-device automatically).
    """
    leaves = [l for l in jax.tree_util.tree_leaves(tree)
              if jnp.issubdtype(jnp.result_type(l), jnp.inexact)]
    if not leaves:
        return jnp.asarray(False)
    out = jnp.asarray(False)
    for l in leaves:
        out = jnp.logical_or(out, jnp.logical_not(jnp.all(jnp.isfinite(l))))
    return out


def rows_nonfinite(x, axis=-1) -> jnp.ndarray:
    """Per-row any-non-finite flag: the batched sibling of
    :func:`tree_nonfinite`, reduced over ``axis`` only.

    The serving quarantine uses it on the decode logits — one bool per
    batch slot, computed INSIDE the compiled step (pure ``jnp``, no host
    callback), so a poisoned request is detected in-graph and its
    sampling branchlessly forced to a sentinel while neighbors' rows are
    untouched (docs/serving.md#resilience)."""
    return jnp.logical_not(jnp.all(jnp.isfinite(x), axis=axis))


def update_ema(state: HealthState, loss, *, window: int,
               zmax: float = 0.0, warmup: Optional[int] = None):
    """One EMA tick + loss-spike z-score, branchless.

    Returns ``(new_state, z, spike)``:

    - ``z``: the current loss's z-score against the PRIOR EMA mean/variance
      (0 while fewer than ``warmup`` finite losses have been absorbed, and
      0 for a non-finite loss — the non-finite sentinel owns that case);
    - ``spike``: ``z > zmax`` (always False when ``zmax <= 0``);
    - EMA absorbs only finite, non-spike losses, so a sustained poison
      window cannot drag the baseline toward itself and mask later spikes.
    """
    if warmup is None:
        warmup = max(4, int(window) // 4)
    alpha = jnp.float32(2.0 / (float(window) + 1.0))
    loss = jnp.asarray(loss, jnp.float32)
    finite = jnp.isfinite(loss)

    var = jnp.maximum(state.ema_sq - state.ema_loss * state.ema_loss, 0.0)
    # relative epsilon: a perfectly flat loss history must not turn the
    # first 1e-7 wiggle into an "infinite" z
    std = jnp.sqrt(var) + 1e-6 * (1.0 + jnp.abs(state.ema_loss))
    warmed = state.count >= jnp.int32(warmup)
    z = jnp.where(finite & warmed, (loss - state.ema_loss) / std, 0.0)
    if zmax > 0.0:
        spike = z > jnp.float32(zmax)
    else:
        spike = jnp.asarray(False)

    absorb = finite & jnp.logical_not(spike)
    l_eff = jnp.where(absorb, loss, state.ema_loss)
    first = state.count == 0
    new_ema = jnp.where(first, l_eff,
                        state.ema_loss + alpha * (l_eff - state.ema_loss))
    new_sq = jnp.where(first, l_eff * l_eff,
                       state.ema_sq + alpha * (l_eff * l_eff - state.ema_sq))
    new_state = HealthState(
        ema_loss=jnp.where(absorb, new_ema, state.ema_loss),
        ema_sq=jnp.where(absorb, new_sq, state.ema_sq),
        count=state.count + absorb.astype(jnp.int32))
    return new_state, z, spike


# ---------------------------------------------------------------------------
# host-side EMA twin (plain floats, same formula as update_ema)
# ---------------------------------------------------------------------------

class HostEma:
    """Host-side twin of the device EMA sentinel, for paths whose step
    metrics are host values already: the streamed-offload runner's in-line
    spike skip, and the monitor's fallback z-score when a step carries no
    device ``health_z``."""

    def __init__(self, window, zmax):
        self.window = int(window)
        self.zmax = float(zmax)
        self.reset()

    def reset(self):
        self._ema = 0.0
        self._sq = 0.0
        self._count = 0

    def update(self, loss):
        """One tick; returns ``(z, spike)`` with the same warmup /
        spike-exclusion semantics as :func:`update_ema`."""
        loss = float(loss)
        warmup = max(4, self.window // 4)
        alpha = 2.0 / (self.window + 1.0)
        finite = np.isfinite(loss)
        var = max(self._sq - self._ema * self._ema, 0.0)
        std = var ** 0.5 + 1e-6 * (1.0 + abs(self._ema))
        z = ((loss - self._ema) / std
             if finite and self._count >= warmup else 0.0)
        spike = self.zmax > 0 and z > self.zmax
        if finite and not spike:
            if self._count == 0:
                self._ema, self._sq = loss, loss * loss
            else:
                self._ema += alpha * (loss - self._ema)
                self._sq += alpha * (loss * loss - self._sq)
            self._count += 1
        return z, spike


# ---------------------------------------------------------------------------
# forensic-dump plumbing shared with the serving circuit breaker
# ---------------------------------------------------------------------------

def json_safe(obj):
    """Non-finite floats -> strings: the whole point of a forensic dump is
    the NaN/Inf values, and bare ``NaN``/``Infinity`` tokens (Python's
    default) are not RFC-8259 JSON — jq / JSON.parse / monitoring
    pipelines would reject the artifact.  Shared by the training
    guardian's dump and the serving circuit breaker's."""
    if isinstance(obj, float) and not np.isfinite(obj):
        return repr(obj)              # 'nan' | 'inf' | '-inf'
    if isinstance(obj, dict):
        return {k: json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_safe(v) for v in obj]
    return obj


def write_forensics(dirpath, filename, payload):
    """Atomically write a forensic JSON artifact (write-temp + replace);
    best-effort — returns the path, or None on failure (a dump failure
    must never mask the abort/trip it accompanies)."""
    path = os.path.join(dirpath, filename)
    try:
        os.makedirs(dirpath, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            # the forensic ARTIFACT itself; announced on the monitor bus
            # by the caller as an `artifact` event
            json.dump(json_safe(payload),  # dstpu: disable=DSTPU104
                      f, indent=2, allow_nan=False)
        os.replace(tmp, path)
    except (OSError, TypeError, ValueError) as e:
        # TypeError/ValueError: a payload value json couldn't serialize
        # (e.g. a numpy scalar a caller smuggled in as a uid) — a dump
        # failure must never mask the abort/trip it accompanies
        logger.warning(f"could not write forensic dump to {path}: {e}")
        return None
    return path


# ---------------------------------------------------------------------------
# host-side monitor: the escalation ladder
# ---------------------------------------------------------------------------

_METRIC_KEYS = ("loss", "grad_norm", "skip", "health_z", "loss_spike",
                "nonfinite_grads", "nonfinite_loss", "nonfinite_params")


class HealthMonitor:
    """Host-side escalation policy over the device sentinels.

    ``observe()`` stashes each step's sentinel scalars as device references
    and TRAILS the device by ``check_interval`` steps: an entry is synced
    (one ``float()``/``bool()`` host read each) only once ``check_interval``
    newer steps have been dispatched.  At the default interval of 1 this
    reads step *t-1* right after step *t* was dispatched — the read blocks
    only on work the device has already moved past, so the engine's
    async-dispatch overlap survives the guardian.  Escalation latency is
    bounded by the same ``check_interval``.  Decisions come back as an
    action string the engine executes: ``"ok"`` | ``"rewind"`` |
    ``"abort"``.

    The streamed-offload path computes no device EMA (its metrics are
    host-side already); the monitor then maintains a :class:`HostEma`
    twin so the z-score telemetry and spike accounting exist on every
    path.

    The forensic step history is a ``monitor.ring.RingBuffer`` (the same
    bounded-ring class behind the telemetry bus's in-memory sink), and
    when the engine runs with an armed monitor the guardian's events —
    rewinds, forensic dumps — are ALSO announced on the bus (``bus=``),
    so the escalation record shows up in the one telemetry stream
    instead of only in scattered log lines.
    """

    def __init__(self, cfg, bus=None):
        self.cfg = cfg
        self.bus = bus
        self.history = RingBuffer(int(cfg.history))
        self.consecutive_skips = 0
        self.total_skips = 0
        self.total_spikes = 0
        self.rewinds = 0           # process-lifetime total (telemetry)
        self.episode_rewinds = 0   # rewinds in the CURRENT poison episode;
        # an episode ends when a clean step is applied after a rewind, and
        # rewind_limit bounds rewinds per episode (see _decide)
        self.clean_since_rewind = 0
        self.last_bad_stream_step = None
        self.last_step = None
        self._pending = []
        # fallback z for metrics that carry no "health_z" — every engine
        # path provides one (device sentinels, or the streamed runner's
        # own HostEma), so this fires only for externally-driven monitors
        self._hema = HostEma(cfg.spike_window, cfg.spike_zmax)

    # ---------------------------------------------------------------- intake
    def observe(self, step_no, stream_step, metrics) -> str:
        """Record one finished step.  Device scalars are kept as references;
        entries older than the ``check_interval`` lag window are synced and
        processed now."""
        m = {k: metrics[k] for k in _METRIC_KEYS if k in metrics}
        self._pending.append((step_no, stream_step, m))
        lag = max(1, int(self.cfg.check_interval))
        if len(self._pending) <= lag:
            return "ok"
        ready, self._pending = self._pending[:-lag], self._pending[-lag:]
        return self._process(ready)

    def flush(self) -> str:
        """Sync + process EVERYTHING pending, lag included; returns the
        escalation action.  Called by the engine at checkpoint saves and
        before a forensic dump (observe() drains steadily in between);
        with the lag at N, up to N final steps can still be unprocessed
        if the process exits without either boundary."""
        ready, self._pending = self._pending, []
        return self._process(ready)

    def _process(self, entries) -> str:
        action = "ok"
        for step_no, stream_step, m in entries:
            rec = self._ingest(step_no, stream_step, m)
            act = self._decide(rec)
            if act != "ok":
                action = act
        return action

    def _ingest(self, step_no, stream_step, m):
        loss = float(m.get("loss", np.nan))
        gnorm = float(m["grad_norm"]) if "grad_norm" in m else None
        if "skip" in m:
            skip = bool(m["skip"])
        else:
            skip = not np.isfinite(loss)
        if "health_z" in m:
            z = float(m["health_z"])
            spike = bool(m.get("loss_spike", False))
        else:
            z, spike = self._hema.update(loss)
        rec = {"step": step_no, "stream_step": stream_step, "loss": loss,
               "grad_norm": gnorm, "z": round(z, 4), "skip": skip,
               "spike": spike}
        for k in ("nonfinite_grads", "nonfinite_loss", "nonfinite_params"):
            if k in m:
                rec[k] = bool(m[k])
        self.history.append(rec)
        self.last_step = step_no
        if skip:
            self.consecutive_skips += 1
            self.total_skips += 1
            if stream_step is not None:
                self.last_bad_stream_step = stream_step
        else:
            self.consecutive_skips = 0
            self.clean_since_rewind += 1
            # a clean APPLIED step after a rewind closes the poison
            # episode: the rewind budget re-arms for the next one
            self.episode_rewinds = 0
        if spike:
            self.total_spikes += 1
            if not skip:
                why = ("health_check.skip_on_spike is off"
                       if not self.cfg.skip_on_spike
                       else "this step's path applied it before the spike "
                            "was classified")
                logger.warning(
                    "health: loss spike at step %s (loss=%.6g z=%.2f > "
                    "zmax=%.2f); step applied (%s)",
                    step_no, loss, z, self.cfg.spike_zmax, why)
        return rec

    # -------------------------------------------------------------- decision
    def _decide(self, rec) -> str:
        budget = int(self.cfg.consecutive_skip_budget)
        if budget <= 0 or self.consecutive_skips < budget:
            return "ok"
        if self.episode_rewinds < int(self.cfg.rewind_limit):
            return "rewind"
        if self.cfg.on_exhausted == "warn":
            logger.warning(
                "health: skip budget exhausted (%d consecutive) and the "
                "episode's rewind limit (%d) spent; on_exhausted=warn — "
                "counters reset, training continues UNPROTECTED against "
                "this fault",
                self.consecutive_skips, self.cfg.rewind_limit)
            self.consecutive_skips = 0
            return "ok"
        return "abort"

    # ------------------------------------------------------------ transitions
    def record_rewind(self, tag=None):
        """Called by the engine after a successful in-process rewind."""
        self.rewinds += 1
        self.episode_rewinds += 1
        self.consecutive_skips = 0
        self.clean_since_rewind = 0
        self._hema.reset()
        log_dist("health rewind engaged: " + json.dumps({
            "event": "health_rewind", "rewind": self.rewinds,
            "episode_rewind": self.episode_rewinds,
            "limit": int(self.cfg.rewind_limit), "restored_tag": tag,
            "replayed_past_stream_step": self.last_bad_stream_step}),
            ranks=[0])
        if self.bus is not None:
            self.bus.counter(
                "health_rewind", self.rewinds, step=self.last_step,
                episode_rewind=self.episode_rewinds,
                restored_tag=tag,
                replayed_past_stream_step=self.last_bad_stream_step)

    def on_checkpoint_load(self):
        """A checkpoint load supersedes the observed run: the consecutive
        counter and host EMA describe discarded steps.  Rewind/skip totals
        persist — they are the process-lifetime escalation record."""
        self.consecutive_skips = 0
        self._pending = []
        self._hema.reset()

    # ------------------------------------------------------------- forensics
    def counters(self):
        return {"consecutive_skips": self.consecutive_skips,
                "total_skips": self.total_skips,
                "total_spikes": self.total_spikes,
                "rewinds": self.rewinds,
                "episode_rewinds": self.episode_rewinds,
                "last_bad_stream_step": self.last_bad_stream_step}

    # alias kept for existing callers; implementation is the module-level
    # json_safe (shared with the serving circuit breaker's dump)
    _json_safe = staticmethod(json_safe)

    def forensic_dump(self, dirpath, reason, last_good_tag=None):
        """Write the forensic JSON (ring-buffer history + counters + policy)
        atomically; returns the path.  Best-effort: a dump failure must not
        mask the abort it accompanies."""
        payload = {
            "event": "health_forensics",
            "reason": reason,
            "time_unix": time.time(),
            "step": self.last_step,
            "last_good_tag": last_good_tag,
            "counters": self.counters(),
            "policy": {
                "skip_nonfinite": bool(self.cfg.skip_nonfinite),
                "spike_window": int(self.cfg.spike_window),
                "spike_zmax": float(self.cfg.spike_zmax),
                "skip_on_spike": bool(self.cfg.skip_on_spike),
                "consecutive_skip_budget":
                    int(self.cfg.consecutive_skip_budget),
                "rewind_limit": int(self.cfg.rewind_limit),
                "on_exhausted": self.cfg.on_exhausted,
                "check_interval": int(self.cfg.check_interval),
            },
            "history": list(self.history),
        }
        step = self.last_step if self.last_step is not None else 0
        path = write_forensics(dirpath, f"health_forensics_step{step}.json",
                               payload)
        if path is None:
            return None
        logger.warning("health forensics written: " + json.dumps({
            "event": "health_forensics_written", "path": path,
            "reason": reason}))
        if self.bus is not None:
            self.bus.artifact("health_forensics", path,
                              step=self.last_step, reason=reason)
            self.bus.flush()
        return path
