"""Hessian eigenvalue estimation via power iteration.

Parity: reference ``deepspeed/runtime/eigenvalue.py:7`` (``Eigenvalue``,
``compute_eigenvalue`` :61) — per-layer largest |eigenvalue| of the loss
Hessian, used by MoQ to pace per-layer quantization (curvier layers quantize
more slowly).

TPU re-design: the reference needs a retained autograd graph and
``torch.autograd.grad(grads, params, grad_outputs=v)`` per iteration; here
Hv is a ``jax.jvp`` through ``jax.grad`` (forward-over-reverse), jitted
once and reused across iterations.  Layer blocks of a scanned model are the
leading axis of the stacked block pytree, so the per-layer power iteration
is VECTORIZED: one Hv evaluates every layer's product simultaneously, with
per-layer inner products/normalization over the non-leading axes.
"""

import functools
from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp

from ..utils.logging import log_dist


def _nan_to_num(t):
    return jax.tree_util.tree_map(
        lambda x: jnp.nan_to_num(x, nan=0.0, posinf=0.0, neginf=0.0), t)


class Eigenvalue:
    def __init__(self, verbose=False, max_iter=100, tol=1e-2, stability=0.0,
                 gas_boundary_resolution=1, layer_name="blocks", layer_num=0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num
        log_dist(
            f"enabled eigenvalue with verbose={verbose}, max_iter={max_iter}, "
            f"tol={tol}, stability={stability}, "
            f"gas_boundary_resolution={gas_boundary_resolution}, "
            f"layer_name={layer_name}, layer_num={layer_num}", ranks=[0])

    # ---------------------------------------------------------------- helpers
    @staticmethod
    def _inner(xs, ys, layerwise: bool):
        """Σ x·y over leaves; per leading-axis index when ``layerwise``."""
        leaves = zip(jax.tree_util.tree_leaves(xs), jax.tree_util.tree_leaves(ys))
        if layerwise:
            return sum(jnp.sum((a * b).reshape(a.shape[0], -1), axis=1)
                       for a, b in leaves)
        return sum(jnp.sum(a * b) for a, b in leaves)

    def _normalize(self, v, layerwise: bool):
        norm = jnp.sqrt(self._inner(v, v, layerwise)) + self.stability
        if layerwise:
            def div(x):
                return x / norm.reshape((-1,) + (1,) * (x.ndim - 1))
        else:
            def div(x):
                return x / norm
        return _nan_to_num(jax.tree_util.tree_map(div, v))

    # ----------------------------------------------------------- computation
    def compute_eigenvalue(self, loss_fn: Callable, params, rng=None,
                           layerwise: bool = True, scale: float = 1.0):
        """Largest |λ| of ∂²loss/∂params² by power iteration.

        ``loss_fn(params) -> scalar`` (close over the batch).  With
        ``layerwise=True`` every leaf's leading axis is treated as the layer
        index (scanned block stacks) and a vector of per-layer eigenvalues is
        returned, post-processed to [0, 1] like the reference (:152-156);
        otherwise a single global eigenvalue.
        """
        rng = rng if rng is not None else jax.random.PRNGKey(0)

        def hvp(p, v):
            return jax.jvp(jax.grad(loss_fn), (p,), (v,))[1]

        hvp = jax.jit(hvp)

        leaves, treedef = jax.tree_util.tree_flatten(params)
        keys = jax.random.split(rng, len(leaves))
        v = treedef.unflatten([jax.random.normal(k, l.shape, jnp.float32)
                               for k, l in zip(keys, leaves)])
        v = self._normalize(v, layerwise)

        ev_prev = jnp.zeros(()) if not layerwise else None
        ev = jnp.ones(()) if not layerwise else None
        i = 0
        while i < self.max_iter:
            Hv = _nan_to_num(hvp(params, v))
            ev_new = self._inner(Hv, v, layerwise)
            v = self._normalize(Hv, layerwise)
            v = jax.tree_util.tree_map(lambda x: x / scale, v)
            if ev is not None:  # global mode: host-side convergence test
                ev_prev, ev = ev, ev_new
                if abs(float(ev)) == 0.0 or \
                        abs((float(ev) - float(ev_prev)) / float(ev)) < self.tol:
                    i += 1
                    break
            else:
                if i > 0:
                    rel = np.abs((np.asarray(ev_new) - np.asarray(ev_layer)) /
                                 np.where(np.asarray(ev_new) == 0, 1,
                                          np.asarray(ev_new)))
                    if (rel < self.tol).all():
                        ev_layer = ev_new
                        i += 1
                        break
                ev_layer = ev_new
            i += 1

        if layerwise:
            values = np.asarray(ev_layer) * scale
            out = self.post_process(list(values))
            if self.verbose:
                log_dist(f"power iterations: {i}, eigenvalues: {out}", ranks=[0])
            return out
        value = float(ev) * scale
        if self.verbose:
            log_dist(f"power iterations: {i}, eigenvalue: {value}", ranks=[0])
        return value

    def post_process(self, value_list):
        """Map |λ| to [0,1]; invalid (0) entries become 1.0 (reference
        :152-156)."""
        max_value = abs(max(value_list, key=abs)) if value_list else 1.0
        if max_value == 0.0:
            return [1.0 for _ in value_list]
        return [abs(v) / max_value if v != 0.0 else 1.0 for v in value_list]
