"""Data pipeline (curriculum learning). Parity: reference
``deepspeed/runtime/data_pipeline/``."""

from .curriculum_scheduler import CurriculumScheduler

__all__ = ["CurriculumScheduler"]
