"""Curriculum learning scheduler.

Parity: reference ``deepspeed/runtime/data_pipeline/curriculum_scheduler.py:8``
(``CurriculumScheduler``) — schedules a difficulty value (typically sequence
length) as a function of global step.  Pure host-side math; identical
config schema and semantics:

- ``fixed_discrete``: difficulty list + max_step boundaries.
- ``fixed_linear`` / ``fixed_root``: difficulty grows like
  ``(step/total)^(1/root)`` from min to max, snapped down to a multiple of
  ``difficulty_step`` (kept multiple-of-8 friendly — on TPU this aligns the
  seq dim to the lane tiling the same way it aligned Tensor Cores).
"""

import math

from ...utils.logging import logger


class CurriculumScheduler:
    def __init__(self, config):
        self.state = {}
        for key in ("curriculum_type", "min_difficulty", "max_difficulty",
                    "schedule_type"):
            assert key in config, \
                f"Curriculum learning requires the config '{key}'"
        self.state["min_difficulty"] = config["min_difficulty"]
        self.state["max_difficulty"] = config["max_difficulty"]
        self.state["current_difficulty"] = config["min_difficulty"]
        self.state["schedule_type"] = config["schedule_type"]
        self.first_step = True
        sched = config.get("schedule_config", {})
        stype = config["schedule_type"]
        if stype == "fixed_discrete":
            assert "difficulty" in sched and "max_step" in sched
            assert len(sched["max_step"]) > 0
            assert len(sched["difficulty"]) == len(sched["max_step"]) + 1
            self.state["schedule"] = sched
        elif stype == "fixed_root":
            for k in ("total_curriculum_step", "difficulty_step", "root_degree"):
                assert k in sched, f"fixed_root schedule requires '{k}'"
            self._warn_step(sched)
            self.state["schedule"] = sched
        elif stype == "fixed_linear":
            for k in ("total_curriculum_step", "difficulty_step"):
                assert k in sched, f"fixed_linear schedule requires '{k}'"
            self._warn_step(sched)
            self.state["schedule"] = sched
        else:
            raise RuntimeError("Unsupported curriculum schedule type")

    @staticmethod
    def _warn_step(sched):
        if sched["difficulty_step"] % 8 != 0:
            logger.warning(
                "difficulty_step should be a multiple of 8 to keep the "
                "sequence dimension aligned to the TPU lane tiling.")

    def get_current_difficulty(self):
        return self.state["current_difficulty"]

    def set_current_difficulty(self, difficulty):
        self.state["current_difficulty"] = difficulty

    def get_state(self):
        return self.state

    def set_state(self, state):
        self.state = state

    def _fixed_discrete(self, global_steps):
        s = self.state["schedule"]
        if global_steps > s["max_step"][-1]:
            return s["difficulty"][-1]
        for i, mx in enumerate(s["max_step"]):
            if global_steps <= mx:
                return s["difficulty"][i]

    def _fixed_root(self, global_steps, root_degree=None):
        s = self.state["schedule"]
        if root_degree is None:
            root_degree = s["root_degree"]
        nd = (float(global_steps) / s["total_curriculum_step"]) ** (1.0 / root_degree)
        nd = math.floor(nd * (self.state["max_difficulty"] -
                              self.state["min_difficulty"]) +
                        self.state["min_difficulty"])
        nd -= nd % s["difficulty_step"]
        return min(nd, self.state["max_difficulty"])

    def get_difficulty(self, global_steps):
        stype = self.state["schedule_type"]
        if stype == "fixed_discrete":
            return self._fixed_discrete(global_steps)
        if stype == "fixed_linear":
            return self._fixed_root(global_steps, 1)
        if stype == "fixed_root":
            return self._fixed_root(global_steps)
        raise RuntimeError("Unsupported curriculum schedule type")

    def update_difficulty(self, global_steps):
        if self.state["current_difficulty"] < self.state["max_difficulty"]:
            self.state["current_difficulty"] = self.get_difficulty(global_steps)
        return self.state["current_difficulty"]
