"""Static + dynamic loss scaling for fp16 training.

Parity: reference ``deepspeed/runtime/fp16/loss_scaler.py:54,77``
(``LossScaler``/``DynamicLossScaler``) with the same knobs:
``init_scale = 2**initial_scale_power``, ``scale_window``, ``scale_factor``,
``min_scale``, ``delayed_shift`` (hysteresis).

TPU-native design: the scaler state is a small pytree carried INSIDE the
jitted train step (no host round-trip per step).  Overflow handling is
branchless: the step computes both the "apply" and "skip" outcomes with
``jnp.where`` — matching the reference's skip-step semantics
(``stage_1_and_2.py:1667-1688``) without data-dependent control flow.

bf16 training needs no scaler (the default on TPU); fp16 parity keeps the
whole config surface working.
"""

from typing import NamedTuple

import jax.numpy as jnp


class LossScaleState(NamedTuple):
    """Device-resident scaler state (all scalars)."""
    cur_scale: jnp.ndarray        # f32
    cur_hysteresis: jnp.ndarray   # i32 — remaining tolerated overflows before shrink
    last_overflow_iter: jnp.ndarray  # i32
    iter_num: jnp.ndarray         # i32


def static_state(loss_scale: float) -> LossScaleState:
    return LossScaleState(
        cur_scale=jnp.asarray(loss_scale, jnp.float32),
        cur_hysteresis=jnp.asarray(0, jnp.int32),
        last_overflow_iter=jnp.asarray(-1, jnp.int32),
        iter_num=jnp.asarray(0, jnp.int32),
    )


def dynamic_state(initial_scale_power: int = 16, delayed_shift: int = 2) -> LossScaleState:
    return LossScaleState(
        cur_scale=jnp.asarray(2.0 ** initial_scale_power, jnp.float32),
        cur_hysteresis=jnp.asarray(delayed_shift, jnp.int32),
        last_overflow_iter=jnp.asarray(-1, jnp.int32),
        iter_num=jnp.asarray(0, jnp.int32),
    )


def update_scale(state: LossScaleState, overflow, *, dynamic: bool,
                 scale_factor: float = 2.0, scale_window: int = 1000,
                 min_scale: float = 1.0, delayed_shift: int = 2,
                 consecutive_hysteresis: bool = False) -> LossScaleState:
    """One ``update_scale`` tick. Parity: reference ``loss_scaler.py:115-139``.

    - On overflow with hysteresis left: consume one hysteresis credit.
    - On overflow without: scale = max(scale/scale_factor, min_scale).
    - After ``scale_window`` clean iters: scale *= scale_factor (and restore
      hysteresis unless ``consecutive_hysteresis``).
    """
    if not dynamic:
        return state._replace(iter_num=state.iter_num + 1)

    overflow = jnp.asarray(overflow)
    iter_num = state.iter_num + 1

    # -- overflow branch
    hysteresis_left = state.cur_hysteresis > 1
    ovf_scale = jnp.where(hysteresis_left, state.cur_scale,
                          jnp.maximum(state.cur_scale / scale_factor, min_scale))
    ovf_hyst = jnp.where(hysteresis_left, state.cur_hysteresis - 1, state.cur_hysteresis)
    ovf_last = state.iter_num  # record this iteration as the overflow point

    # -- clean branch (reference loss_scaler.py:115-139: pre-increment iter,
    # consecutive_hysteresis=True replenishes hysteresis EVERY clean iter,
    # False replenishes only when the window elapses and the scale grows)
    window_elapsed = (state.iter_num - state.last_overflow_iter) % scale_window == 0
    grow = jnp.logical_and(window_elapsed, state.iter_num > state.last_overflow_iter)
    clean_scale = jnp.where(grow, state.cur_scale * scale_factor, state.cur_scale)
    if consecutive_hysteresis:
        clean_hyst = jnp.asarray(delayed_shift, jnp.int32) * jnp.ones_like(
            state.cur_hysteresis)
    else:
        clean_hyst = jnp.where(grow, jnp.asarray(delayed_shift, jnp.int32),
                               state.cur_hysteresis)

    return LossScaleState(
        cur_scale=jnp.where(overflow, ovf_scale, clean_scale),
        cur_hysteresis=jnp.where(overflow, ovf_hyst, clean_hyst).astype(jnp.int32),
        last_overflow_iter=jnp.where(overflow, ovf_last,
                                     state.last_overflow_iter).astype(jnp.int32),
        iter_num=iter_num,
    )


def has_overflow(grads) -> jnp.ndarray:
    """Global any-nonfinite scan over a grad pytree.

    Parity: reference ``CheckOverflow`` / ``_has_inf_or_nan`` (``stage3.py:2498``).
    Under SPMD this is computed on sharded grads and XLA inserts the cross-
    device reduction — the reference needed an explicit allreduce
    (``stage_1_and_2.py:1660``).
    """
    import jax
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.asarray(False)
    flags = [jnp.logical_not(jnp.all(jnp.isfinite(g))) for g in leaves]
    out = flags[0]
    for f in flags[1:]:
        out = jnp.logical_or(out, f)
    return out


class LossScaler:
    """Host-side stateful facade (reference API parity).

    Wraps a :class:`LossScaleState`; the engine reads ``.state`` into the
    jitted step and writes the updated state back.
    """

    def __init__(self, scale=1.0):
        self.dynamic = False
        self.scale_factor = 2.0
        self.scale_window = 1000
        self.min_scale = 1.0
        self.delayed_shift = 1
        self.consecutive_hysteresis = False
        self.state = static_state(scale)

    @property
    def loss_scale(self):
        return float(self.state.cur_scale)

    def update_scale(self, overflow):
        self.state = update_scale(self.state, overflow, dynamic=self.dynamic,
                                  scale_factor=self.scale_factor,
                                  scale_window=self.scale_window,
                                  min_scale=self.min_scale,
                                  delayed_shift=self.delayed_shift,
                                  consecutive_hysteresis=self.consecutive_hysteresis)

    def backward(self, loss):
        # JAX has no .backward(); engine scales inside the jitted step.
        raise RuntimeError("LossScaler.backward is not meaningful under JAX; "
                           "the engine scales the loss inside its train step.")


class DynamicLossScaler(LossScaler):
    def __init__(self, init_scale=2 ** 32, scale_factor=2.0, scale_window=1000,
                 min_scale=1.0, delayed_shift=1, consecutive_hysteresis=False):
        super().__init__(init_scale)
        self.dynamic = True
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self.min_scale = min_scale
        self.delayed_shift = delayed_shift
        self.consecutive_hysteresis = consecutive_hysteresis
        self.state = LossScaleState(
            cur_scale=jnp.asarray(init_scale, jnp.float32),
            cur_hysteresis=jnp.asarray(delayed_shift, jnp.int32),
            last_overflow_iter=jnp.asarray(-1, jnp.int32),
            iter_num=jnp.asarray(0, jnp.int32),
        )


def create_loss_scaler(fp16_config):
    """Build a scaler from the parsed ``fp16`` config section.

    Parity: reference engine scaler selection (``fp16/fused_optimizer.py`` init):
    ``loss_scale == 0`` → dynamic with ``2**initial_scale_power``.
    """
    if fp16_config.dynamic_loss_scale:
        return DynamicLossScaler(init_scale=2.0 ** fp16_config.initial_scale_power,
                                 scale_window=fp16_config.loss_scale_window,
                                 min_scale=fp16_config.min_loss_scale,
                                 delayed_shift=fp16_config.hysteresis)
    return LossScaler(scale=fp16_config.loss_scale)
