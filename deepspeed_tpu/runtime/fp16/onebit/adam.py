"""1-bit Adam: communication-compressed Adam.

Parity: reference ``deepspeed/runtime/fp16/onebit/adam.py:14`` (``OnebitAdam``):

- **warmup** (step < freeze_step): exact Adam with exactly-reduced gradients;
  note the update is ``m / (√v + eps)`` — this optimizer variant applies NO
  bias correction (``adam.py:200-204,237``).
- **compression stage** (step ≥ freeze_step): the variance ``v`` is FROZEN;
  the momentum is updated with local gradients and then synchronized with the
  error-compensated 1-bit compressed allreduce (``adam.py:206-230``); an
  optional ``exp_avg_mask`` zeroes momentum entries that are structurally
  zero (1-bit compression cannot represent exact zero, ``adam.py:222-229``).

TPU re-design: one branchless jitted update (``jnp.where`` on the traced step
vs freeze_step — the reference flips ``adam_freeze_key`` host-side).  The
compressed allreduce runs on a named mesh axis when ``axis_name`` is set
(true per-rank error feedback inside ``shard_map``); without it the same
quantization math runs on the already-averaged gradients — algorithmically
identical, no wire savings (those only matter on DCN-spanning axes).
"""

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ...comm.compressed import compressed_allreduce, init_error_buffers


class OnebitAdamState(NamedTuple):
    exp_avg: dict
    exp_avg_sq: dict
    worker_error: dict
    server_error: dict


class OnebitAdam:
    """Engine-facing optimizer (config key ``OneBitAdam``,
    ``runtime/constants.py`` / reference ``engine.py:917-930``)."""

    name = "onebitadam"

    def __init__(self, lr=1e-3, freeze_step=100000, betas=(0.9, 0.999),
                 eps=1e-8, weight_decay=0.0, bias_correction=True,
                 amsgrad=False, cuda_aware=False, comm_backend_name="nccl",
                 axis_name: Optional[str] = None, exp_avg_mask=None):
        if amsgrad:
            raise RuntimeError("1-bit Adam does not support the AMSGrad variant")
        self.lr = lr
        self.freeze_step = freeze_step
        self.betas = tuple(betas)
        self.eps = eps
        self.weight_decay = weight_decay
        # accepted for config parity; the TPU backend is XLA collectives
        self.comm_backend_name = comm_backend_name
        self.cuda_aware = cuda_aware
        self.axis_name = axis_name
        self.exp_avg_mask = exp_avg_mask
        self.world_size = 1
        # engine-provided transport (collective_router.OnebitTransport):
        # runs the compressed allreduce with TRUE per-rank error buffers
        # inside shard_map on the dp mesh axis.  Without it (and without
        # axis_name) the quantization math runs in its degenerate local
        # mode — algorithmically identical, no wire savings.
        self.comm = None

    def set_comm(self, transport):
        """Engine hook (``runtime/comm/collective_router.py``): route the
        compression stage's momentum allreduce over a real mesh axis."""
        self.comm = transport
        if transport is not None:
            self.world_size = int(transport.world_size)

    def set_world_size(self, n: int):
        """Engine hook: extent of the compression axis (reference reads it
        from the comm backend, ``adam.py:106-108``)."""
        if self.comm is None:
            self.world_size = int(n) if self.axis_name is not None else 1

    def init(self, params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        if self.comm is not None:
            werr, serr = self.comm.init_error_buffers(params)
        else:
            werr, serr = init_error_buffers(
                params, self.world_size if self.axis_name is not None else 1)
        return OnebitAdamState(
            exp_avg=jax.tree_util.tree_map(zeros, params),
            exp_avg_sq=jax.tree_util.tree_map(zeros, params),
            worker_error=werr, server_error=serr)

    def update(self, grads, state: OnebitAdamState, params, *, step, lr=None):
        lr = self.lr if lr is None else lr
        b1, b2 = self.betas
        frozen = jnp.asarray(step, jnp.int32) > self.freeze_step

        def upd(p, g, m, v, werr, serr, mask):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_local = b1 * m + (1.0 - b1) * g
            # variance frozen in compression stage (adam.py:206)
            v_new = jnp.where(frozen, v, b2 * v + (1.0 - b2) * jnp.square(g))
            if self.comm is not None:
                m_comm, werr_n, serr_n = self.comm(m_local, werr, serr)
            else:
                m_comm, werr_n, serr_n = compressed_allreduce(
                    m_local, werr, serr, axis_name=self.axis_name,
                    world_size=self.world_size)
            m_new = jnp.where(frozen, m_comm, m_local)
            if mask is not None:
                m_new = m_new * mask
            werr_n = jnp.where(frozen, werr_n, werr)
            serr_n = jnp.where(frozen, serr_n, serr)
            update = m_new / (jnp.sqrt(v_new) + self.eps)
            if self.weight_decay > 0.0:
                update = update + self.weight_decay * p32
            p_new = (p32 - lr * update).astype(p.dtype)
            return p_new, m_new, v_new, werr_n, serr_n

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.exp_avg)
        flat_v = treedef.flatten_up_to(state.exp_avg_sq)
        flat_we = treedef.flatten_up_to(state.worker_error)
        flat_se = treedef.flatten_up_to(state.server_error)
        flat_mask = (treedef.flatten_up_to(self.exp_avg_mask)
                     if self.exp_avg_mask is not None else [None] * len(flat_p))
        outs = [upd(*args) for args in zip(flat_p, flat_g, flat_m, flat_v,
                                           flat_we, flat_se, flat_mask)]
        unf = lambda i: treedef.unflatten([o[i] for o in outs])
        return unf(0), OnebitAdamState(exp_avg=unf(1), exp_avg_sq=unf(2),
                                       worker_error=unf(3), server_error=unf(4))
