"""1-bit LAMB: communication-compressed LAMB.

Parity: reference ``deepspeed/runtime/fp16/onebit/lamb.py:11`` (``OnebitLamb``):

- **warmup** (step < freeze_step): baseline LAMB — per-tensor trust ratio
  ``lamb_coeff = clamp(‖w‖/‖update‖, min_coeff, max_coeff)`` with an EMA
  tracked in ``lamb_coeff_freeze`` (``lamb.py:237-247``); at ``freeze_step``
  the variance is snapshotted into ``exp_avg_sq_fresh`` (:229) and a
  per-tensor ``scaling_coeff = united_scale / momentum_scale`` is computed
  (:169-184) to equalize momentum magnitudes before 1-bit compression.
- **compression stage**: momentum updated locally, scaled by
  ``scaling_coeff``, compressed-allreduced, unscaled (:249-255, :336); the
  fresh variance keeps updating from the *reconstructed* gradient
  ``(m - β₁ m_prev)/(1-β₁)`` (:352-356); the effective trust ratio is the
  frozen EMA times a drift factor ``max(√v_frozen+eps / √v_fresh+eps)``
  clipped to [factor_min, factor_max] and rate-limited by
  ``factor_threshold`` against its last value (:364-383).

TPU re-design: branchless jitted update; host-side key flips become
``jnp.where`` on the traced step.  The per-tensor ``united_scale`` (a mean
over ALL tensors' momentum scales) is computed inside the same jitted update
at the freeze boundary.
"""

from typing import NamedTuple, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ...comm.compressed import compressed_allreduce, init_error_buffers


class OnebitLambState(NamedTuple):
    exp_avg: dict
    exp_avg_sq: dict
    exp_avg_sq_fresh: dict
    worker_error: dict
    server_error: dict
    scaling_coeff: dict       # scalar per leaf
    lamb_coeff_freeze: dict   # scalar per leaf (EMA of warmup trust ratios)
    last_factor: dict         # scalar per leaf


class OnebitLamb:
    name = "onebitlamb"

    def __init__(self, lr=1e-3, freeze_step=100000, betas=(0.9, 0.999),
                 eps=1e-8, weight_decay=0.0, max_coeff=10.0, min_coeff=0.01,
                 bias_correction=True, amsgrad=False, cuda_aware=False,
                 comm_backend_name="nccl", coeff_beta=0.9, factor_max=4.0,
                 factor_min=0.5, factor_threshold=0.1,
                 axis_name: Optional[str] = None):
        if amsgrad:
            raise RuntimeError("1-bit Lamb does not support the AMSGrad variant")
        self.lr = lr
        self.freeze_step = freeze_step
        self.betas = tuple(betas)
        self.eps = eps
        self.weight_decay = weight_decay
        self.max_coeff = max_coeff
        self.min_coeff = min_coeff
        self.coeff_beta = coeff_beta
        self.factor_max = factor_max
        self.factor_min = factor_min
        self.factor_threshold = factor_threshold
        self.comm_backend_name = comm_backend_name
        self.axis_name = axis_name
        self.world_size = 1

    def set_world_size(self, n: int):
        self.world_size = int(n) if self.axis_name is not None else 1

    def init(self, params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        one = lambda p: jnp.asarray(1.0, jnp.float32)
        zero = lambda p: jnp.asarray(0.0, jnp.float32)
        werr, serr = init_error_buffers(
            params, self.world_size if self.axis_name is not None else 1)
        tm = jax.tree_util.tree_map
        return OnebitLambState(
            exp_avg=tm(zeros, params), exp_avg_sq=tm(zeros, params),
            exp_avg_sq_fresh=tm(zeros, params),
            worker_error=werr, server_error=serr,
            scaling_coeff=tm(one, params),
            lamb_coeff_freeze=tm(zero, params),
            last_factor=tm(one, params))

    def update(self, grads, state: OnebitLambState, params, *, step, lr=None):
        lr = self.lr if lr is None else lr
        b1, b2 = self.betas
        step = jnp.asarray(step, jnp.int32)
        frozen = step > self.freeze_step
        at_freeze = step == self.freeze_step

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        fl = treedef.flatten_up_to
        flat_g = fl(grads)
        flat_m, flat_v = fl(state.exp_avg), fl(state.exp_avg_sq)
        flat_vf = fl(state.exp_avg_sq_fresh)
        flat_we, flat_se = fl(state.worker_error), fl(state.server_error)
        flat_sc = fl(state.scaling_coeff)
        flat_cf = fl(state.lamb_coeff_freeze)
        flat_lf = fl(state.last_factor)

        # momentum update happens in both stages (lamb.py:227,:253)
        flat_m1 = [b1 * m + (1.0 - b1) * g.astype(jnp.float32)
                   for m, g in zip(flat_m, flat_g)]

        # scaling_coeff at the freeze boundary: united (mean) momentum scale
        # over all tensors / this tensor's scale (lamb.py:169-184)
        mom_scales = [jnp.linalg.norm(m) / np.sqrt(m.size) for m in flat_m1]
        united = sum(mom_scales) / len(mom_scales)
        flat_sc = [jnp.where(at_freeze, united / jnp.maximum(ms, 1e-16), sc)
                   for ms, sc in zip(mom_scales, flat_sc)]
        # variance snapshot at the freeze boundary (lamb.py:229)
        flat_vf = [jnp.where(at_freeze, b2 * v + (1.0 - b2) * jnp.square(g),
                             vf)
                   for v, vf, g in zip(flat_v, flat_vf, flat_g)]

        outs = []
        for (p, g, m_prev, m1, v, vf, we, se, sc, cf, lf) in zip(
                flat_p, flat_g, flat_m, flat_m1, flat_v, flat_vf, flat_we,
                flat_se, flat_sc, flat_cf, flat_lf):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)

            # ---- warmup branch -------------------------------------------
            v_warm = b2 * v + (1.0 - b2) * jnp.square(g)
            upd_warm = m1 / (jnp.sqrt(v_warm) + self.eps)
            if self.weight_decay > 0.0:
                upd_warm = upd_warm + self.weight_decay * p32
            wnorm = jnp.linalg.norm(p32)
            unorm = jnp.linalg.norm(upd_warm)
            coeff = jnp.where((wnorm > 0) & (unorm > 0),
                              jnp.clip(wnorm / jnp.maximum(unorm, 1e-16),
                                       self.min_coeff, self.max_coeff), 1.0)
            cf_new = jnp.where(coeff != 1.0,
                               self.coeff_beta * cf + (1 - self.coeff_beta) * coeff,
                               cf)

            # ---- compression branch (lamb.py:326-386) --------------------
            m_comm, we_n, se_n = compressed_allreduce(
                m1 * sc, we, se, axis_name=self.axis_name,
                world_size=self.world_size)
            m_frozen = m_comm / sc
            grad_recon = (m_frozen - m_prev * b1) / (1.0 - b1)
            vf_new = b2 * vf + (1.0 - b2) * jnp.square(grad_recon)
            denom = jnp.sqrt(v) + self.eps            # frozen variance
            upd_prelim = m_frozen / denom
            if self.weight_decay > 0.0:
                upd_frozen = upd_prelim + self.weight_decay * p32
            else:
                upd_frozen = upd_prelim
            denom_real = jnp.sqrt(vf_new) + self.eps
            factor = jnp.max(denom / denom_real)
            if self.weight_decay > 0.0:
                ratio = jnp.minimum(
                    1.0, jnp.linalg.norm(upd_prelim) /
                    jnp.maximum(jnp.linalg.norm(upd_frozen), 1e-16))
                factor = factor * ratio + (1.0 - ratio)
            factor = jnp.clip(factor, self.factor_min, self.factor_max)
            factor = jnp.clip(factor, lf * (1.0 - self.factor_threshold),
                              lf * (1.0 + self.factor_threshold))
            lamb_coeff_frozen = cf * factor

            # ---- select by stage ----------------------------------------
            sel = lambda a, b: jnp.where(frozen, a, b)
            m_new = sel(m_frozen, m1)
            v_new = sel(v, v_warm)
            vf_out = sel(vf_new, vf)
            p_new = sel(p32 - lr * lamb_coeff_frozen * upd_frozen,
                        p32 - lr * coeff * upd_warm).astype(p.dtype)
            outs.append((p_new, m_new, v_new, vf_out,
                         sel(we_n, we), sel(se_n, se), sc,
                         sel(cf, cf_new), sel(factor, lf)))

        unf = lambda i: treedef.unflatten([o[i] for o in outs])
        new_state = OnebitLambState(
            exp_avg=unf(1), exp_avg_sq=unf(2), exp_avg_sq_fresh=unf(3),
            worker_error=unf(4), server_error=unf(5), scaling_coeff=unf(6),
            lamb_coeff_freeze=unf(7), last_factor=unf(8))
        return unf(0), new_state
