"""0/1 Adam: adaptive-frequency compressed Adam (https://arxiv.org/abs/2202.06009).

Parity: reference ``deepspeed/runtime/fp16/onebit/zoadam.py:14`` (``ZeroOneAdam``):

- Variance is updated only on steps where ``step % var_interval == 0``; the
  interval DOUBLES every ``var_update_scaler`` variance updates
  (``zoadam.py:285-291``) — exponentially rarer exact synchronization.
- On non-variance steps the *gradient* is synchronized with the compressed
  allreduce and folded into the momentum (``zoadam.py:213-233``).
- After ``var_freeze_step`` the variance freezes and "local steps" begin:
  parameters drift locally while an accumulator collects the updates; every
  ``local_step_interval`` steps the accumulated update is compressed-synced
  and applied, the momentum is reconstructed from it, and the interval grows
  (doubling, clipped to ``local_step_clipper``) (``zoadam.py:258-282,303-309``).

TPU re-design: the whole policy state machine (intervals, counters, lr sum,
momentum accumulator) lives as traced int32/fp32 scalars in the optimizer
state; every branch is a ``jnp.where`` so the update stays one jitted SPMD
program.
"""

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ...comm.compressed import compressed_allreduce, init_error_buffers


class ZeroOneAdamState(NamedTuple):
    exp_avg: dict
    exp_avg_sq: dict
    worker_error: dict
    server_error: dict
    momentum_accumulator: dict
    var_interval: jnp.ndarray        # i32 scalar
    var_counter: jnp.ndarray         # i32 scalar
    local_step_interval: jnp.ndarray  # i32 scalar
    local_step_counter: jnp.ndarray  # i32 scalar
    lrs: jnp.ndarray                 # f32 scalar — sum of lrs since last sync


class ZeroOneAdam:
    name = "zerooneadam"

    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.9, 0.999),
                 eps=1e-8, weight_decay=0.0, var_freeze_step=100000,
                 var_update_scaler=16, local_step_scaler=32678,
                 local_step_clipper=16, amsgrad=False, cuda_aware=False,
                 comm_backend_name="nccl", axis_name: Optional[str] = None):
        if amsgrad:
            raise RuntimeError("0/1 Adam does not support the AMSGrad variant")
        self.lr = lr
        self.betas = tuple(betas)
        self.eps = eps
        self.weight_decay = weight_decay
        self.var_freeze_step = var_freeze_step
        self.var_update_scaler = var_update_scaler
        self.local_step_scaler = local_step_scaler
        self.local_step_clipper = local_step_clipper
        self.comm_backend_name = comm_backend_name
        self.axis_name = axis_name
        self.world_size = 1

    def set_world_size(self, n: int):
        self.world_size = int(n) if self.axis_name is not None else 1

    def init(self, params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        werr, serr = init_error_buffers(
            params, self.world_size if self.axis_name is not None else 1)
        tm = jax.tree_util.tree_map
        i32 = lambda v: jnp.asarray(v, jnp.int32)
        return ZeroOneAdamState(
            exp_avg=tm(zeros, params), exp_avg_sq=tm(zeros, params),
            worker_error=werr, server_error=serr,
            momentum_accumulator=tm(zeros, params),
            var_interval=i32(1), var_counter=i32(0),
            local_step_interval=i32(1), local_step_counter=i32(0),
            lrs=jnp.asarray(0.0, jnp.float32))

    def update(self, grads, state: ZeroOneAdamState, params, *, step, lr=None):
        lr = jnp.asarray(self.lr if lr is None else lr, jnp.float32)
        b1, b2 = self.betas
        step = jnp.asarray(step, jnp.int32)
        frozen = step > self.var_freeze_step          # zoadam.py:324-326
        var_step = (step % state.var_interval == 0) & ~frozen
        local_sync = (step % state.local_step_interval == 0) & frozen

        lrs_new = jnp.where(frozen, state.lrs + lr, state.lrs)

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        fl = treedef.flatten_up_to
        outs = []
        for p, g, m, v, we, se, acc in zip(
                flat_p, fl(grads), fl(state.exp_avg), fl(state.exp_avg_sq),
                fl(state.worker_error), fl(state.server_error),
                fl(state.momentum_accumulator)):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)

            # gradient compressed-sync on non-variance steps (zoadam.py:218-233)
            g_onebit, we1, se1 = compressed_allreduce(
                g, we, se, axis_name=self.axis_name, world_size=self.world_size)
            g_eff = jnp.where(var_step | frozen, g, g_onebit)
            we = jnp.where(var_step | frozen, we, we1)
            se = jnp.where(var_step | frozen, se, se1)

            m_new = b1 * m + (1.0 - b1) * g_eff
            v_new = jnp.where(var_step, b2 * v + (1.0 - b2) * jnp.square(g), v)

            update = m_new / (jnp.sqrt(v_new) + self.eps)
            if self.weight_decay > 0.0:
                update = update + self.weight_decay * p32
            p1 = p32 - lr * update
            acc1 = jnp.where(frozen, acc - lr * update, acc)

            # local-step sync (zoadam.py:258-282): apply accumulated update
            # exactly, reconstruct momentum from the synced accumulator
            acc_m = acc1 * (jnp.sqrt(v_new) + self.eps)
            acc_sync, we2, se2 = compressed_allreduce(
                acc_m, we, se, axis_name=self.axis_name,
                world_size=self.world_size)
            p_sync = p1 - acc1 + acc_sync / (jnp.sqrt(v_new) + self.eps)
            m_sync = -acc_sync / jnp.maximum(lrs_new, 1e-16)

            do_sync = local_sync
            p_new = jnp.where(do_sync, p_sync, p1).astype(p.dtype)
            m_out = jnp.where(do_sync, m_sync, m_new)
            acc_out = jnp.where(do_sync, jnp.zeros_like(acc1), acc1)
            we_out = jnp.where(do_sync, we2, we)
            se_out = jnp.where(do_sync, se2, se)
            outs.append((p_new, m_out, v_new, we_out, se_out, acc_out))

        # ---- policy-state updates (zoadam.py:285-309) ----------------------
        vc = jnp.where(var_step, state.var_counter + 1, state.var_counter)
        bump = var_step & (vc == self.var_update_scaler)
        var_counter = jnp.where(bump, 0, vc)
        var_interval = jnp.where(bump, state.var_interval * 2,
                                 state.var_interval)
        lc = jnp.where(frozen, state.local_step_counter + 1,
                       state.local_step_counter)
        lbump = frozen & (lc == self.local_step_scaler)
        local_step_counter = jnp.where(lbump, 0, lc)
        local_step_interval = jnp.where(
            lbump, jnp.minimum(self.local_step_clipper,
                               state.local_step_interval * 2),
            state.local_step_interval)
        lrs_out = jnp.where(local_sync, 0.0, lrs_new)

        unf = lambda i: treedef.unflatten([o[i] for o in outs])
        new_state = ZeroOneAdamState(
            exp_avg=unf(1), exp_avg_sq=unf(2), worker_error=unf(3),
            server_error=unf(4), momentum_accumulator=unf(5),
            var_interval=var_interval, var_counter=var_counter,
            local_step_interval=local_step_interval,
            local_step_counter=local_step_counter, lrs=lrs_out)
        return unf(0), new_state
