"""1-bit / communication-compressed optimizers.

Parity: reference ``deepspeed/runtime/fp16/onebit/`` — ``OnebitAdam``
(``adam.py:14``), ``OnebitLamb`` (``lamb.py:11``), ``ZeroOneAdam``
(``zoadam.py:14``).
"""

from .adam import OnebitAdam
from .lamb import OnebitLamb
from .zoadam import ZeroOneAdam

__all__ = ["OnebitAdam", "OnebitLamb", "ZeroOneAdam"]
