"""Config parsing helpers.

Parity: reference ``deepspeed/runtime/config_utils.py`` (``get_scalar_param``,
``dict_raise_error_on_duplicate_keys``).
"""

import json
from collections import Counter


def get_scalar_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_list_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """Reject duplicate keys while JSON parsing (reference behavior)."""
    d = dict((k, v) for k, v in ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = Counter([pair[0] for pair in ordered_pairs])
        keys = [key for key, value in counter.items() if value > 1]
        raise ValueError("Duplicate keys in DeepSpeed config: {}".format(keys))
    return d


def load_config_dict(config):
    """Accept a path to a JSON file or an already-parsed dict."""
    if isinstance(config, dict):
        return config
    if isinstance(config, str):
        with open(config, "r") as f:
            return json.load(f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
    raise ValueError(f"Expected a dict or path to a JSON file, got: {type(config)}")


class ScientificNotationEncoder(json.JSONEncoder):
    """Print large numbers in scientific notation (reference config printing)."""

    def iterencode(self, o, _one_shot=False, level=0):
        indent = self.indent if self.indent is not None else 4
        prefix_close = " " * level * indent
        level += 1
        prefix = " " * level * indent
        if isinstance(o, bool):
            yield str(o).lower()
        elif isinstance(o, float) or isinstance(o, int):
            if o > 1e3:
                yield f"{o:e}"
            else:
                yield f"{o}"
        elif isinstance(o, dict):
            yield "{"
            first = True
            for k, v in o.items():
                if not first:
                    yield ", "
                yield f"\n{prefix}\"{k}\": "
                yield from self.iterencode(v, level=level)
                first = False
            yield f"\n{prefix_close}}}"
        elif isinstance(o, list) or isinstance(o, tuple):
            yield "["
            first = True
            for v in o:
                if not first:
                    yield ", "
                yield from self.iterencode(v, level=level)
                first = False
            yield "]"
        else:
            yield from super().iterencode(o, _one_shot=_one_shot)
