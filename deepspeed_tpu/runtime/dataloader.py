"""Config-driven data loader.

Parity: reference ``deepspeed/runtime/dataloader.py:33`` (``DeepSpeedDataLoader``)
and ``:10`` (``RepeatingLoader``).

TPU-native difference: the reference builds a per-process
``DistributedSampler`` loader (one process per GPU); here ONE process feeds the
whole mesh, so the loader yields GLOBAL micro-batches (micro_batch × dp_world
samples) as host numpy pytrees and the engine shards them across the
(data, fsdp) mesh axes at device_put time.  This is the idiomatic JAX input
path — it also removes the sampler-rank bookkeeping entirely.

Accepted dataset forms:
- tuple/list of numpy arrays with equal leading dim → samples are tuples
- anything with ``__getitem__``/``__len__`` (torch Dataset included)
- a dict of arrays → samples are dicts
"""

import numpy as np

from ..utils.logging import logger


class RepeatingLoader:
    """Wrap an iterable loader to restart on StopIteration
    (parity: reference ``dataloader.py:10``)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            if hasattr(self.loader, "new_epoch"):
                self.loader.new_epoch()
            self.data_iter = iter(self.loader)
            return next(self.data_iter)

    # -- data-pipeline checkpoint state (docs/health-monitor.md) ----------
    def state_dict(self):
        sd = getattr(self.loader, "state_dict", None)
        return sd() if callable(sd) else None

    def load_state_dict(self, state):
        lsd = getattr(self.loader, "load_state_dict", None)
        if callable(lsd) and state is not None:
            exact = lsd(state)
            # drop the in-flight epoch iterator: the restored position
            # takes effect on the next __next__
            self.data_iter = iter(self.loader)
            return exact
        return None


def _default_collate(samples):
    """Stack a list of samples into a batch pytree of numpy arrays."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(s[k]) for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return tuple(np.stack([np.asarray(s[i]) for s in samples])
                     for i in range(len(first)))
    return np.stack([np.asarray(s) for s in samples])


class DeepSpeedDataLoader:
    """Shuffling, batching loader yielding global micro-batches."""

    def __init__(self, dataset, batch_size, *, shuffle=True, seed=0,
                 drop_last=False, collate_fn=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.batch_index = 0      # batches yielded so far this epoch
        self._resume_batch = 0    # one-shot __iter__ offset set by restore
        self.drop_last = drop_last
        self.collate_fn = collate_fn or _default_collate
        self._columnar = None

        if isinstance(dataset, dict):
            lens = {k: len(v) for k, v in dataset.items()}
            assert len(set(lens.values())) == 1, f"ragged dict dataset: {lens}"
            self._len = next(iter(lens.values()))
            self._columnar = "dict"
        elif isinstance(dataset, (tuple, list)) and len(dataset) > 0 and \
                all(isinstance(a, np.ndarray) for a in dataset):
            lens = [len(a) for a in dataset]
            assert len(set(lens)) == 1, f"ragged tuple dataset: {lens}"
            self._len = lens[0]
            self._columnar = "tuple"
        else:
            self._len = len(dataset)

        if self._len < batch_size:
            logger.warning(f"dataset ({self._len}) smaller than global micro-batch "
                           f"({batch_size}); it will be cycled within one batch")

    def __len__(self):
        if self.drop_last:
            return self._len // self.batch_size
        return (self._len + self.batch_size - 1) // self.batch_size

    def new_epoch(self):
        self.epoch += 1
        self.batch_index = 0
        self._resume_batch = 0

    # -- checkpointable sampler state (docs/health-monitor.md) -------------
    # The batch stream is a pure function of (seed, epoch, batch_index):
    # _order() derives the permutation from seed+epoch, so restoring these
    # three integers resumes the EXACT stream — replay after a
    # load_checkpoint / auto_resume / engine.rewind() sees the same batches
    # in the same order instead of restarting the sampler from scratch.
    def state_dict(self):
        # batch_size makes the position RESHARDABLE: an elastic resume on a
        # different mesh changes the global micro-batch, so batch_index has
        # to be converted through the invariant unit (rows consumed)
        return {"seed": self.seed, "epoch": self.epoch,
                "batch_index": self.batch_index,
                "batch_size": self.batch_size}

    def load_state_dict(self, state):
        """Restore the sampler position.  Returns True when the restored
        position is exact, False when a batch-size change (elastic resume
        on a different mesh) landed between batch boundaries and the
        position was floored — the caller then knows up to one batch of
        rows may replay."""
        self.seed = int(state.get("seed", self.seed))
        self.epoch = int(state.get("epoch", 0))
        idx = int(state.get("batch_index", 0))
        saved_bs = int(state.get("batch_size", self.batch_size))
        exact = True
        if saved_bs != self.batch_size:
            # the row stream is a pure function of (seed, epoch) — only the
            # grouping into batches changes with the global micro-batch, so
            # position converts through rows.  Checkpoints land on optimizer
            # -step boundaries, where rows are a multiple of the (preserved)
            # global batch — exact whenever the global batch really was
            # preserved across the resize.
            rows = idx * saved_bs
            idx, rem = divmod(rows, self.batch_size)
            if rem:
                exact = False
                logger.warning(
                    f"data-loader position ({rows} rows at saved batch_size "
                    f"{saved_bs}) does not land on a batch boundary at the "
                    f"new batch_size {self.batch_size}; resuming at batch "
                    f"{idx} — up to {rem} rows replay")
        self.batch_index = idx
        # consumed by the NEXT __iter__ only: a plain re-iteration (no
        # restore) keeps the historical restart-from-zero semantics
        self._resume_batch = self.batch_index
        return exact

    def _order(self):
        idx = np.arange(self._len)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(idx)
        return idx

    def _take(self, indices):
        if self._columnar == "dict":
            return {k: np.asarray(v)[indices] for k, v in self.dataset.items()}
        if self._columnar == "tuple":
            return tuple(np.asarray(a)[indices] for a in self.dataset)
        return self.collate_fn([self.dataset[int(i)] for i in indices])

    def _batch_indices(self, order):
        """The epoch's batch index-arrays, in yield order (deterministic
        given (seed, epoch) — the contract state_dict restore relies on)."""
        n_full = self._len // self.batch_size
        for b in range(n_full):
            yield order[b * self.batch_size:(b + 1) * self.batch_size]
        rem = self._len - n_full * self.batch_size
        if rem and not self.drop_last:
            # pad the tail by cycling (keeps shapes static for jit; np.resize
            # repeats the order as many times as needed for tiny datasets)
            tail = order[n_full * self.batch_size:]
            pad = np.resize(order, self.batch_size - rem)
            yield np.concatenate([tail, pad])
        elif self._len < self.batch_size and n_full == 0:
            # tiny dataset + drop_last: cycle to one full batch rather than
            # yielding nothing (RepeatingLoader would otherwise spin forever)
            yield np.resize(order, self.batch_size)

    def __iter__(self):
        start, self._resume_batch = self._resume_batch, 0
        self.batch_index = start
        order = self._order()
        for i, idx in enumerate(self._batch_indices(order)):
            if i < start:
                continue
            self.batch_index = i + 1
            yield self._take(idx)
