"""Checkpoint loaders for MP-degree-changing loads.

Parity: reference ``deepspeed/runtime/state_dict_factory.py`` —
``SDLoaderFactory`` (:17) and ``MegatronSDLoader`` (:195): given a JSON
descriptor ``{'type': 'Megatron', 'checkpoints': [...], 'version': ...}``
they load MP-sharded torch checkpoints and MERGE (when target mp_world_size
< source) or SPLIT (when larger) attention/mlp weights along the right axes.

TPU re-design: checkpoints here store FULL arrays (gathered at save), so
changing the tensor-parallel degree needs no file surgery — resharding is a
``device_put`` with the new mesh's partition specs.  The factory therefore:

- loads this framework's checkpoints directly (single file), and
- still supports multi-file descriptors by merging shard files with the
  Megatron axis rules (column-parallel concat on the output axis,
  row-parallel on the input axis) so externally produced sharded dumps can
  be imported.
"""

import json
import os
from typing import List, Optional

import numpy as np

from ..checkpoint.serialization import load_tree
from ..utils.logging import logger

AUTO_MODULE_KEY = "auto"


class SDLoaderFactory:
    @staticmethod
    def get_sd_loader_json(json_file_or_dict):
        """Parity: reference ``SDLoaderFactory.get_sd_loader_json`` (:17)."""
        if isinstance(json_file_or_dict, str):
            with open(json_file_or_dict) as f:
                data = json.load(f)
        else:
            data = dict(json_file_or_dict)
        sd_type = data["type"]
        ckpt_list = data["checkpoints"]
        version = data.get("version", 1.0)
        return SDLoaderFactory.get_sd_loader(ckpt_list, sd_type, version)

    @staticmethod
    def get_sd_loader(ckpt_list, sd_type="Megatron", version=None):
        if sd_type.lower() in ("megatron", "ds_model", "auto"):
            return MegatronSDLoader(ckpt_list, version)
        raise ValueError(f"Unknown checkpoint loader type {sd_type}")


class SDLoaderBase:
    def __init__(self, ckpt_list: List[str], version=None):
        self.ckpt_list = list(ckpt_list)
        self.version = version

    def _load_one(self, path):
        tree, meta = load_tree(path, with_meta=True)
        return tree.get("params", tree), meta

    def load(self, mp_world_size: int, mp_rank: int, module_key=AUTO_MODULE_KEY,
             is_pipe_parallel=False, quantize=False, quantize_bits=8,
             quantize_groups=64, mlp_extra_grouping=True):
        """Returns ``(ckpt_file_name, full_param_tree, meta)``.

        Unlike the reference (which returns the mp_rank's slice), the full
        tree is returned — slicing to ``mp_world_size`` happens when the
        caller device_puts with its tensor-parallel partition specs.
        """
        if len(self.ckpt_list) == 1:
            tree, meta = self._load_one(self.ckpt_list[0])
            return self.ckpt_list[0], tree, meta
        return self.merge_state_dict(mp_world_size, mp_rank)

    def merge_state_dict(self, mp_world_size, mp_rank):
        raise NotImplementedError


class MegatronSDLoader(SDLoaderBase):
    """Merges multi-file tensor-parallel shard dumps (parity: reference
    ``MegatronSDLoader`` :195 — qkv/mlp merge rules)."""

    # substrings → concat axis (Megatron column-parallel outputs on the last
    # axis, row-parallel inputs on the first weight axis)
    COLUMN_PARALLEL = ("qkv", "query_key_value", "fc_w", "dense_h_to_4h",
                       "attention.query", "wte")
    ROW_PARALLEL = ("proj_w", "dense_4h_to_h", "attention.dense", "fc_proj_w")

    def merge_state_dict(self, mp_world_size, mp_rank):
        trees = []
        meta = None
        for path in self.ckpt_list:
            t, m = self._load_one(path)
            trees.append(t)
            meta = meta or m

        def merge(key_path, leaves):
            name = "/".join(key_path)
            a0 = np.asarray(leaves[0])
            if all(np.asarray(l).shape == a0.shape for l in leaves[1:]):
                if any(s in name for s in self.COLUMN_PARALLEL):
                    return np.concatenate([np.asarray(l) for l in leaves],
                                          axis=a0.ndim - 1)
                if any(s in name for s in self.ROW_PARALLEL):
                    axis = max(0, a0.ndim - 2)
                    return np.concatenate([np.asarray(l) for l in leaves],
                                          axis=axis)
            # replicated leaves (layernorms, biases of row-parallel): take one
            return a0

        merged = _tree_merge(trees, merge)
        logger.info(f"merged {len(trees)} checkpoint shards")
        return self.ckpt_list[0], merged, meta


def _tree_merge(trees, fn, path=()):
    first = trees[0]
    if isinstance(first, dict):
        return {k: _tree_merge([t[k] for t in trees], fn, path + (k,))
                for k in first}
    return fn(path, trees)
