"""Checkpoint loaders for MP-degree-changing loads.

Parity: reference ``deepspeed/runtime/state_dict_factory.py`` —
``SDLoaderFactory`` (:17) and ``MegatronSDLoader`` (:195): given a JSON
descriptor ``{'type': 'Megatron', 'checkpoints': [...], 'version': ...}``
they load MP-sharded torch checkpoints and MERGE (when target mp_world_size
< source) or SPLIT (when larger) attention/mlp weights along the right axes.

TPU re-design: checkpoints here store FULL arrays (gathered at save), so
changing the tensor-parallel degree needs no file surgery — resharding is a
``device_put`` with the new mesh's partition specs.  The factory therefore:

- loads this framework's checkpoints directly (single file), and
- still supports multi-file descriptors by merging shard files with the
  Megatron axis rules (column-parallel concat on the output axis,
  row-parallel on the input axis) so externally produced sharded dumps can
  be imported.
"""

import json
import os
from typing import List, Optional

import numpy as np

from ..checkpoint.serialization import load_tree
from ..utils.logging import logger

AUTO_MODULE_KEY = "auto"


class SDLoaderFactory:
    @staticmethod
    def get_sd_loader_json(json_file_or_dict):
        """Parity: reference ``SDLoaderFactory.get_sd_loader_json`` (:17)."""
        if isinstance(json_file_or_dict, str):
            with open(json_file_or_dict) as f:
                data = json.load(f)
        else:
            data = dict(json_file_or_dict)
        sd_type = data["type"]
        ckpt_list = data["checkpoints"]
        version = data.get("version", 1.0)
        return SDLoaderFactory.get_sd_loader(ckpt_list, sd_type, version)

    @staticmethod
    def get_sd_loader(ckpt_list, sd_type="Megatron", version=None):
        if sd_type.lower() in ("megatron", "ds_model", "auto"):
            return MegatronSDLoader(ckpt_list, version)
        raise ValueError(f"Unknown checkpoint loader type {sd_type}")


class SDLoaderBase:
    def __init__(self, ckpt_list: List[str], version=None):
        self.ckpt_list = list(ckpt_list)
        self.version = version

    def _load_one(self, path):
        tree, meta = load_tree(path, with_meta=True)
        return tree.get("params", tree), meta

    def load(self, mp_world_size: int, mp_rank: int, module_key=AUTO_MODULE_KEY,
             is_pipe_parallel=False, quantize=False, quantize_bits=8,
             quantize_groups=64, mlp_extra_grouping=True):
        """Returns ``(ckpt_file_name, full_param_tree, meta)``.

        Unlike the reference (which returns the mp_rank's slice), the full
        tree is returned — slicing to ``mp_world_size`` happens when the
        caller device_puts with its tensor-parallel partition specs.
        """
        if len(self.ckpt_list) == 1:
            tree, meta = self._load_one(self.ckpt_list[0])
            return self.ckpt_list[0], tree, meta
        return self.merge_state_dict(mp_world_size, mp_rank)

    def merge_state_dict(self, mp_world_size, mp_rank):
        raise NotImplementedError


class MegatronSDLoader(SDLoaderBase):
    """Merges/splits multi-file tensor-parallel shard dumps (parity:
    reference ``MegatronSDLoader`` :195-453 — version-aware qkv rules +
    column/row merge axes)."""

    # substrings → parallel class.  QKV is handled separately (fused
    # query-key-value layouts vary across Megatron checkpoint versions).
    QKV = ("query_key_value", "qkv")
    COLUMN_PARALLEL = ("fc_w", "dense_h_to_4h", "attention.query", "wte",
                       "word_embeddings")
    ROW_PARALLEL = ("proj_w", "dense_4h_to_h", "attention.dense", "fc_proj_w")

    SUPPORTED_QKV_VERSIONS = (0, 1.0, 2.0)

    @staticmethod
    def _out_axis(name, arr):
        """Torch/Megatron dumps store (out, in) → output axis 0; this
        framework's matmul weights store (in, out) → output axis -1.  The
        torch-style dotted names mark the layout.  Embedding tables shard
        VOCAB-parallel on axis 0 in BOTH layouts (``wte: P('tensor', None)``,
        models/gpt2.py partition_specs)."""
        torch_style = ("query_key_value" in name or "dense" in name
                       or "word_embeddings" in name or "attention." in name)
        embedding = "wte" in name or "word_embeddings" in name
        return 0 if torch_style or embedding or arr.ndim == 1 \
            else arr.ndim - 1

    # ------------------------------------------------ qkv (version-aware)
    def merge_query_key_value(self, param_list, ckpt_ver, axis=0):
        """Merge fused-qkv shards (reference :224-257).

        version 0:        [(3·np·hn), h]  — components grouped q|k|v per
                          shard: split each shard in 3, concat per component
                          across shards, then concat the components;
        version 1.0/2.0:  [(np·hn·3), h] / [(np·3·hn), h] — heads are the
                          outer grouping: plain concat across shards.
        """
        if ckpt_ver not in self.SUPPORTED_QKV_VERSIONS:
            raise AssertionError(
                f"checkpoint version: {ckpt_ver} is not supported")
        arrs = [np.asarray(p) for p in param_list]
        if ckpt_ver == 0:
            assert arrs[0].shape[axis] % 3 == 0
            split_tensors = [np.split(a, 3, axis=axis) for a in arrs]
            comps = [np.concatenate([t[i] for t in split_tensors], axis=axis)
                     for i in range(3)]
            return np.concatenate(comps, axis=axis)
        return np.concatenate(arrs, axis=axis)

    def split_query_key_value(self, param, num_to_split, offset, ckpt_ver,
                              axis=0):
        """Slice one mp_rank's fused-qkv shard back out (reference :264-300)."""
        if ckpt_ver not in self.SUPPORTED_QKV_VERSIONS:
            raise AssertionError(
                f"checkpoint version: {ckpt_ver} is not supported")
        arr = np.asarray(param)
        if ckpt_ver == 0:
            assert arr.shape[axis] % 3 == 0
            comps = np.split(arr, 3, axis=axis)
            assert comps[0].shape[axis] % num_to_split == 0
            picked = [np.split(c, num_to_split, axis=axis)[offset]
                      for c in comps]
            return np.concatenate(picked, axis=axis)
        assert arr.shape[axis] % num_to_split == 0
        return np.split(arr, num_to_split, axis=axis)[offset]

    # --------------------------------------------------------------- merge
    def merge_state_dict(self, mp_world_size, mp_rank):
        trees = []
        meta = None
        for path in self.ckpt_list:
            t, m = self._load_one(path)
            trees.append(t)
            meta = meta or m
        version = self.version if self.version is not None else 1.0

        def merge(key_path, leaves):
            name = "/".join(str(k) for k in key_path)
            a0 = np.asarray(leaves[0])
            if all(np.asarray(l).shape == a0.shape for l in leaves[1:]):
                if any(s in name for s in self.QKV):
                    return self.merge_query_key_value(
                        leaves, version, axis=self._out_axis(name, a0))
                if any(s in name for s in self.COLUMN_PARALLEL):
                    return np.concatenate([np.asarray(l) for l in leaves],
                                          axis=self._out_axis(name, a0))
                if any(s in name for s in self.ROW_PARALLEL) and a0.ndim >= 2:
                    # 1-D row-parallel leaves (biases) are replicated —
                    # fall through to take-one
                    axis = 1 if self._out_axis(name, a0) == 0 \
                        else max(0, a0.ndim - 2)
                    return np.concatenate([np.asarray(l) for l in leaves],
                                          axis=axis)
            # replicated leaves (layernorms, biases of row-parallel): take one
            return a0

        merged = _tree_merge(trees, merge)
        logger.info(f"merged {len(trees)} checkpoint shards "
                    f"(qkv version {version})")
        return self.ckpt_list[0], merged, meta

    # --------------------------------------------------------------- split
    def get_split_state_dict(self, mp_world_size, mp_rank):
        """One mp_rank's shard of the (merged) full tree — the reference's
        split path (:374-453) for exporting to a LARGER tensor-parallel
        degree."""
        _, full, meta = self.load(1, 0)
        version = self.version if self.version is not None else 1.0

        def split(key_path, leaves):
            name = "/".join(str(k) for k in key_path)
            arr = np.asarray(leaves[0])
            if any(s in name for s in self.QKV):
                return self.split_query_key_value(
                    arr, mp_world_size, mp_rank, version,
                    axis=self._out_axis(name, arr))
            if any(s in name for s in self.COLUMN_PARALLEL):
                axis = self._out_axis(name, arr)
                if arr.shape[axis] % mp_world_size == 0:
                    return np.split(arr, mp_world_size, axis=axis)[mp_rank]
                return arr
            if any(s in name for s in self.ROW_PARALLEL):
                axis = 1 if self._out_axis(name, arr) == 0 \
                    else max(0, arr.ndim - 2)
                if arr.ndim >= 2 and arr.shape[axis] % mp_world_size == 0:
                    return np.split(arr, mp_world_size, axis=axis)[mp_rank]
                return arr
            return arr

        return _tree_merge([full], split), meta


def _tree_merge(trees, fn, path=()):
    first = trees[0]
    if isinstance(first, dict):
        return {k: _tree_merge([t[k] for t in trees], fn, path + (k,))
                for k in first}
    return fn(path, trees)
