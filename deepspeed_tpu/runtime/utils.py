"""Runtime helpers: grad norms/clipping, partitioning math, memory reporting.

Parity: reference ``deepspeed/runtime/utils.py`` (``clip_grad_norm_`` :328,
``partition_uniform`` :576, ``partition_balanced`` :642, ``see_memory_usage``
:818, ``DummyOptim`` :37).
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import logger


class DummyOptim:
    """Placeholder optimizer when the engine runs without one
    (parity: reference ``runtime/utils.py:37``)."""

    def __init__(self, params=None):
        self.params = params

    def init(self, params):
        return ()

    def update(self, grads, state, params, *, step, lr=None):
        return params, state


def global_norm(tree, ord=2):
    """Global grad norm across a pytree (fp32 accumulation).

    Parity: reference ``get_grad_norm_direct`` (``stage_1_and_2.py:1496``) /
    ``clip_grad_norm_`` (``utils.py:328``).  Under SPMD the sum-of-squares over
    sharded leaves is reduced by XLA automatically — no mpu allreduce needed.
    """
    leaves = [g for g in jax.tree_util.tree_leaves(tree) if g is not None]
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    if ord == 2:
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
        return jnp.sqrt(sq)
    if ord == float("inf"):
        return jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in leaves]))
    total = sum(jnp.sum(jnp.abs(g.astype(jnp.float32)) ** ord) for g in leaves)
    return total ** (1.0 / ord)


def clip_by_global_norm(tree, max_norm, *, norm=None, eps=1e-6):
    """torch.nn.utils.clip_grad_norm_ semantics (reference ``utils.py:328``):
    scale = max_norm / (total_norm + eps), applied only when < 1."""
    if norm is None:
        norm = global_norm(tree)
    clip_coef = max_norm / (norm + eps)
    clip_coef = jnp.minimum(clip_coef, 1.0)
    return jax.tree_util.tree_map(lambda g: g * clip_coef, tree), norm


def get_global_norm(norm_list):
    """Combine per-group norms (reference ``utils.py get_global_norm``)."""
    total = sum(n ** 2.0 for n in norm_list)
    return np.sqrt(total)


def partition_uniform(num_items, num_parts):
    """Split num_items into num_parts contiguous ranges, remainder spread left.

    Returns ``parts`` of len num_parts+1 (prefix offsets).
    Parity: reference ``utils.py:576``.
    """
    parts = [0] * (num_parts + 1)
    chunksize = num_items // num_parts
    residual = num_items - (chunksize * num_parts)
    for p in range(num_parts):
        parts[p + 1] = parts[p] + chunksize + (1 if p < residual else 0)
    assert parts[-1] == num_items
    return parts

def prefix_sum_inc(weights):
    """Inclusive prefix sum (reference ``utils.py prefix_sum_inc``)."""
    out = list(weights)
    for i in range(1, len(out)):
        out[i] += out[i - 1]
    return out


def partition_balanced(weights, num_parts, eps=1e-3):
    """Binary-search the bottleneck so contiguous parts have near-equal weight.

    Parity: reference ``utils.py:642`` (used by PipelineModule
    ``method='parameters'`` partitioning).
    """
    num_items = len(weights)
    if num_items <= num_parts:
        # degenerate: one item per part
        return partition_uniform(num_items, num_parts)

    prefix = [0] + prefix_sum_inc(weights)

    def parts_for_bottleneck(bottleneck):
        # greedy: pack while under bottleneck
        parts = [0]
        total = 0
        for i, w in enumerate(weights):
            if w > bottleneck:
                return None
            if total + w > bottleneck:
                parts.append(i)
                total = 0
            total += w
        parts.append(num_items)
        return parts if len(parts) <= num_parts + 1 else None

    lo, hi = max(weights), sum(weights)
    while hi - lo > eps * max(1.0, lo):
        mid = (lo + hi) / 2
        if parts_for_bottleneck(mid) is not None:
            hi = mid
        else:
            lo = mid
    parts = parts_for_bottleneck(hi)
    # pad to exactly num_parts ranges
    while len(parts) < num_parts + 1:
        parts.append(num_items)
    return parts


def see_memory_usage(message, force=False, bus=None):
    """Device + host memory report (parity: reference ``utils.py:818``).

    Readings come from the ONE shared helpers in ``monitor/gauges.py``
    (``memory_stats`` for the device, ``host_rss_hwm_bytes`` for the
    Linux ``ru_maxrss`` HWM — that helper's docstring owns the KB-unit
    note, so the conversion is derived exactly once).  With ``bus``
    (a ``MonitorBus``) the readings ALSO land as proper ``gauge``
    events — the log line below is then just a sink-side rendering of
    the same numbers, DSTPU104-consistent instead of a metrics
    side-channel."""
    if not force:
        return
    from ..monitor import gauges as mg
    stats = mg.memory_stats()
    rss_hwm = mg.host_rss_hwm_bytes()
    if bus is not None:
        for name, val in (("device_mem_in_use", stats.get("bytes_in_use")),
                          ("device_mem_peak",
                           stats.get("peak_bytes_in_use")),
                          ("host_rss_hwm", rss_hwm or None)):
            if val:
                bus.gauge(name, int(val), context=message)
    if stats:
        logger.info(
            f"{message} | device mem: "
            f"in_use={stats.get('bytes_in_use', 0) / 2**30:.2f}GB "
            f"peak={stats.get('peak_bytes_in_use', 0) / 2**30:.2f}GB "
            f"limit={stats.get('bytes_limit', 0) / 2**30:.2f}GB")
    else:
        logger.info(f"{message} | device memory stats unavailable")
    if rss_hwm:
        logger.info(f"{message} | host peak RSS {rss_hwm / 2**30:.2f}GB")


def call_to_str(base, *args, **kwargs):
    """Debug formatter (parity: reference ``utils.py call_to_str``)."""
    name = f"{base}("
    if args:
        name += ", ".join(str(arg) for arg in args)
        if kwargs:
            name += ", "
    if kwargs:
        name += ", ".join(f"{key}={arg}" for key, arg in kwargs.items())
    name += ")"
    return name


def tree_cast(tree, dtype):
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)


def tree_zeros_like(tree, dtype=None):
    return jax.tree_util.tree_map(
        lambda x: jnp.zeros(x.shape, dtype or x.dtype), tree)


def tree_size_bytes(tree):
    return sum(x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(tree))


def ensure_divisibility(numerator, denominator, msg=""):
    assert numerator % denominator == 0, \
        f"{msg}{numerator} is not divisible by {denominator}"
