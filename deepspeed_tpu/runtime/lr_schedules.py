"""LR schedules: LRRangeTest, OneCycle, WarmupLR, WarmupDecayLR.

Parity: reference ``deepspeed/runtime/lr_schedules.py:310,563,685,772`` — same
schedule names, parameter names, and shapes of the curves.

TPU-native design: each schedule is fundamentally a PURE function
``lr(step) -> float`` (exposed as ``.lr_fn``) so it can be traced into the
jitted train step (the step counter lives on device).  The class wrappers keep
the reference's stateful API (``step()``, ``get_lr()``, ``state_dict()``)
for users porting DeepSpeed training scripts.
"""

import math

import jax.numpy as jnp

LR_SCHEDULE = "lr_schedule"
LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]

LR_RANGE_TEST_MIN_LR = "lr_range_test_min_lr"
LR_RANGE_TEST_STEP_RATE = "lr_range_test_step_rate"
LR_RANGE_TEST_STEP_SIZE = "lr_range_test_step_size"
LR_RANGE_TEST_STAIRCASE = "lr_range_test_staircase"

WARMUP_MIN_LR = "warmup_min_lr"
WARMUP_MAX_LR = "warmup_max_lr"
WARMUP_NUM_STEPS = "warmup_num_steps"
WARMUP_TYPE = "warmup_type"
WARMUP_LOG_RATE = "log"
WARMUP_LINEAR_RATE = "linear"

TOTAL_NUM_STEPS = "total_num_steps"


class _ScheduleBase:
    """Stateful wrapper over a pure ``lr(step)`` function.

    ``optimizer`` is optional: when the engine owns the update, the schedule's
    ``lr_fn`` is traced into the train step directly and this object only
    mirrors state for logging/checkpointing.
    """

    def __init__(self, optimizer=None, last_batch_iteration=-1):
        self.optimizer = optimizer
        self.last_batch_iteration = last_batch_iteration

    # -- pure function; subclasses implement with jnp so it is traceable ----
    def lr_fn(self, step):
        raise NotImplementedError

    def get_lr(self):
        step = max(0, self.last_batch_iteration)
        return [float(self.lr_fn(step))]

    def get_last_lr(self):
        assert getattr(self, "_last_lr", None) is not None, "need to call step() first"
        return self._last_lr

    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        self._last_lr = self.get_lr()
        if self.optimizer is not None and hasattr(self.optimizer, "set_lr"):
            self.optimizer.set_lr(self._last_lr[0])
        return self._last_lr

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


class LRRangeTest(_ScheduleBase):
    """LR range-test sweep. Parity: reference ``lr_schedules.py:310``."""

    def __init__(self, optimizer=None, lr_range_test_min_lr=1e-3,
                 lr_range_test_step_size=2000, lr_range_test_step_rate=1.0,
                 lr_range_test_staircase=False, last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)
        if lr_range_test_step_size <= 0:
            raise ValueError(f"Step size {lr_range_test_step_size} must be positive")
        self.min_lr = lr_range_test_min_lr
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase

    def lr_fn(self, step):
        step = jnp.asarray(step, jnp.float32)
        if self.staircase:
            interval = jnp.floor(step / self.step_size)
        else:
            interval = step / self.step_size
        return self.min_lr * (1.0 + interval * self.step_rate)


class OneCycle(_ScheduleBase):
    """1-cycle policy (up-phase, down-phase, then decay).

    Parity: reference ``lr_schedules.py:563`` (lr cycling + optional momentum
    cycling; momentum exposed via :meth:`momentum_fn` for optimizers that use it).
    """

    def __init__(self, optimizer=None, cycle_min_lr=0.0, cycle_max_lr=1e-2,
                 decay_lr_rate=0.0, cycle_first_step_size=2000,
                 cycle_second_step_size=None, cycle_first_stair_count=0,
                 cycle_second_stair_count=None, decay_step_size=0,
                 cycle_momentum=True, cycle_min_mom=0.8, cycle_max_mom=0.9,
                 decay_mom_rate=0.0, last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)
        self.cycle_min_lr = cycle_min_lr
        self.cycle_max_lr = cycle_max_lr
        self.decay_lr_rate = decay_lr_rate
        self.first_size = cycle_first_step_size
        self.second_size = (cycle_second_step_size
                            if cycle_second_step_size is not None else cycle_first_step_size)
        self.decay_step_size = decay_step_size
        self.cycle_momentum = cycle_momentum
        self.cycle_min_mom = cycle_min_mom
        self.cycle_max_mom = cycle_max_mom
        self.decay_mom_rate = decay_mom_rate
        self.total_size = self.first_size + self.second_size

    def lr_fn(self, step):
        step = jnp.asarray(step, jnp.float32)
        up = jnp.clip(step / self.first_size, 0.0, 1.0)
        down = jnp.clip((step - self.first_size) / self.second_size, 0.0, 1.0)
        cycle_lr = self.cycle_min_lr + (self.cycle_max_lr - self.cycle_min_lr) * (up - down)
        # decay phase after the cycle completes
        decay_steps = jnp.maximum(step - self.total_size, 0.0)
        if self.decay_step_size > 0:
            decay_intervals = jnp.floor(decay_steps / self.decay_step_size)
        else:
            decay_intervals = decay_steps
        decayed = self.cycle_min_lr / (1.0 + self.decay_lr_rate * decay_intervals)
        return jnp.where(step <= self.total_size, cycle_lr, decayed)

    def momentum_fn(self, step):
        step = jnp.asarray(step, jnp.float32)
        up = jnp.clip(step / self.first_size, 0.0, 1.0)
        down = jnp.clip((step - self.first_size) / self.second_size, 0.0, 1.0)
        # momentum runs opposite to lr: high at the ends, low mid-cycle
        cycle_mom = self.cycle_max_mom - (self.cycle_max_mom - self.cycle_min_mom) * (up - down)
        decay_steps = jnp.maximum(step - self.total_size, 0.0)
        if self.decay_step_size > 0:
            decay_intervals = jnp.floor(decay_steps / self.decay_step_size)
        else:
            decay_intervals = decay_steps
        decayed = self.cycle_max_mom * (1.0 + self.decay_mom_rate * decay_intervals)
        return jnp.where(step <= self.total_size, cycle_mom, decayed)

    def get_mom(self):
        step = max(0, self.last_batch_iteration)
        return [float(self.momentum_fn(step))]


class WarmupLR(_ScheduleBase):
    """Warmup from min to max lr, then hold. Parity: ``lr_schedules.py:685``."""

    def __init__(self, optimizer=None, warmup_min_lr=0.0, warmup_max_lr=0.001,
                 warmup_num_steps=1000, warmup_type=WARMUP_LOG_RATE,
                 last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)
        self.min_lr = warmup_min_lr
        self.max_lr = warmup_max_lr
        self.warmup_num_steps = max(2, warmup_num_steps)
        if warmup_type not in (WARMUP_LOG_RATE, WARMUP_LINEAR_RATE):
            raise ValueError(f"warmup_type {warmup_type} must be 'log' or 'linear'")
        self.warmup_type = warmup_type
        self.inverse_log_warm_up = 1.0 / math.log(self.warmup_num_steps)

    def _warmup_gamma(self, step):
        step = jnp.asarray(step, jnp.float32)
        if self.warmup_type == WARMUP_LOG_RATE:
            # log warmup: gamma = log(step+1)/log(warmup_num_steps)
            gamma = self.inverse_log_warm_up * jnp.log(step + 1.0)
        else:
            gamma = step / self.warmup_num_steps
        return jnp.clip(gamma, 0.0, 1.0)

    def lr_fn(self, step):
        gamma = self._warmup_gamma(step)
        return self.min_lr + (self.max_lr - self.min_lr) * gamma


class WarmupDecayLR(WarmupLR):
    """Warmup then linear decay to zero at total_num_steps.

    Parity: ``lr_schedules.py:772``.
    """

    def __init__(self, optimizer=None, total_num_steps=10000, warmup_min_lr=0.0,
                 warmup_max_lr=0.001, warmup_num_steps=1000,
                 warmup_type=WARMUP_LOG_RATE, last_batch_iteration=-1):
        self.total_num_steps = total_num_steps
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr, warmup_num_steps,
                         warmup_type, last_batch_iteration)
        if self.total_num_steps < self.warmup_num_steps:
            from ..utils.logging import logger
            logger.warning(f"total_num_steps {total_num_steps} is less than "
                           f"warmup_num_steps {warmup_num_steps}")

    def lr_fn(self, step):
        step_f = jnp.asarray(step, jnp.float32)
        warm = super().lr_fn(step)
        decay = jnp.clip(
            (self.total_num_steps - step_f) /
            max(1.0, self.total_num_steps - self.warmup_num_steps),
            0.0, 1.0)
        # decays to warmup_min_lr, not zero (reference lr = min_lr + delta*gamma)
        decayed = self.min_lr + (self.max_lr - self.min_lr) * decay
        return jnp.where(step_f < self.warmup_num_steps, warm, decayed)


SCHEDULE_CLASSES = {
    LR_RANGE_TEST: LRRangeTest,
    ONE_CYCLE: OneCycle,
    WARMUP_LR: WarmupLR,
    WARMUP_DECAY_LR: WarmupDecayLR,
}


def get_lr_scheduler(name, params, optimizer=None):
    """Instantiate a scheduler from the config's ``scheduler`` section."""
    if name not in SCHEDULE_CLASSES:
        raise ValueError(f"Unknown LR schedule {name!r}; valid: {VALID_LR_SCHEDULES}")
    return SCHEDULE_CLASSES[name](optimizer=optimizer, **params)
