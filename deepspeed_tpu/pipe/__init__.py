"""Public pipeline API (parity: reference ``deepspeed/pipe/__init__.py``)."""

from ..runtime.pipe import PipelineModule, LayerSpec, TiedLayerSpec
