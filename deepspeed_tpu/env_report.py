"""``ds_report``: environment + op compatibility report.

Parity: reference ``deepspeed/env_report.py`` (``op_report`` :24, ``main``)
— prints the compatible/installed matrix of ops plus framework versions.
The JIT-compile columns of the reference become backend-compatibility
columns (no CUDA builds on TPU; Pallas/XLA paths either lower or they don't).
"""

import importlib
import sys

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
SUCCESS = f"{GREEN}[OKAY]{END}"
WARNING = f"{YELLOW}[WARNING]{END}"
FAIL = f"{RED}[FAIL]{END}"
INFO = "[INFO]"

COLUMNS = ["op name", "installed", "compatible"]


def op_report():
    """Print the op compatibility matrix (parity: reference ``op_report``)."""
    from . import ops
    max_dots = 23
    print("-" * 64)
    print("DeepSpeed-TPU op report")
    print("-" * 64)
    print("op name" + "." * (max_dots - len("op name")) +
          " installed .. compatible")
    print("-" * 64)

    rows = [
        ("flash_attention[pallas]", True, ops.flash_attention_available()),
        ("sparse_attention[pallas]", True, ops.flash_attention_available()),
        ("fused_adam", True, True),
        ("fused_lamb", True, True),
        ("cpu_adam (host offload)", _has("deepspeed_tpu.ops.adam.fused_adam"), True),
        ("cpu_adagrad", _has("deepspeed_tpu.ops.adagrad.cpu_adagrad"), True),
        ("quantizer", _has("deepspeed_tpu.ops.quantizer.quantizer"), True),
        ("transformer_inference", _has("deepspeed_tpu.inference.engine"), True),
        ("async_io (NVMe)", _has("deepspeed_tpu.ops.aio"), _has("deepspeed_tpu.ops.aio")),
    ]
    for name, installed, compatible in rows:
        print(f"{name}{'.' * max(1, max_dots - len(name))} "
              f"{SUCCESS if installed else FAIL} ...... "
              f"{SUCCESS if compatible else WARNING}")
    for name, entry in sorted(ops.OP_REGISTRY.items()):
        comp = ops.backend() in entry["backends"]
        print(f"{name}{'.' * max(1, max_dots - len(name))} "
              f"{SUCCESS} ...... {SUCCESS if comp else WARNING}")
    print("-" * 64)


def _has(mod):
    try:
        importlib.import_module(mod)
        return True
    except Exception:
        return False


def debug_report():
    """Versions + device info (parity: reference ``debug_report``)."""
    import jax
    from .version import __version__

    devices = []
    try:
        devices = jax.devices()
    except Exception as e:
        devices = [f"<unavailable: {e}>"]

    report = [
        ("deepspeed_tpu install path", __file__),
        ("deepspeed_tpu version", __version__),
        ("jax version", jax.__version__),
        ("jax backend", _safe(lambda: jax.default_backend())),
        ("device count", _safe(lambda: jax.device_count())),
        ("devices", _safe(lambda: [str(d) for d in devices])),
        ("python version", sys.version.replace("\n", " ")),
    ]
    for opt in ("flax", "optax", "orbax.checkpoint", "chex", "numpy"):
        try:
            m = importlib.import_module(opt)
            report.append((f"{opt} version", getattr(m, "__version__", "?")))
        except Exception:
            report.append((f"{opt} version", "not installed"))

    print("-" * 64)
    print("DeepSpeed-TPU general environment info:")
    print("-" * 64)
    for name, value in report:
        print(f"{name} ................... {value}")


def _safe(fn):
    try:
        return fn()
    except Exception as e:
        return f"<unavailable: {e}>"


def compile_cache_report():
    """Persistent compiled-step cache status (docs/compile-cache.md):
    directory, entry count, total bytes, and the last run's hit/miss
    counters — read-only, safe beside a live trainer."""
    from .runtime.compile_cache import disk_report

    print("-" * 64)
    print("Compile cache (DSTPU_COMPILE_CACHE / config `compile_cache`):")
    print("-" * 64)
    rep = disk_report()
    if not rep.get("configured"):
        print("not configured (set --compile-cache-dir, env "
              "DSTPU_COMPILE_CACHE, or config compile_cache.dir)")
        return
    print(f"dir ................... {rep['dir']}")
    if not rep.get("exists"):
        print("status ................ directory does not exist yet "
              "(created on first engine build)")
        return
    print(f"entries ............... {rep['entries']}")
    print(f"total bytes ........... {rep['total_bytes']:,}")
    last = rep.get("last_run")
    if last and isinstance(last.get("stats"), dict):
        s = last["stats"]
        print(f"last run .............. hits={s.get('hits', 0)} "
              f"misses={s.get('misses', 0)} corrupt={s.get('corrupt', 0)} "
              f"compile_ms={round(s.get('compile_ms', 0))} "
              f"deserialize_ms={round(s.get('deserialize_ms', 0))} "
              f"(pid {last.get('pid')})")
    else:
        print("last run .............. no stats recorded yet")


def comms_compression_report():
    """Active quantized-collectives policy (docs/comms-compression.md):
    config defaults + the DSTPU_COMMS_COMPRESSION env override, exactly
    as an engine built in this environment would resolve them."""
    import os as _os
    from .runtime.config import DeepSpeedCommsCompressionConfig

    print("-" * 64)
    print("Comms compression (DSTPU_COMMS_COMPRESSION / config "
          "`comms_compression`):")
    print("-" * 64)
    pol = _safe(lambda: DeepSpeedCommsCompressionConfig({}).describe())
    if not isinstance(pol, dict):
        print(f"policy ................ {pol}")
        return
    env = _os.environ.get("DSTPU_COMMS_COMPRESSION")
    src = (f"env DSTPU_COMMS_COMPRESSION={env}" if env
           else "config default (off)")
    print(f"enabled ............... {pol['enabled']} ({src})")
    print(f"weights ............... int{pol['weights_bits']} qwZ "
          "all-gather" if pol["weights_bits"] else
          "weights ............... full width")
    print(f"grads ................. int{pol['grads_bits']} qgZ "
          "reduce (error-fed)" if pol["grads_bits"] else
          "grads ................. full width")
    moe = pol.get("moe") or {}
    print(f"moe dispatch .......... int{moe['bits']} expert all_to_all "
          f"(block {moe['block_size']})" if moe.get("bits") else
          "moe dispatch .......... full width")
    print(f"block_size ............ {pol['block_size']}")
    print(f"hierarchical .......... {pol['hierarchical']}")
    print(f"min_tensor_bytes ...... {pol['min_tensor_bytes']}")
    print(f"excluded .............. {', '.join(pol['excluded'])}")
    print(f"routes ................ {', '.join(pol['routes'])}")


def monitor_report():
    """Resolved runtime-telemetry policy (docs/monitoring.md): config
    defaults + the DSTPU_MONITOR / DSTPU_MONITOR_DIR env overrides,
    exactly as an engine built in this environment would resolve them."""
    import os as _os
    from .runtime.config import DeepSpeedMonitorConfig
    from .monitor.core import resolve_run_dir

    print("-" * 64)
    print("Monitor (DSTPU_MONITOR / config `monitor`):")
    print("-" * 64)
    pol = _safe(lambda: DeepSpeedMonitorConfig({}).describe())
    if not isinstance(pol, dict):
        print(f"policy ................ {pol}")
        return
    env = _os.environ.get("DSTPU_MONITOR")
    src = f"env DSTPU_MONITOR={env}" if env else "config default (off)"
    print(f"enabled ............... {pol['enabled']} ({src})")
    print(f"sinks ................. {', '.join(pol['sinks'])}")
    print(f"dir ................... {_safe(lambda: resolve_run_dir(pol['dir']))}")
    print(f"interval .............. every {pol['interval']} step(s)")
    print(f"ring_size ............. {pol['ring_size']} events")
    print(f"trace_steps ........... {pol['trace_steps'] or 'disabled'}")
    print(f"rotate_mb ............. {pol['rotate_mb'] or 'disabled'}")
    slo = pol.get("slo")
    n_obj = len((slo or {}).get("objectives", []) or [])
    print(f"slo ................... "
          f"{f'{n_obj} objective(s)' if slo else 'disabled'}")
    print("tail with ............. python -m deepspeed_tpu.monitor <dir>")
    print("fleet view ............ ds_fleet <dir1> <dir2> ...")


def router_report():
    """Resolved replica-router policy (docs/serving.md#replica-router):
    the health state machine's thresholds, probe backoff, and
    degradation knobs as a router built in this environment would
    resolve them."""
    from .inference.router import RouterConfig

    print("-" * 64)
    print("Replica router (bin/ds_router):")
    print("-" * 64)
    pol = _safe(lambda: RouterConfig().describe())
    if not isinstance(pol, dict):
        print(f"policy ................ {pol}")
        return
    print(f"suspect after ......... {pol['suspect_after_s']}s heartbeat "
          "silence (placement stops)")
    print(f"dead after ............ {pol['dead_after_s']}s (journal "
          "replay + requeue onto siblings)")
    print(f"probe backoff ......... {pol['probe_backoff']}")
    print(f"straggler drain ....... z>={pol['straggler_zmax']} and "
          f"excess>={pol['straggler_min_excess']:.0%} (drain, not kill)")
    print(f"drain heals after ..... {pol['drain_clear_evals']} clean "
          "verdict(s)")
    print(f"slo burn drain ........ worst burn >= {pol['slo_burn_drain']}")
    print(f"deadline_ms ........... {pol['deadline_ms'] or 'disabled'}")
    print(f"max_outstanding ....... "
          f"{pol['max_outstanding'] or 'unbounded'}")
    print(f"role pools ............ "
          f"{pol.get('roles') or 'none (every replica mixed)'}")
    print("observe with .......... ds_router <dir1> <dir2> ... [--once]")


def kv_snapshot_report():
    """Resolved KV snapshot/migration policy
    (docs/serving.md#kv-migration): the ``serving.kv_snapshot`` block as
    a serving engine built in this environment would resolve it — off by
    default, with the defaults an armed config would get."""
    from .inference.serving import describe_kv_snapshot

    print("-" * 64)
    print("KV snapshot / crash migration (config `serving.kv_snapshot`):")
    print("-" * 64)
    pol = _safe(lambda: describe_kv_snapshot())
    if not isinstance(pol, dict):
        print(f"policy ................ {pol}")
        return
    eff = pol if pol.get("enabled") else pol.get("defaults_when_armed", {})
    print(f"enabled ............... {pol.get('enabled')} "
          "(off by default; jaxpr-identical when armed)")
    print(f"cadence ............... every {eff.get('every_tokens')} "
          "token(s) per stream")
    print(f"retention ............. keep_n={eff.get('keep_n')} "
          "(rotate like checkpoint.keep_n)")
    print(f"export on evict ....... {eff.get('export_on_evict')} "
          "(deadline-evicted streams stay restorable)")
    print(f"verify ................ {eff.get('verify')} "
          "(manifest + per-block sha256)")
    print(f"handoff ............... {eff.get('handoff')}")
    print(f"wire format ........... {eff.get('wire_format')}")


def transfer_report():
    """Resolved prefill/decode disaggregation policy
    (docs/serving.md#disaggregation): the ``serving.role`` /
    ``serving.transfer`` pair as a serving engine built in this
    environment would resolve them — mixed role with the transfer
    queue off by default, byte-identical to pre-role behavior."""
    from .inference.transfer import ROLES, describe_transfer

    print("-" * 64)
    print("Prefill/decode disaggregation (config `serving.role` / "
          "`serving.transfer`):")
    print("-" * 64)
    pol = _safe(lambda: describe_transfer())
    if not isinstance(pol, dict):
        print(f"policy ................ {pol}")
        return
    eff = pol if pol.get("enabled") else pol.get("defaults_when_armed", {})
    print("role .................. mixed (default; one of "
          f"{', '.join(ROLES)})")
    print(f"transfer queue ........ {pol.get('enabled')} "
          "(armed automatically for prefill/decode roles)")
    print(f"dir ................... {eff.get('dir') or '<journal_dir>/kv_transfer'}")
    print(f"max_pending ........... {eff.get('max_pending')} "
          "(backpressure: prefill degrades to local decode)")
    print(f"keep_n ................ {eff.get('keep_n')} "
          "(GC bound on committed entries)")
    print(f"verify ................ {eff.get('verify')} "
          "(manifest + per-block sha256)")
    print(f"wire format ........... {eff.get('wire_format')}")
    print("router pools .......... fresh->prefill by queue depth, "
          "transfers->decode by free blocks, degrade-to-mixed")


def prefix_cache_report():
    """Resolved multi-tenant prefix-sharing policy
    (docs/serving.md#prefix-sharing): the ``serving.prefix_cache``
    block as a serving engine built in this environment would resolve
    it — off by default, radix COW cache over the paged pool when
    armed (decode jaxpr byte-identical either way)."""
    from .inference.serving import describe_prefix_cache

    print("-" * 64)
    print("KV prefix sharing (config `serving.prefix_cache`):")
    print("-" * 64)
    pol = _safe(lambda: describe_prefix_cache())
    if not isinstance(pol, dict):
        print(f"policy ................ {pol}")
        return
    eff = pol if pol.get("enabled") else pol.get("defaults_when_armed", {})
    print(f"enabled ............... {pol.get('enabled')} "
          "(off by default; jaxpr-identical when armed)")
    print(f"hash .................. {eff.get('hash')}")
    print(f"copy-on-write ......... {eff.get('cow')}")
    print(f"eviction .............. {eff.get('eviction')}")
    print(f"capacity .............. {eff.get('capacity')}")
    print(f"min prefix blocks ..... {eff.get('min_prefix_blocks')}")
    print(f"cached-block cap ...... {eff.get('max_blocks')} "
          "(0 = evict under pool pressure only)")
    print("capacity query ........ ds_mem --max-streams "
          "--shared-prefix-tokens N")


def sanitize_report():
    """Resolved lifecycle shadow-sanitizer policy
    (docs/static-analysis.md#sanitizer): the DSTPU_SANITIZE env
    override + config default, exactly as a serving engine built in
    this environment would arm."""
    from .analysis import sanitize

    print("-" * 64)
    print("Lifecycle sanitizer (DSTPU_SANITIZE / config "
          "`analysis.sanitize`):")
    print("-" * 64)
    pol = _safe(lambda: sanitize.describe())
    if not isinstance(pol, dict):
        print(f"policy ................ {pol}")
        return
    print(f"enabled ............... {pol['enabled']} ({pol['source']})")
    print(f"halt on finding ....... {pol['halt']}")
    codes = ", ".join(f"{k}={v}" for k, v in pol["codes"].items())
    print(f"checks ................ {codes}")
    print("static twin ........... python -m deepspeed_tpu.analysis "
          "--rules DSTPU3xx")
    print("full audit ............ python -m deepspeed_tpu.analysis "
          "--audit-step serving-lifecycle")


def main():
    op_report()
    compile_cache_report()
    comms_compression_report()
    monitor_report()
    router_report()
    kv_snapshot_report()
    transfer_report()
    prefix_cache_report()
    sanitize_report()
    debug_report()


cli_main = main

if __name__ == "__main__":
    main()
