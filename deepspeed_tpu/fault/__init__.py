"""Deterministic fault-injection harness for host-side IO paths.

Every recovery path in the fault-tolerance layer (atomic checkpoint commit,
manifest-validated load, retry/backoff IO) is *provable* in tests because
the failures themselves are injectable: serialization, the NVMe swappers,
and the engine's host-side step wrapper call ``fault.site(name)`` at named
points, and an armed plan turns those calls into crashes, IO errors, or
delays.

Zero overhead when disabled: ``site()`` is one module-global load and an
``is None`` test.  Hooks live ONLY in host-side Python IO code — never
inside jitted functions — so the compiled step is byte-identical with the
harness armed or not (asserted by a tier-1 test via jaxpr equality).

Configuration (env ``DSTPU_FAULT`` or ``configure(spec)``) is a
comma-separated spec, e.g.::

    DSTPU_FAULT=ckpt_crash_after_model_file,io_error_p=0.2,io_delay_ms=50

tokens:
- ``crash_at=<site>[@N]``          raise ``InjectedCrash`` at the named site
                                   (one-shot: disarms after firing so the
                                   recovery path can run in-process).
                                   ``@N`` defers the crash to the N-th
                                   VISIT of the site (1-based) — "die at
                                   scheduler step 12", mid-traffic, not
                                   at the first opportunity
- ``hang_at=<site>[@N]``           sleep ``hang_s`` seconds at the named
                                   site (one-shot, then continue) — a
                                   simulated wedge/GC-pause/network stall
                                   that RESOLVES, unlike a crash: the
                                   process survives and finishes its
                                   work late (the router's
                                   hung-replica-answers-anyway case)
- ``hang_s=<float>``               hang_at sleep duration (default 0.25s)
- ``<area>_crash_<point>``         sugar for ``crash_at=<area>.<point>``
                                   (``ckpt_crash_after_model_file`` ->
                                   ``ckpt.after_model_file``)
- ``io_error_p=<float>``           each ``io.*``/``aio.*`` site raises
                                   ``InjectedIOError`` with probability p
- ``io_delay_ms=<float>``          sleep this long at each io site
- ``max_faults=<int>``             cap on injected io errors (determinism)
- ``seed=<int>``                   seed for the probability draws
- ``grad_nan=<a>[:<b>]``           VALUE corruption: NaN-fill the float
                                   leaves of every batch whose data-stream
                                   index is in [a, b) (b defaults to a+1) —
                                   the deterministic numerical fault that
                                   drives the health guardian's
                                   skip/rewind ladder
- ``loss_spike=<a>[:<b>]``         VALUE corruption: scale the float leaves
                                   by ``spike_factor`` over the window —
                                   finite but wildly out-of-distribution
- ``spike_factor=<float>``         loss_spike multiplier (default 1e4)
- ``corrupt_at=<site>[@N]``        VALUE corruption at a named site: the
                                   site's owner consults
                                   :func:`corrupt_at` (a query, like
                                   ``poison_uid``) and, when armed,
                                   corrupts its own payload bytes
                                   in-place — bit rot, not a crash; the
                                   process continues.  One-shot; ``@N``
                                   defers to the N-th visit.  Drives
                                   ``serving.kv_image_corrupt``
- ``logit_nan=<uid>``              VALUE corruption for SERVING: poison
                                   request ``uid``'s KV blocks right after
                                   its prefill (host-side pool edit — the
                                   compiled decode step is unchanged) so
                                   its decode logits go non-finite; drives
                                   the quarantine ladder
                                   (docs/serving.md#resilience).  Repeat
                                   the token to poison several uids.

Known sites (kept in ``SITES`` so tests and docs can't drift): checkpoint
commit protocol (``ckpt.*``), tree serialization (``io.read``/``io.write``),
AIO submits (``aio.submit``), the engine's host-side step boundary
(``engine.step``), and the serving scheduler's host boundaries
(``serving.step``/``serving.admit``/``serving.prefill``).

Value-corruption faults (``grad_nan``/``loss_spike``) are NOT call sites:
the engine passes each drawn batch through :func:`corrupt_batch` with its
data-stream index, entirely host-side and BEFORE ``device_put`` — the
compiled step program is byte-identical with them armed (the poison rides
the data, exactly like a real corrupt batch would), and a rewind that
fast-forwards the stream past the window genuinely cures the run.
"""

import os
import random
import time

from ..utils.logging import logger

SITES = (
    "ckpt.after_model_file",   # model_states written to staging, optim not yet
    "ckpt.after_optim_file",   # both state files staged, manifest not yet
    "ckpt.before_commit",      # manifest staged, final rename not yet done
    "ckpt.after_commit",       # committed, `latest` pointer not yet updated
    "ckpt.before_latest",      # inside the latest-pointer update, pre-rename
    "io.write",                # serialization writes (save_tree)
    "io.read",                 # serialization reads (load_tree)
    "aio.submit",              # NVMe swap read/write submission
    "engine.step",             # host-side train_batch boundary
    "serving.step",            # serving scheduler iteration (host boundary)
    "serving.admit",           # serving admission (queue -> slot) boundary
    "serving.prefill",         # before a request's prefill dispatch
    # replica-worker loop boundaries (inference/router.py): one visit per
    # worker iteration, so `@N` kills/hangs a REPLICA mid-traffic — the
    # router chaos rung's deterministic replacement for ad-hoc SIGKILL
    "serving.replica_crash_step",   # worker dies here (no clean shutdown)
    "serving.replica_hang_step",    # worker stalls here, then continues
    # between computing a request's answer and journaling its finish:
    # the answered-but-not-durably-finished window (a crash here makes
    # the uid replay as PENDING although a result may already be out —
    # the router's dedup-by-uid case)
    "serving.journal_crash_finish",
    # KV snapshot/migration (docs/fault-tolerance.md#kv-migration):
    # between staging a stream's KV image and its commit rename — a
    # crash here leaves a torn `.tmp` snapshot that manifest resolution
    # must skip (detectable, never restorable)
    "serving.kv_snapshot_torn",
    # post-commit bit rot of a snapshot payload; a VALUE fault
    # (`corrupt_at=`, consulted via :func:`corrupt_at`, not a crash) —
    # restore must catch it via manifest/per-block digests and fall
    # back to recompute with a typed `migration_fallback` event
    "serving.kv_image_corrupt",
    # mid-restore on the SURVIVOR: blocks allocated, image not yet
    # seated — the restore path must unwind without leaking blocks
    "serving.crash_during_restore",
)

_IO_PREFIXES = ("io.", "aio.")


class InjectedCrash(BaseException):
    """Simulated preemption/kill at a named site.  Derives from
    BaseException so ordinary ``except Exception``/``except OSError``
    recovery code cannot accidentally swallow a "kill" — exactly like a
    real SIGKILL, only the test harness catches it."""


class InjectedIOError(OSError):
    """Simulated transient IO failure (retriable by classification)."""


def _parse_window(val):
    """``"a:b"`` -> (a, b); ``"a"`` -> (a, a+1).  Batch-index window,
    half-open."""
    val = str(val).strip()
    if ":" in val:
        a, b = val.split(":", 1)
        lo, hi = int(a), int(b)
    else:
        lo = int(val)
        hi = lo + 1
    if hi <= lo:
        raise ValueError(f"empty fault window {val!r} (need start < stop)")
    return (lo, hi)


def _parse_site_at(val):
    """``"site"`` -> (site, None); ``"site@N"`` -> (site, N) with N the
    1-based visit index the trigger fires on."""
    val = str(val).strip()
    if "@" in val:
        site_name, n = val.rsplit("@", 1)
        visit = int(n)
        if visit < 1:
            raise ValueError(f"visit index must be >= 1 in {val!r}")
        return site_name.strip(), visit
    return val, None


class FaultPlan:
    def __init__(self, crash_sites=(), io_error_p=0.0, io_delay_ms=0.0,
                 max_faults=None, seed=0, grad_nan=None, loss_spike=None,
                 spike_factor=1e4, logit_nan=(), crash_at_visit=None,
                 hang_at=None, hang_s=0.25, corrupt_at=None):
        # crash_at_visit / hang_at / corrupt_at: {site: visit} — fire on
        # that 1-based VISIT of the site (crash_sites entries fire on
        # the next visit)
        self.crash_at_visit = dict(crash_at_visit or {})
        self.hang_at = dict(hang_at or {})
        self.corrupt_at = dict(corrupt_at or {})
        self.hang_s = float(hang_s)
        unknown = (set(crash_sites) | set(self.crash_at_visit)
                   | set(self.hang_at) | set(self.corrupt_at)) - set(SITES)
        assert not unknown, f"unknown fault sites {sorted(unknown)}; " \
                            f"valid: {SITES}"
        self.crash_sites = set(crash_sites)
        self.io_error_p = float(io_error_p)
        self.io_delay_ms = float(io_delay_ms)
        self.max_faults = max_faults
        self.grad_nan = tuple(grad_nan) if grad_nan is not None else None
        self.loss_spike = (tuple(loss_spike) if loss_spike is not None
                           else None)
        self.spike_factor = float(spike_factor)
        if isinstance(logit_nan, int):
            logit_nan = (logit_nan,)
        self.logit_nan = frozenset(int(u) for u in logit_nan)
        self.rng = random.Random(seed)
        self.injected_io_errors = 0
        self.hits = {}            # site -> visit count (test observability)

    @classmethod
    def from_spec(cls, spec):
        crash, kw = [], {}
        for token in str(spec).split(","):
            token = token.strip()
            if not token:
                continue
            if "=" in token:
                key, val = token.split("=", 1)
                key = key.strip()
                if key == "crash_at":
                    site_name, visit = _parse_site_at(val)
                    if visit is None:
                        crash.append(site_name)
                    else:
                        kw.setdefault("crash_at_visit", {})[site_name] = visit
                elif key == "hang_at":
                    site_name, visit = _parse_site_at(val)
                    # visit None = fire on the very next visit
                    kw.setdefault("hang_at", {})[site_name] = visit or 1
                elif key == "corrupt_at":
                    site_name, visit = _parse_site_at(val)
                    kw.setdefault("corrupt_at", {})[site_name] = visit or 1
                elif key in ("io_error_p", "io_delay_ms", "spike_factor",
                             "hang_s"):
                    kw[key] = float(val)
                elif key in ("max_faults", "seed"):
                    kw[key] = int(val)
                elif key in ("grad_nan", "loss_spike"):
                    kw[key] = _parse_window(val)
                elif key == "logit_nan":
                    # may repeat: each token adds one poisoned uid
                    kw.setdefault("logit_nan", []).append(int(val))
                else:
                    raise ValueError(f"unknown fault spec key {key!r}")
            elif "_crash_" in token:
                area, point = token.split("_crash_", 1)
                crash.append(f"{area}.{point}")
            else:
                raise ValueError(f"cannot parse fault spec token {token!r}")
        return cls(crash_sites=crash, **kw)


_PLAN = None  # None = disabled; site() is a load + `is None` test


def configure(spec=None, **kwargs):
    """Arm the harness from a spec string, a FaultPlan, or kwargs."""
    global _PLAN
    if isinstance(spec, FaultPlan):
        _PLAN = spec
    elif spec is not None:
        _PLAN = FaultPlan.from_spec(spec)
    else:
        _PLAN = FaultPlan(**kwargs)
    logger.warning(f"fault injection ARMED: crash_sites="
                   f"{sorted(_PLAN.crash_sites)} io_error_p={_PLAN.io_error_p} "
                   f"io_delay_ms={_PLAN.io_delay_ms}")
    return _PLAN


def reset():
    global _PLAN
    _PLAN = None


def is_enabled():
    return _PLAN is not None


def plan():
    return _PLAN


def site(name, path=None):
    """Fault hook.  Host-side IO code only — never call under jit."""
    if _PLAN is None:
        return
    p = _PLAN
    p.hits[name] = p.hits.get(name, 0) + 1
    if name in p.hang_at and p.hits[name] >= p.hang_at[name]:
        # one-shot stall that RESOLVES: the site continues afterwards
        del p.hang_at[name]
        logger.warning(f"fault: injected {p.hang_s}s hang at {name}")
        time.sleep(p.hang_s)
    if name in p.crash_at_visit and p.hits[name] >= p.crash_at_visit[name]:
        del p.crash_at_visit[name]    # one-shot, like crash_sites
        raise InjectedCrash(f"injected crash at {name} "
                            f"(visit {p.hits[name]})"
                            + (f" ({path})" if path else ""))
    if name in p.crash_sites:
        p.crash_sites.discard(name)   # one-shot: recovery can proceed
        raise InjectedCrash(f"injected crash at {name}"
                            + (f" ({path})" if path else ""))
    if name.startswith(_IO_PREFIXES):
        if p.io_delay_ms > 0:
            time.sleep(p.io_delay_ms / 1e3)
        if p.io_error_p > 0 and (p.max_faults is None
                                 or p.injected_io_errors < p.max_faults):
            if p.rng.random() < p.io_error_p:
                p.injected_io_errors += 1
                raise InjectedIOError(
                    f"injected IO error at {name}"
                    + (f" ({path})" if path else ""))


def _map_float_leaves(batch, fn):
    """Apply ``fn`` to every float numpy leaf of a host batch pytree
    (dict / tuple / list / ndarray), returning a new tree.  No jax import:
    this runs on raw loader output, before any device placement."""
    import numpy as np
    if isinstance(batch, dict):
        return {k: _map_float_leaves(v, fn) for k, v in batch.items()}
    if isinstance(batch, (tuple, list)):
        return type(batch)(_map_float_leaves(v, fn) for v in batch)
    arr = np.asarray(batch)
    if np.issubdtype(arr.dtype, np.floating):
        return fn(arr)
    return batch


def corrupt_batch(batch, index):
    """Deterministic VALUE-corruption hook for the numerical fault sites.

    Host-side only, applied to the raw loader batch BEFORE ``device_put``:
    the compiled step never changes (the DSTPU201 audit and the
    armed-vs-disarmed jaxpr-equality test stay valid), the poison simply
    rides the data.  ``index`` is the engine's monotonic data-stream batch
    index — checkpointed and restored with the data-pipeline state, so a
    rewind replays the SAME poison window and a fast-forward past it
    genuinely clears the fault.

    Zero overhead disarmed: one module-global load and an ``is None`` test.
    """
    if _PLAN is None:
        return batch
    import numpy as np
    p = _PLAN
    idx = int(index)

    def in_window(w):
        return w is not None and w[0] <= idx < w[1]

    if in_window(p.grad_nan):
        p.hits["fault.grad_nan"] = p.hits.get("fault.grad_nan", 0) + 1
        return _map_float_leaves(batch, lambda a: np.full_like(a, np.nan))
    if in_window(p.loss_spike):
        p.hits["fault.loss_spike"] = p.hits.get("fault.loss_spike", 0) + 1
        return _map_float_leaves(batch, lambda a: a * p.spike_factor)
    return batch


def corrupt_at(name):
    """True when the armed plan marks site ``name`` for in-place VALUE
    corruption (spec key ``corrupt_at=<site>[@N]``, one-shot).

    Like :func:`corrupt_batch`/:func:`poison_uid`, this is a QUERY, not
    a raise site: the owning code (the KV snapshot writer for
    ``serving.kv_image_corrupt``) flips its own committed payload bytes
    when this returns True — simulated bit rot the restore path must
    catch by digest, while the process itself keeps running."""
    if _PLAN is None:
        return False
    p = _PLAN
    p.hits[name] = p.hits.get(name, 0) + 1
    if name in p.corrupt_at and p.hits[name] >= p.corrupt_at[name]:
        del p.corrupt_at[name]        # one-shot, like crash_sites
        logger.warning(f"fault: injected payload corruption at {name} "
                       f"(visit {p.hits[name]})")
        return True
    return False


def poison_uid(uid):
    """True when the armed plan marks serving request ``uid`` as a
    ``logit_nan`` target (the serving quarantine's value fault).

    Like :func:`corrupt_batch`, this is NOT a call site: the serving
    scheduler consults it host-side after the request's prefill and
    NaN-fills the request's OWN KV pool blocks — the poison rides the
    data (slot-local by the paged layout's construction), and the
    compiled decode step stays byte-identical armed or not (asserted by
    the serving jaxpr-equality test)."""
    if _PLAN is None or not _PLAN.logit_nan:
        return False
    if int(uid) in _PLAN.logit_nan:
        _PLAN.hits["fault.logit_nan"] = \
            _PLAN.hits.get("fault.logit_nan", 0) + 1
        return True
    return False


# env wiring: a preemption-test job (or `deepspeed --fault=...` launch) arms
# the harness before any engine code runs
if os.environ.get("DSTPU_FAULT"):
    configure(os.environ["DSTPU_FAULT"])
