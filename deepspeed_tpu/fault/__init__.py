"""Deterministic fault-injection harness for host-side IO paths.

Every recovery path in the fault-tolerance layer (atomic checkpoint commit,
manifest-validated load, retry/backoff IO) is *provable* in tests because
the failures themselves are injectable: serialization, the NVMe swappers,
and the engine's host-side step wrapper call ``fault.site(name)`` at named
points, and an armed plan turns those calls into crashes, IO errors, or
delays.

Zero overhead when disabled: ``site()`` is one module-global load and an
``is None`` test.  Hooks live ONLY in host-side Python IO code — never
inside jitted functions — so the compiled step is byte-identical with the
harness armed or not (asserted by a tier-1 test via jaxpr equality).

Configuration (env ``DSTPU_FAULT`` or ``configure(spec)``) is a
comma-separated spec, e.g.::

    DSTPU_FAULT=ckpt_crash_after_model_file,io_error_p=0.2,io_delay_ms=50

tokens:
- ``crash_at=<site>``              raise ``InjectedCrash`` at the named site
                                   (one-shot: disarms after firing so the
                                   recovery path can run in-process)
- ``<area>_crash_<point>``         sugar for ``crash_at=<area>.<point>``
                                   (``ckpt_crash_after_model_file`` ->
                                   ``ckpt.after_model_file``)
- ``io_error_p=<float>``           each ``io.*``/``aio.*`` site raises
                                   ``InjectedIOError`` with probability p
- ``io_delay_ms=<float>``          sleep this long at each io site
- ``max_faults=<int>``             cap on injected io errors (determinism)
- ``seed=<int>``                   seed for the probability draws

Known sites (kept in ``SITES`` so tests and docs can't drift): checkpoint
commit protocol (``ckpt.*``), tree serialization (``io.read``/``io.write``),
AIO submits (``aio.submit``), and the engine's host-side step boundary
(``engine.step``).
"""

import os
import random
import time

from ..utils.logging import logger

SITES = (
    "ckpt.after_model_file",   # model_states written to staging, optim not yet
    "ckpt.after_optim_file",   # both state files staged, manifest not yet
    "ckpt.before_commit",      # manifest staged, final rename not yet done
    "ckpt.after_commit",       # committed, `latest` pointer not yet updated
    "ckpt.before_latest",      # inside the latest-pointer update, pre-rename
    "io.write",                # serialization writes (save_tree)
    "io.read",                 # serialization reads (load_tree)
    "aio.submit",              # NVMe swap read/write submission
    "engine.step",             # host-side train_batch boundary
)

_IO_PREFIXES = ("io.", "aio.")


class InjectedCrash(BaseException):
    """Simulated preemption/kill at a named site.  Derives from
    BaseException so ordinary ``except Exception``/``except OSError``
    recovery code cannot accidentally swallow a "kill" — exactly like a
    real SIGKILL, only the test harness catches it."""


class InjectedIOError(OSError):
    """Simulated transient IO failure (retriable by classification)."""


class FaultPlan:
    def __init__(self, crash_sites=(), io_error_p=0.0, io_delay_ms=0.0,
                 max_faults=None, seed=0):
        unknown = set(crash_sites) - set(SITES)
        assert not unknown, f"unknown fault sites {sorted(unknown)}; " \
                            f"valid: {SITES}"
        self.crash_sites = set(crash_sites)
        self.io_error_p = float(io_error_p)
        self.io_delay_ms = float(io_delay_ms)
        self.max_faults = max_faults
        self.rng = random.Random(seed)
        self.injected_io_errors = 0
        self.hits = {}            # site -> visit count (test observability)

    @classmethod
    def from_spec(cls, spec):
        crash, kw = [], {}
        for token in str(spec).split(","):
            token = token.strip()
            if not token:
                continue
            if "=" in token:
                key, val = token.split("=", 1)
                key = key.strip()
                if key == "crash_at":
                    crash.append(val.strip())
                elif key in ("io_error_p", "io_delay_ms"):
                    kw[key] = float(val)
                elif key in ("max_faults", "seed"):
                    kw[key] = int(val)
                else:
                    raise ValueError(f"unknown fault spec key {key!r}")
            elif "_crash_" in token:
                area, point = token.split("_crash_", 1)
                crash.append(f"{area}.{point}")
            else:
                raise ValueError(f"cannot parse fault spec token {token!r}")
        return cls(crash_sites=crash, **kw)


_PLAN = None  # None = disabled; site() is a load + `is None` test


def configure(spec=None, **kwargs):
    """Arm the harness from a spec string, a FaultPlan, or kwargs."""
    global _PLAN
    if isinstance(spec, FaultPlan):
        _PLAN = spec
    elif spec is not None:
        _PLAN = FaultPlan.from_spec(spec)
    else:
        _PLAN = FaultPlan(**kwargs)
    logger.warning(f"fault injection ARMED: crash_sites="
                   f"{sorted(_PLAN.crash_sites)} io_error_p={_PLAN.io_error_p} "
                   f"io_delay_ms={_PLAN.io_delay_ms}")
    return _PLAN


def reset():
    global _PLAN
    _PLAN = None


def is_enabled():
    return _PLAN is not None


def plan():
    return _PLAN


def site(name, path=None):
    """Fault hook.  Host-side IO code only — never call under jit."""
    if _PLAN is None:
        return
    p = _PLAN
    p.hits[name] = p.hits.get(name, 0) + 1
    if name in p.crash_sites:
        p.crash_sites.discard(name)   # one-shot: recovery can proceed
        raise InjectedCrash(f"injected crash at {name}"
                            + (f" ({path})" if path else ""))
    if name.startswith(_IO_PREFIXES):
        if p.io_delay_ms > 0:
            time.sleep(p.io_delay_ms / 1e3)
        if p.io_error_p > 0 and (p.max_faults is None
                                 or p.injected_io_errors < p.max_faults):
            if p.rng.random() < p.io_error_p:
                p.injected_io_errors += 1
                raise InjectedIOError(
                    f"injected IO error at {name}"
                    + (f" ({path})" if path else ""))


# env wiring: a preemption-test job (or `deepspeed --fault=...` launch) arms
# the harness before any engine code runs
if os.environ.get("DSTPU_FAULT"):
    configure(os.environ["DSTPU_FAULT"])
