"""Autotuner: find the best ZeRO stage + micro-batch size for a model.

Parity: reference ``deepspeed/autotuning/autotuner.py:29`` (``Autotuner``):
``tune()`` (:396) walks ZeRO stages in memory-fit order, and per stage
(``tune_space`` :502) sweeps micro-batch sizes, measuring a metric
(throughput/latency/flops) per experiment; best config is written out.

TPU re-design: experiments run IN-PROCESS (build an engine, time a few
steps, tear down) instead of scheduling jobs over hostfile slots via ssh —
one TPU host already drives all its chips, so the reference's
``ResourceManager``/``scheduler.py`` machinery reduces to a loop.  Memory
fit uses the same analytic model (params × bytes-per-state ÷ shard degree)
with per-chip HBM read from ``device.memory_stats``.
"""

import itertools
import json
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np
import jax

from . import constants as AC
from ..utils.logging import logger

DEFAULT_HBM_BYTES = 16 * (1 << 30)  # v5e-class default when stats unavailable


# ------------------------------------------------------------- memory model
def model_state_bytes_per_chip(num_params: int, zero_stage: int,
                               shard_degree: int) -> int:
    """Per-chip bytes for params+grads+optimizer states under a ZeRO stage
    (parity: reference ``get_instantiation_memory_required_per_gpu`` :261)."""
    p = AC.BYTES_PER_PARAM_BF16
    g = AC.BYTES_PER_PARAM_GRAD
    o = AC.BYTES_PER_PARAM_OPTIM
    n = max(1, shard_degree)
    if zero_stage == 0:
        per_param = p + g + o
    elif zero_stage == 1:
        per_param = p + g + o / n
    elif zero_stage == 2:
        per_param = p + g / n + o / n
    else:
        per_param = (p + g + o) / n
    return int(num_params * per_param)


def get_hbm_bytes() -> int:
    """Per-chip HBM budget through the shared ``monitor/gauges``
    helper — which also carries the CPU-backend fallback this site
    previously lacked (a bare ``memory_stats()`` on the CPU backend
    returns None; the sweep then planned against garbage)."""
    from ..monitor.gauges import hbm_limit_bytes
    return hbm_limit_bytes(default=DEFAULT_HBM_BYTES)


# ------------------------------------------------------------------- tuners
class BaseTuner:
    """Walks an experiment list, tracking the best (parity: reference
    ``tuner/base_tuner.py``)."""

    def __init__(self, exps: List[dict], metric=AC.AUTOTUNING_METRIC_DEFAULT):
        self.all_exps = list(exps)
        self.metric = metric
        self.best_exp = None
        self.best_metric_val = -float("inf")

    def next_batch(self, sample_size: int) -> List[dict]:
        raise NotImplementedError

    def update(self, exp, metric_val):
        if metric_val is not None and metric_val > self.best_metric_val:
            self.best_metric_val = metric_val
            self.best_exp = exp


class GridSearchTuner(BaseTuner):
    def next_batch(self, sample_size):
        batch, self.all_exps = (self.all_exps[:sample_size],
                                self.all_exps[sample_size:])
        return batch


class RandomTuner(BaseTuner):
    def __init__(self, exps, metric=AC.AUTOTUNING_METRIC_DEFAULT, seed=0):
        super().__init__(exps, metric)
        self._rng = np.random.default_rng(seed)
        self._rng.shuffle(self.all_exps)

    next_batch = GridSearchTuner.next_batch


class CostModel:
    """Least-squares metric predictor over experiment features (parity role:
    reference ``tuner/cost_model.py`` XGBoostCostModel — same contract,
    closed-form ridge fit instead of a GBM dependency).

    Features: intercept, log2(micro batch), per-stage indicators, gas.
    """

    N_STAGES = 4

    def featurize(self, exp) -> np.ndarray:
        cfg = exp["ds_config"]
        mbs = cfg.get("train_micro_batch_size_per_gpu", 1)
        gas = cfg.get("gradient_accumulation_steps", 1)
        stage = exp.get("zero_stage",
                        cfg.get("zero_optimization", {}).get("stage", 0))
        f = np.zeros(3 + self.N_STAGES)
        f[0] = 1.0
        f[1] = np.log2(max(1, mbs))
        f[2] = np.log2(max(1, gas))
        f[3 + min(stage, self.N_STAGES - 1)] = 1.0
        return f

    def fit(self, exps: List[dict], vals: List[float]):
        X = np.stack([self.featurize(e) for e in exps])
        y = np.asarray(vals, np.float64)
        d = X.shape[1]
        # ridge: (XᵀX + λI)β = Xᵀy — stable with few observations
        self._beta = np.linalg.solve(X.T @ X + 1e-3 * np.eye(d), X.T @ y)

    def predict(self, exp) -> float:
        return float(self.featurize(exp) @ self._beta)


class ModelBasedTuner(BaseTuner):
    """Cost-model tuner (parity: reference ``tuner/model_based_tuner.py:158``):
    after each measurement, refit the cost model on ALL observations and
    explore the unmeasured experiment with the highest predicted metric —
    converging on the best region without an exhaustive sweep."""

    def __init__(self, exps, metric=AC.AUTOTUNING_METRIC_DEFAULT):
        super().__init__(exps, metric)
        self.observed: List[tuple] = []          # (exp, metric_val)
        self.cost_model = CostModel()
        # warmup: one probe per distinct zero stage (a one-hot stage
        # indicator can't rank a stage never measured — the cold-start
        # mitigation the reference gets from its random warmup sampling)
        by_stage: Dict[int, List[dict]] = {}
        for e in self.all_exps:
            by_stage.setdefault(self._stage(e), []).append(e)
        warm = [grp[len(grp) // 2] for grp in by_stage.values()]
        warm_ids = {id(e) for e in warm}
        # identity, not ==: two equal-config experiments must both survive
        self.all_exps = warm + [e for e in self.all_exps
                                if id(e) not in warm_ids]
        self._warmup = len(warm)

    @staticmethod
    def _stage(exp):
        return exp.get("zero_stage",
                       exp["ds_config"].get("zero_optimization", {})
                       .get("stage", 0))

    def next_batch(self, sample_size):
        if len(self.observed) >= max(2, self._warmup):
            self.cost_model.fit([e for e, _ in self.observed],
                                [v for _, v in self.observed])
            self.all_exps.sort(key=self.cost_model.predict, reverse=True)
        batch, self.all_exps = (self.all_exps[:sample_size],
                                self.all_exps[sample_size:])
        return batch

    def update(self, exp, metric_val):
        super().update(exp, metric_val)
        if metric_val is not None:
            self.observed.append((exp, metric_val))


TUNERS = {AC.AUTOTUNING_TUNER_GRIDSEARCH: GridSearchTuner,
          AC.AUTOTUNING_TUNER_RANDOM: RandomTuner,
          AC.AUTOTUNING_TUNER_MODELBASED: ModelBasedTuner}


# ---------------------------------------------------------------- autotuner
class Autotuner:
    def __init__(self, model, base_config: dict, training_data,
                 mesh=None, collate_fn=None, autotuning_config: Optional[dict] = None,
                 num_params: Optional[int] = None):
        self.model = model
        self.base_config = dict(base_config)
        at = autotuning_config or self.base_config.get(AC.AUTOTUNING, {}) or {}
        self.at = at
        self.training_data = training_data
        self.mesh = mesh
        self.collate_fn = collate_fn
        self.metric = at.get(AC.AUTOTUNING_METRIC, AC.AUTOTUNING_METRIC_DEFAULT)
        self.start_step = at.get(AC.AUTOTUNING_START_PROFILE_STEP,
                                 AC.AUTOTUNING_START_PROFILE_STEP_DEFAULT)
        self.end_step = at.get(AC.AUTOTUNING_END_PROFILE_STEP,
                               AC.AUTOTUNING_END_PROFILE_STEP_DEFAULT)
        self.results_dir = at.get(AC.AUTOTUNING_RESULTS_DIR,
                                  AC.AUTOTUNING_RESULTS_DIR_DEFAULT)
        self.tuner_type = at.get(AC.AUTOTUNING_TUNER_TYPE,
                                 AC.AUTOTUNING_TUNER_TYPE_DEFAULT)
        self.early_stopping = at.get(AC.AUTOTUNING_TUNER_EARLY_STOPPING,
                                     AC.AUTOTUNING_TUNER_EARLY_STOPPING_DEFAULT)
        self.num_trials = at.get(AC.AUTOTUNING_TUNER_NUM_TRIALS,
                                 AC.AUTOTUNING_TUNER_NUM_TRIALS_DEFAULT)
        self.records: Dict[str, list] = {}
        self._num_params = num_params
        self.best_exp = None
        self.best_metric_val = -float("inf")

    # ------------------------------------------------------------- model info
    def get_model_num_params(self):
        """Parity: reference ``model_info_profile_run`` (:664) — here the
        params are countable without a profile job."""
        if self._num_params is None:
            if hasattr(self.model, "num_params"):
                self._num_params = int(self.model.num_params())
            else:
                params = self.model.init(jax.random.PRNGKey(0))
                self._num_params = sum(int(np.prod(p.shape)) for p in
                                       jax.tree_util.tree_leaves(params))
        return self._num_params

    def _shard_degree(self):
        if self.mesh is not None:
            from ..parallel import mesh as M
            return M.dp_world_size(self.mesh)
        return jax.device_count()

    # ---------------------------------------------------------- experiments
    def _mbs_candidates(self) -> List[int]:
        lo = self.at.get(AC.AUTOTUNING_MIN_TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                         AC.AUTOTUNING_MIN_TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)
        hi = self.at.get(AC.AUTOTUNING_MAX_TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                         AC.AUTOTUNING_MAX_TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)
        out, m = [], max(1, lo)
        while m <= hi:
            out.append(m)
            m *= 2
        return out

    def _generate_experiments(self) -> List[dict]:
        """ZeRO stages that fit memory × micro-batch candidates (parity:
        reference ``tune`` stage-fit walk :396-500)."""
        hbm = get_hbm_bytes()
        n_params = self.get_model_num_params()
        shard = self._shard_degree()
        stages = self.at.get(AC.AUTOTUNING_ZERO_STAGES, [0, 1, 2, 3])
        user_stage = self.base_config.get("zero_optimization", {}).get("stage")
        if user_stage is not None:
            stages = [user_stage]
        exps = []
        for stage in stages:
            state_mem = model_state_bytes_per_chip(n_params, stage, shard)
            if state_mem >= hbm:
                logger.info(f"zero stage {stage} does not fit: model states "
                            f"{state_mem / 1e9:.2f}GB >= HBM {hbm / 1e9:.2f}GB")
                continue
            for mbs in self._mbs_candidates():
                cfg = json.loads(json.dumps(self.base_config))
                cfg.pop(AC.AUTOTUNING, None)
                cfg.setdefault("zero_optimization", {})["stage"] = stage
                cfg["train_micro_batch_size_per_gpu"] = mbs
                cfg.pop("train_batch_size", None)
                cfg.setdefault("gradient_accumulation_steps", 1)
                exps.append({"name": f"z{stage}_mbs{mbs}", "ds_config": cfg,
                             "zero_stage": stage})
        return exps

    # -------------------------------------------------------------- running
    def run_experiment(self, exp: dict) -> Optional[float]:
        """Build an engine with the experiment config, time steps
        ``start..end``, return the metric (None = failed/OOM).  Parity:
        reference ``scheduler.py:327 run_experiment`` (subprocess job)."""
        import deepspeed_tpu as ds
        try:
            engine, _, _, _ = ds.initialize(
                config=exp["ds_config"], model=self.model,
                training_data=self.training_data, mesh=self.mesh,
                collate_fn=self.collate_fn)
            for _ in range(self.start_step):
                loss = engine.train_batch()
            float(loss)  # sync
            t0 = time.time()
            for _ in range(self.start_step, self.end_step):
                loss = engine.train_batch()
            final = float(loss)
            dt = time.time() - t0
            if not np.isfinite(final):
                return None
            steps = self.end_step - self.start_step
            latency = dt / max(1, steps)
            if self.metric == AC.AUTOTUNING_METRIC_LATENCY:
                return -latency
            samples = engine.train_batch_size() * steps
            throughput = samples / dt
            if self.metric == AC.AUTOTUNING_METRIC_FLOPS and \
                    hasattr(self.model, "flops_per_token"):
                return throughput * self.model.flops_per_token()
            return throughput
        except Exception as e:
            logger.warning(f"experiment {exp['name']} failed: {e}")
            return None

    def _write_exp_artifact(self, exp: dict, val, seconds: float):
        """Persist one experiment (parity: reference ``ResourceManager`` job
        dirs — ``autotuning_results/<exp>/exp.json`` with config + metric),
        so runs are comparable/resumable across invocations."""
        exp_dir = os.path.join(self.results_dir, exp["name"])
        os.makedirs(exp_dir, exist_ok=True)
        with open(os.path.join(exp_dir, "exp_result.json"), "w") as f:
            json.dump({"name": exp["name"], "metric": self.metric,
                       "metric_val": val, "seconds": round(seconds, 3),
                       "zero_stage": exp["zero_stage"],
                       "ds_config": exp["ds_config"]}, f, indent=2)

    def tune(self) -> Optional[dict]:
        """Run the tuner over the experiment grid; returns the best exp
        (parity: reference ``tune`` :396)."""
        os.makedirs(self.results_dir, exist_ok=True)
        # model-info artifact (reference model_info_profile_run :664)
        with open(os.path.join(self.results_dir, "model_info.json"), "w") as f:
            json.dump({"num_params": self.get_model_num_params()}, f)
        exps = self._generate_experiments()
        if not exps:
            logger.warning("no feasible experiments (model does not fit?)")
            return None
        tuner = TUNERS[self.tuner_type](exps, self.metric)
        trials = 0
        stale = 0
        while trials < self.num_trials:
            batch = tuner.next_batch(1)
            if not batch:
                break
            exp = batch[0]
            t0 = time.time()
            val = self.run_experiment(exp)
            self._write_exp_artifact(exp, val, time.time() - t0)
            self.records.setdefault(f"z{exp['zero_stage']}", []).append(
                (exp, val, 1))
            prev_best = tuner.best_metric_val
            tuner.update(exp, val)
            logger.info(f"experiment {exp['name']}: {self.metric}="
                        f"{val if val is not None else 'failed'}")
            stale = stale + 1 if tuner.best_metric_val <= prev_best else 0
            if stale >= self.early_stopping:
                logger.info(f"early stopping after {trials + 1} trials")
                break
            trials += 1
        self.best_exp = tuner.best_exp
        self.best_metric_val = tuner.best_metric_val
        summary = {
            "metric": self.metric,
            "tuner_type": self.tuner_type,
            "num_experiments_run": sum(len(r) for r in self.records.values()),
            "num_experiments_total": len(exps),
            "best": None,
        }
        if self.best_exp is not None:
            summary["best"] = {"name": self.best_exp["name"],
                               self.metric: self.best_metric_val}
            with open(os.path.join(self.results_dir, "best_config.json"), "w") as f:
                json.dump({"name": self.best_exp["name"],
                           self.metric: self.best_metric_val,
                           "ds_config": self.best_exp["ds_config"]}, f, indent=2)
            logger.info(f"best experiment: {self.best_exp['name']} "
                        f"({self.metric}={self.best_metric_val:.3f})")
        with open(os.path.join(self.results_dir, "summary.json"), "w") as f:
            json.dump(summary, f, indent=2)
        return self.best_exp

    def print_tuning_results(self):
        for space, records in self.records.items():
            for exp, val, n in records:
                logger.info(f"{space}: {exp['name']} -> {val}")


def run_autotuning(args):
    """Launcher hook (parity: reference ``runner.py:305 run_autotuning``).

    The reference schedules tuning jobs over hostfile slots; here the user
    script is expected to construct an Autotuner itself (in-process
    experiments) — point users at the API.
    """
    logger.error(
        "Autotuning from the CLI requires the user script to build an "
        "Autotuner(model, config, data) and call .tune(); in-process "
        "experiments replace the reference's ssh job scheduler on TPU.")
    return 1
