"""Autotuning. Parity: reference ``deepspeed/autotuning/``."""

from .autotuner import (Autotuner, GridSearchTuner, RandomTuner,
                        ModelBasedTuner, model_state_bytes_per_chip)

__all__ = ["Autotuner", "GridSearchTuner", "RandomTuner", "ModelBasedTuner",
           "model_state_bytes_per_chip"]
