"""``ds_mem``: the predictive memory capacity model.

ZeRO's memory layout is a *closed-form* function of (shape, stage,
dtypes, mesh) — arXiv 1910.02054 tabulates it, and ZeRO-Infinity's whole
thesis (arXiv 2104.07857) is engineering against a modeled memory wall.
This module puts that model in the runtime instead of hand arithmetic
over MAXPARAMS.json:

- **closed-form per-subsystem byte formulas** (:func:`train_device_plan`
  for on-device ZeRO state, :func:`host_offload_plan` for the host
  offload tier, :func:`serving_plan` for the paged-KV serving side),
  keyed by the same subsystem names the runtime memory ledger
  (``monitor/memory_ledger.py``) attributes measured bytes to — model
  and measurement cannot drift apart in vocabulary;
- **a fitted host residual**: the MAXPARAMS campaign proved the host RSS
  carries a client term the formulas do not cover (runtime transfer
  buffering + allocator slack, ~linear in model size — the 6.7B
  post-mortem's "~23 GB client term").  :func:`fit_host_residual`
  least-squares fits ``residual_gb ≈ c0 + c1·params_b`` from the
  committed rungs, so :func:`replay_maxparams` reproduces the recorded
  HWMs (acceptance: 1.3B within ±10%) and :func:`max_params_b` answers
  ROADMAP #4's capacity question *before* anything allocates — the model
  must bracket the measured ceiling (2.65B fits, 6.7B does not);
- **serving capacity** (:func:`max_streams`): how many concurrent
  streams a given HBM budget admits at a serving configuration —
  the same math ``ServingEngine`` admission enforces, answerable
  offline;
- **the OOM verdict** (:func:`verdict_from_snapshot`): given a ledger
  snapshot, which subsystem blew the budget and which knob buys the
  needed headroom — what the RESOURCE_EXHAUSTED forensic dumps embed.

CLI (``bin/ds_mem``): ``ds_mem <run_dir>`` renders a monitor stream's
``mem`` events; ``--replay MAXPARAMS.json`` runs the acceptance replay;
``--max-params`` / ``--max-streams`` answer the capacity questions.
"""

import argparse
import json
import os
import sys

GIB = float(2 ** 30)

# bytes per parameter by subsystem (the MAXPARAMS.json
# ram_arithmetic_bytes_per_param table, made executable)
FP32_BYTES = 4
BF16_BYTES = 2
ADAM_MOMENTS_PER_PARAM = 2 * FP32_BYTES      # exp_avg + exp_avg_sq

# moments stay on host RAM up to this size; the MAXPARAMS criterion
# moved them to the NVMe tier above it (and the 16-bit payload image
# with them — the r5a fix)
CPU_MOMENTS_MAX_PARAMS_B = 2.7

# which knob buys headroom, per over-budget subsystem (the OOM verdict's
# advice column; names match monitor/memory_ledger.py)
KNOB_ADVICE = {
    "params": "raise zero_optimization.stage (shard params over fsdp), "
              "stream them (offload_param), or quantize the weights "
              "(int8 serving)",
    "master_fp32": "zero stage >= 1 shards the master; offload_optimizer "
                   "moves it to host RAM",
    "opt_moments": "offload_optimizer.device=cpu|nvme moves the moments "
                   "off-device; nvme tier frees host RAM too",
    "ef_state": "comms_compression off (or hierarchical:false) drops the "
                "qgZ error-feedback state",
    "compiled_programs": "fewer live signatures: pin batch shapes / lower "
                         "prefill bucket count (smaller max_seq)",
    "paged_kv_pool": "kv_bits=8 halves pool bytes; shrink num_blocks / "
                     "batch_slots / block_size; serving.prefix_cache "
                     "shares common-prefix blocks (admission then "
                     "charges unique blocks only)",
    "host_master_fp32": "move the fp32 master to the NVMe swapper tier "
                        "(ROADMAP #4; runtime/swap_tensor/)",
    "host_grad_landing_fp32": "data_types.grad_accum_dtype=bf16 halves "
                              "the gradient landing buffer",
    "host_payload_image_16bit": "offload_param.device=nvme drops the RAM "
                                "image (drop_payload)",
    "host_adam_moments": "offload_optimizer.device=nvme moves the moments "
                         "to disk",
    "h2d_staging": "lower micro batch (bench.plan_micro_backoff) or the "
                   "uploader chunk_bytes",
    "nvme_swap_buffers": "smaller aio buffer_count/buffer_numel",
    "compile_cache": "compile_cache.max_entries LRU bound",
    "residual": "the fitted client term scales with model bytes: smaller "
                "model per host, or more hosts (ds_mem --max-params "
                "prices it)",
}


def _ceil_div(a, b):
    return -(-int(a) // int(b))


# ------------------------------------------------------------ device formulas

def train_device_plan(num_params, *, zero_stage, n_devices=1, fsdp=1,
                      compute_bytes=2, needs_master=True,
                      grad_accum_bytes=None) -> dict:
    """Per-subsystem **device** bytes for one ZeRO training state, summed
    over this process's devices — the same view
    ``memory_ledger.tree_device_bytes`` measures, so the test can assert
    plan == ledger leaf-for-leaf.

    Layout rules (``zero/partition.py``, arXiv 1910.02054): a subsystem
    sharded over the fsdp extent lives ``n_devices / fsdp`` times across
    the process (once per fsdp shard, replicated over the other axes); a
    replicated one lives ``n_devices`` times.  Params shard at stage
    >= 3, master + moments at stage >= 1; gradients are transient
    (inside-step temps, priced by ``preflight_memory``'s temp term, not
    resident state)."""
    P = int(num_params)
    n = max(1, int(n_devices))
    fsdp = max(1, min(int(fsdp), n))
    sharded = n // fsdp            # copies of an fsdp-sharded subsystem
    params_copies = sharded if zero_stage >= 3 else n
    opt_copies = sharded if zero_stage >= 1 else n
    plan = {
        "params": P * compute_bytes * params_copies,
        "master_fp32": (P * FP32_BYTES * opt_copies) if needs_master
        else 0,
        "opt_moments": P * ADAM_MOMENTS_PER_PARAM * opt_copies,
    }
    plan["grads_transient"] = P * (grad_accum_bytes or compute_bytes) \
        * (sharded if zero_stage >= 2 else n)
    plan["resident_bytes"] = (plan["params"] + plan["master_fp32"]
                              + plan["opt_moments"])
    return plan


def host_offload_plan(params_b, *, moments_tier="cpu",
                      param_tier=None, grad_accum_bytes=FP32_BYTES) -> dict:
    """Per-subsystem **host RSS** bytes of the offload tier for a model
    of ``params_b`` billion parameters — the executable form of
    MAXPARAMS.json's ``ram_arithmetic_bytes_per_param`` table.
    ``param_tier`` defaults to the campaign's rule: the 16-bit payload
    image rides host RAM while the moments do (both moved to NVMe
    together at the 6.7B rung, the r5a fix)."""
    if param_tier is None:
        param_tier = moments_tier
    P = params_b * 1e9
    plan = {
        "host_master_fp32": P * FP32_BYTES,
        "host_grad_landing_fp32": P * grad_accum_bytes,
        "host_payload_image_16bit": (P * BF16_BYTES
                                     if param_tier == "cpu" else 0.0),
        "host_adam_moments": (P * ADAM_MOMENTS_PER_PARAM
                              if moments_tier == "cpu" else 0.0),
    }
    plan["plan_bytes"] = sum(plan.values())
    plan["plan_gb"] = plan["plan_bytes"] / GIB
    plan["moments_tier"] = moments_tier
    plan["param_tier"] = param_tier
    return plan


# ------------------------------------------------------- fitted host residual

def fit_host_residual(samples):
    """Least-squares fit of the UNEXPLAINED host term.

    ``samples``: ``[(params_b, measured_rss_gb, plan_gb), ...]`` —
    returns ``{"c0_gb", "c1_gb_per_b", "points"}`` with
    ``residual_gb(params_b) ≈ c0 + c1·params_b``.  The residual is the
    runtime client's transfer buffering + allocator slack — measured to
    scale with model bytes and insensitive to streaming discipline
    (MAXPARAMS ``analysis_6p7b_attempts``), which is exactly what makes
    it fittable."""
    pts = [(float(x), float(m) - float(p)) for x, m, p in samples]
    n = len(pts)
    if n == 0:
        return {"c0_gb": 0.0, "c1_gb_per_b": 0.0, "points": []}
    if n == 1:
        return {"c0_gb": pts[0][1], "c1_gb_per_b": 0.0, "points": pts}
    sx = sum(x for x, _ in pts)
    sy = sum(y for _, y in pts)
    sxx = sum(x * x for x, _ in pts)
    sxy = sum(x * y for x, y in pts)
    denom = n * sxx - sx * sx
    if abs(denom) < 1e-12:
        return {"c0_gb": sy / n, "c1_gb_per_b": 0.0, "points": pts}
    c1 = (n * sxy - sx * sy) / denom
    c0 = (sy - c1 * sx) / n
    return {"c0_gb": c0, "c1_gb_per_b": c1, "points": pts}


def predicted_rss_gb(params_b, fit, *, moments_tier=None,
                     grad_accum_bytes=FP32_BYTES) -> float:
    """Plan + fitted residual for one rung (``moments_tier=None`` →
    the campaign's tier rule: cpu up to 2.7B, nvme above)."""
    if moments_tier is None:
        moments_tier = ("cpu" if params_b <= CPU_MOMENTS_MAX_PARAMS_B
                        else "nvme")
    plan = host_offload_plan(params_b, moments_tier=moments_tier,
                             grad_accum_bytes=grad_accum_bytes)
    return (plan["plan_gb"] + fit["c0_gb"]
            + fit["c1_gb_per_b"] * params_b)


def max_params_b(fit, host_ram_gb, *, grad_accum_bytes=FP32_BYTES,
                 step_b=0.01) -> float:
    """Largest ``params_b`` whose predicted host RSS fits ``host_ram_gb``
    under the tier rule — the ROADMAP #4 question, answered by the model
    instead of by OOM.  Scanned at ``step_b`` granularity (the predicted
    curve has one tier discontinuity; a closed-form solve per tier works
    too, the scan is simply immune to tier-boundary edge cases)."""
    x, best = step_b, 0.0
    while x <= 1000.0:
        if predicted_rss_gb(x, fit,
                            grad_accum_bytes=grad_accum_bytes) \
                <= host_ram_gb:
            best = x
        elif best and x > CPU_MOMENTS_MAX_PARAMS_B:
            break          # past the tier switch and over budget: done
        x = round(x + step_b, 10)
    return round(best, 3)


# ------------------------------------------------------------ MAXPARAMS replay

# acceptance tolerance for the replay (ISSUE 13): predicted vs recorded
# host-RSS HWM per rung
REPLAY_TOLERANCE = 0.10


def _rung_samples(doc):
    """(name, params_b, measured_rss_gb, moments_tier) per recorded rung
    — including the FAILED rung: its parent-observed HWM at the kill is
    a real (params, rss) sample (the process reached it), and the fit
    needs the large-model end of the curve."""
    out = []
    for name, entry in (doc.get("per_size") or {}).items():
        params_b = entry.get("params_b")
        if params_b is None:
            try:
                params_b = float(name.rstrip("bB"))
            except ValueError:
                continue
        measured = entry.get("rss_hwm_gb",
                             entry.get("parent_observed_rss_hwm_gb"))
        if measured is None:
            continue
        tier = entry.get("moments_tier")
        if tier is None:
            prog = entry.get("progress_before_failure") or []
            tier = (prog[0].get("moments") if prog else None) or "nvme"
        out.append((name, float(params_b), float(measured), tier))
    return sorted(out, key=lambda r: r[1])


def replay_maxparams(doc, *, tolerance=REPLAY_TOLERANCE) -> dict:
    """Fit the residual from a MAXPARAMS document's rungs, then replay:
    per-rung predicted vs recorded HWM (±``tolerance``), per-rung
    fits-the-host verdicts, and the model's own max-params answer.  The
    acceptance contract (tests/test_memory.py): the 1.3B rung reproduces
    within ±10% and the model brackets the measured ceiling — the
    largest committed rung fits, the recorded OOM rung does not."""
    host_ram_gb = float(doc.get("host_ram_gb", 0)) or None
    rungs = _rung_samples(doc)
    samples = []
    for name, params_b, measured, tier in rungs:
        plan = host_offload_plan(params_b, moments_tier=tier)
        samples.append((params_b, measured, plan["plan_gb"]))
    fit = fit_host_residual(samples)
    rows = []
    for (name, params_b, measured, tier), (_, _, plan_gb) in zip(rungs,
                                                                 samples):
        pred = (plan_gb + fit["c0_gb"] + fit["c1_gb_per_b"] * params_b)
        err = (pred - measured) / measured if measured else 0.0
        rows.append({
            "rung": name, "params_b": params_b, "moments_tier": tier,
            "plan_gb": round(plan_gb, 2),
            "predicted_rss_gb": round(pred, 2),
            "measured_rss_gb": measured,
            "err_pct": round(100.0 * err, 1),
            "within_tolerance": abs(err) <= tolerance,
            "fits_host": (pred <= host_ram_gb) if host_ram_gb else None,
        })
    out = {
        "fit": {"c0_gb": round(fit["c0_gb"], 3),
                "c1_gb_per_b": round(fit["c1_gb_per_b"], 3)},
        "host_ram_gb": host_ram_gb,
        "rungs": rows,
        "tolerance": tolerance,
        "all_within_tolerance": all(r["within_tolerance"] for r in rows),
    }
    if host_ram_gb:
        out["max_params_b"] = max_params_b(fit, host_ram_gb)
        out["max_params_b_bf16_grad_accum"] = max_params_b(
            fit, host_ram_gb, grad_accum_bytes=BF16_BYTES)
    return out


# ------------------------------------------------------------ serving capacity

def request_unique_blocks(*, prompt_tokens, max_new_tokens, block_size,
                          max_seq=None, shared_prefix_tokens=0) -> dict:
    """THE per-request block math — the one function serving admission
    (``ServingEngine._admit``), ``ds_mem --max-streams`` and the memory
    ledger's shared/unique split all call, so the three can never
    disagree (regression-pinned in tests/test_serving.py).

    ``total_blocks`` is the classic cost (``paged_kv.blocks_needed`` of
    prompt+generation).  ``shared_blocks`` is how many leading blocks a
    prefix-cache hit of ``shared_prefix_tokens`` covers, clamped to
    ``(prompt_tokens - 1) // block_size`` — the final prompt token (and
    every position the decode step will WRITE) must land in a PRIVATE
    block, the same clamp ``ServingEngine._prefix_match`` applies.
    ``unique_blocks`` is what admission actually charges."""
    bs = max(1, int(block_size))
    prompt = max(1, int(prompt_tokens))
    total_tokens = prompt + int(max_new_tokens)
    if max_seq:
        total_tokens = min(total_tokens, int(max_seq))
    total = max(1, _ceil_div(total_tokens, bs))   # = pk.blocks_needed
    shared = max(0, min(int(shared_prefix_tokens) // bs,
                        (prompt - 1) // bs, total))
    return {"total_blocks": total, "shared_blocks": shared,
            "unique_blocks": total - shared}


def serving_plan(*, n_layer, n_head, head_dim, max_seq, block_size=16,
                 kv_bits=16, quant_block=64, batch_slots=8, num_blocks=0,
                 max_new_tokens=64, weight_bytes=0, prompt_tokens=None,
                 shared_prefix_tokens=0) -> dict:
    """Closed-form serving memory plan mirroring ``paged_kv.init_pool``'s
    arithmetic exactly (tested equal to ``pool_bytes`` of a real pool):
    per-block bytes, total pool bytes for the configuration's block
    count, and the per-request block cost at the default generation
    length (the ``ServingEngine.capacity()`` admission math)."""
    nb_max = _ceil_div(max_seq, block_size)
    if not num_blocks:
        num_blocks = 1 + batch_slots * nb_max
    cell = n_head * head_dim
    if kv_bits == 8:
        # the quantizer's pick_block rule (runtime/comm/quantized.py):
        # LARGEST DIVISOR of head_dim <= quant_block — re-stated here
        # (not a halving loop: head_dim=96, qb=64 picks 48, not 32) so
        # the plan mirrors init_pool byte-for-byte on non-power-of-2
        # head dims too (tested against the real pool)
        qb = min(int(quant_block), int(head_dim))
        while qb > 1 and head_dim % qb:
            qb -= 1
        per_tok = 2 * (cell * 1 + (cell // qb) * FP32_BYTES)   # k+v, +scales
    else:
        per_tok = 2 * cell * BF16_BYTES
    per_block = n_layer * block_size * per_tok
    # the unified per-request math (request_unique_blocks): the default
    # prompt (one block) reproduces the classic
    # ceil(min(max_seq, block_size + max_new) / block_size) exactly
    ub = request_unique_blocks(
        prompt_tokens=(block_size if prompt_tokens is None
                       else prompt_tokens),
        max_new_tokens=max_new_tokens, block_size=block_size,
        max_seq=max_seq, shared_prefix_tokens=shared_prefix_tokens)
    return {
        "paged_kv_pool": per_block * num_blocks,
        "per_block_bytes": per_block,
        "num_blocks": num_blocks,
        "nb_max": nb_max,
        "blocks_per_request": ub["total_blocks"],
        "shared_prefix_blocks": ub["shared_blocks"],
        "unique_blocks_per_request": ub["unique_blocks"],
        "weight_bytes": int(weight_bytes),
    }


def max_streams(plan: dict, budget_bytes, *, safety=0.92,
                workspace_bytes=0) -> dict:
    """Concurrent-stream bound for an HBM budget: blocks the budget can
    hold after weights + workspace, divided by the per-request block
    cost — ``ServingEngine`` admission, answerable before anything
    allocates (the serving twin of :func:`max_params_b`)."""
    usable = budget_bytes * safety - plan["weight_bytes"] - workspace_bytes
    blocks = max(0, int(usable // plan["per_block_bytes"]) - 1)  # scratch
    # prefix sharing amortizes the shared head ONCE across every stream;
    # each stream then costs its UNIQUE blocks (the same
    # request_unique_blocks split serving admission charges).  With no
    # sharing, unique == blocks_per_request and this is the classic bound.
    shared = int(plan.get("shared_prefix_blocks", 0))
    unique = int(plan.get("unique_blocks_per_request",
                          plan["blocks_per_request"]))
    streams = max(0, blocks - shared) // max(1, unique)
    return {"budget_bytes": int(budget_bytes), "safety": safety,
            "usable_pool_bytes": max(0, int(usable)),
            "allocatable_blocks": blocks,
            "blocks_per_request": plan["blocks_per_request"],
            "shared_prefix_blocks": shared,
            "unique_blocks_per_request": unique,
            "max_streams": streams}


# ---------------------------------------------------------------- OOM verdict

def verdict_from_snapshot(snapshot: dict, budget_bytes=None,
                          space=None) -> dict:
    """Which subsystem blew the budget, and which knob buys headroom.

    ``space`` names the exhausted space when the caller knows it (an
    allocator RESOURCE_EXHAUSTED / serving preflight is ``"hbm"``, a
    SIGKILL-by-oom-killer is ``"host"``); unset, the verdict picks the
    space with the larger attributed total.  Within the space it names
    the LARGEST subsystem, falling back to the residual itself when it
    out-weighs every named term — the honest answer the 6.7B campaign
    needed four runs to reach."""
    spaces = {}
    for sp in ("hbm", "host"):
        entries = dict(snapshot.get(sp) or {})
        resid = snapshot.get(f"{sp}_residual_bytes")
        if resid and resid > 0:
            entries["residual"] = resid
        if entries:
            spaces[sp] = entries
    if space is not None and space not in spaces:
        space = None
    if not spaces:
        return {"over_budget_subsystem": "unknown", "space": None,
                "advice": "no ledger attribution available"}
    if space is None:
        space = max(spaces, key=lambda s: sum(spaces[s].values()))
    sub = max(spaces[space], key=spaces[space].get)
    nbytes = spaces[space][sub]
    out = {
        "over_budget_subsystem": sub,
        "space": space,
        "bytes": int(nbytes),
        "gb": round(nbytes / GIB, 2),
        "advice": KNOB_ADVICE.get(sub, "see docs/monitoring.md"
                                       "#memory-explainability"),
    }
    if budget_bytes:
        out["budget_bytes"] = int(budget_bytes)
        total = sum(spaces[space].values())
        out["space_attributed_bytes"] = int(total)
        out["over_budget_bytes"] = int(max(0, total - budget_bytes))
    return out


# --------------------------------------------------------------- stream + CLI

def fold_mem_stream(events) -> dict:
    """Newest ``mem`` event per role from a parsed monitor stream (plus
    how many were seen) — what ``ds_mem <run_dir>`` renders."""
    latest = {}
    count = 0
    for e in events:
        if e.kind == "mem":
            count += 1
            latest[e.fields.get("role", e.name)] = dict(e.fields,
                                                        step=e.step)
    return {"latest": latest, "count": count}


def _fmt_gb(nbytes):
    return f"{nbytes / GIB:.2f} GB"


def render_ledger(folded: dict, source: str) -> str:
    lines = [f"ds_mem — memory ledger over {source}", ""]
    if not folded["count"]:
        lines.append(
            "no `mem` events in the stream — run with the monitor "
            "enabled on a build that emits the memory ledger "
            "(docs/monitoring.md#memory-explainability)")
        return "\n".join(lines)
    for role, snap in sorted(folded["latest"].items()):
        lines.append(f"[{role}] step {snap.get('step')}")
        for space in ("hbm", "host", "disk"):
            entries = snap.get(space) or {}
            if not entries:
                continue
            total = sum(entries.values())
            parts = ", ".join(
                f"{k} {_fmt_gb(v)}" for k, v in
                sorted(entries.items(), key=lambda kv: -kv[1]))
            lines.append(f"  {space}: {_fmt_gb(total)} attributed "
                         f"({parts})")
            for k, det in sorted(((snap.get("detail") or {})
                                  .get(space) or {}).items()):
                lines.append("    " + k + ": " + ", ".join(
                    f"{dk}={dv}" for dk, dv in sorted(det.items())))
        if snap.get("host_residual_bytes") is not None:
            lines.append(
                f"  host residual: "
                f"{_fmt_gb(snap['host_residual_bytes'])} "
                f"(RSS {_fmt_gb(snap.get('host_rss_bytes', 0))} − "
                f"attributed "
                f"{_fmt_gb(snap.get('host_attributed_bytes', 0))})")
        lines.append(f"  host RSS HWM: {snap.get('rss_hwm_gb')} GB")
        for ph in snap.get("phases") or ():
            lines.append(
                f"    phase {ph['phase']:>13}: HWM "
                f"{_fmt_gb(ph['rss_hwm_bytes'])} "
                f"(+{_fmt_gb(ph['delta_bytes'])})")
        v = verdict_from_snapshot(snap)
        lines.append(f"  largest term: {v['over_budget_subsystem']} "
                     f"[{v['space']}] {v.get('gb')} GB — knob: "
                     f"{v['advice']}")
        lines.append("")
    return "\n".join(lines)


def render_replay(rep: dict) -> str:
    lines = ["ds_mem — MAXPARAMS replay (predictive host-RSS model)", ""]
    f = rep["fit"]
    lines.append(f"fitted residual: {f['c0_gb']:+.2f} GB "
                 f"{f['c1_gb_per_b']:+.2f} GB per B params "
                 "(the runtime client term the formulas do not cover)")
    lines.append(f"{'rung':>8} {'tier':>6} {'plan':>8} {'predicted':>10} "
                 f"{'measured':>9} {'err':>7}  fits host?")
    for r in rep["rungs"]:
        fits = {True: "yes", False: "NO", None: "-"}[r["fits_host"]]
        lines.append(
            f"{r['rung']:>8} {r['moments_tier']:>6} "
            f"{r['plan_gb']:>7.1f}G {r['predicted_rss_gb']:>9.1f}G "
            f"{r['measured_rss_gb']:>8.1f}G {r['err_pct']:>+6.1f}%  "
            f"{fits}")
    tol = int(rep["tolerance"] * 100)
    lines.append(
        f"replay: {'ALL rungs' if rep['all_within_tolerance'] else 'NOT all'}"
        f" within ±{tol}% of the recorded HWM")
    if rep.get("max_params_b"):
        lines.append(
            f"predicted ceiling on the {rep['host_ram_gb']:.0f} GB host: "
            f"{rep['max_params_b']} B params "
            f"({rep['max_params_b_bf16_grad_accum']} B with "
            "grad_accum_dtype=bf16)")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="ds_mem",
        description="memory explainability: render a run's memory "
                    "ledger, replay MAXPARAMS.json through the capacity "
                    "model, or answer max-params / max-streams "
                    "(docs/monitoring.md#memory-explainability)")
    ap.add_argument("run", nargs="?", default=None,
                    help="monitor run dir (or an events.jsonl path) "
                         "whose `mem` events to render")
    ap.add_argument("--replay", metavar="MAXPARAMS_JSON", default=None,
                    help="fit + replay a committed MAXPARAMS document")
    ap.add_argument("--max-params", action="store_true",
                    help="predict the largest trainable params for "
                         "--host-ram-gb (fit from --replay or "
                         "./MAXPARAMS.json)")
    ap.add_argument("--host-ram-gb", type=float, default=None)
    ap.add_argument("--max-streams", action="store_true",
                    help="serving capacity: concurrent streams an HBM "
                         "budget admits at the given model/config dims")
    ap.add_argument("--budget-gb", type=float, default=16.0,
                    help="HBM budget for --max-streams (default 16, "
                         "v5e-class)")
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--max-seq", type=int, default=1024)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--kv-bits", type=int, default=16, choices=(8, 16))
    ap.add_argument("--max-new", type=int, default=64)
    ap.add_argument("--prompt-tokens", type=int, default=None,
                    help="per-request prompt length for --max-streams "
                         "(default: one block)")
    ap.add_argument("--shared-prefix-tokens", type=int, default=0,
                    help="tokens of common prompt prefix served from the "
                         "radix cache (serving.prefix_cache): the shared "
                         "head is charged ONCE, each stream pays only "
                         "its unique blocks")
    ap.add_argument("--weight-gb", type=float, default=0.0,
                    help="resident weight bytes to subtract from the "
                         "--max-streams budget")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    if args.replay or args.max_params:
        path = args.replay or "MAXPARAMS.json"
        try:
            with open(path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            print(f"ds_mem: cannot load {path}: {e}", file=sys.stderr)
            return 2
        rep = replay_maxparams(doc)
        if args.host_ram_gb:
            fit = {"c0_gb": rep["fit"]["c0_gb"],
                   "c1_gb_per_b": rep["fit"]["c1_gb_per_b"]}
            rep["max_params_b"] = max_params_b(fit, args.host_ram_gb)
            rep["host_ram_gb"] = args.host_ram_gb
        print(json.dumps(rep, indent=2) if args.json
              else render_replay(rep))
        return 0 if rep["all_within_tolerance"] else 1

    if args.max_streams:
        plan = serving_plan(
            n_layer=args.layers, n_head=args.heads, head_dim=args.head_dim,
            max_seq=args.max_seq, block_size=args.block_size,
            kv_bits=args.kv_bits, max_new_tokens=args.max_new,
            weight_bytes=int(args.weight_gb * GIB),
            prompt_tokens=args.prompt_tokens,
            shared_prefix_tokens=args.shared_prefix_tokens)
        ms = max_streams(plan, args.budget_gb * GIB)
        out = {"plan": plan, **ms}
        if args.json:
            print(json.dumps(out, indent=2))
        else:
            shared_note = ""
            if ms["shared_prefix_blocks"]:
                shared_note = (
                    f" ({ms['shared_prefix_blocks']} shared prefix "
                    f"block(s) charged once, "
                    f"{ms['unique_blocks_per_request']} unique/stream)")
            print(f"ds_mem — serving capacity at {args.budget_gb:.1f} GB "
                  f"HBM:\n  per-block {plan['per_block_bytes']} B, "
                  f"{ms['blocks_per_request']} block(s)/request"
                  f"{shared_note}\n"
                  f"  max concurrent streams: {ms['max_streams']}")
        return 0

    if not args.run:
        ap.error("give a monitor run dir, --replay, --max-params, or "
                 "--max-streams")
    from ..monitor.__main__ import StreamFollower, resolve_stream
    stream = resolve_stream(args.run)
    if not os.path.exists(stream):
        print(f"ds_mem: no event stream at {stream}", file=sys.stderr)
        return 1
    folded = fold_mem_stream(StreamFollower(stream).poll())
    if args.json:
        print(json.dumps(folded, indent=2, sort_keys=True))
    else:
        print(render_ledger(folded, stream))
    return 0


if __name__ == "__main__":
    sys.exit(main())
