"""AST lint rule engine with per-site suppressions.

Grown out of the swallowed-OSError check that used to live inline in
``tests/test_fault_tolerance.py``: rules are now first-class objects
with stable ids, findings are machine-readable (``findings.Finding``),
and deliberate violations are suppressed AT THE SITE with a comment —
so the reviewed decision travels with the code, not with an allowlist
in a far-away test file.

Suppression syntax (``docs/static-analysis.md``):

  x = risky()          # dstpu: disable=DSTPU102
  # dstpu: disable=DSTPU101,DSTPU103      <- line above also works
  # dstpu: disable-file=DSTPU102          <- whole file, any line

Rules register themselves in :data:`REGISTRY` (see ``rules.py``); add a
rule by subclassing :class:`Rule` and decorating with
:func:`register`.
"""

import ast
import io
import os
import re
import tokenize

from ..findings import Finding

_SUPPRESS_LINE_RE = re.compile(r"#\s*dstpu:\s*disable=([\w,\s]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*dstpu:\s*disable-file=([\w,\s]+)")

REGISTRY = {}


def register(cls):
    """Class decorator: add a Rule subclass to the registry by id."""
    rule = cls()
    assert rule.id not in REGISTRY, f"duplicate rule id {rule.id}"
    REGISTRY[rule.id] = rule
    return cls


class Rule:
    """One lint rule.  Subclasses set the class attrs and implement
    ``check(tree, src, relpath) -> iterable[Finding]``."""
    id = ""
    name = ""
    severity = "error"
    description = ""

    def check(self, tree: ast.Module, src: str, relpath: str):
        raise NotImplementedError

    def finding(self, relpath, lineno, message):
        return Finding(self.id, self.severity, message,
                       file=relpath, line=lineno)


class Suppressions:
    """Parsed suppression comments for one file.

    Only REAL comment tokens count (via ``tokenize``) — suppression text
    quoted inside a string or docstring (e.g. a module documenting the
    syntax) must not silently disable rules.

    Consumption is tracked: :meth:`active` records which suppression it
    matched, so :func:`lint_file` can flag the STALE ones (DSTPU003 — a
    suppression whose rule no longer fires is debt that hides the next
    real finding)."""

    def __init__(self, src: str):
        self.by_line = {}      # lineno -> set of rule ids
        self.file_level = set()
        self.consumed = set()       # (comment lineno, rule id) pairs used
        self.file_consumed = set()  # file-level rule ids used
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(src).readline))
        except (tokenize.TokenError, SyntaxError, IndentationError):
            return    # unparseable source surfaces as DSTPU000 instead
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_FILE_RE.search(tok.string)
            if m:
                self.file_level |= _ids(m.group(1))
                continue
            m = _SUPPRESS_LINE_RE.search(tok.string)
            if m:
                self.by_line.setdefault(tok.start[0], set()).update(
                    _ids(m.group(1)))

    def active(self, rule_id: str, lineno) -> bool:
        if rule_id in self.file_level:
            self.file_consumed.add(rule_id)
            return True
        if lineno is None:
            return False
        # the flagged line itself, or a standalone comment just above it
        for at in (lineno, lineno - 1):
            if rule_id in self.by_line.get(at, ()):
                self.consumed.add((at, rule_id))
                return True
        return False


def _ids(text):
    return {t.strip() for t in text.split(",") if t.strip()}


@register
class UnusedSuppression(Rule):
    """Engine-level rule: the findings are emitted by :func:`lint_file`
    (stale-suppression detection needs the whole run's consumption
    state, not one AST); ``check`` is intentionally empty.  Registered
    as a normal rule so ``--list-rules``/``--rules`` see it and a site
    can opt out per file."""
    id = "DSTPU003"
    name = "unused-suppression"
    severity = "warning"
    description = ("A `# dstpu: disable=` suppression whose rule did not "
                   "fire at that site — stale debt that would hide the "
                   "next real finding there.  Delete the comment (or fix "
                   "the drift that moved the finding).")

    def check(self, tree, src, relpath):
        return ()


def iter_py_files(paths):
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
            continue
        for root, dirs, names in os.walk(path):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            for name in sorted(names):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def select_rules(rule_ids=None):
    from . import rules as _rules  # noqa: F401  (populates REGISTRY)
    from . import lifecycle as _lifecycle  # noqa: F401  (DSTPU3xx family)
    if rule_ids is None:
        return list(REGISTRY.values())
    expanded = []
    for rid in rule_ids:
        if rid.endswith("xx"):     # family selector, e.g. DSTPU3xx
            family = sorted(r for r in REGISTRY
                            if r.startswith(rid[:-2]))
            assert family, f"no rules in family {rid!r}; " \
                           f"known: {sorted(REGISTRY)}"
            expanded.extend(family)
        else:
            expanded.append(rid)
    unknown = set(expanded) - set(REGISTRY)
    assert not unknown, f"unknown rule ids: {sorted(unknown)}; " \
                        f"known: {sorted(REGISTRY)}"
    return [REGISTRY[r] for r in expanded]


def lint_file(path, rules=None, root=None, src=None):
    """Run rules over one file; returns unsuppressed findings."""
    rules = rules if rules is not None else select_rules()
    relpath = os.path.relpath(path, root) if root else path
    if src is None:
        with open(path, encoding="utf-8") as f:
            src = f.read()
    try:
        tree = ast.parse(src, filename=relpath)
    except SyntaxError as e:
        return [Finding("DSTPU000", "error", f"syntax error: {e.msg}",
                        file=relpath, line=e.lineno)]
    sup = Suppressions(src)
    out = []
    for rule in rules:
        for f in rule.check(tree, src, relpath):
            if not sup.active(f.rule, f.line):
                out.append(f)
    out.extend(_stale_suppressions(sup, rules, relpath))
    return out


def _stale_suppressions(sup, rules, relpath):
    """DSTPU003 findings for suppressions no selected rule consumed.
    Only suppressions of rules that actually RAN can be judged stale —
    a `--rules DSTPU002` pass must not condemn a DSTPU104 comment."""
    ran = {r.id for r in rules}
    if UnusedSuppression.id not in ran:
        return
    stale_rule = REGISTRY[UnusedSuppression.id]
    for lineno, ids in sorted(sup.by_line.items()):
        for rid in sorted((ids & ran) - {UnusedSuppression.id}):
            if (lineno, rid) in sup.consumed:
                continue
            f = stale_rule.finding(
                relpath, lineno,
                f"unused suppression of {rid} — the rule did not fire "
                f"here; delete the stale comment")
            if not sup.active(f.rule, f.line):
                yield f
    for rid in sorted((sup.file_level & ran)
                      - {UnusedSuppression.id} - sup.file_consumed):
        f = stale_rule.finding(
            relpath, 1,
            f"unused file-level suppression of {rid} — the rule did "
            f"not fire anywhere in this file")
        if not sup.active(f.rule, f.line):
            yield f


def lint_paths(paths, rules=None, root=None):
    """Run rules over files/directories; returns sorted findings."""
    rules = rules if rules is not None else select_rules()
    findings = []
    for path in iter_py_files(paths):
        findings.extend(lint_file(path, rules=rules, root=root))
    findings.sort(key=lambda f: (f.file or "", f.line or 0, f.rule))
    return findings
