"""DSTPU3xx: typestate lint for the serving control plane's lifecycles.

The inference control plane (``deepspeed_tpu/inference/``) is ~3.4k LoC
of host-side resource-lifecycle code — KV blocks, request uids, replica
health — where a bug is silent corruption, not a crash.  The jaxpr
auditor can't see it (nothing here is traced), so these rules check the
AST against **declarative lifecycle specs**: each finite-state machine
is written down ONCE (states, legal transitions, owning APIs) and the
rules verify every transition site in the source matches the table.
The runtime shadow sanitizer (``analysis/sanitize.py``) enforces the
same tables dynamically — one spec, two enforcement layers.

Spec syntax (``docs/static-analysis.md#lifecycle-specs``): an FSM is a
dict with ``states``, ``initial``, and ``transitions`` (state -> tuple
of legal successors).  The per-file bindings below attach an FSM to a
source attribute (``attr``), name the only functions allowed to assign
it (``owners`` / ``init_owners``), and name the transition API whose
call sites are checked against the table.

Rules (scoped to ``deepspeed_tpu/inference/``):

- **DSTPU301** illegal lifecycle transition: a state attribute assigned
  outside its owning transition API, or a transition-API call whose
  (guarded-from, to) pair is not in the declared table.
- **DSTPU302** out-of-API mutation: allocator free-lists, per-sequence
  block lists, slot block tables, replica assignment sets, or journal
  buffers mutated outside their owning methods.
- **DSTPU303** unpaired alloc: a ``.alloc(...)``-bound variable reaches
  a ``return``/``raise`` exit path (exception edges included) without
  being freed or escaping to an owner.
- **DSTPU304** set-once result: terminal result fields
  (outcome/tokens/t_done) written outside the declared finalizers, the
  result table created or popped outside its owning APIs.
"""

import ast

from . import Rule, register

# --------------------------------------------------------------------------
# declarative lifecycle specs — the single source of truth shared by the
# static rules here, the runtime shadow sanitizer (analysis/sanitize.py)
# and docs/static-analysis.md#lifecycle-specs.

KV_BLOCK_FSM = {
    "name": "kv-block",
    "states": ("free", "allocated", "quarantined", "shared", "cow"),
    "initial": "free",
    "transitions": {
        "free": ("allocated",),
        # prefix-cache sharing (PR 19): a second holder (co-tenant or the
        # cache itself) promotes allocated -> shared; the block is
        # read-only until every extra holder drops (shared -> allocated)
        # or a diverging writer clones it (shared -> cow -> allocated,
        # the writer's fresh PRIVATE copy).  Scrub/quarantine is legal
        # only from the sole-owner state — never while shared.
        "allocated": ("free", "quarantined", "shared"),
        "shared": ("allocated", "cow"),
        "cow": ("allocated",),
        # quarantined blocks are scrubbed, then returned to the free list
        "quarantined": ("free",),
    },
}

REQUEST_FSM = {
    "name": "request-uid",
    "states": ("submitted", "queued", "placed", "journaled", "transferred",
               "completed", "popped"),
    "initial": "submitted",
    "transitions": {
        # shed/deadline-at-admit may complete a uid from any pre-placed
        # state; results are set once, then popped exactly once
        "submitted": ("queued", "completed"),
        "queued": ("placed", "completed"),
        # disaggregation (docs/serving.md#disaggregation): a prefill
        # worker retires the uid with the TRANSFERRED outcome — the
        # handoff edge, not a terminal answer; the decode side (or the
        # router's recompute fallback) completes it.  transferred ->
        # placed is the re-seat: the stream is admitted again on the
        # decode worker through the restore path.
        "placed": ("journaled", "transferred", "completed"),
        "journaled": ("transferred", "completed"),
        "transferred": ("placed", "completed"),
        "completed": ("popped",),
        "popped": (),
    },
}

REPLICA_FSM = {
    "name": "replica-health",
    "states": ("HEALTHY", "SUSPECT", "DRAINING", "DEAD"),
    "initial": "HEALTHY",
    "transitions": {
        "HEALTHY": ("SUSPECT", "DRAINING", "DEAD"),
        "SUSPECT": ("HEALTHY", "DEAD"),
        "DRAINING": ("SUSPECT", "HEALTHY", "DEAD"),
        "DEAD": (),                     # dead is terminal — never left
    },
}

FSMS = (KV_BLOCK_FSM, REQUEST_FSM, REPLICA_FSM)

# file bindings: which FSM guards which attribute in which file, and the
# owner functions allowed to touch it.  Paths match by suffix so fixture
# tests can replay a binding under a synthetic path.
STATE_BINDINGS = {
    "inference/router.py": {
        "attr": "state",
        "fsm": REPLICA_FSM,
        "owners": ("_set_state",),
        # __init__ may only seed the FSM's initial state
        "init_owners": ("__init__",),
        "api": "_set_state",
        "state_arg": 1,     # self._set_state(st, STATE, now, ...) -> args[1]
    },
}

# attribute name -> owning function/class names (either matches).  A
# store or mutating method call on these outside an owner is DSTPU302.
PROTECTED_ATTRS = {
    "_free": ("BlockAllocator",),        # allocator free list
    "_in_use": ("BlockAllocator",),      # allocator live-block set
    "_refs": ("BlockAllocator",),        # per-block refcounts (sharing)
    "_entries": ("PrefixIndex",),        # radix cache: key -> entry
    "_by_block": ("PrefixIndex",),       # radix cache: block -> key
    "_lru": ("PrefixIndex",),            # radix cache eviction order
    "_buf": ("RequestJournal",),         # journal append buffer
    "assigned": ("_ReplicaState", "_place", "_record_result", "_handoff",
                 "_seat_transfer"),
    # slot block tables: _restore_stream is the migration-era second
    # admission path (seats a restored slot) and _start_shared the
    # prefix-cache-hit seat — peers of _start
    "_tables": ("__init__", "_start", "_start_shared", "_finish",
                "_restore_stream"),
    "blocks": ("__init__",),             # per-sequence block list (_Slot)
}

_MUTATING_METHODS = ("append", "extend", "insert", "pop", "popleft",
                     "remove", "clear", "add", "discard", "update",
                     "setdefault")

# result-table discipline per file: who may create records, who may
# write the terminal (set-once) fields, who may pop.
RESULT_BINDINGS = {
    "inference/router.py": {
        "create": ("submit",),
        "finalize": ("_finalize",),
        "pop": ("pop_result",),
    },
    "inference/serving.py": {
        "create": ("submit", "_recover"),
        "finalize": ("_finalize_unseated", "_finish"),
        "pop": ("pop_result", "reset_stats"),
    },
}

TERMINAL_FIELDS = ("outcome", "tokens", "t_done")

SCOPE_DIR = "deepspeed_tpu/inference/"
_SCOPE_FILES = ("inference/router.py", "inference/serving.py",
                "inference/journal.py", "inference/paged_kv.py",
                "inference/transfer.py")


def _norm(relpath):
    return relpath.replace("\\", "/")


def _in_scope(relpath):
    return _norm(relpath).endswith(_SCOPE_FILES)


def _binding_for(relpath, table):
    norm = _norm(relpath)
    for suffix, binding in table.items():
        if norm.endswith(suffix):
            return binding
    return None


def _parents(tree):
    out = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[child] = node
    return out


def _enclosing_scopes(node, parents):
    """Names of enclosing functions/classes, innermost first."""
    names = []
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            names.append(cur.name)
        cur = parents.get(cur)
    return names


def _owned_by(node, parents, owners):
    return any(name in owners for name in _enclosing_scopes(node, parents))


def _guard_states(node, parents, constants):
    """Intersect the from-states implied by the enclosing positive
    ``if``/``elif`` guards of ``node`` (``x.state == K`` / ``x.state in
    (A, B)``).  Returns a set, empty when nothing is provable."""
    states = None
    prev, cur = node, parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.If) and _stmt_in(prev, cur.body):
            got = _states_from_test(cur.test, constants)
            if got is not None:
                states = got if states is None else states & got
        prev, cur = cur, parents.get(cur)
        if isinstance(prev, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
    return states or set()


def _stmt_in(node, stmts):
    return any(node is s or _contains(s, node) for s in stmts)


def _contains(root, node):
    return any(child is node for child in ast.walk(root))


def _states_from_test(test, constants):
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        sets = [s for s in (_states_from_test(v, constants)
                            for v in test.values) if s is not None]
        if not sets:
            return None
        out = set(sets[0])
        for s in sets[1:]:
            out &= s
        return out
    if isinstance(test, ast.Compare) and len(test.ops) == 1:
        left, op, comp = test.left, test.ops[0], test.comparators[0]
        if isinstance(left, ast.Attribute) and left.attr == "state":
            if (isinstance(op, ast.Eq) and isinstance(comp, ast.Name)
                    and comp.id in constants):
                return {comp.id}
            if (isinstance(op, ast.In)
                    and isinstance(comp, (ast.Tuple, ast.List, ast.Set))):
                names = {e.id for e in comp.elts
                         if isinstance(e, ast.Name) and e.id in constants}
                if names:
                    return names
    return None


@register
class LifecycleTransition(Rule):
    id = "DSTPU301"
    name = "illegal-lifecycle-transition"
    severity = "error"
    description = ("State-machine attribute assigned outside its owning "
                   "transition API, or a transition not in the declared "
                   "lifecycle table (docs/static-analysis.md"
                   "#lifecycle-specs).")

    def check(self, tree, src, relpath):
        binding = _binding_for(relpath, STATE_BINDINGS)
        if binding is None:
            return
        fsm = binding["fsm"]
        constants = set(fsm["states"])
        parents = _parents(tree)
        for node in ast.walk(tree):
            # (a) direct assignment to the guarded attribute
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and tgt.attr == binding["attr"]):
                        yield from self._check_store(
                            node, tgt, parents, binding, fsm, relpath)
            # (b) transition-API call sites vs the table
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == binding["api"]
                    and len(node.args) > binding["state_arg"]):
                arg = node.args[binding["state_arg"]]
                if not (isinstance(arg, ast.Name) and arg.id in constants):
                    continue
                to = arg.id
                for frm in sorted(_guard_states(node, parents, constants)):
                    if to not in fsm["transitions"].get(frm, ()):
                        yield self.finding(
                            relpath, node.lineno,
                            f"illegal {fsm['name']} transition "
                            f"{frm} -> {to} (allowed: "
                            f"{', '.join(fsm['transitions'].get(frm, ())) or 'none — terminal state'})")

    def _check_store(self, node, tgt, parents, binding, fsm, relpath):
        scopes = _enclosing_scopes(node, parents)
        if any(n in binding["owners"] for n in scopes):
            return
        if any(n in binding["init_owners"] for n in scopes):
            v = node.value
            if isinstance(v, ast.Name) and v.id == fsm["initial"]:
                return
            yield self.finding(
                relpath, node.lineno,
                f"{fsm['name']} FSM must start in {fsm['initial']!r}; "
                f"__init__ may not seed any other state")
            return
        yield self.finding(
            relpath, node.lineno,
            f".{binding['attr']} assigned outside "
            f"{'/'.join(binding['owners'])} — all {fsm['name']} "
            f"transitions must go through the owning API so the "
            f"table, logging and handoff hooks apply")


@register
class OutOfApiMutation(Rule):
    id = "DSTPU302"
    name = "out-of-api-mutation"
    severity = "error"
    description = ("Lifecycle-owned internals (allocator free lists, "
                   "block tables, assignment sets, journal buffers) "
                   "mutated outside their owning API.")

    def check(self, tree, src, relpath):
        if not _in_scope(relpath):
            return
        parents = _parents(tree)
        for node in ast.walk(tree):
            attr = self._mutated_attr(node)
            if attr is None or attr not in PROTECTED_ATTRS:
                continue
            if _owned_by(node, parents, PROTECTED_ATTRS[attr]):
                continue
            yield self.finding(
                relpath, node.lineno,
                f".{attr} mutated outside its owner "
                f"({'/'.join(PROTECTED_ATTRS[attr])}) — go through the "
                f"owning API so the lifecycle bookkeeping (and the "
                f"shadow sanitizer, when armed) stays truthful")

    @staticmethod
    def _mutated_attr(node):
        # store/del: x._free = ..., x._free[i] = ..., del x._free[i]
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            tgts = (node.targets if isinstance(node, ast.Assign)
                    else [node.target] if isinstance(node, ast.AugAssign)
                    else node.targets)
            for tgt in tgts:
                base = tgt
                if isinstance(base, ast.Subscript):
                    base = base.value
                if isinstance(base, ast.Attribute):
                    return base.attr
        # mutating method call: x._free.append(...), x.assigned.clear()
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATING_METHODS
                and isinstance(node.func.value, ast.Attribute)):
            return node.func.value.attr
        return None


@register
class UnpairedAlloc(Rule):
    id = "DSTPU303"
    name = "unpaired-alloc"
    severity = "error"
    description = ("A block allocation reaches a return/raise exit path "
                   "(exception edges included) without being freed or "
                   "escaping to an owner — a pool leak.")

    def check(self, tree, src, relpath):
        if not _in_scope(relpath):
            return
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield from self._check_fn(fn, relpath)

    def _check_fn(self, fn, relpath):
        for var, alloc_stmt, chain in self._allocs(fn):
            leaks = []
            released = False
            for block, idx in chain:
                released = self._scan(block[idx:], var, released, leaks)
            if not released and not leaks:
                leaks.append((fn.end_lineno or fn.lineno, "falls out of "
                              "scope at function end"))
            for lineno, how in leaks:
                yield self.finding(
                    relpath, lineno,
                    f"{var!r} allocated at line {alloc_stmt.lineno} "
                    f"{how} without free() or escaping to an owner "
                    f"(kv-block FSM: allocated blocks must return to "
                    f"'free' on every exit path)")

    # -------------------------------------------------------- discovery
    def _allocs(self, fn):
        """(var, alloc_stmt, [(block, next_index), ...innermost first])
        for each ``var = <...>.alloc(...)`` binding in ``fn``."""
        out = []

        def visit(block, chain):
            for i, st in enumerate(block):
                if (isinstance(st, ast.Assign) and len(st.targets) == 1
                        and isinstance(st.targets[0], ast.Name)
                        and isinstance(st.value, ast.Call)
                        and isinstance(st.value.func, ast.Attribute)
                        and st.value.func.attr == "alloc"):
                    out.append((st.targets[0].id, st,
                                [(block, i + 1)] + chain))
                for sub in self._sub_blocks(st):
                    visit(sub, [(block, i + 1)] + chain)
        visit(fn.body, [])
        return out

    @staticmethod
    def _sub_blocks(st):
        for field in ("body", "orelse", "finalbody"):
            blk = getattr(st, field, None)
            if blk and not isinstance(st, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                yield blk
        for h in getattr(st, "handlers", ()):
            yield h.body

    # ------------------------------------------------------ path walker
    def _scan(self, stmts, var, released, leaks):
        """Walk a statement block; record exits where ``var`` is still
        held.  Any Load of ``var`` counts as free/escape (passed to a
        call, returned, stored, iterated); exits under a ``var is None``
        guard are the alloc-failed path and exempt."""
        for st in stmts:
            if isinstance(st, (ast.Return, ast.Raise)):
                if self._loads(st, var):
                    return True
                if not released:
                    kind = ("returns" if isinstance(st, ast.Return)
                            else "raises")
                    leaks.append((st.lineno, kind))
                return released
            if isinstance(st, ast.If):
                exempt = self._none_guard(st.test, var)
                # a non-None-guard test that inspects the var (cleanup
                # code deciding whether to free) releases for the
                # BRANCHES only — the straight-line remainder must
                # still free
                test_rel = (not exempt) and self._loads(st.test, var)
                body_rel = self._scan(st.body, var,
                                      released or exempt or test_rel,
                                      leaks)
                else_rel = self._scan(st.orelse, var,
                                      released or test_rel, leaks)
                released = released or (body_rel and else_rel)
                continue
            if isinstance(st, ast.Try):
                pre = released
                body_rel = self._scan(st.body, var, released, leaks)
                for h in st.handlers:
                    # exception edge: the try body may have aborted
                    # before its release — handlers start un-released
                    self._scan(h.body, var, pre, leaks)
                if st.orelse:
                    body_rel = self._scan(st.orelse, var, body_rel, leaks)
                if st.finalbody:
                    fin_rel = self._scan(st.finalbody, var, pre, leaks)
                    body_rel = body_rel or fin_rel
                released = body_rel
                continue
            if isinstance(st, (ast.For, ast.While)):
                if self._loads(st.iter if isinstance(st, ast.For)
                               else st.test, var):
                    released = True
                self._scan(st.body, var, released, leaks)
                self._scan(st.orelse, var, released, leaks)
                continue
            if isinstance(st, ast.With):
                released = self._scan(
                    st.body, var, released or self._loads(st, var), leaks)
                continue
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if self._loads(st, var):
                released = True
        return released

    @staticmethod
    def _loads(node, var):
        if node is None:
            return False
        return any(isinstance(n, ast.Name) and n.id == var
                   and isinstance(n.ctx, ast.Load)
                   for n in ast.walk(node))

    @staticmethod
    def _none_guard(test, var):
        """``if var is None:`` / ``if not var:`` — the alloc-failed
        branch, where there is nothing to free."""
        if (isinstance(test, ast.Compare) and len(test.ops) == 1
                and isinstance(test.ops[0], ast.Is)
                and isinstance(test.left, ast.Name)
                and test.left.id == var
                and isinstance(test.comparators[0], ast.Constant)
                and test.comparators[0].value is None):
            return True
        return (isinstance(test, ast.UnaryOp)
                and isinstance(test.op, ast.Not)
                and isinstance(test.operand, ast.Name)
                and test.operand.id == var)


@register
class SetOnceResult(Rule):
    id = "DSTPU304"
    name = "set-once-result"
    severity = "error"
    description = ("Result-table discipline: records created, terminal "
                   "fields (outcome/tokens/t_done) written, or records "
                   "popped outside the declared owning APIs — the "
                   "set-once contract the crash-handoff dedup relies "
                   "on.")

    def check(self, tree, src, relpath):
        binding = _binding_for(relpath, RESULT_BINDINGS)
        if binding is None:
            return
        parents = _parents(tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    yield from self._check_store(node, tgt, parents,
                                                 binding, relpath)
            if isinstance(node, ast.Delete):
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Subscript)
                            and self._is_results(tgt.value)
                            and not _owned_by(node, parents,
                                              binding["pop"])):
                        yield self.finding(
                            relpath, node.lineno,
                            f"result record deleted outside "
                            f"{'/'.join(binding['pop'])}")
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "pop"
                    and self._is_results(node.func.value)
                    and not _owned_by(node, parents, binding["pop"])):
                yield self.finding(
                    relpath, node.lineno,
                    f"result record popped outside "
                    f"{'/'.join(binding['pop'])} — uids must be served "
                    f"exactly once (request-uid FSM: completed -> "
                    f"popped)")

    def _check_store(self, node, tgt, parents, binding, relpath):
        if not isinstance(tgt, ast.Subscript):
            return
        # results[uid] = {...}: record creation
        if self._is_results(tgt.value):
            if not _owned_by(node, parents, binding["create"]):
                yield self.finding(
                    relpath, node.lineno,
                    f"result record created outside "
                    f"{'/'.join(binding['create'])}")
            return
        # rec["outcome"] = ...: terminal set-once field
        key = tgt.slice
        if (isinstance(key, ast.Constant)
                and key.value in TERMINAL_FIELDS
                and not _owned_by(node, parents, binding["finalize"])):
            yield self.finding(
                relpath, node.lineno,
                f"terminal result field {key.value!r} written outside "
                f"{'/'.join(binding['finalize'])} — results are "
                f"set-once (the crash-handoff dedup contract)")

    @staticmethod
    def _is_results(node):
        return ((isinstance(node, ast.Attribute)
                 and node.attr == "results")
                or (isinstance(node, ast.Name) and node.id == "results"))
