"""Repo-specific lint rules (tracing safety + IO hygiene).

Rule catalog (see ``docs/static-analysis.md``):

  DSTPU001  bare ``except:``                                       (error)
  DSTPU002  silently swallowed OSError (``except OSError: pass``)  (error)
  DSTPU101  host-impure call inside a jit-traced function:
            ``time.time()``, ``np.random.*``, stdlib ``random.*``,
            ``global`` mutation — all evaluate ONCE at trace time and
            bake a stale value into every step                      (error)
  DSTPU102  raw ``jax.lax`` collective outside
            ``parallel/collectives.py`` — scheduled collectives go
            through the one reviewed wrapper layer                  (error)
  DSTPU103  traced-value materialization inside a jit-traced
            function: ``float()``, ``np.asarray()``/``np.array()``,
            ``jax.device_get()``, ``.item()`` — a host sync (or a
            tracer error) in the hot path                           (error)
  DSTPU104  ad-hoc metric emission (``print``/direct ``json.dump``)
            in runtime/inference code — metrics go through the
            monitor bus (one schema) or the logger; deliberate
            contractual outputs (the bench headline stdout line)
            carry per-site suppressions                             (error)
"""

import ast
import os

from . import Rule, register

JIT_WRAPPERS = {"jit", "pjit", "shard_map", "pallas_call"}

LAX_COLLECTIVES = {"psum", "psum_scatter", "pmean", "pmax", "pmin",
                   "ppermute", "pshuffle", "all_gather", "all_to_all",
                   "pbroadcast"}

_HOST_IMPURE_EXACT = {
    "time.time", "time.perf_counter", "time.monotonic",
    "time.process_time", "time.time_ns", "time.perf_counter_ns",
}
_HOST_IMPURE_PREFIXES = ("np.random.", "numpy.random.", "random.")

_MATERIALIZERS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                  "jax.device_get"}


def _dotted(node):
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _terminal(node):
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _jit_traced_functions(tree):
    """Function/Lambda nodes in this module that get traced by a
    jit-family wrapper: passed to ``jax.jit(...)``/``shard_map(...)``/
    ``pallas_call(...)``, or decorated with one (incl.
    ``@partial(jax.jit, ...)``).  Name-based matching is a deliberate
    over-approximation (same-name methods all count) — a lint, not a
    type system."""
    traced_nodes = []
    traced_names = set()

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _terminal(node.func) in JIT_WRAPPERS:
            if node.args:
                target = node.args[0]
                if isinstance(target, ast.Lambda):
                    traced_nodes.append(target)
                else:
                    name = _terminal(target)
                    if name:
                        traced_names.add(name)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _terminal(dec) in JIT_WRAPPERS:
                    traced_nodes.append(node)
                elif isinstance(dec, ast.Call):
                    if _terminal(dec.func) in JIT_WRAPPERS:
                        traced_nodes.append(node)
                    elif (_terminal(dec.func) == "partial" and dec.args
                          and _terminal(dec.args[0]) in JIT_WRAPPERS):
                        traced_nodes.append(node)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in traced_names:
            traced_nodes.append(node)
    return traced_nodes


def _walk_traced(tree):
    """Yield every AST node inside any jit-traced function body."""
    seen = set()
    for fn in _jit_traced_functions(tree):
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if id(node) not in seen:
                    seen.add(id(node))
                    yield node


@register
class BareExcept(Rule):
    id = "DSTPU001"
    name = "bare-except"
    severity = "error"
    description = ("`except:` catches SystemExit/KeyboardInterrupt and "
                   "hides the real failure; name the exception types")

    def check(self, tree, src, relpath):
        for node in ast.walk(tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                yield self.finding(relpath, node.lineno, "bare `except:`")


def _exception_names(node):
    if node is None:
        return []
    elts = node.elts if isinstance(node, ast.Tuple) else [node]
    return [_terminal(e) for e in elts if _terminal(e)]


@register
class SwallowedOSError(Rule):
    id = "DSTPU002"
    name = "swallowed-oserror"
    severity = "error"
    description = ("IO errors must be retried, logged, or re-raised — "
                   "never silently dropped (docs/fault-tolerance.md)")

    def check(self, tree, src, relpath):
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler) or node.type is None:
                continue
            swallows = (len(node.body) == 1
                        and isinstance(node.body[0], ast.Pass))
            mentions = any(n in ("OSError", "IOError", "EnvironmentError")
                           for n in _exception_names(node.type))
            if swallows and mentions:
                yield self.finding(relpath, node.lineno,
                                   "silently swallowed OSError")


@register
class HostImpureInJit(Rule):
    id = "DSTPU101"
    name = "host-impure-in-jit"
    severity = "error"
    description = ("time.time()/np.random/global mutation inside a "
                   "jit-traced function runs ONCE at trace time; the "
                   "compiled step replays the stale value forever")

    def check(self, tree, src, relpath):
        for node in _walk_traced(tree):
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func)
                if dotted is None:
                    continue
                if dotted in _HOST_IMPURE_EXACT or \
                        any(dotted.startswith(p)
                            for p in _HOST_IMPURE_PREFIXES):
                    yield self.finding(
                        relpath, node.lineno,
                        f"`{dotted}(...)` inside a jit-traced function "
                        "(traces once, bakes the value into the step; "
                        "use jax.random / pass host values as args)")
            elif isinstance(node, ast.Global):
                yield self.finding(
                    relpath, node.lineno,
                    f"`global {', '.join(node.names)}` inside a "
                    "jit-traced function (trace-time side effect; the "
                    "compiled step will not repeat it)")


@register
class RawCollective(Rule):
    id = "DSTPU102"
    name = "raw-collective"
    severity = "error"
    description = ("raw jax.lax collectives live in "
                   "parallel/collectives.py; call the wrappers so the "
                   "comms layer stays auditable in one place")

    ALLOWED_FILES = ("parallel/collectives.py",)

    def check(self, tree, src, relpath):
        norm = relpath.replace("\\", "/")
        if any(norm.endswith(ok) for ok in self.ALLOWED_FILES):
            return
        for node in ast.walk(tree):
            if not isinstance(node, ast.Attribute):
                continue
            if node.attr not in LAX_COLLECTIVES:
                continue
            base = _dotted(node.value)
            if base in ("lax", "jax.lax"):
                yield self.finding(
                    relpath, node.lineno,
                    f"raw collective `{base}.{node.attr}` outside "
                    "parallel/collectives.py (use the "
                    "parallel.collectives wrapper)")


@register
class AdhocMetricEmission(Rule):
    id = "DSTPU104"
    name = "adhoc-metric-emission"
    severity = "error"
    description = ("runtime/inference code must emit metrics through the "
                   "monitor bus (deepspeed_tpu/monitor) or the logger; "
                   "bare print()/json.dump() invents a one-off format "
                   "ds_top and the schema tests cannot see")

    # scope: the runtime + inference trees (where the monitor bus is the
    # one sanctioned metric path) and the bench driver (whose contractual
    # stdout headline carries explicit per-site suppressions)
    SCOPE_DIRS = ("runtime/", "inference/")
    SCOPE_FILES = ("bench.py",)

    def _in_scope(self, relpath):
        norm = relpath.replace("\\", "/")
        if "/monitor/" in norm or norm.startswith("monitor/"):
            return False              # the bus itself (and ds_top's table)
        return any(d in norm for d in self.SCOPE_DIRS) or \
            os.path.basename(norm) in self.SCOPE_FILES

    def check(self, tree, src, relpath):
        if not self._in_scope(relpath):
            return
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if dotted == "print":
                yield self.finding(
                    relpath, node.lineno,
                    "`print(...)` in runtime/inference code — emit "
                    "metrics via the monitor bus or logger (suppress "
                    "per-site for contractual stdout protocols)")
            elif dotted == "json.dump":
                yield self.finding(
                    relpath, node.lineno,
                    "direct `json.dump(...)` of a metrics/artifact dict "
                    "— route it through the monitor bus (artifact "
                    "events), or suppress per-site with the reviewed "
                    "reason")


@register
class TracedValueMaterialization(Rule):
    id = "DSTPU103"
    name = "traced-materialization"
    severity = "error"
    description = ("float()/np.asarray()/.item() on a traced value is a "
                   "host sync (or ConcretizationTypeError) inside the "
                   "step program")

    def check(self, tree, src, relpath):
        for node in _walk_traced(tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if dotted == "float" or dotted in _MATERIALIZERS:
                yield self.finding(
                    relpath, node.lineno,
                    f"`{dotted}(...)` inside a jit-traced function — "
                    "materializes a traced value on the host (use "
                    "jnp.asarray / .astype, or hoist the host math "
                    "out of the step)")
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr == "item" and not node.args
                  and not node.keywords):
                yield self.finding(
                    relpath, node.lineno,
                    "`.item()` inside a jit-traced function — host "
                    "sync on a traced value")
