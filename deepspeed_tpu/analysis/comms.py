"""Per-step collective census + declarative comms budget.

The census is taken at two levels:

  - **jaxpr level**: explicit named-axis collectives (``psum``,
    ``all_gather``, ...) from ``shard_map``/``pmap`` regions — the
    explicitly scheduled paths (pipeline, ring attention, MoE dispatch);
  - **compiled-HLO level**: the collectives XLA's SPMD partitioner
    inserted for sharding constraints (``all-reduce``, ``reduce-scatter``,
    ...) — the implicit ZeRO traffic.

A :class:`CommsBudget` declares per-kind ceilings (op count and payload
bytes per step); :func:`check_budget` turns census overruns into
findings.  ZeRO's comms-volume math (1x / 1x / 1.5x parameter bytes for
stages 1/2/3, ZeRO arXiv:1910.02054 §7) makes these budgets writable in
advance of a bench run.
"""

from dataclasses import dataclass, field
from typing import Optional

from .findings import Finding

# canonical kind names; both jaxpr primitives and HLO opcodes map here
KIND_ALIASES = {
    "psum": "all_reduce", "psum2": "all_reduce", "pmax": "all_reduce",
    "pmin": "all_reduce", "all-reduce": "all_reduce",
    "all_gather": "all_gather", "all-gather": "all_gather",
    "psum_scatter": "reduce_scatter", "reduce_scatter": "reduce_scatter",
    "reduce-scatter": "reduce_scatter",
    "all_to_all": "all_to_all", "all-to-all": "all_to_all",
    "ppermute": "collective_permute", "pshuffle": "collective_permute",
    "collective-permute": "collective_permute",
    "pbroadcast": "broadcast", "collective-broadcast": "broadcast",
}

COLLECTIVE_KINDS = tuple(sorted(set(KIND_ALIASES.values())))


def canonical_kind(name: str) -> Optional[str]:
    return KIND_ALIASES.get(name)


@dataclass
class CensusEntry:
    kind: str                 # canonical kind
    op: str                   # raw primitive / HLO opcode name
    axes: tuple = ()          # named axes (jaxpr level; empty for HLO)
    bytes: int = 0            # payload bytes (sum of output aval bytes)
    eqn_path: Optional[str] = None
    level: str = "jaxpr"      # "jaxpr" | "hlo"

    def to_dict(self):
        return {"kind": self.kind, "op": self.op, "axes": list(self.axes),
                "bytes": self.bytes, "eqn_path": self.eqn_path,
                "level": self.level}


def summarize(census) -> dict:
    """{kind: {"count": n, "bytes": total}} over both census levels."""
    out = {}
    for e in census:
        rec = out.setdefault(e.kind, {"count": 0, "bytes": 0})
        rec["count"] += 1
        rec["bytes"] += e.bytes
    return out


@dataclass
class CommsBudget:
    """Declarative per-step ceilings, checked against the census.

    ``per_kind`` maps a canonical kind (see :data:`COLLECTIVE_KINDS`) to
    ``{"max_count": int|None, "max_bytes": int|None}``; ``None`` (or a
    missing kind) means unlimited.  ``total_max_bytes`` bounds the sum
    over every kind.
    """
    per_kind: dict = field(default_factory=dict)
    total_max_bytes: Optional[int] = None

    def __post_init__(self):
        for kind in self.per_kind:
            assert kind in COLLECTIVE_KINDS, \
                f"unknown collective kind {kind!r}; known: {COLLECTIVE_KINDS}"


def check_budget(census, budget: CommsBudget):
    """Census overruns → findings (rule DSTPU203)."""
    findings = []
    summary = summarize(census)
    for kind, limits in budget.per_kind.items():
        got = summary.get(kind, {"count": 0, "bytes": 0})
        max_count = limits.get("max_count")
        if max_count is not None and got["count"] > max_count:
            findings.append(Finding(
                "DSTPU203", "error",
                f"comms budget exceeded: {got['count']} {kind} ops per step "
                f"(budget {max_count})",
                eqn_path=f"census/{kind}",
                extra={"kind": kind, "count": got["count"],
                       "max_count": max_count}))
        max_bytes = limits.get("max_bytes")
        if max_bytes is not None and got["bytes"] > max_bytes:
            findings.append(Finding(
                "DSTPU203", "error",
                f"comms budget exceeded: {got['bytes']} {kind} payload "
                f"bytes per step (budget {max_bytes})",
                eqn_path=f"census/{kind}",
                extra={"kind": kind, "bytes": got["bytes"],
                       "max_bytes": max_bytes}))
    if budget.total_max_bytes is not None:
        total = sum(rec["bytes"] for rec in summary.values())
        if total > budget.total_max_bytes:
            findings.append(Finding(
                "DSTPU203", "error",
                f"comms budget exceeded: {total} total collective payload "
                f"bytes per step (budget {budget.total_max_bytes})",
                eqn_path="census/total",
                extra={"bytes": total,
                       "max_bytes": budget.total_max_bytes}))
    return findings
