"""Per-step collective census + declarative comms budget.

The census is taken at two levels:

  - **jaxpr level**: explicit named-axis collectives (``psum``,
    ``all_gather``, ...) from ``shard_map``/``pmap`` regions — the
    explicitly scheduled paths (pipeline, ring attention, MoE dispatch);
  - **compiled-HLO level**: the collectives XLA's SPMD partitioner
    inserted for sharding constraints (``all-reduce``, ``reduce-scatter``,
    ...) — the implicit ZeRO traffic.

A :class:`CommsBudget` declares per-kind ceilings (op count and payload
bytes per step); :func:`check_budget` turns census overruns into
findings.  ZeRO's comms-volume math (1x / 1x / 1.5x parameter bytes for
stages 1/2/3, ZeRO arXiv:1910.02054 §7) makes these budgets writable in
advance of a bench run.
"""

from dataclasses import dataclass, field
from typing import Optional

from .findings import Finding

# canonical kind names; both jaxpr primitives and HLO opcodes map here
KIND_ALIASES = {
    "psum": "all_reduce", "psum2": "all_reduce", "pmax": "all_reduce",
    "pmin": "all_reduce", "all-reduce": "all_reduce",
    "all_gather": "all_gather", "all-gather": "all_gather",
    "psum_scatter": "reduce_scatter", "reduce_scatter": "reduce_scatter",
    "reduce-scatter": "reduce_scatter",
    "all_to_all": "all_to_all", "all-to-all": "all_to_all",
    "ppermute": "collective_permute", "pshuffle": "collective_permute",
    "collective-permute": "collective_permute",
    "pbroadcast": "broadcast", "collective-broadcast": "broadcast",
}

COLLECTIVE_KINDS = tuple(sorted(set(KIND_ALIASES.values())))


def canonical_kind(name: str) -> Optional[str]:
    return KIND_ALIASES.get(name)


# wire dtypes that mark a QUANTIZED collective (the int8/int4 payloads of
# runtime/comm/quantized.py; u16 excluded — bf16 parses as u16 in HLO)
QUANT_DTYPE_NAMES = frozenset({"s8", "u8", "int8", "uint8", "s4", "u4",
                               "int4", "uint4"})


@dataclass
class CensusEntry:
    kind: str                 # canonical kind
    op: str                   # raw primitive / HLO opcode name
    axes: tuple = ()          # named axes (jaxpr level; empty for HLO)
    bytes: int = 0            # payload bytes (sum of output aval bytes)
    eqn_path: Optional[str] = None
    level: str = "jaxpr"      # "jaxpr" | "hlo"
    dtypes: tuple = ()        # payload dtype names (classification)
    groups: int = 0           # replica-group count (HLO; 0 = unknown).
    #                           >1 marks a sub-axis ("two-level") phase

    @property
    def quantized(self) -> bool:
        """True when every payload dtype is an int8/int4 wire format."""
        return bool(self.dtypes) and all(d in QUANT_DTYPE_NAMES
                                         for d in self.dtypes)

    def to_dict(self):
        return {"kind": self.kind, "op": self.op, "axes": list(self.axes),
                "bytes": self.bytes, "eqn_path": self.eqn_path,
                "level": self.level, "dtypes": list(self.dtypes),
                "groups": self.groups, "quantized": self.quantized}


def summarize(census) -> dict:
    """{kind: {"count", "bytes", "quantized_count", "quantized_bytes"}}
    over both census levels."""
    out = {}
    for e in census:
        rec = out.setdefault(e.kind, {"count": 0, "bytes": 0,
                                      "quantized_count": 0,
                                      "quantized_bytes": 0})
        rec["count"] += 1
        rec["bytes"] += e.bytes
        if e.quantized:
            rec["quantized_count"] += 1
            rec["quantized_bytes"] += e.bytes
    return out


def wire_report(census, *, full_itemsize: int = 4) -> dict:
    """Wire vs logical accounting for a (possibly compressed) step.

    ``wire_bytes`` is what the census actually measured; for quantized
    entries ``logical_bytes`` re-prices the payload at ``full_itemsize``
    bytes/element (int8: numel == wire bytes; packed int4 is counted as
    its int8 equivalent — the census cannot see through the packing).
    ``grouped`` counts sub-axis (two-level) collective phases.
    """
    wire = logical = q_wire = grouped = 0
    for e in census:
        wire += e.bytes
        if e.quantized:
            q_wire += e.bytes
            logical += e.bytes * full_itemsize
        else:
            logical += e.bytes
        if e.groups > 1:
            grouped += 1
    return {"wire_bytes": wire, "logical_bytes": logical,
            "quantized_wire_bytes": q_wire,
            "quantized_fraction": (q_wire / wire if wire else 0.0),
            "grouped_collectives": grouped,
            "by_kind": summarize(census)}


@dataclass
class CommsBudget:
    """Declarative per-step ceilings, checked against the census.

    ``per_kind`` maps a canonical kind (see :data:`COLLECTIVE_KINDS`) to
    ``{"max_count": int|None, "max_bytes": int|None}``; ``None`` (or a
    missing kind) means unlimited.  ``total_max_bytes`` bounds the sum
    over every kind.
    """
    per_kind: dict = field(default_factory=dict)
    total_max_bytes: Optional[int] = None

    def __post_init__(self):
        for kind in self.per_kind:
            assert kind in COLLECTIVE_KINDS, \
                f"unknown collective kind {kind!r}; known: {COLLECTIVE_KINDS}"


def check_budget(census, budget: CommsBudget):
    """Census overruns → findings (rule DSTPU203)."""
    findings = []
    summary = summarize(census)
    for kind, limits in budget.per_kind.items():
        got = summary.get(kind, {"count": 0, "bytes": 0})
        max_count = limits.get("max_count")
        if max_count is not None and got["count"] > max_count:
            findings.append(Finding(
                "DSTPU203", "error",
                f"comms budget exceeded: {got['count']} {kind} ops per step "
                f"(budget {max_count})",
                eqn_path=f"census/{kind}",
                extra={"kind": kind, "count": got["count"],
                       "max_count": max_count}))
        max_bytes = limits.get("max_bytes")
        if max_bytes is not None and got["bytes"] > max_bytes:
            findings.append(Finding(
                "DSTPU203", "error",
                f"comms budget exceeded: {got['bytes']} {kind} payload "
                f"bytes per step (budget {max_bytes})",
                eqn_path=f"census/{kind}",
                extra={"kind": kind, "bytes": got["bytes"],
                       "max_bytes": max_bytes}))
    if budget.total_max_bytes is not None:
        total = sum(rec["bytes"] for rec in summary.values())
        if total > budget.total_max_bytes:
            findings.append(Finding(
                "DSTPU203", "error",
                f"comms budget exceeded: {total} total collective payload "
                f"bytes per step (budget {budget.total_max_bytes})",
                eqn_path="census/total",
                extra={"bytes": total,
                       "max_bytes": budget.total_max_bytes}))
    return findings
