"""Shared probe models for the audit stages and bench rungs.

One source of truth for the tiny engines that ``--audit-step`` and the
bench wire probes build: keeping a single parameterized fixture (instead
of per-caller near-twins) means a change to the MoE constructor
signature or the ``partition_specs`` contract lands everywhere at once.
Imports stay inside methods — the analysis CLI must not pull jax in for
a lint-only run.
"""


class MoEProbeModel:
    """MoE regression model: linear in → top-1 MoE → linear out.

    ``dim`` is the MoE (expert) width, ``io`` the data/projection width
    (defaults to ``dim``), ``expert_mult`` the expert-MLP hidden
    multiplier.  Callers pick the shape for their purpose:

    - ``--audit-step moe`` (``analysis/__main__.py``) uses
      ``MoEProbeModel(dim, n_experts)`` — square, big enough that the
      expert exchange dominates the budget floors so the tightness
      check has margin.
    - the ``moe_wire_compression_cpu8`` bench rung (``bench.py``) uses
      ``io`` well under ``dim`` so the dense-grad all-reduce is noise
      next to the dispatch/combine payload: on the pure ``expert=8``
      mesh the expert params are EP-sharded (their grads never cross
      the wire), and the exchange IS the wire being measured.
    """

    def __init__(self, dim=16, num_experts=8, io=None, expert_mult=4):
        from ..moe import MoE

        class _Expert:
            def init(self, rng):
                import jax
                import jax.numpy as jnp
                k1, k2 = jax.random.split(rng)
                h = expert_mult * dim
                return {"w1": jax.random.normal(k1, (dim, h),
                                                jnp.float32) * 0.1,
                        "w2": jax.random.normal(k2, (h, dim),
                                                jnp.float32) * 0.1}

            def apply(self, params, x, rng=None):
                import jax
                h = jax.nn.relu(x @ params["w1"])
                return h @ params["w2"]

        self.dim = dim if io is None else io
        self.moe_dim = dim
        self.moe = MoE(dim, _Expert(), num_experts=num_experts, k=1,
                       capacity_factor=2.0, min_capacity=0, use_rts=False)

    def init(self, rng):
        import jax
        import jax.numpy as jnp
        import numpy as np
        k1, k2, k3 = jax.random.split(rng, 3)
        n = lambda k, s: jax.random.normal(k, s, jnp.float32) / np.sqrt(s[0])
        return {"p_in": n(k1, (self.dim, self.moe_dim)),
                "moe": self.moe.init(k2),
                "p_out": n(k3, (self.moe_dim, self.dim))}

    def loss(self, params, batch, rng):
        import jax.numpy as jnp
        x, y = batch
        h = x @ params["p_in"]
        h, l_aux, _ = self.moe.apply(params["moe"], h, rng=rng)
        p = h @ params["p_out"]
        return jnp.mean(jnp.square(p - y)) + 0.01 * l_aux

    def partition_specs(self, params):
        from jax.sharding import PartitionSpec as P
        return {"p_in": P(), "p_out": P(),
                "moe": self.moe.partition_specs(params["moe"])}
