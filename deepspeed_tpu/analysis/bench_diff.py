"""``ds_bench_diff``: the perf-regression gate over bench artifacts.

Compares two bench JSON documents — live ``bench.py`` headlines
(``parse_headline_tail`` output), committed ``BENCH_*.json`` /
``SERVING_BENCH.json`` / ``INFERENCE_BENCH.json`` artifacts, or any mix
— metric by metric, with per-metric **noise bands**, and exits non-zero
on a regression beyond the band.  This is the gate the bench trajectory
lacked: the artifacts were compared by eye across PRs.

Metric classification (by key name, innermost key of the JSON path):

- **higher-better** (throughput family): ``tokens_per_sec``, ``tok_s``,
  ``mfu`` (and ``projected_mfu*``), ``samples_per_sec``,
  ``fraction_of_bound``, ``achieved_frac``, ``reduction_x``,
  ``bound_tokens_per_sec``, ``decode_tokens_per_sec``, and the
  migration wins ``migrated_streams`` / ``recompute_tokens_saved``
  (restore-first handoffs and the decode work they avoided);
- **lower-better** (latency/cost family): keys ending in ``_ms``/``_s``
  (``p50_ms``, ``p99_ms``, ``ttft_*``, ``prefill_ms``, compile times),
  ``ms_per_token*``, ``*_bytes``/``*_bytes_per_step`` (wire/pool cost),
  ``host_pct``/``overhead_pct``, the memory family
  (``rss_hwm_gb``, ``pool_bytes``, ``peak_bytes`` — capacity costs),
  and the slo family (``*burn_rate*``, ``slo_breaches`` — error-budget
  costs), and the router family (``lost_requests``,
  ``duplicate_answers``, ``handoff_requeue_ms`` — zero-loss serving
  costs: any growth is a robustness regression), and the migration
  family (``migration_fallbacks`` — each one is a stream that paid
  full recompute because its image was unusable; ``restore_ms`` gates
  through the ``_ms`` suffix rule);
- everything else numeric is **informational** — reported when it moved,
  never gated (counts, shapes, config echoes).

Band defaults (docs/monitoring.md#ds_bench_diff): ``--band 0.2`` —
±20%, this container's measured fast-tier run-to-run swing (CHANGES.md
PR-6/PR-9 notes); TPU runs are steadier, ``--band 0.05`` is apt there.
Per-metric overrides: ``--band-for p99_ms=0.5`` (tail latencies are
noisier than medians).  A metric present on one side only is reported
as added/removed, never gated; so is one whose baseline is zero (a
relative band cannot price an infinite delta).

Exit codes: 0 = no regression beyond band, 1 = regression(s), 2 = usage.
"""

import argparse
import json
import sys

DEFAULT_BAND = 0.2         # ±20%: this container's measured CPU-tier noise

HIGHER_BETTER = ("tokens_per_sec", "tok_s", "samples_per_sec", "mfu",
                 "fraction_of_bound", "achieved_frac", "reduction_x",
                 "bound_tokens_per_sec", "decode_tokens_per_sec",
                 "migrated_streams", "recompute_tokens_saved",
                 "prefix_hit_rate", "max_streams")
LOWER_BETTER_SUFFIX = ("_ms", "_s")
LOWER_BETTER = ("ms_per_token", "overhead_pct", "host_pct")
LOWER_BETTER_BYTES = ("wire_bytes", "bytes_per_step")
# memory family (docs/monitoring.md#memory-explainability): host-RSS
# high-water marks, KV-pool residency and projected/measured peaks are
# capacity costs — growth beyond band is a regression
LOWER_BETTER_MEM = ("rss_hwm_gb", "pool_bytes", "peak_bytes")
# slo family (docs/monitoring.md#slo-tracking): burn rates and breach
# counts are budget costs — growth beyond band is a regression
LOWER_BETTER_SLO = ("burn_rate", "slo_breaches")
# router family (docs/serving.md#replica-router): lost requests and
# duplicate answers must be exactly zero (the zero-loss contract), and
# handoff requeue latency is the fail-over cost — growth is a
# robustness regression
LOWER_BETTER_ROUTER = ("lost_requests", "duplicate_answers",
                       "handoff_requeue_ms")
# sanitizer family (docs/static-analysis.md#sanitizer): a clean rung
# must report zero lifecycle findings — any growth is a serving bug,
# not noise
LOWER_BETTER_SANITIZE = ("sanitizer_findings",)
# migration family (docs/serving.md#kv-migration): every fallback is a
# stream that paid full recompute because its KV image was torn,
# corrupt, or unplaceable — growth is a robustness regression
# (restore_ms gates via the _ms suffix rule)
LOWER_BETTER_MIGRATION = ("migration_fallbacks",)
# prefix-sharing family (docs/serving.md#prefix-sharing):
# unique_block_frac is physical-over-logical block residency — a rise
# means the radix cache is deduplicating LESS of the co-tenant KV
# (prefix_hit_rate gates the other direction via HIGHER_BETTER)
LOWER_BETTER_PREFIX = ("unique_block_frac",)
# disaggregation family (docs/serving.md#disaggregation): the per-stream
# handoff cost (publish + seat + restore) and the decode-side
# inter-token p99 the role split exists to flatten — both explicit here
# even though the _ms suffix rule would catch them: the rung's headline
# metrics must never silently drop to informational under a rename
LOWER_BETTER_DISAGG = ("handoff_ms", "decode_cadence_p99_ms")
# exact count contracts where ZERO is the baseline by design: any
# growth regresses even though a relative band cannot gate it (the
# zero-baseline report-never-regress policy below is for
# rounded-to-0.0 gauges, not for these)
ZERO_CONTRACT = ("sanitizer_findings", "lost_requests",
                 "duplicate_answers", "slo_breaches")


def classify(key: str):
    """'higher' | 'lower' | None (informational) for one metric key."""
    k = key.lower()
    for name in HIGHER_BETTER:
        if name in k:
            return "higher"
    for name in (LOWER_BETTER + LOWER_BETTER_BYTES + LOWER_BETTER_MEM
                 + LOWER_BETTER_SLO + LOWER_BETTER_ROUTER
                 + LOWER_BETTER_SANITIZE + LOWER_BETTER_MIGRATION
                 + LOWER_BETTER_PREFIX + LOWER_BETTER_DISAGG):
        if name in k:
            return "lower"
    if k.endswith(LOWER_BETTER_SUFFIX):
        return "lower"
    return None


def _numeric_leaves(doc, prefix=""):
    """Flatten a bench JSON into {path: float} over its numeric leaves
    (bools excluded — `breaker_open: false` is a flag, not a metric)."""
    out = {}
    if isinstance(doc, dict):
        for k, v in doc.items():
            out.update(_numeric_leaves(v, f"{prefix}.{k}" if prefix else k))
    elif isinstance(doc, (int, float)) and not isinstance(doc, bool):
        out[prefix] = float(doc)
    return out


def compare(base: dict, new: dict, band: float = DEFAULT_BAND,
            bands: dict = None) -> dict:
    """Per-metric comparison.  Returns ``{"rows": [...], "regressions":
    [...], "added": [...], "removed": [...]}`` — a row per shared
    numeric leaf that moved, each with the applied band and verdict."""
    bands = bands or {}
    a, b = _numeric_leaves(base), _numeric_leaves(new)
    rows, regressions = [], []
    for path in sorted(set(a) & set(b)):
        key = path.rsplit(".", 1)[-1]
        direction = classify(key)
        va, vb = a[path], b[path]
        if va == vb:
            continue
        if not va and not any(name in key.lower()
                              for name in ZERO_CONTRACT):
            # zero baseline: no relative band can gate this (delta is
            # infinite for ANY change) — report, never regress.  A
            # rounded-to-0.0 gap_host_pct moving to 0.3 is noise, not
            # a perf cliff; absolute gating needs a real baseline.
            # Exact zero-contract counts (ZERO_CONTRACT) stay gated:
            # there, zero IS the contract and any growth is a bug.
            direction = None
        delta = (vb - va) / abs(va) if va else float("inf")
        this_band = bands.get(key, bands.get(path, band))
        verdict = "info"
        if direction is not None and abs(delta) > this_band:
            bad = (delta < 0) if direction == "higher" else (delta > 0)
            verdict = "REGRESSION" if bad else "improved"
        row = {"path": path, "base": va, "new": vb,
               "delta_pct": round(100.0 * delta, 2),
               "direction": direction, "band_pct": round(100 * this_band, 1),
               "verdict": verdict}
        rows.append(row)
        if verdict == "REGRESSION":
            regressions.append(row)
    return {"rows": rows, "regressions": regressions,
            "added": sorted(set(b) - set(a)),
            "removed": sorted(set(a) - set(b))}


def _load(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        # a bench stdout capture: the headline is the strict final line
        for line in reversed(text.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        raise


def render(result: dict, base_path: str, new_path: str) -> str:
    lines = [f"ds_bench_diff: {base_path} -> {new_path}"]
    shown = [r for r in result["rows"] if r["verdict"] != "info"] or \
        result["rows"][:20]
    for r in shown:
        arrow = {"higher": "↑ better", "lower": "↓ better",
                 None: ""}[r["direction"]]
        lines.append(
            f"  [{r['verdict']:>10}] {r['path']}: {r['base']:g} -> "
            f"{r['new']:g} ({r['delta_pct']:+.1f}%, band "
            f"±{r['band_pct']:.0f}%) {arrow}")
    if result["added"]:
        lines.append(f"  added: {len(result['added'])} metric(s) "
                     f"(e.g. {result['added'][0]})")
    if result["removed"]:
        lines.append(f"  removed: {len(result['removed'])} metric(s) "
                     f"(e.g. {result['removed'][0]})")
    n = len(result["regressions"])
    lines.append(f"verdict: {n} regression(s) beyond the noise band"
                 if n else "verdict: no regression beyond the noise band")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="ds_bench_diff",
        description="compare two bench JSONs with per-metric noise "
                    "bands; exit 1 on regression beyond the band "
                    "(docs/monitoring.md#ds_bench_diff)")
    ap.add_argument("base", help="baseline JSON (headline or committed "
                                 "BENCH_*.json artifact)")
    ap.add_argument("new", help="candidate JSON")
    ap.add_argument("--band", type=float, default=DEFAULT_BAND,
                    help=f"relative noise band (default {DEFAULT_BAND} "
                         "= ±20%%, the measured CPU-tier swing)")
    ap.add_argument("--band-for", action="append", default=[],
                    metavar="METRIC=BAND",
                    help="per-metric override, e.g. p99_ms=0.5 "
                         "(repeatable; matches the key or the full path)")
    ap.add_argument("--json", action="store_true",
                    help="emit the comparison as JSON")
    args = ap.parse_args(argv)

    bands = {}
    for spec in args.band_for:
        if "=" not in spec:
            ap.error(f"--band-for wants METRIC=BAND, got {spec!r}")
        key, val = spec.rsplit("=", 1)
        bands[key] = float(val)
    try:
        base, new = _load(args.base), _load(args.new)
    except (OSError, json.JSONDecodeError) as e:
        print(f"ds_bench_diff: cannot load inputs: {e}", file=sys.stderr)
        return 2
    result = compare(base, new, band=args.band, bands=bands)
    if args.json:
        print(json.dumps(result, indent=2))
    else:
        print(render(result, args.base, args.new))
    return 1 if result["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
