"""Jaxpr-level auditor for compiled train steps.

The perf properties that kill a TPU run are invisible at runtime until
they cost a bench cycle: a host callback serializing the step, an fp32
matmul hiding in a bf16 path, donation that silently didn't apply
(doubling peak HBM), an unbudgeted collective, a weak-typed Python
scalar forcing retrace churn.  This module checks them STATICALLY from
the three artifacts every jitted callable already exposes:

  closed jaxpr   → host callbacks, dtype promotions, explicit
                   collectives, weak-typed/constant recompile hazards
  lowered HLO    → per-argument donation aliasing (``tf.aliasing_output``)
  compiled exe   → executable-level ``input_output_alias`` + the SPMD
                   partitioner's inserted collectives

Rule ids (audit namespace DSTPU2xx):

  DSTPU201  host callback / infeed / outfeed inside the step (error)
  DSTPU202  dtype promotion above the configured compute dtype (warning;
            f64 anywhere is error)
  DSTPU203  collective census over the declared comms budget (error)
  DSTPU204  donation declared but not honored by the executable (error)
  DSTPU205  recompile hazard: weak-typed scalar argument (warning) or
            large closure-captured constant (info)
"""

import re
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from .comms import CensusEntry, CommsBudget, canonical_kind, check_budget, \
    summarize
from .findings import Finding, counts_by_severity

# primitives that round-trip through the host (serialize the step on the
# dispatch path); anything name-matching *callback is caught too
HOST_SYNC_PRIMS = {"pure_callback", "io_callback", "debug_callback",
                   "callback", "infeed", "outfeed", "host_local_array_to_global_array"}

# primitives whose operand dtypes define the "compute dtype" of a path
COMPUTE_PRIMS = {"dot_general", "conv_general_dilated"}

_F64_NAMES = ("float64", "complex128")

_LARGE_CONST_BYTES = 1 << 20     # 1 MB baked into the program text


def _dtype_name(aval) -> Optional[str]:
    dt = getattr(aval, "dtype", None)
    try:
        return None if dt is None else np.dtype(dt).name
    except TypeError:
        return None      # extended dtypes (PRNG keys) have no numpy name


def _aval_bytes(aval) -> int:
    dt = getattr(aval, "dtype", None)
    shape = getattr(aval, "shape", None)
    if dt is None or shape is None:
        return 0
    try:
        itemsize = np.dtype(dt).itemsize
    except TypeError:
        return 0
    return int(np.prod(shape or (1,))) * itemsize


def _float_width(name: str) -> int:
    return {"bfloat16": 16, "float16": 16, "float32": 32,
            "float64": 64}.get(name, 0)


def iter_eqns(jaxpr, path=""):
    """Yield ``(eqn, eqn_path)`` over a jaxpr and every sub-jaxpr
    (pjit/scan/cond/while/custom_* bodies), depth-first."""
    for i, eqn in enumerate(getattr(jaxpr, "eqns", ())):
        here = f"{path}/{eqn.primitive.name}[{i}]" if path else \
            f"{eqn.primitive.name}[{i}]"
        yield eqn, here
        for sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub, here)


def _sub_jaxprs(eqn):
    for v in eqn.params.values():
        for sub in _as_jaxprs(v):
            yield sub


def _as_jaxprs(v):
    if hasattr(v, "eqns"):                      # core.Jaxpr
        yield v
    elif hasattr(v, "jaxpr"):                   # core.ClosedJaxpr
        yield v.jaxpr
    elif isinstance(v, (list, tuple)):
        for item in v:
            yield from _as_jaxprs(item)


def _all_consts(closed):
    """Consts of a closed jaxpr AND of every nested closed sub-jaxpr
    (jit hoists closure captures into the inner pjit's consts)."""
    seen = set()

    def walk(node):
        consts = getattr(node, "consts", None)
        if consts is not None:
            for c in consts:
                if id(c) not in seen:
                    seen.add(id(c))
                    yield c
        for eqn in getattr(getattr(node, "jaxpr", node), "eqns", ()):
            for v in eqn.params.values():
                for item in (v if isinstance(v, (list, tuple)) else [v]):
                    if hasattr(item, "jaxpr") or hasattr(item, "eqns"):
                        yield from walk(item)

    yield from walk(closed)


@dataclass
class AuditReport:
    findings: list = field(default_factory=list)
    census: list = field(default_factory=list)       # CensusEntry list
    donation: dict = field(default_factory=dict)
    n_eqns: int = 0

    @property
    def host_callbacks(self):
        return [f for f in self.findings if f.rule == "DSTPU201"]

    @property
    def promotions(self):
        return [f for f in self.findings if f.rule == "DSTPU202"]

    @property
    def recompile_hazards(self):
        return [f for f in self.findings if f.rule == "DSTPU205"]

    def ok(self) -> bool:
        return not any(f.severity == "error" for f in self.findings)

    def to_dict(self) -> dict:
        return {"findings": [f.to_dict() for f in self.findings],
                "census": [c.to_dict() for c in self.census],
                "census_summary": summarize(self.census),
                "donation": self.donation,
                "n_eqns": self.n_eqns,
                "counts": counts_by_severity(self.findings),
                "ok": self.ok()}


# --------------------------------------------------------------- jaxpr pass
def _audit_jaxpr(closed, compute_dtype, report):
    compute_name = (np.dtype(compute_dtype).name
                    if compute_dtype is not None else None)
    compute_width = _float_width(compute_name) if compute_name else None

    for eqn, path in iter_eqns(closed.jaxpr):
        report.n_eqns += 1
        name = eqn.primitive.name

        # --- host round-trips -----------------------------------------
        if name in HOST_SYNC_PRIMS or name.endswith("callback"):
            cb = eqn.params.get("callback", None)
            report.findings.append(Finding(
                "DSTPU201", "error",
                f"host callback `{name}` inside the compiled step "
                f"({getattr(cb, '__name__', None) or 'opaque'}): every "
                "dispatch round-trips to Python, serializing the step",
                eqn_path=path))

        # --- explicit collectives -------------------------------------
        kind = canonical_kind(name)
        if kind is not None:
            axes = eqn.params.get("axes",
                                  eqn.params.get("axis_name", ()))
            if not isinstance(axes, (tuple, list)):
                axes = (axes,)
            payload = sum(_aval_bytes(v.aval) for v in eqn.outvars)
            dtypes = tuple(d for d in (_dtype_name(v.aval)
                                       for v in eqn.outvars) if d)
            report.census.append(CensusEntry(
                kind=kind, op=name, axes=tuple(str(a) for a in axes),
                bytes=payload, eqn_path=path, level="jaxpr",
                dtypes=dtypes))

        # --- dtype promotion ------------------------------------------
        for v in eqn.outvars:
            dn = _dtype_name(v.aval)
            if dn in _F64_NAMES:
                report.findings.append(Finding(
                    "DSTPU202", "error",
                    f"f64 value produced by `{name}` — silent float64 "
                    "promotion (TPUs emulate f64; check jax_enable_x64 "
                    "and np-scalar leaks)", eqn_path=path))
                break
        if compute_width and name in COMPUTE_PRIMS:
            op_widths = {_dtype_name(v.aval) for v in eqn.invars
                         if hasattr(v, "aval")}
            wide = sorted(w for w in op_widths
                          if w and _float_width(w) > compute_width)
            if wide:
                report.findings.append(Finding(
                    "DSTPU202", "warning",
                    f"`{name}` consumes {'/'.join(wide)} operands in a "
                    f"{compute_name} path — a missing cast runs this "
                    "matmul above the configured compute dtype",
                    eqn_path=path,
                    extra={"operand_dtypes": wide,
                           "compute_dtype": compute_name}))

    # --- recompile hazards --------------------------------------------
    for i, v in enumerate(closed.jaxpr.invars):
        aval = v.aval
        if getattr(aval, "weak_type", False) and \
                getattr(aval, "shape", None) == ():
            report.findings.append(Finding(
                "DSTPU205", "warning",
                f"argument {i} is a weak-typed scalar (a Python "
                "int/float leaked into the step): a type flip across "
                "steps forces recompilation; pass "
                "jnp.asarray(x, explicit_dtype) instead",
                eqn_path=f"invars[{i}]"))
    for i, const in enumerate(_all_consts(closed)):
        nbytes = getattr(const, "nbytes", 0)
        if nbytes >= _LARGE_CONST_BYTES:
            report.findings.append(Finding(
                "DSTPU205", "info",
                f"{nbytes / 1e6:.1f} MB constant baked into the program "
                "(closure-captured array): it is re-traced and re-staged "
                "on every compile — pass it as an argument",
                eqn_path=f"consts[{i}]"))


# ------------------------------------------------------- lowered / compiled
_ALIAS_ENTRY_RE = re.compile(r"\((\d+),\s*\{[^}]*\},\s*[\w-]+\)")


def _alias_param_numbers(hlo_text):
    """Entry-parameter numbers aliased to an output, from the HloModule
    header's ``input_output_alias={ {out}: (param, {idx}, kind), ... }``
    (brace-matched by hand: the set nests braces)."""
    idx = hlo_text.find("input_output_alias=")
    if idx < 0:
        return set()
    start = hlo_text.find("{", idx)
    depth, end = 0, start
    for end in range(start, len(hlo_text)):
        if hlo_text[end] == "{":
            depth += 1
        elif hlo_text[end] == "}":
            depth -= 1
            if depth == 0:
                break
    seg = hlo_text[start:end + 1]
    return {int(m.group(1)) for m in _ALIAS_ENTRY_RE.finditer(seg)}


# one result shape `f32[8,16]` — or a variadic tuple of them `(f32[..], ..)`
# (XLA's combiner merges per-tensor reductions into ONE tuple-result op;
# missing those would under-count exactly the dominant traffic)
_HLO_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([\d,]*)\]")
_HLO_COLLECTIVE_RE = re.compile(
    r"=\s*(\([^)]*\)|[a-z0-9]+\[[\d,]*\][^=(]*?)\s*"
    r"(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)"
    r"(?:-start|-scatter)?\(")

_HLO_DTYPE_NP = {"bf16": "uint16", "f16": "float16", "f32": "float32",
                 "f64": "float64", "s32": "int32", "s8": "int8",
                 "u8": "uint8", "u16": "uint16", "u32": "uint32",
                 "pred": "bool", "s64": "int64", "u64": "uint64",
                 "s16": "int16"}


# replica_groups={{0,4},{1,5}} (explicit) or =[2,4]<=[8] (iota: 2 groups of 4)
_REPLICA_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),\d+\]")
_REPLICA_GROUPS_EXPL_RE = re.compile(r"replica_groups=\{(\{[^=]*?\})\}")


def _group_count(line: str) -> int:
    m = _REPLICA_GROUPS_IOTA_RE.search(line)
    if m:
        return int(m.group(1))
    m = _REPLICA_GROUPS_EXPL_RE.search(line)
    if m:
        return m.group(1).count("{")
    return 0


def census_from_hlo_text(hlo_text):
    """Collective census entries from an HLO module's text (parses both
    array-result and variadic tuple-result collectives).  Entries carry
    the payload dtype names (int8/int4 wire = a QUANTIZED collective,
    ``comms.QUANT_DTYPE_NAMES``) and the replica-group count (>1 marks a
    sub-axis phase of a two-level decomposition)."""
    out = []
    for m in _HLO_COLLECTIVE_RE.finditer(hlo_text):
        result, op = m.group(1), m.group(2)
        payload = 0
        dtypes = []
        for dtype_name, dims in _HLO_SHAPE_RE.findall(result):
            try:
                itemsize = np.dtype(
                    _HLO_DTYPE_NP.get(dtype_name, dtype_name)).itemsize
            except TypeError:
                continue
            numel = int(np.prod([int(d) for d in dims.split(",") if d]
                                or [1]))
            payload += numel * itemsize
            dtypes.append(dtype_name)
        line_end = hlo_text.find("\n", m.end())
        line = hlo_text[m.start():line_end if line_end > 0 else len(hlo_text)]
        out.append(CensusEntry(
            kind=canonical_kind(op) or op, op=op, axes=(),
            bytes=payload, eqn_path=None, level="hlo",
            dtypes=tuple(dtypes), groups=_group_count(line)))
    return out


def _flat_args_info(lowered):
    """Flattened (donated, aval) per lowered argument, or None."""
    try:
        import jax
        infos = jax.tree_util.tree_leaves(lowered.args_info)
        return [(bool(getattr(a, "donated", False)), a) for a in infos]
    except Exception:
        return None


def _donor_args(lowered_text):
    """``{lowered main arg number: tensor type}`` for every argument the
    lowering marked as a donor — ``tf.aliasing_output`` (aliasing pinned
    by jax) or ``jax.buffer_donor`` (aliasing deferred to XLA, the
    sharded-lowering path).  Lowered arg numbering == the executable's
    entry-parameter numbering; note jit DROPS donated-but-unused args
    from the lowered main, so these are a subset of ``args_info``."""
    sig = lowered_text[lowered_text.find("func.func public @main"):]
    cut = sig.find("{\n")
    sig = sig[:cut if cut > 0 else len(sig)]
    donors, n_args = {}, 0
    for seg in re.split(r"(?=%arg\d+)", sig):
        m = re.match(r"%arg(\d+):\s*tensor<([^>]*)>", seg)
        if not m:
            continue
        n_args += 1
        if "tf.aliasing_output" in seg or "jax.buffer_donor" in seg:
            donors[int(m.group(1))] = (m.group(2), "tf.aliasing_output" in seg)
    return donors, n_args


def _audit_donation(lowered, compiled, report):
    """Donation declared (``args_info.donated``) vs honored (the compiled
    executable's ``input_output_alias`` set; for un-compiled audits, the
    ``tf.aliasing_output`` pins in the lowered module)."""
    infos = _flat_args_info(lowered)
    try:
        text = lowered.as_text()
    except Exception as e:
        report.donation = {"checked": False, "reason": f"lowering: {e}"}
        return
    donors, n_main_args = _donor_args(text)

    # lowering refused the donation outright (no output matches the
    # arg's shape/sharding): the arg appears in main WITHOUT a donor
    # marker.  Attributable per-arg only when no unused args were
    # dropped (then lowered arg order == flattened args_info order).
    unusable = []
    if infos is not None and n_main_args == len(infos):
        unusable = [i for i, (don, _) in enumerate(infos)
                    if don and i not in donors]

    exe_aliased = None
    if compiled is not None:
        try:
            hlo = compiled.runtime_executable().hlo_modules()[0].to_string()
            exe_aliased = _alias_param_numbers(hlo)
        except Exception:
            exe_aliased = None

    if exe_aliased is not None:
        honored = sorted(set(donors) & exe_aliased)
    else:
        # without an executable only the pinned aliases are provable;
        # jax.buffer_donor args stay "unknown" and are reported unhonored
        honored = sorted(a for a, (_, pinned) in donors.items() if pinned)
    unaliased = sorted(set(donors) - set(honored))
    unhonored = unaliased + unusable
    n_declared = (sum(1 for don, _ in infos if don)
                  if infos is not None else len(donors))
    report.donation = {
        "checked": True,
        "declared": n_declared,
        "lowered_donors": len(donors),
        # args the lowering dropped entirely (unused under
        # keep_unused=False): a donated one is freed at dispatch anyway,
        # so this is waste on the call wire, not a live-memory hazard
        "args_dropped_by_lowering": (len(infos) - n_main_args
                                     if infos is not None else 0),
        "honored": len(honored),
        "unhonored_args": unhonored,
        "source": "executable" if exe_aliased is not None else "lowered",
    }
    for i in unusable:
        aval = infos[i][1]
        report.findings.append(Finding(
            "DSTPU204", "error",
            f"donation declared for argument {i} (shape "
            f"{getattr(aval, 'shape', '?')}) but the lowering could not "
            "use it: no output matches its shape/sharding, so the input "
            "buffer cannot be reused (peak memory = old + new copies)",
            eqn_path=f"main/%arg{i}"))
    for a in unaliased:
        report.findings.append(Finding(
            "DSTPU204", "error",
            f"donation declared for input %arg{a} "
            f"(tensor<{donors[a][0]}>) but the compiled executable does "
            "not alias it to any output: the input buffer stays live "
            "through the step (peak memory = old + new copies)",
            eqn_path=f"main/%arg{a}"))


def _audit_hlo_collectives(compiled, report):
    if compiled is None:
        return
    try:
        hlo = compiled.runtime_executable().hlo_modules()[0].to_string()
    except Exception:
        return
    report.census.extend(census_from_hlo_text(hlo))


# ------------------------------------------------------------- public API
def train_step_jaxpr_text(engine, batch=None, rng=None) -> str:
    """Normalized jaxpr text of an engine's traced train step — the
    byte-identity term of the monitor purity gate (``--audit-step
    monitor`` and the tier-1 twin test compare armed vs unarmed engines
    through this ONE helper so the normalization cannot drift).  Object
    addresses (``0x...`` inside partial/function reprs) are scrubbed:
    instance noise, not program content."""
    import jax

    if batch is None:
        batch = engine._stack_microbatches([next(engine._data_iterator)])
    if rng is None:
        rng = jax.random.fold_in(engine._base_rng, 0)
    with jax.set_mesh(engine.mesh):
        text = str(jax.make_jaxpr(engine._train_step)(engine.state, batch,
                                                      rng))
    return re.sub(r"0x[0-9a-f]+", "0x", text)


def audit_fn(fn, *example_args, donate_argnums=(), compute_dtype=None,
             comms_budget: Optional[CommsBudget] = None, mesh=None,
             compile: bool = True, **example_kwargs) -> AuditReport:
    """Audit a callable (or an already-``jax.jit``-wrapped one) on example
    arguments.  Tracing/lowering only — the step is never executed, and
    donated example buffers are not consumed."""
    import jax
    from contextlib import nullcontext

    wrapped = fn if hasattr(fn, "lower") else \
        jax.jit(fn, donate_argnums=donate_argnums)
    report = AuditReport()
    ctx = jax.set_mesh(mesh) if mesh is not None else nullcontext()
    with ctx:
        closed = jax.make_jaxpr(wrapped)(*example_args, **example_kwargs)
        _audit_jaxpr(closed, compute_dtype, report)
        lowered = wrapped.lower(*example_args, **example_kwargs)
        compiled = None
        if compile:
            # CachedStep entry points: audit THE executable that is (or
            # will be) dispatching — for a warm-started engine that is the
            # DESERIALIZED executable, so DSTPU204 (donation honored) is
            # proven for AOT warm starts, not just fresh compiles.
            live = getattr(wrapped, "live_executable", None)
            if live is not None:
                compiled = live(*example_args, **example_kwargs)
            if compiled is None:
                acquire = getattr(wrapped, "executable", None)
                try:
                    compiled = (acquire(*example_args, **example_kwargs)
                                if acquire is not None
                                else lowered.compile())
                except Exception as e:
                    report.findings.append(Finding(
                        "DSTPU200", "warning",
                        f"could not compile for executable-level checks: {e}",
                        eqn_path="compile"))
        _audit_donation(lowered, compiled, report)
        _audit_hlo_collectives(compiled, report)
    if comms_budget is not None:
        # budget the compiled program when available (it holds BOTH the
        # explicit collectives and the ones the SPMD partitioner inserted);
        # the jaxpr census would double-count the explicit ones
        hlo_census = [c for c in report.census if c.level == "hlo"]
        report.findings.extend(check_budget(
            hlo_census if hlo_census else report.census, comms_budget))
    return report


def audit_engine(engine, batch=None, rng=None,
                 comms_budget: Optional[CommsBudget] = None,
                 compile: bool = True) -> AuditReport:
    """Audit a ``DeepSpeedEngine``'s compiled train step on a real batch.

    Audits ``_jit_train_step`` (donating the state, exactly as
    ``train_batch`` dispatches it); offload engines audit the device
    half (``_jit_grad_step``) instead, since their optimizer update is a
    host-side design decision, not a hidden host sync.
    """
    import jax

    if batch is None:
        it = getattr(engine, "_data_iterator", None)
        assert it is not None, \
            "audit_engine needs a batch= or an engine built with training_data"
        gas = engine.gradient_accumulation_steps()
        batch = engine._stack_microbatches([next(it) for _ in range(gas)])
    if rng is None:
        rng = jax.random.fold_in(engine._base_rng, 0)
    if getattr(engine, "_param_stream", None) is not None:
        raise NotImplementedError(
            "audit_engine: the streamed (offload_param) step is a Python "
            "loop over per-layer programs; audit those via audit_fn")
    if getattr(engine, "_offload", None) is not None:
        fn = engine._jit_grad_step
    else:
        fn = engine._jit_train_step
    return audit_fn(fn, engine.state, batch, rng,
                    compute_dtype=engine.compute_dtype,
                    comms_budget=comms_budget, mesh=engine.mesh,
                    compile=compile)
